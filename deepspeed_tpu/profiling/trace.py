"""Profiler traces + range annotations.

Parity surface: the reference's NVTX instrumentation
(``deepspeed/utils/nvtx.py`` ``instrument_w_nvtx``, used throughout
ZeRO-3) and ``accelerator.range_push/range_pop``. TPU-native form: the
XLA profiler — ``trace()`` captures a TensorBoard-loadable trace
(HLO timelines, per-op device time, memory viewer), ``annotate``/
``instrument`` put named ranges on the host track exactly where the
reference put NVTX ranges, and ``step`` marks step boundaries so the
profiler's step view groups ops per training step.
"""

from __future__ import annotations

import contextlib
import functools
from typing import Iterator, Optional

import jax


@contextlib.contextmanager
def trace(logdir: str, create_perfetto_link: bool = False) -> Iterator[None]:
    """Capture an XLA profiler trace into ``logdir`` (view with
    TensorBoard's profile plugin)."""
    jax.profiler.start_trace(logdir,
                             create_perfetto_link=create_perfetto_link)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named range on the profiler's host track (the range_push/range_pop
    analog). Usable as a context manager."""
    return jax.profiler.TraceAnnotation(name)


def step(step_num: int):
    """Step-boundary annotation: groups device ops under one training step
    in the profiler's step view."""
    return jax.profiler.StepTraceAnnotation("train", step_num=step_num)


def instrument(fn=None, *, name: Optional[str] = None):
    """Decorator putting a named range around every call (reference
    ``instrument_w_nvtx``)."""
    def wrap(f):
        label = name or getattr(f, "__qualname__", getattr(f, "__name__", "fn"))

        @functools.wraps(f)
        def inner(*args, **kwargs):
            with jax.profiler.TraceAnnotation(label):
                return f(*args, **kwargs)

        return inner

    return wrap(fn) if fn is not None else wrap
