"""Profiler traces + range annotations.

Parity surface: the reference's NVTX instrumentation
(``deepspeed/utils/nvtx.py`` ``instrument_w_nvtx``, used throughout
ZeRO-3) and ``accelerator.range_push/range_pop``. TPU-native form: the
XLA profiler — ``trace()`` captures a TensorBoard-loadable trace
(HLO timelines, per-op device time, memory viewer), ``annotate``/
``instrument`` put named ranges on the host track exactly where the
reference put NVTX ranges, and ``step`` marks step boundaries so the
profiler's step view groups ops per training step.

The request tracer (``telemetry/tracing.py``) bridges onto the same
host track: while :func:`trace` is active (:func:`trace_active`), every
scoped tracer span also opens a profiler annotation with the same name,
so tracer timelines line up with the device timeline in
TensorBoard/Perfetto. This module must stay import-safe with profiling
off — jax is imported lazily and every entry point degrades to a no-op
when it is unavailable.
"""

from __future__ import annotations

import contextlib
import functools
from typing import Iterator, Optional


_warned_no_jax = False


def _jax():
    """Lazy jax handle; None when jax is not installed (profiling off /
    stripped environments — annotations degrade to no-ops, with one
    warning so a requested capture never fails silently). A jax that is
    installed but BROKEN still raises loudly — only a clean ImportError
    is the degrade path."""
    global _warned_no_jax
    try:
        import jax

        return jax
    except ImportError:
        if not _warned_no_jax:
            _warned_no_jax = True
            import logging

            logging.getLogger(__name__).warning(
                "jax unavailable: profiler traces/annotations are no-ops")
        return None


# nesting depth of active profiler captures (trace() is re-entrant in
# principle; the tracer bridge only needs "is anything capturing")
_ACTIVE = 0


def trace_active() -> bool:
    """True while a :func:`trace` capture is running — the signal the
    request tracer uses to bridge spans onto the profiler host track."""
    return _ACTIVE > 0


@contextlib.contextmanager
def trace(logdir: str, create_perfetto_link: bool = False) -> Iterator[None]:
    """Capture an XLA profiler trace into ``logdir`` (view with
    TensorBoard's profile plugin)."""
    global _ACTIVE
    jax = _jax()
    if jax is None:
        yield
        return
    jax.profiler.start_trace(logdir,
                             create_perfetto_link=create_perfetto_link)
    _ACTIVE += 1
    try:
        yield
    finally:
        _ACTIVE -= 1
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named range on the profiler's host track (the range_push/range_pop
    analog). Usable as a context manager; a no-op context when jax is
    unavailable."""
    jax = _jax()
    if jax is None:
        return contextlib.nullcontext()
    return jax.profiler.TraceAnnotation(name)


def step(step_num: int):
    """Step-boundary annotation: groups device ops under one training step
    in the profiler's step view."""
    jax = _jax()
    if jax is None:
        return contextlib.nullcontext()
    return jax.profiler.StepTraceAnnotation("train", step_num=step_num)


def instrument(fn=None, *, name: Optional[str] = None):
    """Decorator putting a named range around every call (reference
    ``instrument_w_nvtx``)."""
    def wrap(f):
        label = name or getattr(f, "__qualname__", getattr(f, "__name__", "fn"))

        @functools.wraps(f)
        def inner(*args, **kwargs):
            with annotate(label):
                return f(*args, **kwargs)

        return inner

    return wrap(fn) if fn is not None else wrap
