"""Measured (not modeled) ZeRO-3 comm-overlap accounting.

The comm-overlap wins shipped in the compressed-collectives and fused-
kernel PRs are certified by ``comm.compressed.modeled_exposure`` — an
*analytic* T3 model (bytes / bandwidth vs uniform compute windows).
This module is the layer that keeps those claims honest:
:func:`overlap_report` drives the REAL :class:`~deepspeed_tpu.parallel
.zero.Zero3BlockSchedule` eagerly on the host — every per-block phase
(weight gather, forward, backward re-gather, backward, gradient
reduce) is its own jitted program, timed fence-to-fence through the
schedule's probe seam — and then applies the schedule's own issue-order
semantics to the **measured** durations:

* ``serial comm``   = every gather/regather/reduce fully exposed;
* ``overlapped``    = pipeline fill (block 0's gather, block L-1's
  re-gather) + drain (block 0's reduce) + per-block excess where a
  block's comm outruns the compute window it hides behind — exactly the
  accounting ``modeled_exposure`` books, but with per-block measured
  times instead of uniform bytes-over-bandwidth estimates.

The comparison against the model is apples-to-apples by construction:
the link bandwidth fed to ``modeled_exposure`` is *calibrated* so the
model's serial comm time equals the measured serial comm time, and the
model's compute budget is the measured compute total — so any
measured-vs-modeled disagreement isolates exactly the model's
uniformity assumptions (equal per-block comm, fwd:bwd = 1:2 windows),
which is what the trace lane's agreement band gates
(``scripts/trace_smoke.py`` → ``TIMELINE_r01.json``).

Wire bytes are joined from the CommsLogger ledger: each per-block
collective program books its (logical, wire) bytes at trace time, so
the report carries the physical volume behind every measured duration.

Timelines land in the tracer (telemetry/tracing.py) on two tracks —
the real serial drive as it executed, and the accounted overlapped
schedule at its computed offsets — exportable as Chrome-trace JSON next
to the serving request trees. When a ``jax.profiler`` capture is
active, the measured phases also appear on the profiler host track
(``profiling/trace.py`` bridge).
"""

from __future__ import annotations

import statistics
from typing import Any, Callable, Dict, List, Optional

__all__ = ["overlap_report", "PhaseTimings"]


class PhaseTimings:
    """The schedule probe: times each (phase, block) thunk fence-to-
    fence on the host clock and forwards the result unchanged. Installed
    on :class:`~deepspeed_tpu.parallel.zero.Zero3BlockSchedule` via its
    ``probe`` seam — only ever on the eager measurement drive, never
    inside jit."""

    def __init__(self, clock=None, tracer=None, track: str = "zero3"):
        from ..resilience.clock import get_clock

        self.clock = clock if clock is not None else get_clock()
        self.tracer = tracer
        self.track = track
        self.durations: Dict[tuple, List[float]] = {}

    def __call__(self, phase: str, i: int, fn: Callable[[], Any]) -> Any:
        import jax

        sp = None
        if self.tracer is not None and self.tracer.enabled:
            sp = self.tracer.span(f"zero3/{phase}", track=self.track,
                                  block=i)
            sp.__enter__()
        try:
            t0 = self.clock.now()
            out = fn()
            # fence: jitted programs return before the work completes
            jax.tree_util.tree_map(
                lambda x: x.block_until_ready()
                if hasattr(x, "block_until_ready") else x, out)
            self.durations.setdefault((phase, i), []).append(
                self.clock.now() - t0)
            return out
        finally:
            # a raising program must not leave the span open on the
            # thread-local stack (later spans would mis-parent under it)
            # nor leak an active profiler annotation
            if sp is not None:
                sp.__exit__(None, None, None)

    def reset(self) -> None:
        self.durations.clear()

    def median(self, phase: str, i: int) -> float:
        durs = self.durations.get((phase, i), [])
        return statistics.median(durs) if durs else 0.0


def _tree_bytes(tree) -> int:
    import jax

    return sum(x.size * x.dtype.itemsize
               for x in jax.tree_util.tree_leaves(tree)
               if hasattr(x, "size"))


def _tree_numel(tree) -> int:
    import jax

    return sum(int(x.size) for x in jax.tree_util.tree_leaves(tree)
               if hasattr(x, "size"))


def _ledger_delta(before: Dict[str, Dict[str, float]],
                  after: Dict[str, Dict[str, float]]
                  ) -> Dict[str, Dict[str, float]]:
    out: Dict[str, Dict[str, float]] = {}
    for op, cur in after.items():
        prev = before.get(op, {})
        d = {k: cur[k] - prev.get(k, 0.0) for k in cur}
        if any(d.get(k) for k in ("count", "bytes", "wire_bytes")):
            out[op] = d
    return out


def overlap_report(engine, batch, *, repeats: int = 3,
                   agreement_band: float = 3.0,
                   tracer=None, clock=None) -> Dict[str, Any]:
    """Measure per-block ZeRO-3 phase timelines on ``engine``'s model
    and compare measured comm exposure against ``modeled_exposure``.

    ``engine`` must be a staged-capable TrainEngine (its model exposes
    ``zero3_blocks``); ``batch`` a host batch like ``train_batch``
    takes. Runs one warmup drive (compiles every per-block program, and
    books their ledger rows) plus ``repeats`` timed drives; per-phase
    durations are medians. Returns the report dict (see
    docs/performance.md "Measured vs modeled exposure"); raises
    ``ValueError`` on unmeasurable geometry. The ``agreement_band`` is
    recorded in the report; gating is the caller's job (the trace lane
    gates measured/modeled within the documented band)."""
    import jax
    import jax.numpy as jnp

    from ..comm import compressed as ccomm
    from ..comm.comm import configure_comms_logger, get_comms_logger
    from ..parallel.mesh import shard_map_compat
    from ..parallel.zero import Zero3BlockSchedule
    from ..resilience.clock import get_clock
    from ..telemetry.tracing import get_tracer

    if not hasattr(engine.model, "zero3_blocks"):
        raise ValueError("overlap_report needs a model exposing "
                         "zero3_blocks (the staged ZeRO-3 protocol)")
    clock = clock if clock is not None else get_clock()
    tracer = tracer if tracer is not None else get_tracer()
    PartitionSpec = jax.sharding.PartitionSpec

    env = engine._facade_prelude(engine.params, batch)
    prog_struct = engine.model.zero3_blocks(env["pc_specs"], None)
    block_specs = prog_struct.blocks
    prog = engine.model.zero3_blocks(env["pc"], batch, None)
    L = len(prog.block_fns)
    world = env["outer_world"] * env["inner_world"]
    rep = PartitionSpec()
    is_spec = env["is_spec"]

    def rep_tree(i):
        return jax.tree_util.tree_map(lambda _: rep, block_specs[i],
                                      is_leaf=is_spec)

    # per-block collectives as standalone jitted shard_map programs —
    # XLA collectives only run inside compiled programs, so (like
    # measure_comm_latencies) each phase is its own fenced executable
    def make_gather(i):
        def g(blk):
            return jax.tree_util.tree_map(
                lambda x, spec: ccomm.gather_param_leaf(
                    x, spec,
                    outer_axes=(env["outer"],) if env["outer"] else (),
                    qspec=env["wq"]),
                blk, block_specs[i], is_leaf=is_spec)

        return jax.jit(shard_map_compat(
            g, mesh=engine.topo.mesh, axis_names=set(env["axes"]),
            in_specs=(block_specs[i],), out_specs=rep_tree(i),
            check_vma=False))

    def make_reduce(i):
        def r(gtree):
            return ccomm.tree_hierarchical_pmean(
                gtree, outer_axis=env["outer"],
                outer_world=env["outer_world"], inner_axis=env["inner"],
                inner_world=env["inner_world"], qspec=env["gq"])

        return jax.jit(shard_map_compat(
            r, mesh=engine.topo.mesh, axis_names=set(env["axes"]),
            in_specs=(rep_tree(i),), out_specs=rep_tree(i),
            check_vma=False))

    gathers = [make_gather(i) for i in range(L)]
    reduces = [make_reduce(i) for i in range(L)]
    jit_fns = [jax.jit(f) for f in prog.block_fns]
    prog.block_fns = jit_fns

    log = get_comms_logger()
    was_enabled = log.enabled
    configure_comms_logger(True)
    probe = PhaseTimings(clock=clock, tracer=tracer,
                         track="zero3/measured")
    sched = Zero3BlockSchedule(
        gather=lambda i, blk: gathers[i](blk),
        reduce=lambda i, g: reduces[i](g),
        overlapped=False, probe=probe)
    scale = jnp.ones([], jnp.float32)

    # warmup drive: compiles every program and books its ledger rows
    # (record_collective fires at trace time); the per-block wire join
    # is the ledger delta across each phase's first execution
    wire: Dict[tuple, Dict[str, Dict[str, float]]] = {}

    def warm_probe(phase, i, fn):
        before = log.snapshot_totals()
        out = probe(phase, i, fn)
        wire[(phase, i)] = _ledger_delta(before, log.snapshot_totals())
        return out

    try:
        sched.probe = warm_probe
        sched.loss_and_grads(prog, scale)
        probe.reset()
        sched.probe = probe
        for _ in range(max(1, int(repeats))):
            loss, _ = sched.loss_and_grads(prog, scale)
    finally:
        # a raising drive must not leave the process-global ledger
        # enabled on callers that never asked for it
        if not was_enabled:
            configure_comms_logger(False)

    g = [probe.median("gather", i) for i in range(L)]
    f = [probe.median("fwd", i) for i in range(L)]
    rg = [probe.median("regather", i) for i in range(L)]
    b = [probe.median("bwd", i) for i in range(L)]
    r = [probe.median("reduce", i) for i in range(L)]
    compute_s = sum(f) + sum(b)

    def wire_sum(phase, i):
        return sum(d.get("wire_bytes", 0.0)
                   for d in wire.get((phase, i), {}).values())

    blocks = [{
        "block": i,
        "fused": i in sched.fused,
        "gather_s": g[i], "fwd_s": f[i], "regather_s": rg[i],
        "bwd_s": b[i], "reduce_s": r[i],
        "gather_wire_bytes": wire_sum("gather", i),
        # the backward re-gather hits the SAME compiled program as the
        # forward gather (jit cache), so its trace-time ledger delta is
        # empty — it moves the gather's wire again
        "regather_wire_bytes": (wire_sum("regather", i)
                                or wire_sum("gather", i)),
        "reduce_wire_bytes": wire_sum("reduce", i),
    } for i in range(L)]

    # the schedule's issue-order overlap accounting over MEASURED times:
    # fwd — gather(i) hides behind fwd(i-1), gather(0) is the fill;
    # bwd — regather(i-1) and reduce(i) hide behind bwd(i), regather of
    # block L-1 is the fill and block 0's reduce the drain
    fwd_fill = g[0]
    fwd_excess = sum(max(0.0, g[i] - f[i - 1]) for i in range(1, L))
    bwd_fill = rg[L - 1]
    drain = r[0]
    bwd_excess = sum(max(0.0, rg[i] + r[i + 1] - b[i + 1])
                     for i in range(L - 1))
    measured_overlapped = fwd_fill + fwd_excess + bwd_fill + drain \
        + bwd_excess
    measured_serial = sum(g) + sum(rg) + sum(r)

    # calibrated model comparison (see module docstring): bandwidth such
    # that the model's serial comm equals the measured serial comm
    param_bytes = _tree_bytes(env["pc"])
    numel_w = _tree_numel(env["pc"])
    w_itemsize = max(1, param_bytes // max(1, numel_w))
    grad_itemsize = w_itemsize          # grads reduce in compute dtype
    grad_bytes = numel_w * grad_itemsize
    wq, gq = env["wq"], env["gq"]
    w_wire = wq.wire_nbytes(numel_w) if wq else param_bytes
    g_wire = gq.wire_nbytes(numel_w) if gq else grad_bytes
    frac = (world - 1) / world if world > 1 else 0.0
    modeled = None
    agreement = None
    link_bps = None
    if frac > 0.0 and measured_serial > 0.0:
        link_bps = (2 * w_wire + g_wire) * frac / measured_serial
        modeled = ccomm.modeled_exposure(
            param_bytes=param_bytes, grad_bytes=grad_bytes, n_blocks=L,
            compute_s=compute_s, link_bps=link_bps, world=world,
            weight_qspec=wq, grad_qspec=gq,
            weight_itemsize=w_itemsize, grad_itemsize=grad_itemsize)
        if modeled["overlapped_compressed_s"] > 0.0:
            agreement = (measured_overlapped
                         / modeled["overlapped_compressed_s"])

    # assembled overlapped forward timeline on its own tracer track:
    # gather(i) drawn concurrent with fwd(i-1) exactly as the schedule
    # issues it, next to the measured serial drive — one Chrome export
    # shows the real phases and where the accounting hides them
    if tracer.enabled:
        t0 = clock.time()
        fwd_start = [0.0] * L
        fwd_start[0] = g[0]
        tracer.span_complete("zero3/gather[0]", t0, t0 + g[0],
                             track="zero3/accounted", block=0)
        for i in range(1, L):
            g_start = fwd_start[i - 1]          # issued with fwd(i-1)
            tracer.span_complete(f"zero3/gather[{i}]", t0 + g_start,
                                 t0 + g_start + g[i],
                                 track="zero3/accounted", block=i)
            fwd_start[i] = max(fwd_start[i - 1] + f[i - 1],
                               g_start + g[i])
        for i in range(L):
            tracer.span_complete(f"zero3/fwd[{i}]", t0 + fwd_start[i],
                                 t0 + fwd_start[i] + f[i],
                                 track="zero3/accounted", block=i)

    ledger_totals: Dict[str, Dict[str, float]] = {}
    for d in wire.values():
        for op, entry in d.items():
            cur = ledger_totals.setdefault(
                op, {"count": 0.0, "bytes": 0.0, "wire_bytes": 0.0})
            for k in cur:
                cur[k] += entry.get(k, 0.0)

    report = {
        "n_blocks": L,
        "world": world,
        "axes": list(env["axes"]),
        "repeats": int(repeats),
        "loss": float(jax.device_get(loss)),
        "blocks": blocks,
        "compute_s": compute_s,
        "measured": {
            "serial_comm_s": measured_serial,
            "overlapped_exposed_s": measured_overlapped,
            "fwd_fill_s": fwd_fill, "fwd_excess_s": fwd_excess,
            "bwd_fill_s": bwd_fill, "drain_s": drain,
            "bwd_excess_s": bwd_excess,
        },
        "modeled": modeled,
        "calibrated_link_bps": link_bps,
        "agreement_ratio": agreement,
        "agreement_band": float(agreement_band),
        "wire": {
            "param_bytes": param_bytes, "grad_bytes": grad_bytes,
            "w_wire_model": w_wire, "g_wire_model": g_wire,
            "ledger": ledger_totals,
        },
    }
    return report
