"""FLOPs profiler.

Parity with the reference's ``deepspeed/profiling/flops_profiler/profiler.py``
(FlopsProfiler :28 — ``start_profile`` :72, ``stop_profile``,
``get_total_flops/params/duration``, ``print_model_profile`` :282,
``get_model_profile`` module entry). The reference monkey-patches
``torch.nn.functional`` to count MACs module-by-module; under XLA the
compiler already knows the FLOPs of the optimized program, so this profiler
reads ``Compiled.cost_analysis()`` — the numbers reflect what actually runs
(post-fusion), not a Python-side estimate — and falls back to the model's
analytic ``flops_per_token`` when cost analysis is unavailable.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

from ..utils.logging import log_dist, logger


def count_params(params: Any) -> int:
    return int(sum(np.prod(x.shape) for x in jax.tree_util.tree_leaves(params)
                   if hasattr(x, "shape")))


def flops_of(fn: Callable, *args, **kwargs) -> Optional[float]:
    """FLOPs of one call of ``fn`` as XLA will execute it (post-fusion),
    via compiled cost analysis. None when the backend doesn't report it."""
    try:
        compiled = jax.jit(fn).lower(*args, **kwargs).compile()
        cost = compiled.cost_analysis()
        if isinstance(cost, list):  # older jax returns [dict]
            cost = cost[0] if cost else {}
        f = cost.get("flops")
        return float(f) if f and f > 0 else None
    except Exception as e:  # pragma: no cover - backend-specific
        logger.debug(f"cost_analysis unavailable: {e}")
        return None


@dataclass
class ProfileResult:
    flops: float                 # per step
    macs: float
    params: int
    duration_s: float = 0.0
    tflops_per_s: float = 0.0
    mfu: float = 0.0

    def __repr__(self):
        return (f"ProfileResult(flops={self.flops:.3e}, params={self.params:,}, "
                f"tflops/s={self.tflops_per_s:.1f}, mfu={self.mfu:.1%})")


class FlopsProfiler:
    """Step profiler around a jitted train/eval function.

    Usage parity with the reference (start_profile/stop_profile/
    get_total_*): attach to an engine (``engine.flops_profiler``) or use
    standalone around any function.
    """

    def __init__(self, peak_flops: Optional[float] = None):
        self.peak_flops = peak_flops or _peak_flops_per_device() * len(jax.devices())
        self._flops: Optional[float] = None
        self._params: int = 0
        self._t0: Optional[float] = None
        self._steps = 0
        self._elapsed = 0.0

    # -- reference API surface -----------------------------------------
    def start_profile(self) -> None:
        self._t0 = time.perf_counter()

    def stop_profile(self) -> None:
        if self._t0 is not None:
            self._elapsed += time.perf_counter() - self._t0
            self._steps += 1
            self._t0 = None

    def reset_profile(self) -> None:
        self._steps = 0
        self._elapsed = 0.0

    def get_total_flops(self, as_string: bool = False):
        total = (self._flops or 0.0) * max(self._steps, 1)
        return _num_to_string(total, "FLOPs") if as_string else total

    def get_total_params(self, as_string: bool = False):
        return _num_to_string(self._params, "params") if as_string else self._params

    def get_total_duration(self, as_string: bool = False):
        return f"{self._elapsed:.3f} s" if as_string else self._elapsed

    # -- measurement ----------------------------------------------------
    def measure(self, fn: Callable, *args, analytic_flops: Optional[float] = None,
                params: Any = None, iters: int = 5, warmup: int = 2,
                **kwargs) -> ProfileResult:
        """Compile-count + wall-time ``fn``; returns per-step numbers."""
        if params is not None:
            self._params = count_params(params)
        flops = flops_of(fn, *args, **kwargs) or analytic_flops or 0.0
        self._flops = flops
        jitted = jax.jit(fn)  # dslint: disable=recompile-hazard -- the profiler measures compile + first-run cost deliberately
        out = jitted(*args, **kwargs)
        jax.block_until_ready(out)
        for _ in range(max(warmup - 1, 0)):
            out = jitted(*args, **kwargs)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(iters):
            out = jitted(*args, **kwargs)
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / iters
        tflops = flops / dt / 1e12 if dt > 0 else 0.0
        return ProfileResult(
            flops=flops, macs=flops / 2, params=self._params, duration_s=dt,
            tflops_per_s=tflops,
            mfu=(flops / dt / self.peak_flops) if dt > 0 and self.peak_flops else 0.0)

    def print_model_profile(self, result: ProfileResult, detailed: bool = True) -> str:
        lines = [
            "-------------------------- DeepSpeed-TPU Flops Profiler --------------------------",
            f"params:                 {_num_to_string(result.params, '')}",
            f"fwd+bwd FLOPs per step: {_num_to_string(result.flops, 'FLOPs')}",
            f"MACs per step:          {_num_to_string(result.macs, 'MACs')}",
            f"step latency:           {result.duration_s * 1e3:.2f} ms",
            f"achieved:               {result.tflops_per_s:.2f} TFLOPS ({result.mfu:.1%} MFU)",
            "----------------------------------------------------------------------------------",
        ]
        text = "\n".join(lines)
        log_dist(text)
        return text


def get_model_profile(model, batch, rng=None, params=None,
                      peak_flops: Optional[float] = None) -> ProfileResult:
    """Module-level entry (reference get_model_profile): profile one
    training loss step of a deepspeed_tpu model."""
    import jax.numpy as jnp

    rng = rng if rng is not None else jax.random.PRNGKey(0)
    params = params if params is not None else model.init(rng)
    prof = FlopsProfiler(peak_flops=peak_flops)
    tokens = batch["input_ids"] if isinstance(batch, dict) else batch
    analytic = None
    if hasattr(model, "config") and hasattr(model.config, "flops_per_token"):
        b, s = tokens.shape
        # forward-only: 1/3 of the fwd+bwd estimate (6N -> 2N)
        analytic = model.config.flops_per_token(s) / 3.0 * b * s
    return prof.measure(lambda p, t: model.loss(p, {"input_ids": t}, rng),
                        params, jnp.asarray(tokens),
                        analytic_flops=analytic, params=params)


def _peak_flops_per_device() -> float:
    try:
        kind = jax.devices()[0].device_kind.lower()
    except Exception:
        return 0.0
    if "v5 lite" in kind or "v5e" in kind:
        return 197e12
    if "v5p" in kind or "v5" in kind:
        return 459e12
    if "v4" in kind:
        return 275e12
    if "v6" in kind or "trillium" in kind:
        return 918e12
    return 0.0  # unknown (CPU): MFU reported as 0


def _num_to_string(num: float, unit: str) -> str:
    for scale, suffix in ((1e12, "T"), (1e9, "G"), (1e6, "M"), (1e3, "K")):
        if abs(num) >= scale:
            return f"{num / scale:.2f} {suffix}{unit}"
    return f"{num:.2f} {unit}"
