"""Compression suite: QAT weight quantization, magnitude pruning (sparse /
row / head), layer reduction, staged schedule.

Reference surface: ``deepspeed/compression/compress.py``
(``init_compression`` / ``redundancy_clean``), ``basic_layer.py``
(LinearLayer_Compress et al.), ``scheduler.py`` (schedule offsets),
``config.py`` + ``constants.py`` (the ``compression_training`` JSON
vocabulary, which this module accepts verbatim).

TPU-first redesign: the reference swaps nn.Modules for compress-aware
subclasses whose forwards quantize/mask their weights. Under jit there is
no module to swap — compression is a *pure params transform* installed at
the engine's compute-cast boundary (``TrainEngine.register_param_transform``):

* QAT weight quantization — ``ops.quantizer.fake_quantize`` (straight-
  through estimator) on matched leaves;
* sparse/row/head pruning — magnitude masks computed ONCE when a
  technique's ``schedule_offset`` is crossed (from the live params, like
  the reference's mask creation) and multiplied in thereafter;
* layer reduction — a physical slice of the stacked ``layers`` subtree
  (the student keeps ``teacher_layer``-indexed layers);
* ``redundancy_clean`` — bakes the masks into the params for serving.

Techniques match leaves by key-path substring (``modules`` scope, "*" =
every float matrix), mirroring the reference's module-name matching.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.quantizer import fake_quantize
from ..utils.logging import log_dist


# ----------------------------------------------------------------------
# config (vocabulary parity with reference compression/constants.py)

def _groups(section: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Normalize shared_parameters + different_groups into a group list."""
    shared = section.get("shared_parameters", {})
    if not shared.get("enabled", False):
        return []
    out = []
    dg = section.get("different_groups", {}) or {"default": {}}
    for name, g in dg.items():
        params = dict(g.get("params", {}))
        out.append({
            "name": name,
            "modules": g.get("modules", ["*"]),
            "schedule_offset": int(shared.get("schedule_offset", 0)),
            "schedule_offset_end": shared.get("schedule_offset_end"),
            "method": shared.get("method", "l1"),
            **params,
        })
    return out


@dataclass
class CompressionConfig:
    weight_quantization: List[Dict[str, Any]] = field(default_factory=list)
    sparse_pruning: List[Dict[str, Any]] = field(default_factory=list)
    row_pruning: List[Dict[str, Any]] = field(default_factory=list)
    head_pruning: List[Dict[str, Any]] = field(default_factory=list)
    layer_reduction: Dict[str, Any] = field(default_factory=dict)

    @classmethod
    def from_dict(cls, cfg: Optional[Dict[str, Any]]) -> "CompressionConfig":
        cfg = cfg or {}
        ct = cfg.get("compression_training", cfg)
        return cls(
            weight_quantization=_groups(ct.get("weight_quantization", {})),
            sparse_pruning=_groups(ct.get("sparse_pruning", {})),
            row_pruning=_groups(ct.get("row_pruning", {})),
            head_pruning=_groups(ct.get("head_pruning", {})),
            layer_reduction=(ct.get("layer_reduction", {})
                             if ct.get("layer_reduction", {}).get("enabled")
                             else {}),
        )

    def any_enabled(self) -> bool:
        return bool(self.weight_quantization or self.sparse_pruning
                    or self.row_pruning or self.head_pruning
                    or self.layer_reduction)


# ----------------------------------------------------------------------
def _leaf_paths(params: Any) -> List[Tuple[str, Any]]:
    leaves, _ = jax.tree_util.tree_flatten_with_path(params)
    return [(jax.tree_util.keystr(path), leaf) for path, leaf in leaves]


def _matches(path: str, modules: List[str]) -> bool:
    return any(m == "*" or m in path for m in modules)


def _prunable(leaf) -> bool:
    return (hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype, jnp.floating)
            and getattr(leaf, "ndim", 0) >= 2)


class Compressor:
    """Holds per-technique masks + schedule state; produces the traced
    params transform for the engine."""

    def __init__(self, config: CompressionConfig):
        self.config = config
        self.masks: Dict[str, np.ndarray] = {}        # path -> mask
        self._mask_done: set = set()                  # activated groups
        self._active_quant: bool = False
        self._active_groups: set = set()              # quant groups in window
        self._quant_bits: Dict[str, int] = {}         # group name -> bits

    @staticmethod
    def _bits_at(g: Dict[str, Any], step: int) -> int:
        """Progressive bit reduction (reference runtime/quantize.py +
        compression start_bits/target_bits/quantization_period): bits halve
        from start toward target every quantization_period steps past the
        schedule offset."""
        target = int(g.get("target_bits", g.get("start_bits", 8)))
        start = int(g.get("start_bits", target))
        period = int(g.get("quantization_period", 1))
        if start <= target or period <= 0:
            return target
        halvings = max(0, (step - int(g["schedule_offset"])) // period)
        return max(target, start >> min(halvings, start.bit_length()))

    # -- mask construction (reference helper.py sparse/row/head mask math)
    def _compute_masks(self, params: Any, kind: str,
                       group: Dict[str, Any]) -> None:
        ratio = float(group.get("dense_ratio", 0.5))
        for path, leaf in _leaf_paths(params):
            if not (_prunable(leaf) and _matches(path, group["modules"])):
                continue
            w = np.asarray(jax.device_get(leaf), np.float32)
            if kind == "sparse":
                k = max(1, int(round(w.size * ratio)))
                thresh = np.partition(np.abs(w).reshape(-1), -k)[-k]
                mask = (np.abs(w) >= thresh).astype(np.float32)
            elif kind == "row":
                # output-feature pruning: native layout [..., in, out]
                norms = np.sum(np.abs(w), axis=tuple(range(w.ndim - 1)))
                k = max(1, int(round(norms.size * ratio)))
                thresh = np.partition(norms, -k)[-k]
                mask = (norms >= thresh).astype(np.float32)  # [out]
            elif kind == "head":
                nh = int(group["num_heads"])
                din = w.shape[-2]
                assert din % nh == 0, (path, w.shape, nh)
                hd = din // nh
                # per-head importance: |w| summed over EVERYTHING except the
                # head axis (leading dims, the within-head rows, and the
                # output columns)
                per_head = (np.abs(w).reshape(-1, nh, hd, w.shape[-1])
                            .sum(axis=(0, 2, 3)))               # [nh]
                k = max(1, int(round(nh * ratio)))
                thresh = np.partition(per_head, -k)[-k]
                hmask = (per_head >= thresh).astype(np.float32)  # [nh]
                mask = np.repeat(hmask, hd)                      # [in]
                mask = mask[:, None]                             # bcast on out
            else:
                raise ValueError(kind)
            prev = self.masks.get(path)
            self.masks[path] = mask if prev is None else prev * mask
        log_dist(f"compression: {kind} mask activated for group "
                 f"'{group['name']}' ({group['modules']})")

    # -- schedule (reference scheduler.py) ------------------------------
    @staticmethod
    def _in_window(g: Dict[str, Any], step: int) -> bool:
        end = g.get("schedule_offset_end")
        return step >= g["schedule_offset"] and (end is None or step < int(end))

    def step(self, engine, global_step: int) -> None:
        """Engine step hook: (re)computes masks at offset crossings,
        retires techniques past ``schedule_offset_end``, and reinstalls the
        transform only when the active set changes."""
        changed = False
        for kind, groups in (("sparse", self.config.sparse_pruning),
                             ("row", self.config.row_pruning),
                             ("head", self.config.head_pruning)):
            for g in groups:
                key = (kind, g["name"])
                if key not in self._mask_done and self._in_window(g, global_step):
                    params = (engine._materialized_params()
                              if hasattr(engine, "_materialized_params")
                              else engine.params)
                    self._compute_masks(params, kind, g)
                    self._mask_done.add(key)
                    changed = True
                end = g.get("schedule_offset_end")
                if (key in self._mask_done and end is not None
                        and global_step >= int(end)):
                    # retire: drop this group's masks (recompute survivors)
                    self._mask_done.discard(key)
                    g["schedule_offset"] = float("inf")  # never re-arms
                    self.masks.clear()
                    for k2, gs2 in (("sparse", self.config.sparse_pruning),
                                    ("row", self.config.row_pruning),
                                    ("head", self.config.head_pruning)):
                        for g2 in gs2:
                            if (k2, g2["name"]) in self._mask_done:
                                params = (engine._materialized_params()
                                          if hasattr(engine, "_materialized_params")
                                          else engine.params)
                                self._compute_masks(params, k2, g2)
                    changed = True
        want_quant = any(self._in_window(g, global_step)
                         for g in self.config.weight_quantization)
        if want_quant != self._active_quant:
            self._active_quant = want_quant
            changed = True
        # per-group gating: a group quantizes only inside ITS window
        active_names = {g["name"] for g in self.config.weight_quantization
                        if self._in_window(g, global_step)}
        if active_names != self._active_groups:
            self._active_groups = active_names
            changed = True
        for g in self.config.weight_quantization:
            if g["name"] not in active_names:
                continue
            bits = self._bits_at(g, global_step)
            if self._quant_bits.get(g["name"]) != bits:
                self._quant_bits[g["name"]] = bits
                changed = True
        if changed and hasattr(engine, "register_param_transform"):
            engine.register_param_transform(self.transform)

    # -- the traced transform ------------------------------------------
    def transform(self, params: Any) -> Any:
        masks = dict(self.masks)
        active = self._active_groups
        quant_groups = ([g for g in self.config.weight_quantization
                         if g["name"] in active]
                        if self._active_quant else [])

        def leaf_fn(path, leaf):
            p = jax.tree_util.keystr(path)
            m = masks.get(p)
            if m is not None:
                leaf = leaf * jnp.asarray(m, leaf.dtype)
            for g in quant_groups:
                if _prunable(leaf) and _matches(p, g["modules"]):
                    bits = self._quant_bits.get(
                        g["name"], int(g.get("target_bits",
                                             g.get("start_bits", 8))))
                    block = next((b for b in (256, 128, 64, 32, 16)
                                  if leaf.size % b == 0), None)
                    if bits < 16 and block is not None:
                        leaf = fake_quantize(leaf, bits=8 if bits > 4 else 4,
                                             block=block)
                    break
            return leaf

        return jax.tree_util.tree_map_with_path(leaf_fn, params)

    def student_params(self, params: Any) -> Any:
        """Apply layer reduction (student init) to a raw params tree —
        BEFORE engine construction (shapes change)."""
        if not self.config.layer_reduction:
            return params
        return _apply_layer_reduction(params, self.config.layer_reduction)

    # -- serving-time cleanup ------------------------------------------
    def clean(self, params: Any) -> Any:
        """Bake masks into the weights (reference redundancy_clean —
        physical removal is layout-dependent; zeroed rows/heads cost no
        MXU work after XLA's sparsity-oblivious but mask-stable constant
        folding, and keep every consumer shape-compatible)."""
        masks = dict(self.masks)

        def leaf_fn(path, leaf):
            m = masks.get(jax.tree_util.keystr(path))
            if m is not None and hasattr(leaf, "dtype"):
                return (jnp.asarray(leaf) * jnp.asarray(m, leaf.dtype)
                        ).astype(leaf.dtype)
            return leaf

        return jax.tree_util.tree_map_with_path(leaf_fn, params)


# ----------------------------------------------------------------------
def _apply_layer_reduction(params: Any, lr_cfg: Dict[str, Any]) -> Any:
    """Student init: keep ``teacher_layer``-indexed layers of the stacked
    ``layers`` subtree (reference compress.py student_initialization)."""
    keep = lr_cfg.get("teacher_layer")
    if keep is None:
        keep = list(range(int(lr_cfg["keep_number_layer"])))
    idx = jnp.asarray(keep, jnp.int32)

    def slice_leaf(x):
        return jnp.take(jnp.asarray(x), idx, axis=0)

    out = dict(params)
    out["layers"] = jax.tree_util.tree_map(slice_leaf, params["layers"])
    log_dist(f"compression: layer reduction -> {len(keep)} layers {keep}")
    return out


def init_compression(engine_or_params: Any, config: Any) -> Compressor:
    """Reference ``init_compression(model, ds_config)`` parity. Pass a
    TrainEngine to wire the schedule + transform automatically; pass a
    params tree to drive the compressor manually (``compressor.step`` /
    ``compressor.transform``). Layer reduction is applied physically to the
    engine params up front (student init)."""
    ccfg = (config if isinstance(config, CompressionConfig)
            else CompressionConfig.from_dict(config))
    comp = Compressor(ccfg)
    engine = engine_or_params if hasattr(engine_or_params, "train_batch") else None
    if engine is not None:
        if ccfg.layer_reduction:
            # layer reduction changes param SHAPES — opt state and shardings
            # of a live engine would go stale. Like the reference's student
            # initialization, it must happen before engine construction.
            raise ValueError(
                "layer_reduction must be applied before initialize(): "
                "comp = init_compression(params, cfg); "
                "params = comp.student_params(params)")
        engine.register_step_hook(comp.step)
        comp.step(engine, engine.global_steps)  # offsets at 0 activate now
    return comp


def redundancy_clean(params_or_engine: Any, config: Any,
                     compressor: Optional[Compressor] = None) -> Any:
    """Reference ``redundancy_clean`` parity: returns params with masks
    baked (and layer reduction applied if not already)."""
    ccfg = (config if isinstance(config, CompressionConfig)
            else CompressionConfig.from_dict(config))
    engine = params_or_engine if hasattr(params_or_engine, "train_batch") else None
    params = engine.params if engine is not None else params_or_engine
    if engine is not None and hasattr(engine, "_materialized_params"):
        params = engine._materialized_params()
    comp = compressor or Compressor(ccfg)
    return comp.clean(params)
