from .compress import (  # noqa: F401
    CompressionConfig,
    Compressor,
    init_compression,
    redundancy_clean,
)
