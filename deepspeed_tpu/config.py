"""JSON configuration system.

Capability parity with the reference's ``runtime/config.py`` (DeepSpeedConfig:
JSON -> typed config with batch-size arithmetic and per-subsystem sub-configs)
and ``runtime/config_utils.py`` (pydantic base supporting ``"auto"`` values).
Rebuilt on plain dataclasses — no pydantic dependency — and extended with a
TPU-native ``mesh`` section describing the device-mesh axes
(data / seq / pipe / model / expert) that replaces the reference's
process-group plumbing (``deepspeed/utils/groups.py``).

The batch invariant from the reference
(``train_batch_size == micro_batch_per_device * gradient_accumulation_steps *
data_parallel_world_size``) is resolved and validated in
:meth:`Config.resolve_batch_config`, mirroring ``runtime/config.py``'s
``_configure_train_batch_size``.
"""

from __future__ import annotations

import copy
import dataclasses
import json
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Union

from .utils.logging import logger

AUTO = "auto"


class ConfigError(ValueError):
    pass


def _is_auto(v: Any) -> bool:
    return isinstance(v, str) and v.lower() == AUTO


def _take(d: Dict[str, Any], key: str, default: Any) -> Any:
    v = d.pop(key, default)
    return default if v is None else v


def _warn_unknown(d: Dict[str, Any], section: str) -> None:
    for k in d:
        logger.warning(f"Unknown config key '{k}' in section '{section}' — ignored")


@dataclass
class OptimizerConfig:
    """Mirrors the reference's ``optimizer`` block (runtime/config.py get_optimizer_*)."""

    type: str = "adamw"
    params: Dict[str, Any] = field(default_factory=dict)
    # Reference: "legacy_fusion" etc. are CUDA-specific; fused-by-construction under jit.

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "OptimizerConfig":
        if not d:
            return cls()
        d = dict(d)
        out = cls(type=str(_take(d, "type", "adamw")).lower(), params=dict(_take(d, "params", {})))
        _warn_unknown(d, "optimizer")
        return out


@dataclass
class SchedulerConfig:
    """Mirrors the reference's ``scheduler`` block (runtime/lr_schedules.py)."""

    type: Optional[str] = None
    params: Dict[str, Any] = field(default_factory=dict)

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "SchedulerConfig":
        if not d:
            return cls()
        d = dict(d)
        out = cls(type=_take(d, "type", None), params=dict(_take(d, "params", {})))
        _warn_unknown(d, "scheduler")
        return out


@dataclass
class FP16Config:
    """Mirrors reference ``fp16`` block incl. dynamic loss scaling knobs
    (runtime/fp16/loss_scaler.py:91 DynamicLossScaler)."""

    enabled: bool = False
    loss_scale: float = 0.0  # 0 => dynamic
    initial_scale_power: int = 16
    loss_scale_window: int = 1000
    hysteresis: int = 2
    min_loss_scale: float = 1.0
    consecutive_hysteresis: bool = False

    @property
    def dynamic_loss_scale(self) -> bool:
        return self.loss_scale == 0

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "FP16Config":
        if not d:
            return cls()
        d = dict(d)
        out = cls(
            enabled=bool(_take(d, "enabled", False)),
            loss_scale=float(_take(d, "loss_scale", 0.0)),
            initial_scale_power=int(_take(d, "initial_scale_power", 16)),
            loss_scale_window=int(_take(d, "loss_scale_window", 1000)),
            hysteresis=int(_take(d, "hysteresis", 2)),
            min_loss_scale=float(_take(d, "min_loss_scale", 1.0)),
            consecutive_hysteresis=bool(_take(d, "consecutive_hysteresis", False)),
        )
        d.pop("auto_cast", None)  # torch-amp specific; casting is explicit in JAX
        d.pop("fp16_master_weights_and_grads", None)
        _warn_unknown(d, "fp16")
        return out


@dataclass
class BF16Config:
    enabled: bool = False

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "BF16Config":
        if not d:
            return cls()
        d = dict(d)
        out = cls(enabled=bool(_take(d, "enabled", False)))
        d.pop("immediate_grad_update", None)
        _warn_unknown(d, "bf16")
        return out


@dataclass
class OffloadConfig:
    """Mirrors reference ``runtime/zero/offload_config.py`` (device: cpu|nvme)."""

    device: str = "none"  # none | cpu | nvme
    nvme_path: Optional[str] = None
    pin_memory: bool = True
    buffer_count: int = 4
    buffer_size: int = 100_000_000
    fast_init: bool = False
    ratio: float = 1.0

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "OffloadConfig":
        if not d:
            return cls()
        d = dict(d)
        out = cls(
            device=str(_take(d, "device", "none")),
            nvme_path=_take(d, "nvme_path", None),
            pin_memory=bool(_take(d, "pin_memory", True)),
            buffer_count=int(_take(d, "buffer_count", 4)),
            buffer_size=int(_take(d, "buffer_size", 100_000_000)),
            fast_init=bool(_take(d, "fast_init", False)),
            ratio=float(_take(d, "ratio", 1.0)),
        )
        d.pop("max_in_cpu", None)
        _warn_unknown(d, "offload")
        return out

    @property
    def enabled(self) -> bool:
        return self.device not in ("none", None)


@dataclass
class ZeroConfig:
    """Mirrors reference ``runtime/zero/config.py`` DeepSpeedZeroConfig.

    On TPU the stages translate to sharding choices over the ``data`` mesh
    axis rather than hook machinery (SURVEY.md §2.2):
      stage 0 — replicated params/grads/opt state (plain DP, psum grads)
      stage 1 — optimizer states sharded (reduce-scatter grads, shard update,
                all-gather params)
      stage 2 — + gradients sharded (identical XLA program to stage 1; kept
                distinct for config parity)
      stage 3 — + parameters sharded (FSDP-style; XLA inserts all-gathers)
    """

    stage: int = 0
    # Communication/bucketing knobs (accepted for parity; XLA schedules
    # collectives, so these do not change the compiled program).
    allgather_partitions: bool = True
    allgather_bucket_size: int = 500_000_000
    overlap_comm: bool = True
    reduce_scatter: bool = True
    reduce_bucket_size: int = 500_000_000
    contiguous_gradients: bool = True
    offload_param: OffloadConfig = field(default_factory=OffloadConfig)
    offload_optimizer: OffloadConfig = field(default_factory=OffloadConfig)
    sub_group_size: int = 1_000_000_000
    # stage-3 partitioning thresholds: params smaller than this stay replicated
    stage3_param_persistence_threshold: int = 10_000
    stage3_max_live_parameters: int = 1_000_000_000
    stage3_max_reuse_distance: int = 1_000_000_000
    stage3_prefetch_bucket_size: int = 50_000_000
    stage3_gather_16bit_weights_on_model_save: bool = False
    # ZeRO++ style knobs
    zero_hpz_partition_size: int = 1
    zero_quantized_weights: bool = False
    zero_quantized_gradients: bool = False
    # MiCS
    mics_shard_size: int = -1
    mics_hierarchical_params_gather: bool = False
    round_robin_gradients: bool = False
    ignore_unused_parameters: bool = True

    def zero_inner_size(self) -> int:
        """Inner (zshard) factor of the data-parallel dimension: MiCS
        sub-group size takes precedence over the hpZ secondary partition
        (a MiCS run shards everything at that granularity already)."""
        if (self.mics_shard_size or 0) > 0:
            return int(self.mics_shard_size)
        if self.zero_hpz_partition_size > 1:
            return int(self.zero_hpz_partition_size)
        return 1

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "ZeroConfig":
        if not d:
            return cls()
        d = dict(d)
        out = cls(
            stage=int(_take(d, "stage", 0)),
            allgather_partitions=bool(_take(d, "allgather_partitions", True)),
            allgather_bucket_size=int(float(_take(d, "allgather_bucket_size", 500_000_000))),
            overlap_comm=bool(_take(d, "overlap_comm", True)),
            reduce_scatter=bool(_take(d, "reduce_scatter", True)),
            reduce_bucket_size=int(float(_take(d, "reduce_bucket_size", 500_000_000))),
            contiguous_gradients=bool(_take(d, "contiguous_gradients", True)),
            offload_param=OffloadConfig.from_dict(_take(d, "offload_param", None)),
            offload_optimizer=OffloadConfig.from_dict(_take(d, "offload_optimizer", None)),
            sub_group_size=int(float(_take(d, "sub_group_size", 1_000_000_000))),
            stage3_param_persistence_threshold=int(float(_take(d, "stage3_param_persistence_threshold", 10_000))),
            stage3_max_live_parameters=int(float(_take(d, "stage3_max_live_parameters", 1_000_000_000))),
            stage3_max_reuse_distance=int(float(_take(d, "stage3_max_reuse_distance", 1_000_000_000))),
            stage3_prefetch_bucket_size=int(float(_take(d, "stage3_prefetch_bucket_size", 50_000_000))),
            stage3_gather_16bit_weights_on_model_save=bool(
                _take(d, "stage3_gather_16bit_weights_on_model_save", False)
            ),
            zero_hpz_partition_size=int(_take(d, "zero_hpz_partition_size", 1)),
            zero_quantized_weights=bool(_take(d, "zero_quantized_weights", False)),
            zero_quantized_gradients=bool(_take(d, "zero_quantized_gradients", False)),
            mics_shard_size=int(_take(d, "mics_shard_size", -1)),
            mics_hierarchical_params_gather=bool(_take(d, "mics_hierarchical_params_gather", False)),
            round_robin_gradients=bool(_take(d, "round_robin_gradients", False)),
            ignore_unused_parameters=bool(_take(d, "ignore_unused_parameters", True)),
        )
        if out.stage not in (0, 1, 2, 3):
            raise ConfigError(f"zero_optimization.stage must be 0..3, got {out.stage}")
        # Accepted-but-inert reference keys.
        for k in ("cpu_offload", "cpu_offload_params", "load_from_fp32_weights", "elastic_checkpoint",
                  "zero_quantized_nontrainable_weights", "memory_efficient_linear", "param_persistence_threshold",
                  "model_persistence_threshold", "max_live_parameters", "max_reuse_distance",
                  "prefetch_bucket_size", "gather_16bit_weights_on_model_save", "use_multi_rank_bucket_allreduce",
                  "legacy_stage1"):
            d.pop(k, None)
        _warn_unknown(d, "zero_optimization")
        return out


@dataclass
class MeshConfig:
    """TPU-native topology description (replaces reference groups.py).

    Axis sizes; -1 means "use all remaining devices". Axis order is outermost
    to innermost: (data, seq, pipe, expert, model). ``model`` is innermost so
    tensor-parallel collectives ride the fastest ICI links.
    """

    data: int = -1
    seq: int = 1
    pipe: int = 1
    expert: int = 1
    model: int = 1

    AXES = ("data", "seq", "pipe", "expert", "model")

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "MeshConfig":
        if not d:
            return cls()
        d = dict(d)
        out = cls(
            data=int(_take(d, "data", -1)),
            seq=int(_take(d, "seq", 1)),
            pipe=int(_take(d, "pipe", 1)),
            expert=int(_take(d, "expert", 1)),
            model=int(_take(d, "model", 1)),
        )
        _warn_unknown(d, "mesh")
        return out

    def resolve(self, n_devices: int) -> Dict[str, int]:
        sizes = {a: getattr(self, a) for a in self.AXES}
        fixed = 1
        free_axes = [a for a, s in sizes.items() if s == -1]
        for a, s in sizes.items():
            if s != -1:
                fixed *= s
        if n_devices % fixed != 0:
            raise ConfigError(f"mesh axes {sizes} do not divide device count {n_devices}")
        rem = n_devices // fixed
        if not free_axes:
            if fixed != n_devices:
                raise ConfigError(f"mesh axes {sizes} product {fixed} != device count {n_devices}")
        elif len(free_axes) == 1:
            sizes[free_axes[0]] = rem
        else:
            # first free axis soaks up the remainder, rest get 1
            sizes[free_axes[0]] = rem
            for a in free_axes[1:]:
                sizes[a] = 1
        return sizes


@dataclass
class ActivationCheckpointingConfig:
    """Mirrors reference ``runtime/activation_checkpointing/config.py``.

    On TPU this maps to ``jax.checkpoint`` (remat) policies; partitioned
    activations map to remat + sharding constraints.
    """

    partition_activations: bool = False
    cpu_checkpointing: bool = False
    contiguous_memory_optimization: bool = False
    number_checkpoints: Optional[int] = None
    synchronize_checkpoint_boundary: bool = False
    profile: bool = False
    # TPU-native: which remat policy to use ("full", "dots", "nothing")
    policy: str = "full"

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "ActivationCheckpointingConfig":
        if not d:
            return cls()
        d = dict(d)
        out = cls(
            partition_activations=bool(_take(d, "partition_activations", False)),
            cpu_checkpointing=bool(_take(d, "cpu_checkpointing", False)),
            contiguous_memory_optimization=bool(_take(d, "contiguous_memory_optimization", False)),
            number_checkpoints=_take(d, "number_checkpoints", None),
            synchronize_checkpoint_boundary=bool(_take(d, "synchronize_checkpoint_boundary", False)),
            profile=bool(_take(d, "profile", False)),
            policy=str(_take(d, "policy", "full")),
        )
        _warn_unknown(d, "activation_checkpointing")
        return out


@dataclass
class MonitorConfig:
    """Mirrors reference ``monitor/config.py`` (tensorboard/csv/wandb)."""

    tensorboard_enabled: bool = False
    tensorboard_output_path: str = ""
    tensorboard_job_name: str = "DeepSpeedTPUJob"
    csv_enabled: bool = False
    csv_output_path: str = ""
    csv_job_name: str = "DeepSpeedTPUJob"
    wandb_enabled: bool = False
    wandb_project: Optional[str] = None
    wandb_team: Optional[str] = None
    wandb_group: Optional[str] = None

    @classmethod
    def from_dict(cls, tb: Optional[Dict], csv: Optional[Dict], wandb: Optional[Dict]) -> "MonitorConfig":
        tb = dict(tb or {})
        csv = dict(csv or {})
        wandb = dict(wandb or {})
        return cls(
            tensorboard_enabled=bool(tb.get("enabled", False)),
            tensorboard_output_path=str(tb.get("output_path", "")),
            tensorboard_job_name=str(tb.get("job_name", "DeepSpeedTPUJob")),
            csv_enabled=bool(csv.get("enabled", False)),
            csv_output_path=str(csv.get("output_path", "")),
            csv_job_name=str(csv.get("job_name", "DeepSpeedTPUJob")),
            wandb_enabled=bool(wandb.get("enabled", False)),
            wandb_project=wandb.get("project"),
            wandb_team=wandb.get("team"),
            wandb_group=wandb.get("group"),
        )

    @property
    def enabled(self) -> bool:
        return self.tensorboard_enabled or self.csv_enabled or self.wandb_enabled


@dataclass
class TelemetryConfig:
    """Unified telemetry pipeline (``telemetry`` block — TPU-native, no
    reference analog; see docs/observability.md).

    When enabled, the train engine emits one StepStats JSONL record per
    optimizer step (wall time, tokens/s, MFU, comm breakdown, memory
    watermarks) and runs heartbeat/stall detection. Disabled (default),
    the engine adds zero extra per-step host synchronization.
    """

    enabled: bool = False
    output_dir: str = "telemetry"
    jsonl_path: Optional[str] = None       # default: <output_dir>/steps.jsonl
    prometheus_path: Optional[str] = None  # e.g. <output_dir>/metrics.prom
    flush_every: int = 1
    export_every: int = 10
    stall_detection: bool = True
    stall_factor: float = 3.0
    stall_window: int = 20
    stall_warmup_steps: int = 2
    heartbeat_path: Optional[str] = None
    # serving-request span records (docs/serving.md); None defaults to
    # <output_dir>/requests.jsonl, "" disables the sink
    requests_jsonl_path: Optional[str] = None
    # request-scoped distributed tracing + flight recorder
    # (telemetry/tracing.py, docs/observability.md). Off by default:
    # zero extra host syncs / clock reads on every hot path.
    tracing: bool = False
    trace_ring: int = 4096          # finished-span ring buffer size
    flight_capacity: int = 512      # flight-recorder ring size
    flight_dump_dir: Optional[str] = None  # auto-dump dir; None = in-memory

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "TelemetryConfig":
        if not d:
            return cls()
        d = dict(d)
        out = cls(
            enabled=bool(_take(d, "enabled", False)),
            output_dir=str(_take(d, "output_dir", "telemetry")),
            jsonl_path=_take(d, "jsonl_path", None),
            prometheus_path=_take(d, "prometheus_path", None),
            flush_every=int(_take(d, "flush_every", 1)),
            export_every=int(_take(d, "export_every", 10)),
            stall_detection=bool(_take(d, "stall_detection", True)),
            stall_factor=float(_take(d, "stall_factor", 3.0)),
            stall_window=int(_take(d, "stall_window", 20)),
            stall_warmup_steps=int(_take(d, "stall_warmup_steps", 2)),
            heartbeat_path=_take(d, "heartbeat_path", None),
            requests_jsonl_path=_take(d, "requests_jsonl_path", None),
            tracing=bool(_take(d, "tracing", False)),
            trace_ring=int(_take(d, "trace_ring", 4096)),
            flight_capacity=int(_take(d, "flight_capacity", 512)),
            flight_dump_dir=_take(d, "flight_dump_dir", None),
        )
        if out.trace_ring < 1 or out.flight_capacity < 1:
            raise ConfigError(
                "telemetry.trace_ring and telemetry.flight_capacity must "
                f"be >= 1, got {out.trace_ring}/{out.flight_capacity}")
        if out.stall_factor <= 1.0:
            raise ConfigError(
                f"telemetry.stall_factor must exceed 1.0, got {out.stall_factor}")
        _warn_unknown(d, "telemetry")
        return out


@dataclass
class DataLoaderConfig:
    """The ``dataloader`` block: async input-pipeline knobs
    (docs/performance.md — TPU-native analog of the reference's
    pinned-memory staged loaders).

    ``prefetch_depth`` batches are collated + uploaded by a producer
    thread ahead of the training loop (0 = synchronous inline loading;
    2 = double buffering, the default). ``initialize()`` threads this
    into the :class:`~deepspeed_tpu.runtime.dataloader.DataLoader` it
    builds; checkpoints stay FT-safe — the loader position always
    reflects consumed batches, never producer read-ahead."""

    prefetch_depth: int = 2

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "DataLoaderConfig":
        if not d:
            return cls()
        d = dict(d)
        out = cls(prefetch_depth=int(_take(d, "prefetch_depth", 2)))
        if out.prefetch_depth < 0:
            raise ConfigError(
                f"dataloader.prefetch_depth must be >= 0, got {out.prefetch_depth}")
        _warn_unknown(d, "dataloader")
        return out


@dataclass
class CompileConfig:
    """The ``compile`` block: XLA compilation-cache + warmup knobs
    (docs/performance.md).

    ``cache_dir`` enables JAX's persistent compilation cache there (time-
    to-first-step across process restarts drops to cache-deserialize
    time). ``aot_warmup`` makes ``initialize()`` AOT-compile the fused
    train step (``lower().compile()``) in a background thread, overlapped
    with the input pipeline's warm fill; the resulting executable serves
    the steady-state steps directly. ``warn_on_recompile`` logs (once)
    when a new batch shape misses the train-step jit cache — every new
    shape compiles a new program; the counter ``train/recompiles`` tracks
    it either way."""

    cache_dir: Optional[str] = None
    min_compile_time_s: float = 0.0
    aot_warmup: bool = True
    warn_on_recompile: bool = True

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "CompileConfig":
        if not d:
            return cls()
        d = dict(d)
        out = cls(
            cache_dir=_take(d, "cache_dir", None),
            min_compile_time_s=float(_take(d, "min_compile_time_s", 0.0)),
            aot_warmup=bool(_take(d, "aot_warmup", True)),
            warn_on_recompile=bool(_take(d, "warn_on_recompile", True)),
        )
        _warn_unknown(d, "compile")
        return out


@dataclass
class FlopsProfilerConfig:
    """Mirrors reference ``profiling/config.py``."""

    enabled: bool = False
    profile_step: int = 1
    module_depth: int = -1
    top_modules: int = 1
    detailed: bool = True
    output_file: Optional[str] = None

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "FlopsProfilerConfig":
        if not d:
            return cls()
        d = dict(d)
        out = cls(
            enabled=bool(_take(d, "enabled", False)),
            profile_step=int(_take(d, "profile_step", 1)),
            module_depth=int(_take(d, "module_depth", -1)),
            top_modules=int(_take(d, "top_modules", 1)),
            detailed=bool(_take(d, "detailed", True)),
            output_file=_take(d, "output_file", None),
        )
        _warn_unknown(d, "flops_profiler")
        return out


@dataclass
class CommsLoggerConfig:
    """Mirrors reference ``comms_logger`` block (utils/comms_logging.py)."""

    enabled: bool = False
    verbose: bool = False
    prof_all: bool = True
    debug: bool = False
    prof_ops: List[str] = field(default_factory=list)

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "CommsLoggerConfig":
        if not d:
            return cls()
        d = dict(d)
        out = cls(
            enabled=bool(_take(d, "enabled", False)),
            verbose=bool(_take(d, "verbose", False)),
            prof_all=bool(_take(d, "prof_all", True)),
            debug=bool(_take(d, "debug", False)),
            prof_ops=list(_take(d, "prof_ops", [])),
        )
        _warn_unknown(d, "comms_logger")
        return out


@dataclass
class CommCompressionConfig:
    """The ``comm_compression`` block: the compressed-collectives facade
    (comm/compressed.py, docs/communication.md) — quantized weight
    all-gather (qwZ), hierarchical quantized gradient reduce-scatter
    (qgZ) and the T3-style staged overlap schedule as the shipped ZeRO-3
    path on large meshes.

    ``enabled`` is tri-state: ``"auto"`` (default) turns compression on
    exactly when the ZeRO data-parallel group reaches
    ``mesh_size_threshold`` ranks — small meshes keep the dense path
    (the pack/unpack bracket only pays for itself across slow links, see
    scripts/tpu_quant_comm_bench.py break-even analysis); ``true``/
    ``false`` force it. The explicit ZeRO++ knobs
    (``zero_optimization.zero_quantized_weights`` / ``_gradients``)
    still opt individual legs in regardless of the threshold.

    ``grad_bits`` applies to the INTER-slice gradient hop only — the
    intra-slice (fast-ICI) hop always reduces dense fp (the ZeRO++
    hierarchical positioning). ``overlap`` picks the per-block issue
    order of the staged schedule for models exposing ``zero3_blocks``:
    ``"staged"`` prefetches the next block's gather and defers the
    previous block's reduce (T3), ``"serial"`` issues each collective
    immediately at its consumer, ``"off"`` disables the block schedule.
    ``error_stats`` adds traced quantization-error scalars to the step
    metrics (one extra host fetch per step when telemetry is on)."""

    enabled: Any = "auto"      # "auto" | True | False
    mesh_size_threshold: int = 16
    weight_bits: int = 8
    weight_block: int = 256
    grad_bits: int = 8
    grad_block: int = 256
    overlap: str = "staged"    # staged | serial | off
    error_stats: bool = False
    # kernel backend of the facade (comm/backends.py): "auto" fuses the
    # quantize/pack bracket into the adjacent matmul via Pallas kernels
    # on TPU and keeps the plain XLA collectives elsewhere; "pallas" /
    # "xla" force a backend ("pallas" off-TPU runs interpret mode — the
    # CPU evidence-lane configuration)
    kernel_backend: str = "auto"   # auto | xla | pallas

    def resolve_enabled(self, dp_size: int) -> bool:
        if isinstance(self.enabled, bool):
            return self.enabled
        return dp_size >= self.mesh_size_threshold

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "CommCompressionConfig":
        if not d:
            return cls()
        d = dict(d)
        enabled = _take(d, "enabled", "auto")
        if not isinstance(enabled, bool):
            if str(enabled).lower() != "auto":
                raise ConfigError(
                    f"comm_compression.enabled must be true/false/'auto', "
                    f"got {enabled!r}")
            enabled = "auto"
        out = cls(
            enabled=enabled,
            mesh_size_threshold=int(_take(d, "mesh_size_threshold", 16)),
            weight_bits=int(_take(d, "weight_bits", 8)),
            weight_block=int(_take(d, "weight_block", 256)),
            grad_bits=int(_take(d, "grad_bits", 8)),
            grad_block=int(_take(d, "grad_block", 256)),
            overlap=str(_take(d, "overlap", "staged")),
            error_stats=bool(_take(d, "error_stats", False)),
            kernel_backend=str(_take(d, "kernel_backend", "auto")),
        )
        for name, bits in (("weight_bits", out.weight_bits),
                           ("grad_bits", out.grad_bits)):
            if bits not in (4, 8):
                raise ConfigError(
                    f"comm_compression.{name} must be 4 or 8, got {bits}")
        for name, block in (("weight_block", out.weight_block),
                            ("grad_block", out.grad_block)):
            if block <= 0 or block % 2:
                raise ConfigError(
                    f"comm_compression.{name} must be positive and even, "
                    f"got {block}")
        if out.overlap not in ("staged", "serial", "off"):
            raise ConfigError(
                f"comm_compression.overlap must be 'staged', 'serial' or "
                f"'off', got '{out.overlap}'")
        if out.mesh_size_threshold < 1:
            raise ConfigError(
                f"comm_compression.mesh_size_threshold must be >= 1, got "
                f"{out.mesh_size_threshold}")
        if out.kernel_backend not in ("auto", "xla", "pallas"):
            raise ConfigError(
                f"comm_compression.kernel_backend must be 'auto', 'xla' or "
                f"'pallas', got '{out.kernel_backend}'")
        _warn_unknown(d, "comm_compression")
        return out


@dataclass
class PipelineConfig:
    """Pipeline execution knobs (reference: PipelineModule/PipelineEngine args)."""

    stages: int = 1
    partition_method: str = "parameters"  # uniform | parameters | type:regex
    activation_checkpoint_interval: int = 0
    pipe_schedule: str = "1f1b"  # 1f1b | gpipe

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "PipelineConfig":
        if not d:
            return cls()
        d = dict(d)
        out = cls(
            stages=int(_take(d, "stages", 1)),
            partition_method=str(_take(d, "partition_method", "parameters")),
            activation_checkpoint_interval=int(_take(d, "activation_checkpoint_interval", 0)),
            pipe_schedule=str(_take(d, "pipe_schedule", "1f1b")).lower(),
        )
        _warn_unknown(d, "pipeline")
        return out


@dataclass
class CheckpointConfig:
    """Mirrors reference ``checkpoint`` block (tag validation, parallel
    write), extended with the fault-tolerance knobs
    (docs/fault_tolerance.md): a save dir the engine auto-saves to and
    rolls back from, auto-resume on startup, keep-last-N garbage
    collection, and manifest checksum verification on load."""

    tag_validation: str = "Warn"  # Ignore | Warn | Fail
    load_universal: bool = False
    use_node_local_storage: bool = False
    parallel_write_pipeline: bool = False
    async_save: bool = False
    save_dir: Optional[str] = None   # enables auto-save / rollback / emergency saves
    auto_resume: bool = False        # initialize() loads the newest valid tag
    save_interval: int = 0           # auto-save every N steps (0 = off)
    keep_last_n: int = 0             # GC old valid tags (0 = keep all)
    verify_checksums: bool = True    # manifest CRC verification on load

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "CheckpointConfig":
        if not d:
            return cls()
        d = dict(d)
        out = cls(
            tag_validation=str(_take(d, "tag_validation", "Warn")).capitalize(),
            load_universal=bool(_take(d, "load_universal", False)),
            use_node_local_storage=bool(_take(d, "use_node_local_storage", False)),
            parallel_write_pipeline=bool(_take(d, "parallel_write", {}).get("pipeline_stage", False))
            if isinstance(d.get("parallel_write"), dict)
            else False,
            async_save=bool(_take(d, "async_save", False)),
            save_dir=_take(d, "save_dir", None),
            auto_resume=bool(_take(d, "auto_resume", False)),
            save_interval=int(_take(d, "save_interval", 0)),
            keep_last_n=int(_take(d, "keep_last_n", 0)),
            verify_checksums=bool(_take(d, "verify_checksums", True)),
        )
        d.pop("parallel_write", None)
        if out.save_interval < 0:
            raise ConfigError(f"checkpoint.save_interval must be >= 0, got {out.save_interval}")
        if out.keep_last_n < 0:
            raise ConfigError(f"checkpoint.keep_last_n must be >= 0, got {out.keep_last_n}")
        _warn_unknown(d, "checkpoint")
        return out


@dataclass
class DivergenceConfig:
    """Divergence guards in the engine step path (resilience/divergence.py).

    ``nan_action``: off | skip | rollback | halt — "skip" compiles the
    non-finite check into the train step (old params kept on-device, zero
    extra host syncs); rollback/halt fetch the loss each step.
    ``spike_action``: off | warn | rollback | halt — loss exceeding
    ``spike_factor`` x the rolling median of the last ``window`` finite
    losses (after ``warmup_steps``).
    """

    nan_action: str = "off"
    spike_action: str = "off"
    spike_factor: float = 10.0
    window: int = 20
    warmup_steps: int = 5
    # rollbacks that fail to progress past the previously-diverging step
    # escalate to halt after this many attempts (a deterministic NaN
    # replays bit-exactly — unbounded rollback would loop forever)
    max_rollbacks: int = 2

    @property
    def wants_host_check(self) -> bool:
        return self.nan_action in ("rollback", "halt") or self.spike_action != "off"

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "DivergenceConfig":
        if not d:
            return cls()
        d = dict(d)
        out = cls(
            nan_action=str(_take(d, "nan_action", "off")).lower(),
            spike_action=str(_take(d, "spike_action", "off")).lower(),
            spike_factor=float(_take(d, "spike_factor", 10.0)),
            window=int(_take(d, "window", 20)),
            warmup_steps=int(_take(d, "warmup_steps", 5)),
            max_rollbacks=int(_take(d, "max_rollbacks", 2)),
        )
        if out.max_rollbacks < 1:
            raise ConfigError(
                f"divergence.max_rollbacks must be >= 1, got {out.max_rollbacks}")
        if out.nan_action not in ("off", "skip", "rollback", "halt"):
            raise ConfigError(f"divergence.nan_action must be off|skip|rollback|halt, got {out.nan_action!r}")
        if out.spike_action not in ("off", "warn", "rollback", "halt"):
            raise ConfigError(f"divergence.spike_action must be off|warn|rollback|halt, got {out.spike_action!r}")
        if out.spike_action != "off" and out.spike_factor <= 1.0:
            raise ConfigError(f"divergence.spike_factor must exceed 1.0, got {out.spike_factor}")
        _warn_unknown(d, "resilience.divergence")
        return out


@dataclass
class ChaosConfig:
    """Seeded fault injection (resilience/chaos.py FaultInjector). All
    ``*_at_save`` are 1-based save counts, ``*_at_step`` match the engine's
    ``global_steps`` at the start of a train_batch; -1 disables."""

    enabled: bool = False
    seed: int = 0
    crash_before_commit_at_save: int = -1
    crash_after_commit_at_save: int = -1
    corrupt_shard_at_save: int = -1
    sigterm_at_step: int = -1
    crash_at_step: int = -1
    exit_process: bool = False  # os._exit instead of raising InjectedFault
    exit_code: int = 113
    collective_fail_op: str = ""
    collective_fail_at_call: int = -1
    collective_delay_s: float = 0.0
    collective_delay_every: int = 0
    serving_tick_fail_at: int = -1
    serving_tick_fail_every: int = 0
    # kill serving replica #replica_die_index once its engine has run
    # replica_die_at_tick ticks (-1 disables; one-shot)
    replica_die_at_tick: int = -1
    replica_die_index: int = 0
    # kill serving cell #cell_die_index (whole failure domain) once any
    # of its replicas has run cell_die_at_tick ticks (-1 disables)
    cell_die_at_tick: int = -1
    cell_die_index: int = 0
    # delay every fleet autoscaler decision by this many (virtual)
    # seconds — models real controller observe/decide/boot lag
    autoscaler_lag_s: float = 0.0
    # rollout-targeted faults (serving/rollout.py): corrupt the next N
    # hot-swap weight loads (the swap must fall back to the old version,
    # the controller must retry/rollback — never strand the replica);
    # kill the replica being flipped on the Nth flip (1-based, one-shot,
    # -1 disables); stall every other engine tick of one model version
    # (the injected canary SLO regression auto-rollback is gated on)
    corrupt_swap_count: int = 0
    die_at_flip: int = -1
    degrade_version: int = -1
    # gray-failure faults (docs/fault_tolerance.md "Gray failures"):
    # every Nth serving KV import raises a recoverable fault (the
    # adoption falls back to a requeue; 0 disables). The per-replica
    # k x-slowdowns and stall bursts are runtime-armed on the injector
    # (degrade_replica / arm_stall_burst), not config keys — they name
    # replicas that only exist once the fleet is up.
    flaky_import_every: int = 0
    # global-KV-tier faults (docs/serving.md "Global KV tier"): every
    # Nth directory publish also injects one bogus residency entry (a
    # directory lie — routing must detect the miss and fall back);
    # every Nth prefix export corrupts the wire payload while keeping
    # the stamped checksum (the importer's verify() must catch it);
    # every Nth cold-tier put is dropped (host memory pressure — the
    # prefix degrades to re-prefill, never double-frees). 0 disables.
    stale_directory_every: int = 0
    corrupt_adopt_every: int = 0
    cold_pressure_every: int = 0

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "ChaosConfig":
        if not d:
            return cls()
        d = dict(d)
        out = cls(
            enabled=bool(_take(d, "enabled", False)),
            seed=int(_take(d, "seed", 0)),
            crash_before_commit_at_save=int(_take(d, "crash_before_commit_at_save", -1)),
            crash_after_commit_at_save=int(_take(d, "crash_after_commit_at_save", -1)),
            corrupt_shard_at_save=int(_take(d, "corrupt_shard_at_save", -1)),
            sigterm_at_step=int(_take(d, "sigterm_at_step", -1)),
            crash_at_step=int(_take(d, "crash_at_step", -1)),
            exit_process=bool(_take(d, "exit_process", False)),
            exit_code=int(_take(d, "exit_code", 113)),
            collective_fail_op=str(_take(d, "collective_fail_op", "")),
            collective_fail_at_call=int(_take(d, "collective_fail_at_call", -1)),
            collective_delay_s=float(_take(d, "collective_delay_s", 0.0)),
            collective_delay_every=int(_take(d, "collective_delay_every", 0)),
            serving_tick_fail_at=int(_take(d, "serving_tick_fail_at", -1)),
            serving_tick_fail_every=int(_take(d, "serving_tick_fail_every", 0)),
            replica_die_at_tick=int(_take(d, "replica_die_at_tick", -1)),
            replica_die_index=int(_take(d, "replica_die_index", 0)),
            cell_die_at_tick=int(_take(d, "cell_die_at_tick", -1)),
            cell_die_index=int(_take(d, "cell_die_index", 0)),
            autoscaler_lag_s=float(_take(d, "autoscaler_lag_s", 0.0)),
            corrupt_swap_count=int(_take(d, "corrupt_swap_count", 0)),
            die_at_flip=int(_take(d, "die_at_flip", -1)),
            degrade_version=int(_take(d, "degrade_version", -1)),
            flaky_import_every=int(_take(d, "flaky_import_every", 0)),
            stale_directory_every=int(_take(d, "stale_directory_every", 0)),
            corrupt_adopt_every=int(_take(d, "corrupt_adopt_every", 0)),
            cold_pressure_every=int(_take(d, "cold_pressure_every", 0)),
        )
        if out.autoscaler_lag_s < 0:
            raise ConfigError(
                f"resilience.chaos.autoscaler_lag_s must be >= 0, got "
                f"{out.autoscaler_lag_s}")
        if out.corrupt_swap_count < 0:
            raise ConfigError(
                f"resilience.chaos.corrupt_swap_count must be >= 0, got "
                f"{out.corrupt_swap_count}")
        if out.flaky_import_every < 0:
            raise ConfigError(
                f"resilience.chaos.flaky_import_every must be >= 0, got "
                f"{out.flaky_import_every}")
        for knob in ("stale_directory_every", "corrupt_adopt_every",
                     "cold_pressure_every"):
            if getattr(out, knob) < 0:
                raise ConfigError(
                    f"resilience.chaos.{knob} must be >= 0, got "
                    f"{getattr(out, knob)}")
        _warn_unknown(d, "resilience.chaos")
        return out


@dataclass
class ResilienceConfig:
    """The ``resilience`` block: divergence guards + chaos injection
    (docs/fault_tolerance.md)."""

    divergence: DivergenceConfig = field(default_factory=DivergenceConfig)
    chaos: ChaosConfig = field(default_factory=ChaosConfig)

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "ResilienceConfig":
        if not d:
            return cls()
        d = dict(d)
        out = cls(
            divergence=DivergenceConfig.from_dict(_take(d, "divergence", None)),
            chaos=ChaosConfig.from_dict(_take(d, "chaos", None)),
        )
        _warn_unknown(d, "resilience")
        return out


@dataclass
class FleetConfig:
    """The ``serving.fleet`` block: multi-replica router front-end
    (docs/serving.md).

    ``router`` picks the routing policy (``"least_loaded"`` or
    ``"prefix_affinity"`` — consistent hashing on the prompt's full-block
    prefix so repeat traffic lands on the replica holding its cached KV).
    ``disaggregated`` splits the fleet into ``prefill_replicas`` replicas
    that only compute prompt KV and hand pages off to the decode
    replicas. ``autoscale`` turns on the telemetry-driven controller; the
    sizing policy itself lives in
    :class:`deepspeed_tpu.elasticity.ServingElasticityConfig` (the
    ``min_replicas``..``sla_low`` knobs here are forwarded to it, so
    training and serving elasticity share one policy surface).
    ``failover`` re-queues a dead replica's in-flight requests onto the
    survivors via the bit-exact resume path; ``respawn`` additionally
    replaces dead replicas while the healthy count sits below
    ``min_replicas``."""

    replicas: int = 1
    router: str = "least_loaded"
    affinity_vnodes: int = 64
    affinity_spill_load: int = 0
    disaggregated: bool = False
    prefill_replicas: int = 1
    health_interval_s: float = 0.05
    failover: bool = True
    respawn: bool = True
    autoscale: bool = False
    autoscale_interval_s: float = 1.0
    min_replicas: int = 1
    max_replicas: int = 8
    scale_up_queue_per_replica: float = 8.0
    scale_down_queue_per_replica: float = 1.0
    kv_high: float = 0.85
    sla_low: float = 0.90
    sla_window: int = 64
    # route-retry discipline (resilience/retry.py RetryBudget): each
    # refused replica pick past the first consumes one unit from a
    # budget shared fleet-wide (and region-wide when the fleet belongs
    # to a ServingCell), with jittered exponential backoff between
    # attempts — a replica/cell that refuses forever is given up on
    # explicitly (REJECTED span) instead of being hammered in a tight
    # loop. 0 budget = first refusal already rejects.
    route_retry_budget: int = 256
    route_backoff_s: float = 0.02
    route_backoff_jitter: float = 0.5
    # gray-failure resilience plane (docs/fault_tolerance.md "Gray
    # failures"; serving/health.py) — all default OFF so the behavioral
    # pins (exact tick-count TTFT gates) are untouched unless opted in.
    # ``quarantine`` drains a replica whose continuous health score
    # breaches ``quarantine_threshold`` for ``quarantine_after``
    # consecutive monitor polls out of the NEW-work routing view (never
    # below ``min_replicas`` — the capacity floor), dwells
    # ``quarantine_dwell_s``, then probes it with live traffic and
    # re-admits after ``quarantine_readmit_polls`` clean polls (a
    # probation breach doubles the dwell — hysteresis against flap).
    # ``breakers`` arms per-replica routing circuit breakers
    # (closed -> open after ``breaker_failures`` consecutive failures,
    # half-open single probe after ``breaker_cooldown_s``).  ``hedge``
    # dispatches a backup leg for an interactive request once
    # ``hedge_ttft_fraction`` of its TTFT deadline has elapsed with no
    # first token — first token wins, the loser is cancelled with its
    # KV discarded, and the SLO ledger judges the request once.
    quarantine: bool = False
    quarantine_threshold: float = 0.5
    quarantine_after: int = 3
    quarantine_dwell_s: float = 8.0
    quarantine_readmit_polls: int = 3
    breakers: bool = False
    breaker_failures: int = 4
    breaker_cooldown_s: float = 5.0
    hedge: bool = False
    hedge_ttft_fraction: float = 0.6

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "FleetConfig":
        if not d:
            return cls()
        d = dict(d)
        out = cls(
            replicas=int(_take(d, "replicas", 1)),
            router=str(_take(d, "router", "least_loaded")),
            affinity_vnodes=int(_take(d, "affinity_vnodes", 64)),
            affinity_spill_load=int(_take(d, "affinity_spill_load", 0)),
            disaggregated=bool(_take(d, "disaggregated", False)),
            prefill_replicas=int(_take(d, "prefill_replicas", 1)),
            health_interval_s=float(_take(d, "health_interval_s", 0.05)),
            failover=bool(_take(d, "failover", True)),
            respawn=bool(_take(d, "respawn", True)),
            autoscale=bool(_take(d, "autoscale", False)),
            autoscale_interval_s=float(_take(d, "autoscale_interval_s", 1.0)),
            min_replicas=int(_take(d, "min_replicas", 1)),
            max_replicas=int(_take(d, "max_replicas", 8)),
            scale_up_queue_per_replica=float(
                _take(d, "scale_up_queue_per_replica", 8.0)),
            scale_down_queue_per_replica=float(
                _take(d, "scale_down_queue_per_replica", 1.0)),
            kv_high=float(_take(d, "kv_high", 0.85)),
            sla_low=float(_take(d, "sla_low", 0.90)),
            sla_window=int(_take(d, "sla_window", 64)),
            route_retry_budget=int(_take(d, "route_retry_budget", 256)),
            route_backoff_s=float(_take(d, "route_backoff_s", 0.02)),
            route_backoff_jitter=float(
                _take(d, "route_backoff_jitter", 0.5)),
            quarantine=bool(_take(d, "quarantine", False)),
            quarantine_threshold=float(
                _take(d, "quarantine_threshold", 0.5)),
            quarantine_after=int(_take(d, "quarantine_after", 3)),
            quarantine_dwell_s=float(
                _take(d, "quarantine_dwell_s", 8.0)),
            quarantine_readmit_polls=int(
                _take(d, "quarantine_readmit_polls", 3)),
            breakers=bool(_take(d, "breakers", False)),
            breaker_failures=int(_take(d, "breaker_failures", 4)),
            breaker_cooldown_s=float(
                _take(d, "breaker_cooldown_s", 5.0)),
            hedge=bool(_take(d, "hedge", False)),
            hedge_ttft_fraction=float(
                _take(d, "hedge_ttft_fraction", 0.6)),
        )
        if out.route_retry_budget < 0:
            raise ConfigError(
                f"serving.fleet.route_retry_budget must be >= 0, got "
                f"{out.route_retry_budget}")
        if out.route_backoff_s < 0 or out.route_backoff_jitter < 0:
            raise ConfigError(
                "serving.fleet route_backoff_s and route_backoff_jitter "
                "must be >= 0")
        if out.router not in ("least_loaded", "prefix_affinity",
                              "residency"):
            raise ConfigError(
                f"serving.fleet.router must be 'least_loaded', "
                f"'prefix_affinity' or 'residency', got '{out.router}'")
        if out.replicas < 1:
            raise ConfigError(
                f"serving.fleet.replicas must be >= 1, got {out.replicas}")
        if out.disaggregated and out.prefill_replicas < 1:
            raise ConfigError(
                f"serving.fleet.prefill_replicas must be >= 1 in "
                f"disaggregated mode, got {out.prefill_replicas}")
        if not 1 <= out.min_replicas <= out.max_replicas:
            raise ConfigError(
                f"serving.fleet needs 1 <= min_replicas <= max_replicas, "
                f"got [{out.min_replicas}, {out.max_replicas}]")
        if out.scale_down_queue_per_replica > out.scale_up_queue_per_replica:
            # fail at parse, not as an ElasticityError inside every
            # monitor poll (the hysteresis band must be non-negative)
            raise ConfigError(
                "serving.fleet.scale_down_queue_per_replica must not "
                "exceed scale_up_queue_per_replica "
                f"({out.scale_down_queue_per_replica} > "
                f"{out.scale_up_queue_per_replica})")
        if out.sla_window < 1:
            raise ConfigError(
                f"serving.fleet.sla_window must be >= 1, got "
                f"{out.sla_window}")
        if not 0.0 < out.quarantine_threshold <= 1.0:
            raise ConfigError(
                f"serving.fleet.quarantine_threshold must be in (0, 1], "
                f"got {out.quarantine_threshold}")
        if out.quarantine_after < 1 or out.quarantine_readmit_polls < 1:
            raise ConfigError(
                "serving.fleet quarantine_after and "
                "quarantine_readmit_polls must be >= 1")
        if out.quarantine_dwell_s <= 0:
            raise ConfigError(
                f"serving.fleet.quarantine_dwell_s must be > 0, got "
                f"{out.quarantine_dwell_s}")
        if out.breaker_failures < 1 or out.breaker_cooldown_s <= 0:
            raise ConfigError(
                "serving.fleet breaker_failures must be >= 1 and "
                "breaker_cooldown_s > 0")
        if not 0.0 < out.hedge_ttft_fraction < 1.0:
            # 0 would hedge EVERY interactive request on submit; 1
            # would hedge only after the deadline is already blown
            raise ConfigError(
                f"serving.fleet.hedge_ttft_fraction must be in (0, 1), "
                f"got {out.hedge_ttft_fraction}")
        _warn_unknown(d, "serving.fleet")
        return out


@dataclass
class RegionConfig:
    """The ``serving.region`` block: the cell-based fleet-of-fleets
    front-end (docs/serving.md "Region & cells").

    ``cells`` fleets (each a :class:`FleetConfig`-shaped failure domain)
    sit behind one :class:`~deepspeed_tpu.serving.Region` that routes by
    a two-tier consistent hash: a ``cell_ring_vnodes``-point cell ring
    picks the failure domain from each cell's PUBLISHED load/health
    digest (queue depth, KV demand, in-SLA window — refreshed on the
    monitor cadence, never scanned per route), then the cell's own
    router picks the replica. ``cell_spill_load`` (0 = off) spills a
    request off an overloaded primary cell to the least-loaded
    reachable one (digest queue depth per healthy replica >= the
    threshold), mirroring the replica ring's spill valve one tier up.

    Brownout: when reachable demand exceeds ``brownout_queue_per_replica``
    queued requests per healthy reachable replica, the region sheds NEW
    work below a priority floor that climbs one tier per additional
    multiple of the threshold (the brownout ladder), always with a
    REJECTED span — explicit degradation, never silent drops.
    ``brownout_exit_ratio`` is the hysteresis: a floor level is left
    only once pressure falls below ``ratio`` x its entry threshold.

    ``rebalance_threshold`` (queued requests per replica above the
    reachable mean, 0 = off) lets a heal re-spread QUEUED work from
    cells that bore the partition onto the rejoined capacity.

    Telemetry plane (docs/observability.md "Region rollups"): every
    ``telemetry_rollup_every``-th digest refresh the region pulls each
    cell's telemetry digest delta (sketch merges + counter deltas +
    SLO verdicts) into its accumulator and SLO tracker. The ``slo_*``
    knobs parameterize the per-tenant SLO objective
    (:class:`~deepspeed_tpu.telemetry.slo.SLOObjective`): target in-SLA
    ratio over ``slo_window_s`` of virtual time, with fast/slow
    burn-rate alert windows and thresholds."""

    cells: int = 2
    cell_ring_vnodes: int = 32
    cell_spill_load: int = 0
    brownout_queue_per_replica: float = 8.0
    brownout_exit_ratio: float = 0.5
    rebalance_threshold: float = 4.0
    health_interval_s: float = 0.05
    telemetry_rollup_every: int = 1
    slo_target: float = 0.95
    slo_window_s: float = 240.0
    slo_fast_window_s: float = 300.0
    slo_slow_window_s: float = 3600.0
    slo_fast_burn: float = 14.4
    slo_slow_burn: float = 6.0
    slo_min_samples: int = 4

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "RegionConfig":
        if not d:
            return cls()
        d = dict(d)
        out = cls(
            cells=int(_take(d, "cells", 2)),
            cell_ring_vnodes=int(_take(d, "cell_ring_vnodes", 32)),
            cell_spill_load=int(_take(d, "cell_spill_load", 0)),
            brownout_queue_per_replica=float(
                _take(d, "brownout_queue_per_replica", 8.0)),
            brownout_exit_ratio=float(
                _take(d, "brownout_exit_ratio", 0.5)),
            rebalance_threshold=float(
                _take(d, "rebalance_threshold", 4.0)),
            health_interval_s=float(_take(d, "health_interval_s", 0.05)),
            telemetry_rollup_every=int(
                _take(d, "telemetry_rollup_every", 1)),
            slo_target=float(_take(d, "slo_target", 0.95)),
            slo_window_s=float(_take(d, "slo_window_s", 240.0)),
            slo_fast_window_s=float(_take(d, "slo_fast_window_s", 300.0)),
            slo_slow_window_s=float(
                _take(d, "slo_slow_window_s", 3600.0)),
            slo_fast_burn=float(_take(d, "slo_fast_burn", 14.4)),
            slo_slow_burn=float(_take(d, "slo_slow_burn", 6.0)),
            slo_min_samples=int(_take(d, "slo_min_samples", 4)),
        )
        if out.cells < 1:
            raise ConfigError(
                f"serving.region.cells must be >= 1, got {out.cells}")
        if out.cell_ring_vnodes < 1:
            raise ConfigError(
                f"serving.region.cell_ring_vnodes must be >= 1, got "
                f"{out.cell_ring_vnodes}")
        if out.brownout_queue_per_replica <= 0:
            raise ConfigError(
                f"serving.region.brownout_queue_per_replica must be > 0, "
                f"got {out.brownout_queue_per_replica}")
        if not 0.0 <= out.brownout_exit_ratio <= 1.0:
            # exit above entry would re-enter the level it just left on
            # the very next poll (oscillation, not hysteresis)
            raise ConfigError(
                f"serving.region.brownout_exit_ratio must be in [0, 1], "
                f"got {out.brownout_exit_ratio}")
        if out.rebalance_threshold < 0:
            raise ConfigError(
                f"serving.region.rebalance_threshold must be >= 0, got "
                f"{out.rebalance_threshold}")
        if out.telemetry_rollup_every < 1:
            # 0 would divide-by-zero the poll's cadence modulo; a named
            # error at parse beats a ZeroDivisionError mid-rollup
            raise ConfigError(
                f"serving.region.telemetry_rollup_every must be >= 1, "
                f"got {out.telemetry_rollup_every}")
        _warn_unknown(d, "serving.region")
        return out


@dataclass
class RolloutConfig:
    """The ``serving.rollout`` block: zero-downtime model rollout
    (docs/serving.md "Rollout, canary, and migration").

    ``canary_fraction`` is the tenant-sticky traffic slice routed to the
    canary version while the controller observes it.  The canary is
    judged after ``canary_observe_ticks`` controller steps: if the
    canary's in-SLA ratio sits more than ``slo_regression_threshold``
    below the stable version's over at least ``min_canary_samples``
    retired requests, the rollout rolls back automatically; otherwise it
    promotes.  ``warmup_ticks`` is the post-swap AOT warmup countdown a
    flipped replica serves through before re-opening admission.
    ``swap_retry_limit`` bounds hot-swap retries per replica (a corrupt
    new-version checkpoint falls back to the old weights each time);
    ``max_flip_attempts`` bounds how often the controller re-targets a
    flip after the victim dies mid-flip — past either bound the rollout
    rolls back instead of wedging."""

    canary_fraction: float = 0.10
    canary_observe_ticks: int = 40
    slo_regression_threshold: float = 0.20
    min_canary_samples: int = 8
    warmup_ticks: int = 2
    swap_retry_limit: int = 2
    max_flip_attempts: int = 4

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "RolloutConfig":
        if not d:
            return cls()
        d = dict(d)
        out = cls(
            canary_fraction=float(_take(d, "canary_fraction", 0.10)),
            canary_observe_ticks=int(_take(d, "canary_observe_ticks", 40)),
            slo_regression_threshold=float(
                _take(d, "slo_regression_threshold", 0.20)),
            min_canary_samples=int(_take(d, "min_canary_samples", 8)),
            warmup_ticks=int(_take(d, "warmup_ticks", 2)),
            swap_retry_limit=int(_take(d, "swap_retry_limit", 2)),
            max_flip_attempts=int(_take(d, "max_flip_attempts", 4)),
        )
        if not 0.0 < out.canary_fraction <= 1.0:
            raise ConfigError(
                f"serving.rollout.canary_fraction must be in (0, 1], got "
                f"{out.canary_fraction}")
        if out.canary_observe_ticks < 1:
            raise ConfigError(
                f"serving.rollout.canary_observe_ticks must be >= 1, got "
                f"{out.canary_observe_ticks}")
        if not 0.0 <= out.slo_regression_threshold <= 1.0:
            raise ConfigError(
                f"serving.rollout.slo_regression_threshold must be in "
                f"[0, 1], got {out.slo_regression_threshold}")
        if out.min_canary_samples < 1:
            raise ConfigError(
                f"serving.rollout.min_canary_samples must be >= 1, got "
                f"{out.min_canary_samples}")
        if out.warmup_ticks < 0:
            raise ConfigError(
                f"serving.rollout.warmup_ticks must be >= 0, got "
                f"{out.warmup_ticks}")
        if out.swap_retry_limit < 0:
            raise ConfigError(
                f"serving.rollout.swap_retry_limit must be >= 0, got "
                f"{out.swap_retry_limit}")
        if out.max_flip_attempts < 1:
            raise ConfigError(
                f"serving.rollout.max_flip_attempts must be >= 1, got "
                f"{out.max_flip_attempts}")
        _warn_unknown(d, "serving.rollout")
        return out


@dataclass
class KVTierConfig:
    """The ``serving.kv_tier`` block: the global KV tier
    (docs/serving.md "Global KV tier"). Default OFF — with
    ``enabled=False`` no directory, adoption pen, or cold tier is
    constructed and old traces/seeds replay bit-identically.

    ``publish_interval_s`` is the residency-publication cadence (each
    replica's driver snapshots its prefix-cache keys at most this often,
    piggybacked on the fleet's poll); ``directory_staleness_s`` bounds
    how old a directory entry may be before routing stops trusting it —
    it must be at least the publish interval, or every entry would
    expire before its holder could refresh it. ``adoption`` gates
    cross-replica prefix adoption (directory hit on another replica ->
    quantized pages on the wire); ``cold_tier``/``cold_capacity_pages``
    gate the host-memory spill store for evicted prefixes."""

    enabled: bool = False
    publish_interval_s: float = 1.0
    directory_staleness_s: float = 5.0
    adoption: bool = True
    cold_tier: bool = True
    cold_capacity_pages: int = 256

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "KVTierConfig":
        if not d:
            return cls()
        d = dict(d)
        out = cls(
            enabled=bool(_take(d, "enabled", False)),
            publish_interval_s=float(_take(d, "publish_interval_s", 1.0)),
            directory_staleness_s=float(
                _take(d, "directory_staleness_s", 5.0)),
            adoption=bool(_take(d, "adoption", True)),
            cold_tier=bool(_take(d, "cold_tier", True)),
            cold_capacity_pages=int(_take(d, "cold_capacity_pages", 256)),
        )
        if out.publish_interval_s <= 0:
            raise ConfigError(
                f"serving.kv_tier.publish_interval_s must be > 0, got "
                f"{out.publish_interval_s}")
        if out.directory_staleness_s < out.publish_interval_s:
            raise ConfigError(
                f"serving.kv_tier.directory_staleness_s must be >= "
                f"publish_interval_s ({out.publish_interval_s}), got "
                f"{out.directory_staleness_s}")
        if out.cold_tier and out.cold_capacity_pages < 1:
            raise ConfigError(
                f"serving.kv_tier.cold_capacity_pages must be >= 1 when "
                f"the cold tier is enabled, got {out.cold_capacity_pages}")
        _warn_unknown(d, "serving.kv_tier")
        return out


@dataclass
class ServingConfig:
    """The ``serving`` block: knobs for the request front-end over the
    ragged engine (docs/serving.md).

    ``policy`` selects the admission/preemption policy (``"slo"`` —
    priority tiers + earliest-deadline-first + KV-pressure preemption —
    or ``"fcfs"``, the strict-arrival-order baseline).  ``max_queue``
    bounds the admission queue: submissions beyond it are REJECTED
    immediately (explicit backpressure).  ``reserve_output_blocks``
    charges admission for the whole remaining output, so an admitted
    request cannot exhaust the KV pool mid-decode; turning it off admits
    more aggressively and relies on mid-tick preemption to recover.
    ``tick_retry_limit`` is the per-request budget for re-queue-on-tick-
    fault before the request is failed.  ``stuck_tick_timeout_s`` arms
    the watchdog (0 disables it).

    ``speculative`` turns on prompt-lookup speculative decoding inside
    the serving tick (docs/serving.md "Speculative scheduling"): draft
    chains verify in the one static SplitFuse shape, greedy output stays
    token-identical, and drafting consumes only token-budget SLACK (an
    acceptance-rate-aware credit per priority class — EMA smoothing
    ``spec_ema`` — sizes chains, so drafting never starves prefill).
    Per request, drafting falls back to plain decode when its rolling
    acceptance EMA drops below ``spec_accept_floor`` after at least
    ``spec_floor_min_proposed`` proposed tokens. ``kv_quant`` declares
    the engines' KV-cache quantization mode; the serving layer validates
    it against each engine's own config (one knob, fleet-wide)."""

    max_queue: int = 256
    policy: str = "slo"
    kv_pressure: float = 0.90
    reject_expired: bool = True
    preemption: bool = True
    reserve_output_blocks: bool = True
    default_max_new_tokens: int = 128
    poll_interval_s: float = 0.002
    drain_timeout_s: float = 120.0
    stuck_tick_timeout_s: float = 30.0
    # after this many CONSECUTIVE stuck watchdog polls the engine marks
    # itself watchdog-unhealthy so the fleet monitor evacuates the
    # replica (0 = log-only, the pre-escalation behavior)
    stuck_tick_escalate_polls: int = 3
    tick_retry_limit: int = 1
    speculative: bool = False
    spec_lookahead: int = 4
    spec_ngram: int = 3
    spec_accept_floor: float = 0.25
    spec_floor_min_proposed: int = 16
    spec_ema: float = 0.25
    kv_quant: str = "none"
    # the model version the fleet starts serving (serving/rollout.py);
    # monotonically bumped by rollouts, never by config reload
    model_version: int = 0
    fleet: FleetConfig = field(default_factory=FleetConfig)
    region: RegionConfig = field(default_factory=RegionConfig)
    rollout: RolloutConfig = field(default_factory=RolloutConfig)
    kv_tier: KVTierConfig = field(default_factory=KVTierConfig)

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "ServingConfig":
        if not d:
            return cls()
        d = dict(d)
        out = cls(
            fleet=FleetConfig.from_dict(_take(d, "fleet", None)),
            region=RegionConfig.from_dict(_take(d, "region", None)),
            rollout=RolloutConfig.from_dict(_take(d, "rollout", None)),
            kv_tier=KVTierConfig.from_dict(_take(d, "kv_tier", None)),
            max_queue=int(_take(d, "max_queue", 256)),
            policy=str(_take(d, "policy", "slo")),
            kv_pressure=float(_take(d, "kv_pressure", 0.90)),
            reject_expired=bool(_take(d, "reject_expired", True)),
            preemption=bool(_take(d, "preemption", True)),
            reserve_output_blocks=bool(_take(d, "reserve_output_blocks", True)),
            default_max_new_tokens=int(_take(d, "default_max_new_tokens", 128)),
            poll_interval_s=float(_take(d, "poll_interval_s", 0.002)),
            drain_timeout_s=float(_take(d, "drain_timeout_s", 120.0)),
            stuck_tick_timeout_s=float(_take(d, "stuck_tick_timeout_s", 30.0)),
            stuck_tick_escalate_polls=int(
                _take(d, "stuck_tick_escalate_polls", 3)),
            tick_retry_limit=int(_take(d, "tick_retry_limit", 1)),
            speculative=bool(_take(d, "speculative", False)),
            spec_lookahead=int(_take(d, "spec_lookahead", 4)),
            spec_ngram=int(_take(d, "spec_ngram", 3)),
            spec_accept_floor=float(_take(d, "spec_accept_floor", 0.25)),
            spec_floor_min_proposed=int(
                _take(d, "spec_floor_min_proposed", 16)),
            spec_ema=float(_take(d, "spec_ema", 0.25)),
            kv_quant=str(_take(d, "kv_quant", "none")),
            model_version=int(_take(d, "model_version", 0)),
        )
        if out.model_version < 0:
            raise ConfigError(
                f"serving.model_version must be >= 0, got "
                f"{out.model_version}")
        if out.policy not in ("slo", "fcfs"):
            raise ConfigError(
                f"serving.policy must be 'slo' or 'fcfs', got '{out.policy}'")
        if out.max_queue < 1:
            raise ConfigError(
                f"serving.max_queue must be >= 1, got {out.max_queue}")
        if not 0.0 <= out.kv_pressure <= 1.0:
            raise ConfigError(
                f"serving.kv_pressure must be in [0, 1], got {out.kv_pressure}")
        if out.tick_retry_limit < 0:
            raise ConfigError(
                f"serving.tick_retry_limit must be >= 0, got "
                f"{out.tick_retry_limit}")
        if out.stuck_tick_escalate_polls < 0:
            raise ConfigError(
                f"serving.stuck_tick_escalate_polls must be >= 0, got "
                f"{out.stuck_tick_escalate_polls}")
        if out.default_max_new_tokens < 1:
            raise ConfigError(
                f"serving.default_max_new_tokens must be >= 1, got "
                f"{out.default_max_new_tokens}")
        if out.spec_lookahead < 1 or out.spec_ngram < 1:
            raise ConfigError(
                f"serving.spec_lookahead/spec_ngram must be >= 1, got "
                f"{out.spec_lookahead}/{out.spec_ngram}")
        if not 0.0 <= out.spec_accept_floor <= 1.0:
            raise ConfigError(
                f"serving.spec_accept_floor must be in [0, 1], got "
                f"{out.spec_accept_floor}")
        if not 0.0 < out.spec_ema <= 1.0:
            raise ConfigError(
                f"serving.spec_ema must be in (0, 1], got {out.spec_ema}")
        if out.kv_quant not in ("none", "int8", "int4"):
            raise ConfigError(
                f"serving.kv_quant must be 'none', 'int8' or 'int4', got "
                f"'{out.kv_quant}'")
        _warn_unknown(d, "serving")
        return out


@dataclass
class DataEfficiencyConfig:
    """Curriculum learning / data sampling (reference runtime/data_pipeline)."""

    enabled: bool = False
    seed: int = 1234
    curriculum_enabled: bool = False
    curriculum_metrics: Dict[str, Any] = field(default_factory=dict)

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "DataEfficiencyConfig":
        if not d:
            return cls()
        d = dict(d)
        cl = d.pop("data_sampling", {}) or {}
        cur = (cl.get("curriculum_learning") or {}) if isinstance(cl, dict) else {}
        out = cls(
            enabled=bool(_take(d, "enabled", False)),
            seed=int(_take(d, "seed", 1234)),
            curriculum_enabled=bool(cur.get("enabled", False)),
            curriculum_metrics=dict(cur.get("curriculum_metrics", {})),
        )
        d.pop("data_routing", None)
        _warn_unknown(d, "data_efficiency")
        return out


@dataclass
class Config:
    """Top-level typed config. Parity with reference ``DeepSpeedConfig``."""

    # batch arithmetic
    train_batch_size: Optional[int] = None
    train_micro_batch_size_per_gpu: Optional[int] = None
    gradient_accumulation_steps: Optional[int] = None

    steps_per_print: int = 10
    gradient_clipping: float = 0.0
    prescale_gradients: bool = False
    gradient_predivide_factor: float = 1.0
    communication_data_type: Optional[str] = None
    seq_parallel_communication_data_type: Optional[str] = None
    disable_allgather: bool = False
    dump_state: bool = False
    wall_clock_breakdown: bool = False
    memory_breakdown: bool = False
    sparse_gradients: bool = False
    train_seed: int = 42

    optimizer: OptimizerConfig = field(default_factory=OptimizerConfig)
    scheduler: SchedulerConfig = field(default_factory=SchedulerConfig)
    fp16: FP16Config = field(default_factory=FP16Config)
    bf16: BF16Config = field(default_factory=BF16Config)
    zero: ZeroConfig = field(default_factory=ZeroConfig)
    mesh: MeshConfig = field(default_factory=MeshConfig)
    activation_checkpointing: ActivationCheckpointingConfig = field(default_factory=ActivationCheckpointingConfig)
    monitor: MonitorConfig = field(default_factory=MonitorConfig)
    telemetry: TelemetryConfig = field(default_factory=TelemetryConfig)
    dataloader: DataLoaderConfig = field(default_factory=DataLoaderConfig)
    compile: CompileConfig = field(default_factory=CompileConfig)
    flops_profiler: FlopsProfilerConfig = field(default_factory=FlopsProfilerConfig)
    comms_logger: CommsLoggerConfig = field(default_factory=CommsLoggerConfig)
    comm_compression: CommCompressionConfig = field(default_factory=CommCompressionConfig)
    pipeline: PipelineConfig = field(default_factory=PipelineConfig)
    checkpoint: CheckpointConfig = field(default_factory=CheckpointConfig)
    resilience: ResilienceConfig = field(default_factory=ResilienceConfig)
    serving: ServingConfig = field(default_factory=ServingConfig)
    data_efficiency: DataEfficiencyConfig = field(default_factory=DataEfficiencyConfig)

    raw: Dict[str, Any] = field(default_factory=dict)

    # ------------------------------------------------------------------
    @classmethod
    def from_any(cls, config: Union[str, Dict[str, Any], "Config", None]) -> "Config":
        if config is None:
            return cls()
        if isinstance(config, Config):
            return config
        if isinstance(config, str):
            if not os.path.isfile(config):
                raise ConfigError(f"config file not found: {config}")
            with open(config) as f:
                config = json.load(f)
        if not isinstance(config, dict):
            raise ConfigError(f"config must be a dict or path, got {type(config)}")
        return cls.from_dict(config)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Config":
        raw = copy.deepcopy(d)
        d = copy.deepcopy(d)

        def intval(key, default=None):
            v = _take(d, key, default)
            if v is None or _is_auto(v):
                return None
            return int(v)

        cfg = cls(
            train_batch_size=intval("train_batch_size"),
            train_micro_batch_size_per_gpu=intval("train_micro_batch_size_per_gpu"),
            gradient_accumulation_steps=intval("gradient_accumulation_steps"),
            steps_per_print=int(_take(d, "steps_per_print", 10)),
            gradient_clipping=float(_take(d, "gradient_clipping", 0.0)),
            prescale_gradients=bool(_take(d, "prescale_gradients", False)),
            gradient_predivide_factor=float(_take(d, "gradient_predivide_factor", 1.0)),
            communication_data_type=_take(d, "communication_data_type", None),
            seq_parallel_communication_data_type=_take(d, "seq_parallel_communication_data_type", None),
            disable_allgather=bool(_take(d, "disable_allgather", False)),
            dump_state=bool(_take(d, "dump_state", False)),
            wall_clock_breakdown=bool(_take(d, "wall_clock_breakdown", False)),
            memory_breakdown=bool(_take(d, "memory_breakdown", False)),
            sparse_gradients=bool(_take(d, "sparse_gradients", False)),
            train_seed=int(_take(d, "seed", 42)),
            optimizer=OptimizerConfig.from_dict(_take(d, "optimizer", None)),
            scheduler=SchedulerConfig.from_dict(_take(d, "scheduler", None)),
            fp16=FP16Config.from_dict(_take(d, "fp16", None)),
            bf16=BF16Config.from_dict(_take(d, "bf16", None)),
            zero=ZeroConfig.from_dict(_take(d, "zero_optimization", None)),
            mesh=MeshConfig.from_dict(_take(d, "mesh", None)),
            activation_checkpointing=ActivationCheckpointingConfig.from_dict(_take(d, "activation_checkpointing", None)),
            monitor=MonitorConfig.from_dict(
                _take(d, "tensorboard", None), _take(d, "csv_monitor", None), _take(d, "wandb", None)
            ),
            telemetry=TelemetryConfig.from_dict(_take(d, "telemetry", None)),
            dataloader=DataLoaderConfig.from_dict(_take(d, "dataloader", None)),
            compile=CompileConfig.from_dict(_take(d, "compile", None)),
            flops_profiler=FlopsProfilerConfig.from_dict(_take(d, "flops_profiler", None)),
            comms_logger=CommsLoggerConfig.from_dict(_take(d, "comms_logger", None)),
            comm_compression=CommCompressionConfig.from_dict(_take(d, "comm_compression", None)),
            pipeline=PipelineConfig.from_dict(_take(d, "pipeline", None)),
            checkpoint=CheckpointConfig.from_dict(_take(d, "checkpoint", None)),
            resilience=ResilienceConfig.from_dict(_take(d, "resilience", None)),
            serving=ServingConfig.from_dict(_take(d, "serving", None)),
            data_efficiency=DataEfficiencyConfig.from_dict(_take(d, "data_efficiency", None)),
            raw=raw,
        )
        if cfg.fp16.enabled and cfg.bf16.enabled:
            raise ConfigError("fp16 and bf16 cannot both be enabled")
        # Accepted-but-unused reference top-level keys (features configured
        # elsewhere in this framework or CUDA-specific).
        for k in ("amp", "zero_allow_untested_optimizer", "zero_force_ds_cpu_optimizer",
                  "gradient_accumulation_dtype", "dataloader_drop_last", "data_types",
                  "compression_training", "autotuning", "elasticity", "nebula",
                  "curriculum_learning", "sparse_attention", "hybrid_engine"):
            d.pop(k, None)
        _warn_unknown(d, "<top-level>")
        return cfg

    # ------------------------------------------------------------------
    def resolve_batch_config(self, dp_world_size: int) -> None:
        """Resolve the train_batch = micro_batch * GAS * dp_world invariant.

        Mirrors reference ``runtime/config.py`` ``_configure_train_batch_size``:
        any two of the three determine the third; a single given value is
        completed with defaults; all three given are validated.
        """
        tb, mb, gas = self.train_batch_size, self.train_micro_batch_size_per_gpu, self.gradient_accumulation_steps
        if tb is not None and mb is not None and gas is not None:
            if tb != mb * gas * dp_world_size:
                raise ConfigError(
                    f"train_batch_size ({tb}) != micro_batch ({mb}) * gas ({gas}) * dp_world ({dp_world_size})"
                )
        elif tb is not None and mb is not None:
            if tb % (mb * dp_world_size) != 0:
                raise ConfigError(
                    f"train_batch_size ({tb}) not divisible by micro_batch ({mb}) * dp_world ({dp_world_size})"
                )
            gas = tb // (mb * dp_world_size)
        elif tb is not None and gas is not None:
            if tb % (gas * dp_world_size) != 0:
                raise ConfigError(
                    f"train_batch_size ({tb}) not divisible by gas ({gas}) * dp_world ({dp_world_size})"
                )
            mb = tb // (gas * dp_world_size)
        elif mb is not None and gas is not None:
            tb = mb * gas * dp_world_size
        elif tb is not None:
            gas = 1
            if tb % dp_world_size != 0:
                raise ConfigError(f"train_batch_size ({tb}) not divisible by dp world size ({dp_world_size})")
            mb = tb // dp_world_size
        elif mb is not None:
            gas = 1
            tb = mb * dp_world_size
        else:
            raise ConfigError(
                "At least one of train_batch_size / train_micro_batch_size_per_gpu /"
                " gradient_accumulation_steps must be set"
            )
        self.train_batch_size, self.train_micro_batch_size_per_gpu, self.gradient_accumulation_steps = tb, mb, gas

    # ------------------------------------------------------------------
    @property
    def compute_dtype(self):
        import jax.numpy as jnp

        if self.bf16.enabled:
            return jnp.bfloat16
        if self.fp16.enabled:
            return jnp.float16
        return jnp.float32

    def to_dict(self) -> Dict[str, Any]:
        def conv(obj):
            if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
                return {f.name: conv(getattr(obj, f.name)) for f in dataclasses.fields(obj) if f.name != "raw"}
            return obj

        return conv(self)


def add_config_arguments(parser):
    """Parity with reference ``deepspeed.add_config_arguments`` (__init__.py:246)."""
    group = parser.add_argument_group("DeepSpeed-TPU", "DeepSpeed-TPU configurations")
    group.add_argument("--deepspeed", default=False, action="store_true",
                       help="Enable DeepSpeed-TPU (helper flag for compat)")
    group.add_argument("--deepspeed_config", default=None, type=str,
                       help="Path to the JSON config file")
    group.add_argument("--deepscale", default=False, action="store_true", help=argparse_suppress())
    group.add_argument("--local_rank", default=-1, type=int,
                       help="Local process rank (compat; unused on TPU)")
    return parser


def argparse_suppress():
    import argparse

    return argparse.SUPPRESS
