"""Hybrid engine: one model flipping between training and generation
(RLHF inner loop).

Parity with reference ``runtime/hybrid_engine.py:32``
(DeepSpeedHybridEngine — ``generate`` :174 runs inference with injected
kernels on the SAME weights ZeRO-3 trains, ``_zero3_forward`` :363 gathers
partitions for generation, LoRA fuse/unfuse :138-:152). The reference's
hard part — unpartitioning ZeRO-3 weights into inference containers and
back — is free in JAX: the training params ARE the inference params (same
arrays, different jitted programs); GSPMD re-lays them out per program.
So the hybrid engine is composition:

* ``train_batch`` / ``backward`` / ``step`` delegate to the TrainEngine;
* ``generate`` runs the decode program against the CURRENT fp32 master
  params cast to the inference dtype — no copy, no gather choreography,
  no separate weight store;
* the per-call cast is the only overhead (the analog of the reference's
  fuse/unfuse), and XLA dedupes it across decode steps within a call.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..inference.engine import InferenceConfig, InferenceEngine, _sample
from ..utils.logging import log_dist
from .engine import TrainEngine


class HybridEngine:
    """Wraps a TrainEngine; adds generate() on live training weights."""

    def __init__(self, train_engine: TrainEngine,
                 inference_config: Optional[InferenceConfig] = None):
        if train_engine.model is None:
            raise ValueError("HybridEngine needs a model-backed TrainEngine")
        self.engine = train_engine
        self.icfg = inference_config or InferenceConfig(
            dtype="bfloat16" if train_engine.config.bf16.enabled else "float32")
        self._prefill_fn = None
        self._decode_fn = None
        log_dist("HybridEngine: generation shares live training parameters")

    # -- training surface (delegation) ----------------------------------
    def train_batch(self, batch):
        return self.engine.train_batch(batch)

    def backward(self, batch):
        return self.engine.backward(batch)

    def step(self):
        return self.engine.step()

    @property
    def params(self):
        return self.engine.params

    # -- generation surface ---------------------------------------------
    def _infer_params(self):
        dtype = self.icfg.jnp_dtype
        return jax.tree_util.tree_map(
            lambda x: x.astype(dtype)
            if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating) else x,
            self.engine.params)

    def generate(self, input_ids, max_new_tokens: int = 64,
                 eos_token_id: Optional[int] = None) -> np.ndarray:
        """Decode with the current training weights (reference generate
        :174 — eval-mode forward through the injected containers)."""
        model = self.engine.model
        input_ids = jnp.asarray(input_ids, jnp.int32)
        b, s = input_ids.shape
        max_len = s + max_new_tokens
        assert max_len <= model.config.max_seq_len

        if self._prefill_fn is None:
            def prefill(params, tokens, caches):
                logits, caches = model.apply(params, tokens, kv_caches=caches,
                                             cache_pos=0)
                return logits[:, -1, :], caches

            def decode(params, caches, last_tokens, cache_pos, rng):
                logits, caches = model.apply(
                    params, last_tokens[:, None],
                    positions=cache_pos[None, None],
                    kv_caches=caches, cache_pos=cache_pos)
                nxt = _sample(logits[:, 0, :], rng, self.icfg.temperature,
                              self.icfg.top_k, self.icfg.top_p)
                return caches, nxt

            self._prefill_fn = jax.jit(prefill, donate_argnums=(2,))
            self._decode_fn = jax.jit(decode, donate_argnums=(1,))

        c = model.config
        params = self._infer_params()
        shape = (c.n_layers, b, max_len, c.n_kv_heads, c.head_dim)
        caches = (jnp.zeros(shape, self.icfg.jnp_dtype),
                  jnp.zeros(shape, self.icfg.jnp_dtype))
        rng = jax.random.PRNGKey(self.icfg.seed + self.engine.global_steps)
        logits, caches = self._prefill_fn(params, input_ids, caches)
        next_tok = _sample(logits, rng, self.icfg.temperature,
                           self.icfg.top_k, self.icfg.top_p)
        out = [np.asarray(next_tok)]
        finished = np.zeros((b,), bool)
        if eos_token_id is not None:
            finished |= out[0] == eos_token_id
        for i in range(max_new_tokens - 1):
            if finished.all():
                break
            rng, sub = jax.random.split(rng)
            caches, next_tok = self._decode_fn(
                params, caches, next_tok, jnp.asarray(s + i, jnp.int32), sub)
            step_toks = np.asarray(next_tok)
            if eos_token_id is not None:
                step_toks = np.where(finished, eos_token_id, step_toks)
                finished |= step_toks == eos_token_id
                next_tok = jnp.asarray(step_toks)
            out.append(step_toks)
        return np.concatenate([np.asarray(input_ids), np.stack(out, 1)], axis=1)

    # reference API stubs kept for parity
    def fuse_lora_weight(self):
        log_dist("fuse_lora_weight: no-op (no separate inference weight store)")

    def unfuse_lora_weight(self):
        log_dist("unfuse_lora_weight: no-op")
