"""Top-Hessian-eigenvalue estimation by power iteration.

Parity with the reference's ``runtime/eigenvalue.py:12`` (Eigenvalue — power
iteration on the loss curvature, used to schedule quantization aggressiveness
in the compression stack). The reference builds Hessian-vector products from
``torch.autograd.grad(grad, v)``; here HVPs are one line of composed
transforms (``jax.jvp`` of ``jax.grad``) and the whole iteration jits.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp


def _tree_dot(a, b):
    return sum(jnp.vdot(x, y) for x, y in zip(jax.tree_util.tree_leaves(a),
                                              jax.tree_util.tree_leaves(b)))


def _tree_norm(a):
    return jnp.sqrt(_tree_dot(a, a).real)


def _normalize(tree):
    n = _tree_norm(tree) + 1e-12
    return jax.tree_util.tree_map(lambda x: x / n, tree)


class Eigenvalue:
    """Power iteration for the dominant eigenvalue of the loss Hessian.

    Reference knobs (runtime/eigenvalue.py): max_iter, tol, stability,
    gas_boundary_resolution, layer filtering (the reference computes per-
    block values; pass a sub-pytree of params for the same effect).
    """

    def __init__(self, verbose: bool = False, max_iter: int = 100,
                 tol: float = 1e-2, stability: float = 1e-6,
                 gas_boundary_resolution: int = 1):
        self.max_iter = max_iter
        self.tol = tol
        self.stability = stability
        self.gas_boundary_resolution = gas_boundary_resolution
        self.verbose = verbose

    def compute_eigenvalue(self, loss_fn: Callable[[Any], jnp.ndarray],
                           params: Any, rng=None) -> float:
        """Dominant |eigenvalue| of H = d2 loss / d params2 at ``params``."""
        rng = rng if rng is not None else jax.random.PRNGKey(0)

        def hvp(v):
            return jax.jvp(jax.grad(loss_fn), (params,), (v,))[1]

        leaves, treedef = jax.tree_util.tree_flatten(params)
        keys = jax.random.split(rng, len(leaves))
        v = jax.tree_util.tree_unflatten(
            treedef, [jax.random.normal(k, l.shape, jnp.float32)
                      for k, l in zip(keys, leaves)])
        v = _normalize(v)

        @jax.jit
        def step(v):
            hv = hvp(v)
            ev = _tree_dot(v, hv).real
            return _normalize(hv), ev

        ev_prev = jnp.inf
        ev = jnp.zeros([])
        for i in range(self.max_iter):
            v, ev = step(v)
            if abs(float(ev) - float(ev_prev)) < self.tol * max(abs(float(ev)), self.stability):
                break
            ev_prev = ev
        return float(ev)
