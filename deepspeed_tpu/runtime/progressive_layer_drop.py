"""Progressive Layer Drop (reference runtime/progressive_layer_drop.py).

Same theta schedule: keep probability theta(t) = (1 - gamma)*exp(-gamma*t)
... the reference uses theta(t) ramping from 0.5 to theta_bar with
exponential decay constant gamma: theta(t) = (1 - theta_bar) * exp(-gamma*t)
+ theta_bar. Each transformer block i gets keep probability
p_i = 1 - (i / L) * (1 - theta) (deeper layers dropped more).

TPU integration: the per-layer keep decisions are a [n_layers] bernoulli
mask folded into the layer scan — residual branches are scaled by
mask / p (inverted-dropout style) so expectation is preserved and shapes
stay static under jit.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


class ProgressiveLayerDrop:
    """theta schedule + state (reference class: update_state(global_step),
    get_state/get_theta)."""

    def __init__(self, theta: float = 0.5, gamma: float = 0.001):
        self.theta_bar = theta
        self.gamma = gamma
        self.current_theta = 1.0

    def get_state(self):
        return {"progressive_layer_drop": True, "pld_theta": self.get_theta()}

    def get_theta(self) -> float:
        return self.current_theta

    def update_state(self, global_step: int) -> float:
        def _prob(x, gamma, p):
            return (1.0 - p) * np.exp(-gamma * x) + p

        self.current_theta = float(_prob(global_step, self.gamma, self.theta_bar))
        return self.current_theta


def layer_keep_probs(theta: float, n_layers: int) -> jnp.ndarray:
    """Per-layer keep probability: deeper layers dropped more aggressively
    (reference PLD paper schedule: p_i = 1 - i/L * (1 - theta))."""
    i = jnp.arange(n_layers, dtype=jnp.float32)
    return 1.0 - (i / max(n_layers, 1)) * (1.0 - theta)


def sample_layer_mask(rng, theta: float, n_layers: int) -> jnp.ndarray:
    """[n_layers] float mask, each entry mask_i/p_i or 0 (inverted dropout
    over whole layers — multiply each block's residual branch by it)."""
    p = layer_keep_probs(theta, n_layers)
    keep = jax.random.bernoulli(rng, p)
    return jnp.where(keep, 1.0 / p, 0.0)
