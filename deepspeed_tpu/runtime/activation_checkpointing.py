"""Activation checkpointing (rematerialization).

Parity with the reference's Megatron-compatible
``runtime/activation_checkpointing/checkpointing.py`` (``checkpoint()``
:485, ``configure()`` :1065, partitioned/CPU-checkpointed activations,
``CudaRNGStatesTracker`` :122). On TPU the whole subsystem maps onto
``jax.checkpoint`` policies:

* ``checkpoint(fn)``                    -> ``jax.checkpoint`` (recompute in bwd)
* partition_activations across MP ranks -> a sharding constraint on the
  saved residuals (GSPMD shards what IS saved; nothing to partition by hand)
* cpu_checkpointing                     -> ``offload_checkpoint`` policy
  (saved residuals parked in host memory)
* contiguous_memory_optimization       -> n/a (XLA's allocator)
* RNG-state tracking                   -> n/a (functional PRNG keys thread
  through ``fn`` explicitly; replaying is deterministic by construction)

``selective`` policy implements "checkpoint everything except matmul
outputs" (jax's ``checkpoint_dots``) — the sweet spot on TPU where
recomputing elementwise ops is free but recomputing MXU work is not.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax

from ..config import ActivationCheckpointingConfig
from ..utils.logging import log_dist

_POLICIES = {
    "full": None,  # save nothing, recompute all
    "selective": jax.checkpoint_policies.checkpoint_dots,
    # selective + the flash kernel's named residuals (out, lse): without
    # this, checkpoint_dots can't see inside the opaque pallas_call and
    # the backward replays the whole flash forward per layer (one extra
    # fwd-attention pass per layer per step) just to rebuild them
    "selective_flash": jax.checkpoint_policies.save_from_both_policies(
        jax.checkpoint_policies.checkpoint_dots,
        jax.checkpoint_policies.save_only_these_names(
            "flash_out", "flash_lse")),
    "dots_with_no_batch_dims": jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims,
    "nothing": jax.checkpoint_policies.everything_saveable,
}

_config = ActivationCheckpointingConfig()
_configured = False


def configure(config: Optional[ActivationCheckpointingConfig] = None, **kwargs) -> None:
    """Reference configure() parity: set the process-wide default policy."""
    global _config, _configured
    if config is not None:
        _config = config
    for k, v in kwargs.items():
        if hasattr(_config, k):
            setattr(_config, k, v)
    _configured = True
    log_dist(f"activation checkpointing configured: {_config}")


def is_configured() -> bool:
    return _configured


def checkpoint(fn: Callable, *args, policy: Optional[str] = None,
               offload: Optional[bool] = None) -> Any:
    """Reference ``checkpoint(function, *args)`` parity: run ``fn`` under
    remat. When called with args, applies immediately (Megatron style);
    with no args, returns the wrapped function."""
    wrapped = checkpoint_wrapper(fn, policy=policy, offload=offload)
    if args:
        return wrapped(*args)
    return wrapped


def checkpoint_wrapper(fn: Callable, policy: Optional[str] = None,
                       offload: Optional[bool] = None) -> Callable:
    policy = policy if policy is not None else _config.policy
    if policy in (None, "none"):
        return fn
    offload = offload if offload is not None else _config.cpu_checkpointing
    if offload:
        from .engine import host_memory_kind

        pol = jax.checkpoint_policies.offload_dot_products(
            "device", host_memory_kind()) \
            if hasattr(jax.checkpoint_policies, "offload_dot_products") else None
        return jax.checkpoint(fn, policy=pol)
    if policy not in _POLICIES:
        raise ValueError(f"unknown remat policy {policy!r}; have {sorted(_POLICIES)}")
    pol = _POLICIES[policy]
    return jax.checkpoint(fn, policy=pol) if pol is not None else jax.checkpoint(fn)


# Megatron-parity aliases (reference exposes these module-level)
def model_parallel_cuda_manual_seed(seed: int) -> None:
    """No-op shim: JAX PRNG keys are explicit; kept for API parity with
    megatron-style callers (reference checkpointing.py RNG tracker)."""
    log_dist(f"model_parallel_cuda_manual_seed({seed}): functional PRNG — no-op")


def get_rng_state_tracker():
    return None
