"""Optimizer library.

Capability parity with the reference's optimizer zoo — FusedAdam
(``csrc/adam/multi_tensor_adam.cu`` + ``ops/adam/fused_adam.py:18``),
DeepSpeedCPUAdam (``csrc/adam/cpu_adam_impl.cpp``), FusedLamb
(``csrc/lamb/fused_lamb_cuda.cu``), Lion (``csrc/lion/``), CPUAdagrad
(``csrc/adagrad/cpu_adagrad.cpp``) — rebuilt as pure-JAX update rules. Under
``jit`` every update fuses into a handful of elementwise XLA kernels per
weight shard, which *is* the multi-tensor-apply optimization the reference
implements by hand in CUDA: no Python-per-tensor loop survives compilation,
and with ZeRO sharding each device only touches its shard.

The API is optax-compatible (``init(params) -> state``,
``update(grads, state, params) -> (updates, state)``) so user-supplied optax
transforms drop in, matching how the reference accepts client torch
optimizers (engine.py:1197 _configure_optimizer).
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

ScalarOrSchedule = Any  # float or callable(step)->float


class Transform(NamedTuple):
    """Minimal optax-style gradient transformation."""

    init: Callable[[Any], Any]
    update: Callable[..., Tuple[Any, Any]]


def _lr_at(lr: ScalarOrSchedule, count):
    return lr(count) if callable(lr) else lr


class AdamState(NamedTuple):
    count: jnp.ndarray
    mu: Any
    nu: Any


def adam(lr: ScalarOrSchedule = 1e-3, betas=(0.9, 0.999), eps: float = 1e-8,
         weight_decay: float = 0.0, adam_w_mode: bool = True,
         bias_correction: bool = True) -> Transform:
    """Adam/AdamW. Parity with reference FusedAdam (ops/adam/fused_adam.py:18
    — same knobs: bias_correction, adam_w_mode, weight_decay)."""
    b1, b2 = betas

    def init(params):
        zeros = lambda p: jnp.zeros_like(p, dtype=jnp.float32)
        return AdamState(count=jnp.zeros([], jnp.int32),
                         mu=jax.tree_util.tree_map(zeros, params),
                         nu=jax.tree_util.tree_map(zeros, params))

    def update(grads, state, params):
        count = state.count + 1
        lr_t = _lr_at(lr, count)
        mu = jax.tree_util.tree_map(lambda m, g: b1 * m + (1 - b1) * g.astype(jnp.float32), state.mu, grads)
        nu = jax.tree_util.tree_map(lambda v, g: b2 * v + (1 - b2) * jnp.square(g.astype(jnp.float32)),
                                    state.nu, grads)
        if bias_correction:
            c1 = 1 - b1 ** count.astype(jnp.float32)
            c2 = 1 - b2 ** count.astype(jnp.float32)
        else:
            c1 = c2 = jnp.ones([], jnp.float32)

        def upd(m, v, p):
            step = (m / c1) / (jnp.sqrt(v / c2) + eps)
            if weight_decay:
                if adam_w_mode:
                    step = step + weight_decay * p.astype(jnp.float32)
            return (-lr_t * step).astype(p.dtype)

        if weight_decay and not adam_w_mode:
            # classic (L2) mode: decay folded into the gradient
            grads = jax.tree_util.tree_map(lambda g, p: g + weight_decay * p.astype(g.dtype), grads, params)
            mu = jax.tree_util.tree_map(lambda m, g: b1 * m + (1 - b1) * g.astype(jnp.float32), state.mu, grads)
            nu = jax.tree_util.tree_map(lambda v, g: b2 * v + (1 - b2) * jnp.square(g.astype(jnp.float32)),
                                        state.nu, grads)
        updates = jax.tree_util.tree_map(upd, mu, nu, params)
        return updates, AdamState(count, mu, nu)

    return Transform(init, update)


def adamw(lr: ScalarOrSchedule = 1e-3, betas=(0.9, 0.999), eps: float = 1e-8,
          weight_decay: float = 0.01) -> Transform:
    return adam(lr, betas, eps, weight_decay, adam_w_mode=True)


class SgdState(NamedTuple):
    count: jnp.ndarray
    momentum: Any


def sgd(lr: ScalarOrSchedule = 1e-3, momentum: float = 0.0, weight_decay: float = 0.0,
        nesterov: bool = False) -> Transform:
    def init(params):
        mom = jax.tree_util.tree_map(lambda p: jnp.zeros_like(p, dtype=jnp.float32), params) \
            if momentum else None
        return SgdState(count=jnp.zeros([], jnp.int32), momentum=mom)

    def update(grads, state, params):
        count = state.count + 1
        lr_t = _lr_at(lr, count)
        if weight_decay:
            grads = jax.tree_util.tree_map(lambda g, p: g + weight_decay * p.astype(g.dtype), grads, params)
        if momentum:
            mom = jax.tree_util.tree_map(lambda m, g: momentum * m + g.astype(jnp.float32),
                                         state.momentum, grads)
            if nesterov:
                eff = jax.tree_util.tree_map(lambda g, m: g.astype(jnp.float32) + momentum * m, grads, mom)
            else:
                eff = mom
        else:
            mom, eff = None, grads
        updates = jax.tree_util.tree_map(lambda e, p: (-lr_t * e).astype(p.dtype), eff, params)
        return updates, SgdState(count, mom)

    return Transform(init, update)


class LambState(NamedTuple):
    count: jnp.ndarray
    mu: Any
    nu: Any


def lamb(lr: ScalarOrSchedule = 1e-3, betas=(0.9, 0.999), eps: float = 1e-6,
         weight_decay: float = 0.0, min_trust: float = 0.01, max_trust: float = 10.0) -> Transform:
    """LAMB: layerwise-adaptive Adam. Parity with reference FusedLamb
    (csrc/lamb/fused_lamb_cuda.cu, ops/lamb/fused_lamb.py) including the
    trust-ratio clamp (min_coeff/max_coeff there)."""
    b1, b2 = betas

    def init(params):
        zeros = lambda p: jnp.zeros_like(p, dtype=jnp.float32)
        return LambState(count=jnp.zeros([], jnp.int32),
                         mu=jax.tree_util.tree_map(zeros, params),
                         nu=jax.tree_util.tree_map(zeros, params))

    def update(grads, state, params):
        count = state.count + 1
        lr_t = _lr_at(lr, count)
        c1 = 1 - b1 ** count.astype(jnp.float32)
        c2 = 1 - b2 ** count.astype(jnp.float32)
        mu = jax.tree_util.tree_map(lambda m, g: b1 * m + (1 - b1) * g.astype(jnp.float32), state.mu, grads)
        nu = jax.tree_util.tree_map(lambda v, g: b2 * v + (1 - b2) * jnp.square(g.astype(jnp.float32)),
                                    state.nu, grads)

        def upd(m, v, p):
            u = (m / c1) / (jnp.sqrt(v / c2) + eps)
            if weight_decay:
                u = u + weight_decay * p.astype(jnp.float32)
            w_norm = jnp.linalg.norm(p.astype(jnp.float32))
            u_norm = jnp.linalg.norm(u)
            trust = jnp.where(
                (w_norm > 0) & (u_norm > 0),
                jnp.clip(w_norm / u_norm, min_trust, max_trust),
                1.0,
            )
            return (-lr_t * trust * u).astype(p.dtype)

        updates = jax.tree_util.tree_map(upd, mu, nu, params)
        return updates, LambState(count, mu, nu)

    return Transform(init, update)


class LionState(NamedTuple):
    count: jnp.ndarray
    mu: Any


def lion(lr: ScalarOrSchedule = 1e-4, betas=(0.9, 0.99), weight_decay: float = 0.0) -> Transform:
    """Lion. Parity with reference FusedLion/DeepSpeedCPULion (csrc/lion/)."""
    b1, b2 = betas

    def init(params):
        return LionState(count=jnp.zeros([], jnp.int32),
                         mu=jax.tree_util.tree_map(lambda p: jnp.zeros_like(p, dtype=jnp.float32), params))

    def update(grads, state, params):
        count = state.count + 1
        lr_t = _lr_at(lr, count)

        def upd(m, g, p):
            g32 = g.astype(jnp.float32)
            direction = jnp.sign(b1 * m + (1 - b1) * g32)
            if weight_decay:
                direction = direction + weight_decay * p.astype(jnp.float32)
            return (-lr_t * direction).astype(p.dtype)

        updates = jax.tree_util.tree_map(upd, state.mu, grads, params)
        mu = jax.tree_util.tree_map(lambda m, g: b2 * m + (1 - b2) * g.astype(jnp.float32), state.mu, grads)
        return updates, LionState(count, mu)

    return Transform(init, update)


class AdagradState(NamedTuple):
    count: jnp.ndarray
    accum: Any


def adagrad(lr: ScalarOrSchedule = 1e-2, eps: float = 1e-10, weight_decay: float = 0.0,
            initial_accumulator_value: float = 0.0) -> Transform:
    """Adagrad. Parity with reference DeepSpeedCPUAdagrad (csrc/adagrad/)."""

    def init(params):
        return AdagradState(
            count=jnp.zeros([], jnp.int32),
            accum=jax.tree_util.tree_map(
                lambda p: jnp.full_like(p, initial_accumulator_value, dtype=jnp.float32), params),
        )

    def update(grads, state, params):
        count = state.count + 1
        lr_t = _lr_at(lr, count)
        if weight_decay:
            grads = jax.tree_util.tree_map(lambda g, p: g + weight_decay * p.astype(g.dtype), grads, params)
        accum = jax.tree_util.tree_map(lambda a, g: a + jnp.square(g.astype(jnp.float32)), state.accum, grads)
        updates = jax.tree_util.tree_map(
            lambda a, g, p: (-lr_t * g.astype(jnp.float32) / (jnp.sqrt(a) + eps)).astype(p.dtype),
            accum, grads, params)
        return updates, AdagradState(count, accum)

    return Transform(init, update)


# ----------------------------------------------------------------------
# Registry — parity with engine._configure_basic_optimizer (engine.py:1245)
# name matching of the reference ("adam", "adamw", "lamb", "lion",
# "adagrad", "sgd", "onebitadam"...). 1-bit optimizers are realized as the
# plain rule + quantized gradient collectives (ops/quantization.py), since
# error-compensated compressed allreduce is a comm-layer concern on TPU.

OPTIMIZER_REGISTRY = {
    "adam": adam,
    "adamw": adamw,
    "fusedadam": adam,
    "cpuadam": adam,  # offload variant — same math, placement handled by engine
    "deepspeedcpuadam": adam,
    "sgd": sgd,
    "lamb": lamb,
    "fusedlamb": lamb,
    "lion": lion,
    "fusedlion": lion,
    "cpulion": lion,
    "adagrad": adagrad,
    "cpuadagrad": adagrad,
    "onebitadam": adam,
    "zerooneadam": adam,
    "onebitlamb": lamb,
}

_COMMON_RENAMES = {"learning_rate": "lr", "beta1": None, "beta2": None}


def build_optimizer(name: str, params_dict: Optional[dict] = None,
                    lr_schedule: Optional[Callable] = None) -> Transform:
    """Build an optimizer from config ``{"type": ..., "params": {...}}``.

    Accepts the reference's param spellings: lr, betas, eps, weight_decay,
    momentum, bias_correction, adam_w_mode.
    """
    key = name.lower().replace("_", "").replace("-", "")
    if key not in OPTIMIZER_REGISTRY:
        raise ValueError(f"Unknown optimizer '{name}'. Known: {sorted(set(OPTIMIZER_REGISTRY))}")
    factory = OPTIMIZER_REGISTRY[key]
    kwargs = dict(params_dict or {})
    kwargs.pop("torch_adam", None)
    kwargs.pop("adamw_mode", None) and kwargs.setdefault("adam_w_mode", True)
    if "adamw_mode" in (params_dict or {}):
        kwargs["adam_w_mode"] = bool(params_dict["adamw_mode"])
    if "freeze_step" in kwargs:  # 1-bit warmup knob — accepted, comm-layer concern
        kwargs.pop("freeze_step")
    for k in ("cuda_aware", "comm_backend_name", "coeff_beta", "factor_max", "factor_min", "factor_threshold"):
        kwargs.pop(k, None)
    if lr_schedule is not None:
        kwargs["lr"] = lr_schedule
    import inspect

    sig = inspect.signature(factory)
    accepted = {k: v for k, v in kwargs.items() if k in sig.parameters}
    dropped = set(kwargs) - set(accepted)
    if dropped:
        from ..utils.logging import logger

        logger.warning(f"Optimizer '{name}': ignoring unsupported params {sorted(dropped)}")
    if "betas" in accepted:
        accepted["betas"] = tuple(accepted["betas"])
    return factory(**accepted)


def as_transform(opt: Any) -> Transform:
    """Wrap an optax GradientTransformation (or anything with init/update)."""
    if isinstance(opt, Transform):
        return opt
    if hasattr(opt, "init") and hasattr(opt, "update"):
        return Transform(init=opt.init, update=lambda g, s, p: opt.update(g, s, p))
    raise TypeError(f"Cannot interpret {opt!r} as an optimizer")
