"""Persistent XLA compilation-cache wiring (docs/performance.md).

JAX ships a content-addressed on-disk cache of compiled executables; with
it enabled, time-to-first-step across process restarts (elastic resume,
preemption comebacks, dev iteration) drops from a full XLA compile to a
cache deserialize. This module is the one place the knobs are set, so the
engine, ``initialize()`` and standalone scripts configure it identically.

The cache also turns AOT warmup (``TrainEngine.warmup``) into a strict
win even when the jit call path later re-requests the program: the warmup
compile writes the cache entry and the jit call reads it back instead of
compiling a second time.
"""

from __future__ import annotations

import threading
from typing import Optional

from ..utils.logging import logger

_LOCK = threading.Lock()
_CONFIGURED_DIR: Optional[str] = None


def enable_persistent_cache(cache_dir: str,
                            min_compile_time_s: float = 0.0) -> bool:
    """Point JAX's persistent compilation cache at ``cache_dir``.

    ``min_compile_time_s=0`` caches every program (the right call for
    training jobs, where even small programs recompile on every restart);
    raise it to skip trivially cheap compiles. Idempotent per directory;
    returns False (with a warning) when the running JAX cannot honor the
    knobs instead of failing the caller."""
    global _CONFIGURED_DIR
    with _LOCK:
        if _CONFIGURED_DIR == cache_dir:
            return True
        import jax

        try:
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              float(min_compile_time_s))
            # cache small executables too — a training job's step program
            # is cheap to store and expensive to recompile
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        except Exception as e:  # older/newer jax without these knobs
            logger.warning(f"persistent compilation cache unavailable: {e}")
            return False
        # JAX latches the cache as initialized-disabled at the FIRST compile
        # of the process; any compile before this call (sharded param init,
        # another engine) would make the config update above a silent no-op.
        # Resetting the cache state makes the next compile re-initialize it
        # against the directory just configured.
        try:
            from jax._src import compilation_cache as _cc

            _cc.reset_cache()
        except Exception:  # private API moved — cache still works when the
            pass           # config landed before the first compile
        _CONFIGURED_DIR = cache_dir
        logger.info(f"persistent XLA compilation cache at {cache_dir}")
        return True


def configured_cache_dir() -> Optional[str]:
    return _CONFIGURED_DIR
