"""Tensor swapping: NVMe tier for optimizer state (ZeRO-Infinity).

Parity with the reference's ``runtime/swap_tensor/`` stack —
``AsyncPartitionedParameterSwapper`` (partitioned_param_swapper.py:36),
``OptimizerSwapper``/``PartitionedOptimizerSwapper``
(partitioned_optimizer_swapper.py), the double-buffered
``AsyncTensorSwapper`` (async_swapper.py) — driven by the native aio engine
(ops/aio.py over csrc/aio/ds_aio.cpp).

TPU-first shape: the reference swaps flattened fp32 partitions per
parameter group; here each optimizer-state *leaf shard* is one file, and
swap-out/swap-in overlap with compute through the aio thread pool
(submit returns immediately; ``wait_all`` fences before the data is
needed). Host RAM is the staging tier: device->host via
``jax.device_get``, host->NVMe async.
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from ..utils.logging import log_dist
from ..ops.aio import AsyncIOHandle


class AsyncTensorSwapper:
    """Low-level double-buffered array<->file swapper (reference
    async_swapper.py AsyncTensorSwapper)."""

    def __init__(self, swap_dir: str, n_threads: int = 4):
        self.swap_dir = Path(swap_dir)
        self.swap_dir.mkdir(parents=True, exist_ok=True)
        self.handle = AsyncIOHandle(n_threads=n_threads)
        self._pending = 0

    def _path(self, key: str) -> str:
        return str(self.swap_dir / f"{key}.bin")

    def swap_out(self, key: str, array: np.ndarray) -> None:
        """Async write; array must stay alive until wait_all (the handle
        pins it)."""
        arr = np.ascontiguousarray(array)
        # whole-file rewrite: truncate so a smaller tensor re-swapped to the
        # same key can't leave a stale tail on disk
        self.handle.async_pwrite(arr, self._path(key), truncate=True)
        self._pending += 1

    def swap_in(self, key: str, shape, dtype) -> np.ndarray:
        """Async read into a fresh host buffer; call wait_all before use."""
        buf = np.empty(shape, dtype)
        self.handle.async_pread(buf, self._path(key))
        self._pending += 1
        return buf

    def wait_all(self) -> None:
        while self._pending > 0:
            try:
                got = self.handle.wait(1)
            except OSError as e:
                # the handle drains all completions before raising; account
                # for both successes and failures so a failed IO can't leave
                # _pending stuck forever
                self._pending -= len(getattr(e, "completed", [])) + \
                    len(getattr(e, "failed", [(None, None)]))
                raise
            self._pending -= len(got)

    def bytes_on_disk(self) -> int:
        return sum(f.stat().st_size for f in self.swap_dir.glob("*.bin"))


class OptimizerSwapper:
    """Swap a whole optimizer-state pytree to NVMe between steps
    (reference partitioned_optimizer_swapper.py).

    Usage: ``swap_out(opt_state)`` after an optimizer step frees HBM/host
    memory; ``opt_state = swap_in()`` before the next step. Leaf files are
    keyed by pytree path so layout changes are detected.
    """

    def __init__(self, swap_dir: str, n_threads: int = 4):
        self.swapper = AsyncTensorSwapper(swap_dir, n_threads=n_threads)
        self._spec: Optional[List[Tuple[str, Tuple, Any]]] = None
        self._treedef = None

    def swap_out(self, opt_state: Any) -> None:
        leaves, treedef = jax.tree_util.tree_flatten_with_path(opt_state)
        self._treedef = jax.tree_util.tree_structure(opt_state)
        spec = []
        host_leaves = jax.device_get([v for _, v in leaves])
        for (path, _), host in zip(leaves, host_leaves):
            key = _sanitize(jax.tree_util.keystr(path))
            arr = np.asarray(host)
            spec.append((key, arr.shape, arr.dtype))
            self.swapper.swap_out(key, arr)
        self._spec = spec
        self.swapper.wait_all()
        log_dist(f"optimizer state swapped out: "
                 f"{self.swapper.bytes_on_disk() / 1e6:.1f} MB on disk")

    def swap_in(self, shardings: Any = None) -> Any:
        assert self._spec is not None, "nothing swapped out"
        bufs = [self.swapper.swap_in(k, shape, dtype)
                for k, shape, dtype in self._spec]
        self.swapper.wait_all()
        tree = jax.tree_util.tree_unflatten(self._treedef, bufs)
        if shardings is not None:
            tree = jax.device_put(tree, shardings)
        return tree


def _sanitize(keystr: str) -> str:
    return "".join(c if c.isalnum() or c in "._-" else "_" for c in keystr)
