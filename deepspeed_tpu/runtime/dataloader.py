"""Data loading utilities.

Capability parity with the reference's ``runtime/dataloader.py:41``
(DeepSpeedDataLoader: DP-aware DistributedSampler + curriculum hooks) and the
``deepspeed_io`` factory (engine.py:1669). TPU-native shape: instead of a
per-rank sampler, the loader yields *global* batches placed as sharded
``jax.Array``s over the mesh's batch axes — each host only materializes the
shard it feeds (via ``jax.make_array_from_process_local_data``), which is the
multi-host analog of DistributedSampler rank slicing.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator, Optional, Sequence

import jax
import numpy as np

from ..parallel.mesh import Topology


class DataLoader:
    """Iterates a dataset in global batches sharded over the 'data' axis.

    ``dataset`` may be any sequence (or numpy arrays pytree with a leading
    sample dim). Yields pytrees of jax.Arrays with global leading dim
    ``batch_size`` sharded over the mesh batch axes.
    """

    def __init__(self, dataset: Any, batch_size: int, topo: Topology, *,
                 shuffle: bool = True, seed: int = 0, drop_last: bool = True,
                 collate_fn: Optional[Callable[[list], Any]] = None,
                 curriculum_fn: Optional[Callable[[int, Any], Any]] = None):
        self.dataset = dataset
        self.batch_size = batch_size
        self.topo = topo
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.collate_fn = collate_fn or _default_collate
        self.curriculum_fn = curriculum_fn
        self.epoch = 0
        self._batch_index = 0  # batches consumed in the current epoch
        self._n = _dataset_len(dataset)
        if batch_size > self._n and drop_last:
            raise ValueError(f"batch_size {batch_size} exceeds dataset size {self._n}")

    def __len__(self) -> int:
        if self.drop_last:
            return self._n // self.batch_size
        return (self._n + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch
        self._batch_index = 0

    # ------------------------------------------------------------------
    # checkpointable position (runtime/checkpoint.py commit protocol: the
    # engine stores this in client_state so a resumed run replays the exact
    # remaining batch order — the shuffle is a pure function of
    # seed + epoch, so (epoch, batch_index) IS the pipeline position)
    def state_dict(self) -> dict:
        """Position of the NEXT batch to yield. A position at the end of
        an epoch is normalized to (epoch+1, 0): a checkpoint taken right
        after an epoch's last batch must resume into the next epoch, not
        replay the one just finished."""
        epoch, b = int(self.epoch), int(self._batch_index)
        nb = len(self)
        if nb > 0 and b >= nb:
            epoch, b = epoch + 1, 0
        return {"epoch": epoch, "batch_index": b, "seed": int(self.seed)}

    def load_state_dict(self, sd: dict) -> None:
        """Restore position. Takes effect on the next ``iter()`` AND on a
        live iterator (the engine's divergence rollback rewinds the data
        stream without the training loop restarting its ``for`` loop —
        the iterator re-reads the position before every yield)."""
        if int(sd.get("seed", self.seed)) != self.seed:
            from ..utils.logging import logger

            logger.warning(
                f"dataloader resume: checkpoint seed {sd.get('seed')} != "
                f"configured seed {self.seed}; batch order will diverge")
        self.epoch = int(sd.get("epoch", 0))
        self._batch_index = int(sd.get("batch_index", 0))

    def _epoch_order(self, epoch: int) -> np.ndarray:
        order = np.arange(self._n)
        if self.shuffle:
            rng = np.random.default_rng(self.seed + epoch)
            rng.shuffle(order)
        return order

    def __iter__(self) -> Iterator[Any]:
        nb = len(self)
        # a fully-consumed epoch (or a fresh loader) starts from 0; a
        # mid-epoch position restored by load_state_dict resumes there
        if self._batch_index >= nb:
            self._batch_index = 0
        epoch = self.epoch
        order = self._epoch_order(epoch)
        while self._batch_index < nb:
            if self.epoch != epoch:  # position rewound across epochs
                epoch = self.epoch
                order = self._epoch_order(epoch)
            b = self._batch_index
            idx = order[b * self.batch_size:(b + 1) * self.batch_size]
            if len(idx) < self.batch_size:
                if self.drop_last:
                    break
                idx = np.concatenate([idx, order[: self.batch_size - len(idx)]])
            batch = self.collate_fn([_dataset_get(self.dataset, int(i)) for i in idx])
            if self.curriculum_fn is not None:
                batch = self.curriculum_fn(epoch * nb + b, batch)
            self._batch_index = b + 1
            yield self.shard(batch)

    def shard(self, batch: Any) -> Any:
        """Place a host-global numpy batch as sharded jax.Arrays."""
        sharding_cache = {}

        def place(x):
            x = np.asarray(x)
            sh = sharding_cache.get(x.ndim)
            if sh is None:
                sh = self.topo.batch_sharding(x.ndim) if x.ndim > 1 else self.topo.data_sharding(max(x.ndim, 1))
                sharding_cache[x.ndim] = sh
            return jax.device_put(x, sh)

        return jax.tree_util.tree_map(place, batch)


def shard_batch(batch: Any, topo: Topology) -> Any:
    """Place a host numpy batch pytree as sharded jax.Arrays over the mesh's
    batch axes (standalone helper mirroring DataLoader.shard)."""

    def place(x):
        x = np.asarray(x)
        sh = topo.batch_sharding(x.ndim) if x.ndim > 1 else topo.data_sharding(max(x.ndim, 1))
        return jax.device_put(x, sh)

    return jax.tree_util.tree_map(place, batch)


def _dataset_len(ds: Any) -> int:
    if isinstance(ds, dict):
        return int(jax.tree_util.tree_leaves(ds)[0].shape[0])
    if hasattr(ds, "__len__"):
        return len(ds)
    return int(jax.tree_util.tree_leaves(ds)[0].shape[0])


def _dataset_get(ds: Any, i: int) -> Any:
    if hasattr(ds, "__getitem__") and not isinstance(ds, dict):
        return ds[i]
    return jax.tree_util.tree_map(lambda a: a[i], ds)


def _default_collate(samples: list) -> Any:
    first = samples[0]
    if isinstance(first, (tuple, list)):
        return type(first)(np.stack([s[j] for s in samples]) for j in range(len(first)))
    if isinstance(first, dict):
        return {k: np.stack([s[k] for s in samples]) for k in first}
    return np.stack(samples)


class RepeatingLoader:
    """Wraps a loader to cycle forever (reference runtime/dataloader.py
    RepeatingLoader, used by the pipeline engine)."""

    def __init__(self, loader: Iterable):
        self.loader = loader
        self._it = iter(loader)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self._it)
        except StopIteration:
            if hasattr(self.loader, "set_epoch"):
                self.loader.set_epoch(getattr(self.loader, "epoch", 0) + 1)
            self._it = iter(self.loader)
            return next(self._it)


def prefetch(iterator: Iterable, size: int = 2,
             sharding=None) -> Iterator[Any]:
    """Prefetching wrapper: keeps ``size`` batches in flight so batch N+1
    preparation overlaps device compute on batch N (the TPU analog of the
    reference loaders' pin_memory + non_blocking copies;
    flax.jax_utils.prefetch_to_device pattern).

    With ``sharding`` given, each queued batch is tree-mapped through
    ``jax.device_put`` at enqueue time — device_put is async, so the queue
    holds device arrays whose uploads are already enqueued and the
    training loop never waits on host->device transfer. Without it, only
    host-side iterator work (collate/tokenize) is overlapped; pass the
    batch sharding (or use runtime.dataloader.shard_batch downstream) to
    get the transfer overlap too."""
    import collections

    queue: collections.deque = collections.deque()
    it = iter(iterator)

    def _place(item):
        if sharding is None:
            return item
        import jax

        return jax.tree_util.tree_map(
            lambda a: jax.device_put(a, sharding), item)

    def enqueue(n):
        for _ in range(n):
            try:
                queue.append(_place(next(it)))
            except StopIteration:
                return

    enqueue(size)
    while queue:
        yield queue.popleft()
        enqueue(1)
