"""Data loading utilities.

Capability parity with the reference's ``runtime/dataloader.py:41``
(DeepSpeedDataLoader: DP-aware DistributedSampler + curriculum hooks) and the
``deepspeed_io`` factory (engine.py:1669). TPU-native shape: instead of a
per-rank sampler, the loader yields *global* batches placed as sharded
``jax.Array``s over the mesh's batch axes — each host only materializes the
shard it feeds (via ``jax.make_array_from_process_local_data``), which is the
multi-host analog of DistributedSampler rank slicing.

Async input pipeline (docs/performance.md): with ``prefetch_depth > 0`` a
producer thread runs collate + curriculum + sharding-aware ``device_put``
into a bounded queue, so batch N+1 is already resident on device while the
step on batch N runs — the TPU analog of the reference's pinned-memory
staged loaders. The checkpointable position (``state_dict``) always reports
the CONSUMER's position, never the producer's read-ahead: a mid-epoch resume
replays exactly the batches the training loop had not yet received.
"""

from __future__ import annotations

import queue as queue_mod
import threading
import time
import weakref
from typing import Any, Callable, Iterable, Iterator, Optional, Sequence

import jax
import numpy as np

from ..parallel.mesh import Topology


class DataLoader:
    """Iterates a dataset in global batches sharded over the 'data' axis.

    ``dataset`` may be any sequence (or numpy arrays pytree with a leading
    sample dim). Yields pytrees of jax.Arrays with global leading dim
    ``batch_size`` sharded over the mesh batch axes.

    ``prefetch_depth > 0`` turns on the background pipeline: that many
    batches are kept in flight (collated + uploaded) ahead of the consumer.
    ``collate_fn``/``curriculum_fn`` then run on the producer thread and
    must be thread-safe. ``data_wait_s`` accumulates the host time the
    consumer spent waiting for (sync: producing) each batch — the
    engine's ``data_wait_ms`` ledger reads deltas of it.
    """

    def __init__(self, dataset: Any, batch_size: int, topo: Topology, *,
                 shuffle: bool = True, seed: int = 0, drop_last: bool = True,
                 collate_fn: Optional[Callable[[list], Any]] = None,
                 curriculum_fn: Optional[Callable[[int, Any], Any]] = None,
                 prefetch_depth: int = 0):
        self.dataset = dataset
        self.batch_size = batch_size
        self.topo = topo
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.collate_fn = collate_fn or _default_collate
        self.curriculum_fn = curriculum_fn
        self.prefetch_depth = int(prefetch_depth)
        self.epoch = 0
        self._batch_index = 0  # batches consumed in the current epoch
        self._n = _dataset_len(dataset)
        # position generation: bumped whenever the position is rewound out
        # from under a live iterator (rollback / resume); the prefetch
        # consumer restarts its producer when it observes a bump
        self._position_gen = 0
        # weakly held: a strong reference would keep an abandoned iterator
        # reachable forever and its finalizer's GC leg could never fire
        self._active_prefetch: Optional[weakref.ref] = None
        self.data_wait_s = 0.0  # cumulative host-ledger counter
        if batch_size > self._n and drop_last:
            raise ValueError(f"batch_size {batch_size} exceeds dataset size {self._n}")

    def __len__(self) -> int:
        if self.drop_last:
            return self._n // self.batch_size
        return (self._n + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch
        self._batch_index = 0
        self._position_gen += 1

    # ------------------------------------------------------------------
    # checkpointable position (runtime/checkpoint.py commit protocol: the
    # engine stores this in client_state so a resumed run replays the exact
    # remaining batch order — the shuffle is a pure function of
    # seed + epoch, so (epoch, batch_index) IS the pipeline position)
    def state_dict(self) -> dict:
        """Position of the NEXT batch to yield. A position at the end of
        an epoch is normalized to (epoch+1, 0): a checkpoint taken right
        after an epoch's last batch must resume into the next epoch, not
        replay the one just finished.

        Under an active prefetch pipeline this is the CONSUMER position —
        batches the producer has read ahead but the training loop has not
        yet received are NOT counted as consumed, so a resume replays
        them."""
        epoch, b = int(self.epoch), int(self._batch_index)
        nb = len(self)
        if nb > 0 and b >= nb:
            epoch, b = epoch + 1, 0
        return {"epoch": epoch, "batch_index": b, "seed": int(self.seed)}

    def load_state_dict(self, sd: dict) -> None:
        """Restore position. Takes effect on the next ``iter()`` AND on a
        live iterator (the engine's divergence rollback rewinds the data
        stream without the training loop restarting its ``for`` loop —
        the sync iterator re-reads the position before every yield; the
        prefetch iterator drains its queue and restarts the producer at
        the restored position)."""
        if int(sd.get("seed", self.seed)) != self.seed:
            from ..utils.logging import logger

            logger.warning(
                f"dataloader resume: checkpoint seed {sd.get('seed')} != "
                f"configured seed {self.seed}; batch order will diverge")
        self.epoch = int(sd.get("epoch", 0))
        self._batch_index = int(sd.get("batch_index", 0))
        self._position_gen += 1

    def _epoch_order(self, epoch: int) -> np.ndarray:
        order = np.arange(self._n)
        if self.shuffle:
            rng = np.random.default_rng(self.seed + epoch)
            rng.shuffle(order)
        return order

    def _assemble(self, epoch: int, b: int, order: np.ndarray, nb: int,
                  *, pad_partial: bool) -> Optional[Any]:
        """Index-slice + collate + curriculum for batch ``b`` of ``epoch``
        on the host — no device placement. A trailing partial batch is
        dropped (None) when ``drop_last`` holds and ``pad_partial`` is
        False; otherwise it is padded by wrapping to the epoch head."""
        idx = order[b * self.batch_size:(b + 1) * self.batch_size]
        if len(idx) < self.batch_size:
            if self.drop_last and not pad_partial:
                return None
            idx = np.concatenate([idx, order[: self.batch_size - len(idx)]])
        batch = self.collate_fn([_dataset_get(self.dataset, int(i)) for i in idx])
        if self.curriculum_fn is not None:
            batch = self.curriculum_fn(epoch * nb + b, batch)
        return batch

    def _produce(self, epoch: int, b: int, order: np.ndarray,
                 nb: int) -> Optional[Any]:
        """Collate + curriculum + device placement for batch ``b`` of
        ``epoch``. Returns None for a dropped trailing partial batch.
        Pure function of its arguments (no loader-position mutation), so
        the producer thread and the sync iterator share it."""
        batch = self._assemble(epoch, b, order, nb, pad_partial=False)
        if batch is None:
            return None
        return self.shard(batch)

    def __iter__(self) -> Iterator[Any]:
        if self._active_prefetch is not None:
            active = self._active_prefetch()
            if active is not None:
                active.close()
            self._active_prefetch = None
        nb = len(self)
        # a fully-consumed epoch (or a fresh loader) starts from 0; a
        # mid-epoch position restored by load_state_dict resumes there
        if self._batch_index >= nb:
            self._batch_index = 0
        if self.prefetch_depth > 0:
            it = _PrefetchIterator(self, self.prefetch_depth)
            self._active_prefetch = weakref.ref(it)
            return it
        return self._sync_iter()

    def _sync_iter(self) -> Iterator[Any]:
        nb = len(self)
        epoch = self.epoch
        order = self._epoch_order(epoch)
        while self._batch_index < nb:
            if self.epoch != epoch:  # position rewound across epochs
                epoch = self.epoch
                order = self._epoch_order(epoch)
            b = self._batch_index
            t0 = time.perf_counter()
            batch = self._produce(epoch, b, order, nb)
            self.data_wait_s += time.perf_counter() - t0
            if batch is None:
                break
            self._batch_index = b + 1
            yield batch

    def batch_struct(self) -> Optional[Any]:
        """ShapeDtypeStruct tree (with shardings) of the next batch this
        loader would yield — the abstract signature the engine's AOT
        warmup lowers against, at the cost of one collate and zero
        device transfers. Does not advance the loader position."""
        nb = len(self)
        if nb == 0 or self._n == 0:
            return None
        b = self._batch_index if self._batch_index < nb else 0
        order = self._epoch_order(self.epoch)
        batch = self._assemble(self.epoch, b, order, nb, pad_partial=True)
        batch = jax.tree_util.tree_map(np.asarray, batch)
        cache: dict = {}

        def struct(x):
            sh = cache.get(x.ndim)
            if sh is None:
                sh = _ndim_sharding(self.topo, x.ndim)
                cache[x.ndim] = sh
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sh)

        return jax.tree_util.tree_map(struct, batch)

    def shard(self, batch: Any) -> Any:
        """Place a host-global numpy batch as sharded jax.Arrays with ONE
        ``device_put`` dispatch for the whole pytree (a batched transfer),
        instead of one dispatch per leaf."""
        return shard_batch(batch, self.topo)


def _ndim_sharding(topo: Topology, ndim: int):
    if ndim > 1:
        return topo.batch_sharding(ndim)
    return topo.data_sharding(max(ndim, 1))


_END_OF_EPOCH = "__end_of_epoch__"
_PRODUCER_ERROR = "__error__"


def _queue_put(q: queue_mod.Queue, stop: threading.Event, item) -> bool:
    """Bounded put that stays responsive to the stop event (a plain
    blocking put on a full queue would deadlock close())."""
    while not stop.is_set():
        try:
            q.put(item, timeout=0.1)
            return True
        except queue_mod.Full:
            continue
    return False


def _producer_loop(loader: "DataLoader", q: queue_mod.Queue,
                   stop: threading.Event, gen: int, epoch: int, b: int,
                   nb: int) -> None:
    """Prefetch producer body: walk the epoch order from (epoch, b),
    enqueuing produced batches until the epoch ends or ``stop`` is set.
    Module-level on purpose — holding the iterator would pin it for the
    thread's lifetime (see _PrefetchIterator._start_producer)."""
    try:
        order = loader._epoch_order(epoch)
        while b < nb and not stop.is_set():
            batch = loader._produce(epoch, b, order, nb)
            if batch is None:  # dropped trailing partial batch
                break
            if not _queue_put(q, stop, (gen, epoch, b + 1, batch)):
                return
            b += 1
        _queue_put(q, stop, (gen, _END_OF_EPOCH, 0, None))
    except Exception as e:  # surface producer crashes to the consumer
        _queue_put(q, stop, (gen, _PRODUCER_ERROR, 0, e))


class _PrefetchIterator:
    """Consumer half of the background input pipeline.

    A producer thread walks the epoch order from the loader's position,
    running collate + curriculum + ``device_put`` (async upload) and
    enqueuing ``(generation, epoch, next_index, batch)`` into a bounded
    queue of ``depth`` slots — double-buffered at depth 2. The consumer
    commits the loader position only when it dequeues a batch, so
    ``state_dict`` never observes read-ahead. A position rewound out from
    under the iterator (rollback / resume — the loader bumps
    ``_position_gen``) drains the queue, stops the producer and restarts
    it at the restored position."""

    _END = _END_OF_EPOCH

    def __init__(self, loader: DataLoader, depth: int):
        self.loader = loader
        self.depth = max(1, int(depth))
        self._closed = False
        self._queue: queue_mod.Queue = None  # type: ignore[assignment]
        self._stop: threading.Event = None   # type: ignore[assignment]
        self._thread: Optional[threading.Thread] = None
        self._finalizer: Optional[weakref.finalize] = None
        self._gen = -1
        self._start_producer()

    # -- producer -------------------------------------------------------
    def _start_producer(self) -> None:
        loader = self.loader
        self._gen = loader._position_gen
        self._queue = queue_mod.Queue(maxsize=self.depth)
        self._stop = threading.Event()
        nb = len(loader)
        epoch = loader.epoch
        b = loader._batch_index
        if b >= nb:  # restart at a consumed-epoch position: next epoch's 0
            b = 0
        # the thread target is a MODULE-LEVEL function holding the loader,
        # not this iterator: a bound-method target would keep the iterator
        # alive for the thread's lifetime and the GC leg of the finalizer
        # below could never run for an abandoned iterator
        self._thread = threading.Thread(
            target=_producer_loop,
            args=(loader, self._queue, self._stop, self._gen, epoch, b, nb),
            name="dst-prefetch", daemon=True)
        # an abandoned iterator must still stop its producer — a daemon
        # thread killed mid-device_put at interpreter teardown aborts the
        # process from XLA's C++ side. finalize() runs on GC AND at exit.
        if self._finalizer is not None:
            self._finalizer.detach()
        self._finalizer = weakref.finalize(
            self, _shutdown_producer, self._stop, self._queue, self._thread)
        self._thread.start()

    # -- consumer -------------------------------------------------------
    def __iter__(self) -> "_PrefetchIterator":
        return self

    def __next__(self) -> Any:
        if self._closed:
            raise StopIteration
        loader = self.loader
        while True:
            if self._gen != loader._position_gen:
                self._restart()
            t0 = time.perf_counter()
            item = self._queue.get()
            loader.data_wait_s += time.perf_counter() - t0
            gen, epoch, next_b, batch = item
            if gen != self._gen:  # stale leftover from before a restart
                continue
            if epoch == _PRODUCER_ERROR:
                self.close()
                raise batch
            if epoch == self._END:
                if self._gen != loader._position_gen:
                    continue  # rewound during the final get — restart
                self.close()
                raise StopIteration
            # commit the consumer position (same semantics as the sync
            # path's pre-yield `_batch_index = b + 1`)
            loader.epoch = epoch
            loader._batch_index = next_b
            return batch

    def _restart(self) -> None:
        """Rewind observed: drop everything in flight and restart the
        producer from the loader's (restored) position."""
        self._stop_and_drain()
        self._start_producer()

    def _stop_and_drain(self) -> None:
        if self._finalizer is not None:
            self._finalizer.detach()
            self._finalizer = None
        _shutdown_producer(self._stop, self._queue, self._thread)
        self._thread = None

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._stop_and_drain()
        ref = self.loader._active_prefetch
        if ref is not None and ref() is self:
            self.loader._active_prefetch = None


def _shutdown_producer(stop: threading.Event, q: queue_mod.Queue,
                       thread: Optional[threading.Thread]) -> None:
    """Stop a producer thread and let it exit cleanly (module-level so
    weakref.finalize holds no reference back to the iterator)."""
    stop.set()
    while True:  # unblock a producer stuck on a full queue
        try:
            q.get_nowait()
        except queue_mod.Empty:
            break
    if thread is not None and thread.is_alive():
        thread.join(timeout=30.0)


def shard_batch(batch: Any, topo: Topology) -> Any:
    """Place a host numpy batch pytree as sharded jax.Arrays over the mesh's
    batch axes in one batched ``device_put`` dispatch (standalone helper
    mirroring DataLoader.shard)."""
    batch = jax.tree_util.tree_map(np.asarray, batch)
    cache: dict = {}

    def sh_for(x):
        sh = cache.get(x.ndim)
        if sh is None:
            sh = _ndim_sharding(topo, x.ndim)
            cache[x.ndim] = sh
        return sh

    shardings = jax.tree_util.tree_map(sh_for, batch)
    return jax.device_put(batch, shardings)


def _dataset_len(ds: Any) -> int:
    if isinstance(ds, dict):
        return int(jax.tree_util.tree_leaves(ds)[0].shape[0])
    if hasattr(ds, "__len__"):
        return len(ds)
    return int(jax.tree_util.tree_leaves(ds)[0].shape[0])


def _dataset_get(ds: Any, i: int) -> Any:
    if hasattr(ds, "__getitem__") and not isinstance(ds, dict):
        return ds[i]
    return jax.tree_util.tree_map(lambda a: a[i], ds)


def _default_collate(samples: list) -> Any:
    first = samples[0]
    if isinstance(first, (tuple, list)):
        return type(first)(np.stack([s[j] for s in samples]) for j in range(len(first)))
    if isinstance(first, dict):
        return {k: np.stack([s[k] for s in samples]) for k in first}
    return np.stack(samples)


class RepeatingLoader:
    """Wraps a loader to cycle forever (reference runtime/dataloader.py
    RepeatingLoader, used by the pipeline engine)."""

    def __init__(self, loader: Iterable):
        self.loader = loader
        self._it = iter(loader)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self._it)
        except StopIteration:
            if hasattr(self.loader, "set_epoch"):
                self.loader.set_epoch(getattr(self.loader, "epoch", 0) + 1)
            self._it = iter(self.loader)
            return next(self._it)


def prefetch(iterator: Iterable, size: int = 2,
             sharding=None) -> Iterator[Any]:
    """Prefetching wrapper: keeps ``size`` batches in flight so batch N+1
    preparation overlaps device compute on batch N (the TPU analog of the
    reference loaders' pin_memory + non_blocking copies;
    flax.jax_utils.prefetch_to_device pattern).

    For :class:`DataLoader` sources prefer ``prefetch_depth`` on the loader
    itself — it adds a true producer THREAD (host collate overlaps device
    compute) and keeps the checkpointable position consumer-accurate. This
    wrapper stays for arbitrary iterators: it only overlaps the async
    device_put upload, not the host-side iterator work.

    With ``sharding`` given, each queued batch is tree-mapped through
    ``jax.device_put`` at enqueue time — device_put is async, so the queue
    holds device arrays whose uploads are already enqueued and the
    training loop never waits on host->device transfer. Without it, only
    host-side iterator work (collate/tokenize) is overlapped; pass the
    batch sharding (or use runtime.dataloader.shard_batch downstream) to
    get the transfer overlap too."""
    import collections

    queue: collections.deque = collections.deque()
    it = iter(iterator)

    def _place(item):
        if sharding is None:
            return item
        import jax

        return jax.tree_util.tree_map(
            lambda a: jax.device_put(a, sharding), item)

    def enqueue(n):
        for _ in range(n):
            try:
                queue.append(_place(next(it)))
            except StopIteration:
                return
    enqueue(size)
    while queue:
        yield queue.popleft()
        enqueue(1)
