"""Learning-rate schedules.

Capability parity with the reference's ``runtime/lr_schedules.py`` (878 LoC:
WarmupLR, WarmupDecayLR, WarmupCosineLR, OneCycle, LRRangeTest), rebuilt as
pure functions ``step -> lr`` that trace cleanly inside ``jit`` (the
reference mutates param-group lr per step from Python; under XLA the
schedule is part of the compiled update).
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, Optional

import jax.numpy as jnp

Schedule = Callable[[Any], Any]  # step (int / traced int32) -> lr (float32)


def constant_lr(lr: float) -> Schedule:
    return lambda step: jnp.asarray(lr, jnp.float32)


def warmup_lr(warmup_min_lr: float = 0.0, warmup_max_lr: float = 1e-3,
              warmup_num_steps: int = 1000, warmup_type: str = "log") -> Schedule:
    """WarmupLR (reference lr_schedules.py:636 WarmupLR): ramp then hold.

    gamma = log(step+1) / log(warmup_num_steps) for the default "log" type
    (warmup_num_steps floored at 2, per reference __init__), step/steps for
    "linear"; gamma clamps to 1 once warmup completes.
    """
    steps = max(2, warmup_num_steps)
    inverse_log_warm_up = 1.0 / math.log(steps)

    def sched(step):
        step = jnp.asarray(step, jnp.float32)
        if warmup_type == "log":
            ramp = inverse_log_warm_up * jnp.log(step + 1.0)
        else:
            ramp = step / steps
        ramp = jnp.where(step < steps, ramp, 1.0)
        return jnp.asarray(warmup_min_lr + (warmup_max_lr - warmup_min_lr) * ramp, jnp.float32)

    return sched


def warmup_decay_lr(total_num_steps: int, warmup_min_lr: float = 0.0,
                    warmup_max_lr: float = 1e-3, warmup_num_steps: int = 1000,
                    warmup_type: str = "linear") -> Schedule:
    """WarmupDecayLR: linear warmup then linear decay to 0 at total steps."""

    def sched(step):
        step = jnp.asarray(step, jnp.float32)
        warm = jnp.clip(step / max(warmup_num_steps, 1), 0.0, 1.0)
        lr_warm = warmup_min_lr + (warmup_max_lr - warmup_min_lr) * warm
        denom = max(total_num_steps - warmup_num_steps, 1)
        decay = jnp.clip((total_num_steps - step) / denom, 0.0, 1.0)
        return jnp.where(step < warmup_num_steps, lr_warm, warmup_max_lr * decay).astype(jnp.float32)

    return sched


def warmup_cosine_lr(total_num_steps: int, warmup_min_ratio: float = 0.0,
                     warmup_num_steps: int = 1000, cos_min_ratio: float = 1e-4,
                     warmup_max_lr: float = 1e-3) -> Schedule:
    """WarmupCosineLR (reference lr_schedules.py WarmupCosineLR)."""

    def sched(step):
        step = jnp.asarray(step, jnp.float32)
        warm = jnp.clip(step / max(warmup_num_steps, 1), 0.0, 1.0)
        ratio_warm = warmup_min_ratio + (1 - warmup_min_ratio) * warm
        denom = max(total_num_steps - warmup_num_steps, 1)
        prog = jnp.clip((step - warmup_num_steps) / denom, 0.0, 1.0)
        cos = cos_min_ratio + (1 - cos_min_ratio) * 0.5 * (1 + jnp.cos(jnp.pi * prog))
        ratio = jnp.where(step < warmup_num_steps, ratio_warm, cos)
        return (warmup_max_lr * ratio).astype(jnp.float32)

    return sched


def one_cycle(cycle_min_lr: float, cycle_max_lr: float, cycle_first_step_size: int = 2000,
              cycle_second_step_size: Optional[int] = None, decay_step_size: int = 0,
              decay_lr_rate: float = 0.0, **_ignored) -> Schedule:
    """OneCycle (reference lr_schedules.py OneCycle — lr leg only; momentum
    cycling folds into the optimizer betas when needed)."""
    second = cycle_second_step_size if cycle_second_step_size is not None else cycle_first_step_size
    cycle_len = cycle_first_step_size + second

    def sched(step):
        step = jnp.asarray(step, jnp.float32)
        up = jnp.clip(step / max(cycle_first_step_size, 1), 0.0, 1.0)
        down = jnp.clip((step - cycle_first_step_size) / max(second, 1), 0.0, 1.0)
        in_cycle_lr = jnp.where(
            step <= cycle_first_step_size,
            cycle_min_lr + (cycle_max_lr - cycle_min_lr) * up,
            cycle_max_lr - (cycle_max_lr - cycle_min_lr) * down,
        )
        if decay_step_size > 0:
            decay_steps = jnp.maximum(step - cycle_len, 0.0) / decay_step_size
            decayed = cycle_min_lr / (1.0 + decay_steps * decay_lr_rate)
        else:
            decayed = jnp.asarray(cycle_min_lr, jnp.float32)
        return jnp.where(step <= cycle_len, in_cycle_lr, decayed).astype(jnp.float32)

    return sched


def lr_range_test(lr_range_test_min_lr: float = 1e-3, lr_range_test_step_size: int = 2000,
                  lr_range_test_step_rate: float = 1.0, lr_range_test_staircase: bool = False) -> Schedule:
    """LRRangeTest (reference lr_schedules.py LRRangeTest)."""

    def sched(step):
        step = jnp.asarray(step, jnp.float32)
        interval = step / max(lr_range_test_step_size, 1)
        if lr_range_test_staircase:
            interval = jnp.floor(interval)
        return (lr_range_test_min_lr * (1.0 + interval * lr_range_test_step_rate)).astype(jnp.float32)

    return sched


SCHEDULE_REGISTRY: Dict[str, Callable[..., Schedule]] = {
    "warmuplr": warmup_lr,
    "warmupdecaylr": warmup_decay_lr,
    "warmupcosinelr": warmup_cosine_lr,
    "onecycle": one_cycle,
    "lrrangetest": lr_range_test,
    "constant": lambda lr=1e-3, **_: constant_lr(lr),
}


def build_schedule(name: Optional[str], params: Optional[dict] = None,
                   fallback_lr: float = 1e-3) -> Schedule:
    """Build from config ``{"type": ..., "params": {...}}`` (reference
    scheduler block / engine._configure_lr_scheduler engine.py:892)."""
    if not name:
        return constant_lr(fallback_lr)
    key = name.lower().replace("_", "").replace("-", "")
    if key not in SCHEDULE_REGISTRY:
        raise ValueError(f"Unknown scheduler '{name}'. Known: {sorted(SCHEDULE_REGISTRY)}")
    import inspect

    factory = SCHEDULE_REGISTRY[key]
    params = dict(params or {})
    sig = inspect.signature(factory)
    accepted = {k: v for k, v in params.items() if k in sig.parameters}
    dropped = set(params) - set(accepted)
    if dropped:
        from ..utils.logging import logger

        logger.warning(f"Scheduler '{name}': ignoring params {sorted(dropped)}")
    return factory(**accepted)
