"""1-bit optimizers: communication-compressed Adam / LAMB.

Reference surface: ``runtime/fp16/onebit/`` — OnebitAdam (adam.py:14),
OnebitLamb (lamb.py), ZeroOneAdam (zoadam.py), all built on the
error-compensated compressed allreduce in ``runtime/comm/nccl.py:51``.

Algorithm (1-bit Adam, NeurIPS'21): a dense warmup phase runs standard
Adam; after ``freeze_step`` the variance term is FROZEN and each step
communicates the *momentum* through the error-compensated 1-bit collective
(parallel/compressed.py) instead of dense gradients — ~25x smaller wire
volume for the dominant traffic.

TPU-first: the whole step (local grad, momentum update, compressed
collective, Adam math) is ONE jitted shard_map program; warmup/compressed
phases are a ``lax.cond``-free select on a step counter so a single
compiled program serves both phases.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.compressed import init_error_feedback, tree_onebit_allreduce
from ..parallel.mesh import shard_map_compat
from ..utils.logging import log_dist


class OnebitAdam:
    """Self-contained data-parallel trainer with 1-bit Adam semantics.

    Reference-parity knobs: lr, betas, eps, weight_decay, freeze_step
    (warmup length before compression kicks in). ``cuda_aware``/``comm_
    backend_name`` from the reference have no TPU analog.
    """

    def __init__(self, loss_fn: Callable, params: Any, mesh: Mesh,
                 axis_name: str = "data", lr: float = 1e-3,
                 betas: Tuple[float, float] = (0.9, 0.999), eps: float = 1e-8,
                 weight_decay: float = 0.0, freeze_step: int = 100):
        self.loss_fn = loss_fn
        self.mesh = mesh
        self.axis_name = axis_name
        self.lr = lr
        self.betas = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.freeze_step = freeze_step
        self.world = mesh.shape[axis_name]

        repl = NamedSharding(mesh, P())
        err_shard = NamedSharding(mesh, P(axis_name))
        self.params = jax.device_put(params, repl)
        zeros = lambda p: jnp.zeros(p.shape, jnp.float32)
        self.m = jax.device_put(jax.tree_util.tree_map(zeros, params), repl)
        self.v = jax.device_put(jax.tree_util.tree_map(zeros, params), repl)
        we, se = init_error_feedback(params, self.world)
        self.worker_error = jax.device_put(we, err_shard)
        self.server_error = jax.device_put(se, err_shard)
        self.steps = 0
        self._step_fn = None
        log_dist(f"OnebitAdam: freeze_step={freeze_step} world={self.world}")

    @property
    def compression_active(self) -> bool:
        return self.steps >= self.freeze_step

    def _apply_update(self, p, mm, vv, bc1, bc2):
        upd = (mm / bc1) / (jnp.sqrt(vv / bc2) + self.eps)
        if self.weight_decay > 0:
            upd = upd + self.weight_decay * p.astype(jnp.float32)
        return (p - self.lr * upd).astype(p.dtype)

    def _build_step(self, compressed: bool):
        """Two SEPARATE compiled programs: the warmup one contains only the
        dense pmean, the compressed one only the 1-bit collective — a
        masked-out branch would still execute its collective every step and
        the wire-volume saving would be fiction."""
        b1, b2 = self.betas
        axis, world = self.axis_name, self.world
        loss_fn = self.loss_fn

        def spmd(params, m, v, we, se, batch, step):
            loss, grads = jax.value_and_grad(
                lambda p: loss_fn(p, batch, None))(params)
            loss = jax.lax.pmean(loss, axis)
            if compressed:
                # local momentum update; only the momentum crosses the wire,
                # 1-bit compressed; variance stays frozen
                m_new = jax.tree_util.tree_map(
                    lambda mm, g: b1 * mm + (1 - b1) * g.astype(jnp.float32),
                    m, grads)
                m_new, we, se = tree_onebit_allreduce(m_new, we, se, axis, world)
                v_new = v
            else:
                g_dense = jax.tree_util.tree_map(
                    lambda g: jax.lax.pmean(g.astype(jnp.float32), axis), grads)
                m_new = jax.tree_util.tree_map(
                    lambda mm, g: b1 * mm + (1 - b1) * g, m, g_dense)
                v_new = jax.tree_util.tree_map(
                    lambda vv, g: b2 * vv + (1 - b2) * g * g, v, g_dense)

            t = (step + 1).astype(jnp.float32)
            bc1 = 1 - b1 ** t
            bc2 = 1 - b2 ** t
            params_new = jax.tree_util.tree_map(
                lambda p, mm, vv: self._apply_update(p, mm, vv, bc1, bc2),
                params, m_new, v_new)
            return params_new, m_new, v_new, we, se, loss

        fn = shard_map_compat(
            spmd, mesh=self.mesh, axis_names={axis},
            in_specs=(P(), P(), P(), P(axis), P(axis), P(axis), P()),
            out_specs=(P(), P(), P(), P(axis), P(axis), P()),
            check_vma=False)
        return jax.jit(fn, donate_argnums=(0, 1, 2, 3, 4))

    def step(self, batch) -> float:
        """One optimizer step over a global batch (dim 0 sharded over the
        data axis)."""
        if self._step_fn is None:
            self._step_fn = {False: self._build_step(False),
                             True: self._build_step(True)}
        fn = self._step_fn[self.compression_active]
        (self.params, self.m, self.v, self.worker_error, self.server_error,
         loss) = fn(self.params, self.m, self.v, self.worker_error,
                    self.server_error, batch,
                    jnp.asarray(self.steps, jnp.int32))
        self.steps += 1
        return float(loss)


class OnebitLamb(OnebitAdam):
    """1-bit LAMB (reference runtime/fp16/onebit/lamb.py): LAMB's layer-wise
    trust-ratio update on top of the 1-bit momentum collective. Warmup runs
    dense LAMB; after ``freeze_step`` the variance freezes and the momentum
    travels through the error-compensated 1-bit allreduce. Trust ratio is
    recomputed per step from the live params/update and clamped to the
    reference's [min_coeff, max_coeff]."""

    def __init__(self, *args, max_coeff: float = 10.0, min_coeff: float = 0.01,
                 **kwargs):
        self.max_coeff = max_coeff
        self.min_coeff = min_coeff
        super().__init__(*args, **kwargs)

    def _apply_update(self, p, mm, vv, bc1, bc2):
        u = (mm / bc1) / (jnp.sqrt(vv / bc2) + self.eps)
        if self.weight_decay > 0:
            u = u + self.weight_decay * p.astype(jnp.float32)
        # layer-wise trust ratio (LAMB), clamped like the reference
        pn = jnp.sqrt(jnp.sum(jnp.square(p.astype(jnp.float32))))
        un = jnp.sqrt(jnp.sum(jnp.square(u)))
        ratio = jnp.where((pn > 0) & (un > 0),
                          jnp.clip(pn / un, self.min_coeff, self.max_coeff),
                          1.0)
        return (p - self.lr * ratio * u).astype(p.dtype)


class ZeroOneAdam:
    """0/1 Adam (reference runtime/fp16/onebit/zoadam.py): communication
    further reduced via LOCAL STEPS — the cross-replica sync runs only at
    exponentially-growing intervals; between syncs each replica updates
    from its local gradients with no collective at all.

    At a sync step the momentum goes through the error-compensated 1-bit
    collective and the params are mean-reconciled (one dense allreduce per
    interval — a deviation from the reference, which lets params drift
    until checkpoint time; reconciling at sync bounds the drift with
    amortized-negligible cost on ICI). The variance learns until
    ``var_freeze_step`` then freezes. Two separate compiled programs (local
    / sync) make the skipped communication real, not a masked-out branch.

    Knobs (reference parity): var_freeze_step, local_step_scaler,
    local_step_clipper — the sync interval starts at 1 and doubles every
    ``local_step_scaler`` steps, clipped to ``local_step_clipper``.
    """

    def __init__(self, loss_fn: Callable, params: Any, mesh: Mesh,
                 axis_name: str = "data", lr: float = 1e-3,
                 betas: Tuple[float, float] = (0.9, 0.999), eps: float = 1e-8,
                 weight_decay: float = 0.0, var_freeze_step: int = 100,
                 local_step_scaler: int = 100, local_step_clipper: int = 16):
        self.loss_fn = loss_fn
        self.mesh = mesh
        self.axis_name = axis_name
        self.lr = lr
        self.betas = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.var_freeze_step = var_freeze_step
        self.local_step_scaler = local_step_scaler
        self.local_step_clipper = local_step_clipper
        self.world = mesh.shape[axis_name]

        repl = NamedSharding(mesh, P())
        err_shard = NamedSharding(mesh, P(axis_name))
        self.params = jax.device_put(params, repl)
        zeros = lambda p: jnp.zeros(p.shape, jnp.float32)
        self.m = jax.device_put(jax.tree_util.tree_map(zeros, params), repl)
        self.v = jax.device_put(jax.tree_util.tree_map(zeros, params), repl)
        we, se = init_error_feedback(params, self.world)
        self.worker_error = jax.device_put(we, err_shard)
        self.server_error = jax.device_put(se, err_shard)
        self.steps = 0
        self.sync_steps = 0          # observability: collectives actually run
        self._next_sync = 0
        self._interval = 1
        self._last_double = 0        # step of the last interval doubling
        self._local_fn = None
        self._sync_fn = None
        log_dist(f"ZeroOneAdam: var_freeze={var_freeze_step} "
                 f"clipper={local_step_clipper} world={self.world}")

    def _adam_update(self, params, m, v, step):
        b1, b2 = self.betas
        t = (step + 1).astype(jnp.float32)
        bc1, bc2 = 1 - b1 ** t, 1 - b2 ** t

        def upd(p, mm, vv):
            u = (mm / bc1) / (jnp.sqrt(vv / bc2) + self.eps)
            if self.weight_decay > 0:
                u = u + self.weight_decay * p.astype(jnp.float32)
            return (p - self.lr * u).astype(p.dtype)

        return jax.tree_util.tree_map(upd, params, m, v)

    def _build(self, sync: bool):
        b1, b2 = self.betas
        axis, world = self.axis_name, self.world
        loss_fn = self.loss_fn
        var_freeze = self.var_freeze_step

        def spmd(params, m, v, we, se, batch, step):
            loss, grads = jax.value_and_grad(
                lambda p: loss_fn(p, batch, None))(params)
            m_new = jax.tree_util.tree_map(
                lambda mm, g: b1 * mm + (1 - b1) * g.astype(jnp.float32),
                m, grads)
            learn_var = step < var_freeze
            v_new = jax.tree_util.tree_map(
                lambda vv, g: jnp.where(
                    learn_var,
                    b2 * vv + (1 - b2) * jnp.square(g.astype(jnp.float32)),
                    vv),
                v, grads)
            if sync:
                m_new, we, se = tree_onebit_allreduce(m_new, we, se, axis, world)
                loss = jax.lax.pmean(loss, axis)
            params_new = self._adam_update(params, m_new, v_new, step)
            if sync:
                # bounded-drift reconciliation (see class docstring)
                params_new = jax.tree_util.tree_map(
                    lambda p: jax.lax.pmean(p.astype(jnp.float32), axis)
                    .astype(p.dtype), params_new)
            return params_new, m_new, v_new, we, se, loss

        fn = shard_map_compat(
            spmd, mesh=self.mesh, axis_names={axis},
            in_specs=(P(), P(), P(), P(axis), P(axis), P(axis), P()),
            out_specs=(P(), P(), P(), P(axis), P(axis), P()),
            check_vma=False)
        return jax.jit(fn, donate_argnums=(0, 1, 2, 3, 4))

    def step(self, batch) -> float:
        do_sync = self.steps >= self._next_sync
        if do_sync:
            # exponential local-step schedule (reference zoadam counters):
            # double once per local_step_scaler WINDOW (boundary-crossing
            # check — an exact-modulo test would stall whenever sync steps
            # drift off the scaler's phase)
            if self.steps - self._last_double >= self.local_step_scaler:
                self._interval = min(self._interval * 2,
                                     self.local_step_clipper)
                self._last_double = self.steps
            self._next_sync = self.steps + self._interval
            self.sync_steps += 1
            if self._sync_fn is None:
                self._sync_fn = self._build(sync=True)
            fn = self._sync_fn
        else:
            if self._local_fn is None:
                self._local_fn = self._build(sync=False)
            fn = self._local_fn
        (self.params, self.m, self.v, self.worker_error, self.server_error,
         loss) = fn(self.params, self.m, self.v, self.worker_error,
                    self.server_error, batch, jnp.asarray(self.steps, jnp.int32))
        self.steps += 1
        return float(loss)
