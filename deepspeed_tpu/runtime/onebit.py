"""1-bit optimizers: communication-compressed Adam / LAMB.

Reference surface: ``runtime/fp16/onebit/`` — OnebitAdam (adam.py:14),
OnebitLamb (lamb.py), ZeroOneAdam (zoadam.py), all built on the
error-compensated compressed allreduce in ``runtime/comm/nccl.py:51``.

Algorithm (1-bit Adam, NeurIPS'21): a dense warmup phase runs standard
Adam; after ``freeze_step`` the variance term is FROZEN and each step
communicates the *momentum* through the error-compensated 1-bit collective
(parallel/compressed.py) instead of dense gradients — ~25x smaller wire
volume for the dominant traffic.

TPU-first: the whole step (local grad, momentum update, compressed
collective, Adam math) is ONE jitted shard_map program; warmup/compressed
phases are a ``lax.cond``-free select on a step counter so a single
compiled program serves both phases.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.compressed import init_error_feedback, tree_onebit_allreduce
from ..utils.logging import log_dist


class OnebitAdam:
    """Self-contained data-parallel trainer with 1-bit Adam semantics.

    Reference-parity knobs: lr, betas, eps, weight_decay, freeze_step
    (warmup length before compression kicks in). ``cuda_aware``/``comm_
    backend_name`` from the reference have no TPU analog.
    """

    def __init__(self, loss_fn: Callable, params: Any, mesh: Mesh,
                 axis_name: str = "data", lr: float = 1e-3,
                 betas: Tuple[float, float] = (0.9, 0.999), eps: float = 1e-8,
                 weight_decay: float = 0.0, freeze_step: int = 100):
        self.loss_fn = loss_fn
        self.mesh = mesh
        self.axis_name = axis_name
        self.lr = lr
        self.betas = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.freeze_step = freeze_step
        self.world = mesh.shape[axis_name]

        repl = NamedSharding(mesh, P())
        err_shard = NamedSharding(mesh, P(axis_name))
        self.params = jax.device_put(params, repl)
        zeros = lambda p: jnp.zeros(p.shape, jnp.float32)
        self.m = jax.device_put(jax.tree_util.tree_map(zeros, params), repl)
        self.v = jax.device_put(jax.tree_util.tree_map(zeros, params), repl)
        we, se = init_error_feedback(params, self.world)
        self.worker_error = jax.device_put(we, err_shard)
        self.server_error = jax.device_put(se, err_shard)
        self.steps = 0
        self._step_fn = None
        log_dist(f"OnebitAdam: freeze_step={freeze_step} world={self.world}")

    @property
    def compression_active(self) -> bool:
        return self.steps >= self.freeze_step

    def _build_step(self):
        b1, b2 = self.betas
        eps, wd, lr = self.eps, self.weight_decay, self.lr
        axis, world = self.axis_name, self.world
        loss_fn = self.loss_fn
        freeze = self.freeze_step

        def spmd(params, m, v, we, se, batch, step):
            loss, grads = jax.value_and_grad(
                lambda p: loss_fn(p, batch, None))(params)
            loss = jax.lax.pmean(loss, axis)
            frozen = step >= freeze

            # dense path: average grads, classic Adam moment updates
            g_dense = jax.tree_util.tree_map(
                lambda g: jax.lax.pmean(g.astype(jnp.float32), axis), grads)
            m_dense = jax.tree_util.tree_map(
                lambda mm, g: b1 * mm + (1 - b1) * g, m, g_dense)
            v_dense = jax.tree_util.tree_map(
                lambda vv, g: b2 * vv + (1 - b2) * g * g, v, g_dense)

            # compressed path: local momentum update, 1-bit allreduce of it
            m_local = jax.tree_util.tree_map(
                lambda mm, g: b1 * mm + (1 - b1) * g.astype(jnp.float32),
                m, grads)
            m_comp, nwe, nse = tree_onebit_allreduce(m_local, we, se, axis, world)

            sel = lambda a, b: jnp.where(frozen, a, b)
            m_new = jax.tree_util.tree_map(sel, m_comp, m_dense)
            v_new = jax.tree_util.tree_map(sel, v, v_dense)  # frozen after warmup
            we_new = jax.tree_util.tree_map(sel, nwe, we)
            se_new = jax.tree_util.tree_map(sel, nse, se)

            t = (step + 1).astype(jnp.float32)
            bc1 = 1 - b1 ** t
            bc2 = 1 - b2 ** t

            def update(p, mm, vv):
                upd = (mm / bc1) / (jnp.sqrt(vv / bc2) + eps)
                if wd > 0:
                    upd = upd + wd * p
                return (p - lr * upd).astype(p.dtype)

            params_new = jax.tree_util.tree_map(update, params, m_new, v_new)
            return params_new, m_new, v_new, we_new, se_new, loss

        fn = jax.shard_map(
            spmd, mesh=self.mesh, axis_names={axis},
            in_specs=(P(), P(), P(), P(axis), P(axis), P(axis), P()),
            out_specs=(P(), P(), P(), P(axis), P(axis), P()),
            check_vma=False)
        return jax.jit(fn, donate_argnums=(0, 1, 2, 3, 4))

    def step(self, batch) -> float:
        """One optimizer step over a global batch (dim 0 sharded over the
        data axis)."""
        if self._step_fn is None:
            self._step_fn = self._build_step()
        (self.params, self.m, self.v, self.worker_error, self.server_error,
         loss) = self._step_fn(self.params, self.m, self.v, self.worker_error,
                               self.server_error, batch,
                               jnp.asarray(self.steps, jnp.int32))
        self.steps += 1
        return float(loss)
