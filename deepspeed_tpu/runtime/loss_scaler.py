"""Dynamic loss scaling for fp16.

Capability parity with the reference's ``runtime/fp16/loss_scaler.py:91``
(DynamicLossScaler: scale-up window, hysteresis backoff, min scale) rebuilt
as a pure pytree state + update function so the whole overflow check + scale
adjustment lives inside the jitted train step (the reference does a separate
allreduce of the overflow flag — stage3.py step; here the finite-check is a
fused reduction over gradient shards and needs no extra collective beyond
the psum XLA already inserts).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp


class LossScaleState(NamedTuple):
    scale: jnp.ndarray          # f32 current loss scale
    good_steps: jnp.ndarray     # i32 consecutive overflow-free steps
    hysteresis: jnp.ndarray     # i32 remaining tolerated overflows before backoff


def make_state(initial_scale_power: int = 16, hysteresis: int = 2) -> LossScaleState:
    return LossScaleState(
        scale=jnp.asarray(2.0 ** initial_scale_power, jnp.float32),
        good_steps=jnp.zeros([], jnp.int32),
        hysteresis=jnp.asarray(hysteresis, jnp.int32),
    )


def static_state(loss_scale: float) -> LossScaleState:
    return LossScaleState(
        scale=jnp.asarray(loss_scale, jnp.float32),
        good_steps=jnp.zeros([], jnp.int32),
        hysteresis=jnp.asarray(1 << 30, jnp.int32),
    )


def grads_finite(grads: Any) -> jnp.ndarray:
    """All-finite check over a gradient pytree (reference _has_inf_or_nan,
    stage3.py:2097, inverted)."""
    leaves = jax.tree_util.tree_leaves(grads)
    if not leaves:
        return jnp.asarray(True)
    per_leaf = [jnp.all(jnp.isfinite(g)) for g in leaves]
    return jnp.stack(per_leaf).all()


def update(state: LossScaleState, finite: jnp.ndarray, *,
           dynamic: bool = True, scale_window: int = 1000,
           scale_factor: float = 2.0, min_scale: float = 1.0,
           max_scale: float = 2.0 ** 24,
           consecutive_hysteresis: bool = False,
           init_hysteresis: int = 2) -> LossScaleState:
    """One scaler step. Mirrors DynamicLossScaler.update_scale
    (loss_scaler.py:91): on overflow consume hysteresis then halve; after
    ``scale_window`` clean steps double."""
    if not dynamic:
        return state

    def on_overflow(s: LossScaleState) -> LossScaleState:
        hys = s.hysteresis - 1
        backoff = hys <= 0
        new_scale = jnp.where(backoff, jnp.maximum(s.scale / scale_factor, min_scale), s.scale)
        new_hys = jnp.where(backoff, jnp.asarray(init_hysteresis, jnp.int32), hys)
        return LossScaleState(scale=new_scale, good_steps=jnp.zeros([], jnp.int32), hysteresis=new_hys)

    def on_clean(s: LossScaleState) -> LossScaleState:
        good = s.good_steps + 1
        grow = good >= scale_window
        new_scale = jnp.where(grow, jnp.minimum(s.scale * scale_factor, max_scale), s.scale)
        new_good = jnp.where(grow, jnp.zeros([], jnp.int32), good)
        new_hys = jnp.asarray(init_hysteresis, jnp.int32) if consecutive_hysteresis else s.hysteresis
        return LossScaleState(scale=new_scale, good_steps=new_good, hysteresis=new_hys)

    return jax.tree_util.tree_map(
        lambda a, b: jnp.where(finite, a, b),
        on_clean(state), on_overflow(state),
    )
