"""Sharded checkpoint save/load.

Capability parity with the reference's checkpoint stack:
  - engine save/load (``runtime/engine.py:3010 save_checkpoint`` /
    ``:2661 load_checkpoint``) with tag files and tag validation,
  - the ``latest`` tag pointer (engine.py:3206),
  - universal (topology-independent) checkpoints
    (``deepspeed/checkpoint/ds_to_universal.py``) — here the *native* layout
    is already topology-independent: every leaf is written as a full logical
    array (orbax/tensorstore handles the per-shard IO), so reloading onto a
    different mesh/ZeRO stage is just a different ``jax.device_put``. What
    the reference needs an offline converter for, this framework gets from
    GSPMD placement being separate from storage layout.
  - ``zero_to_fp32``-style full-precision consolidation
    (:meth:`consolidate_full_state`), parity with
    deepspeed/utils/zero_to_fp32.py and engine._zero3_consolidated_16bit_state_dict
    (engine.py:3423).

The checkpoint-engine abstraction (reference
runtime/checkpoint_engine/checkpoint_engine.py:9) maps to orbax's
Checkpointer; async save (NebulaCheckpointEngine parity) uses orbax's async
path when ``checkpoint.async_save`` is on.
"""

from __future__ import annotations

import json
import os
import shutil
from typing import Any, Dict, Optional

import jax
import numpy as np

from ..utils.logging import log_dist, logger

LATEST_FILE = "latest"


def _ckpt_dir(save_dir: str, tag: str) -> str:
    return os.path.join(save_dir, str(tag))


def _is_multiprocess() -> bool:
    return jax.process_count() > 1


class CheckpointEngine:
    """Orbax-backed sharded checkpoint engine.

    Layout under ``save_dir/tag/``:
      state/      — orbax tree of {params, opt_state, scaler, step, ...}
      meta.json   — config snapshot + pytree structure info + client state
    ``save_dir/latest`` holds the most recent tag (reference engine.py:3206).
    """

    def __init__(self, async_save: bool = False):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self._async_save = async_save
        self._ckptr = ocp.StandardCheckpointer() if not async_save else ocp.AsyncCheckpointer(
            ocp.StandardCheckpointHandler())

    # ------------------------------------------------------------------
    def save(self, save_dir: str, tag: str, state: Dict[str, Any],
             client_state: Optional[Dict[str, Any]] = None,
             config_snapshot: Optional[Dict[str, Any]] = None) -> str:
        path = _ckpt_dir(save_dir, tag)
        os.makedirs(save_dir, exist_ok=True)
        state_path = os.path.join(path, "state")
        if os.path.exists(state_path):
            shutil.rmtree(state_path)
        os.makedirs(path, exist_ok=True)
        self._ckptr.save(os.path.abspath(state_path), state)
        # orbax may finalize in the background even on the "sync" path (the
        # state dir appears as *.orbax-checkpoint-tmp until renamed) — wait
        # so callers can read the checkpoint immediately after save()
        if hasattr(self._ckptr, "wait_until_finished"):
            self._ckptr.wait_until_finished()
        import time as _time

        for _ in range(600):
            if os.path.isdir(state_path):
                break
            _time.sleep(0.05)
        else:
            raise RuntimeError(f"checkpoint finalize timed out: {state_path}")
        meta = {
            "tag": tag,
            "client_state": client_state or {},
            "config": config_snapshot or {},
            "version": 1,
        }
        if jax.process_index() == 0:
            with open(os.path.join(path, "meta.json"), "w") as f:
                json.dump(meta, f, indent=2, default=str)
            with open(os.path.join(save_dir, LATEST_FILE), "w") as f:
                f.write(str(tag))
        log_dist(f"Saved checkpoint {path}")
        return path

    # ------------------------------------------------------------------
    def load(self, load_dir: str, tag: Optional[str] = None,
             template: Optional[Any] = None) -> Optional[Dict[str, Any]]:
        """Restore. ``template`` is a pytree of ShapeDtypeStruct (or arrays)
        with target shardings — loading re-places shards for the *current*
        mesh, which is the universal-checkpoint reshape path."""
        if tag is None:
            latest = os.path.join(load_dir, LATEST_FILE)
            if not os.path.isfile(latest):
                logger.warning(f"No '{LATEST_FILE}' file in {load_dir}; nothing to load")
                return None
            with open(latest) as f:
                tag = f.read().strip()
        path = _ckpt_dir(load_dir, tag)
        state_path = os.path.join(path, "state")
        if not os.path.isdir(state_path):
            logger.warning(f"Checkpoint dir {state_path} not found")
            return None
        if template is not None:
            restored = self._ckptr.restore(os.path.abspath(state_path), target=template)
        else:
            restored = self._ckptr.restore(os.path.abspath(state_path))
        meta_path = os.path.join(path, "meta.json")
        meta = {}
        if os.path.isfile(meta_path):
            with open(meta_path) as f:
                meta = json.load(f)
        log_dist(f"Loaded checkpoint {path}")
        return {"state": restored, "meta": meta, "tag": tag}


def validate_tag_consistency(tag: str, mode: str = "Warn") -> None:
    """Tag validation across processes (reference engine._checkpoint_tag_validation
    engine.py:2993). With a single JAX controller tags cannot diverge; in
    multi-process mode we broadcast-and-compare via a host callback."""
    if not _is_multiprocess() or mode == "Ignore":
        return
    from jax.experimental import multihost_utils

    try:
        arr = np.frombuffer(tag.encode()[:64].ljust(64, b"\0"), dtype=np.uint8).copy()
        agreed = multihost_utils.broadcast_one_to_all(arr)
        if not np.array_equal(arr, agreed):
            msg = f"Checkpoint tag '{tag}' differs across processes"
            if mode == "Fail":
                raise RuntimeError(msg)
            logger.warning(msg)
    except Exception as e:  # pragma: no cover - defensive on exotic backends
        logger.warning(f"tag validation skipped: {e}")


def consolidate_full_state(params: Any, dtype=None) -> Any:
    """Gather a (possibly sharded) param tree into host numpy arrays —
    parity with zero_to_fp32 / save_16bit_model (engine.py:3492)."""
    def to_host(x):
        arr = np.asarray(jax.device_get(x))
        return arr.astype(dtype) if dtype is not None else arr

    return jax.tree_util.tree_map(to_host, params)
