"""Sharded checkpoint save/load with a crash-safe commit protocol.

Capability parity with the reference's checkpoint stack:
  - engine save/load (``runtime/engine.py:3010 save_checkpoint`` /
    ``:2661 load_checkpoint``) with tag files and tag validation,
  - the ``latest`` tag pointer (engine.py:3206),
  - universal (topology-independent) checkpoints
    (``deepspeed/checkpoint/ds_to_universal.py``) — here the *native* layout
    is already topology-independent: every leaf is written as a full logical
    array (orbax/tensorstore handles the per-shard IO), so reloading onto a
    different mesh/ZeRO stage is just a different ``jax.device_put``. What
    the reference needs an offline converter for, this framework gets from
    GSPMD placement being separate from storage layout.
  - ``zero_to_fp32``-style full-precision consolidation
    (:meth:`consolidate_full_state`), parity with
    deepspeed/utils/zero_to_fp32.py and engine._zero3_consolidated_16bit_state_dict
    (engine.py:3423).

Crash safety (docs/fault_tolerance.md) — a preemption can land at any byte
of a save, so every tag follows a write-to-temp -> fsync -> atomic-rename
commit protocol:

  1. the whole tag (orbax state tree, meta.json) is assembled under
     ``save_dir/.tmp-<tag>-<pid>``, invisible to every reader;
  2. a ``manifest.json`` of per-file CRC32 checksums and sizes is written,
     then a ``COMMITTED`` marker, each fsynced;
  3. one ``os.rename`` publishes the tag — the only mutation a reader can
     ever observe is the atomic appearance of a complete, checksummed tag.

``load`` verifies the marker + manifest and, when a tag is torn, corrupted
or uncommitted, falls back to the newest valid tag (commit-time order —
robust even when a crash landed between commit and the ``latest`` pointer
update). ``keep_last_n`` garbage collection removes old *valid* tags and
never deletes the only one. The ``latest`` pointer itself is written by
rank 0 only, after a cross-process barrier, via temp-file + rename.

The checkpoint-engine abstraction (reference
runtime/checkpoint_engine/checkpoint_engine.py:9) maps to orbax's
Checkpointer; async save (NebulaCheckpointEngine parity) uses orbax's async
path when ``checkpoint.async_save`` is on.
"""

from __future__ import annotations

import json
import os
import shutil
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from ..resilience.retry import RetryBudget, RetryPolicy, retry_call
from ..utils.fileio import fsync_dir as _fsync_dir
from ..utils.fileio import write_json_atomic
from ..utils.logging import log_dist, logger

LATEST_FILE = "latest"
COMMITTED_FILE = "COMMITTED"
MANIFEST_FILE = "manifest.json"
TMP_PREFIX = ".tmp-"

# filesystem ops around a save/load hit GCS/NFS-style flakes in production;
# short jittered retries absorb them (resilience/retry.py). Each save/load
# operation shares ONE RetryBudget across its several fs ops, so a
# persistently degraded backend fails the operation promptly instead of
# stretching every sub-op to its per-call maximum.
_FS_RETRY = RetryPolicy(max_attempts=3, backoff_s=0.2, max_backoff_s=2.0,
                        jitter=0.5)
_FS_BUDGET_PER_OP = 6


def _ckpt_dir(save_dir: str, tag: str) -> str:
    return os.path.join(save_dir, str(tag))


def _is_multiprocess() -> bool:
    return jax.process_count() > 1


def _chaos():
    """The installed fault injector, or None (resilience/chaos.py)."""
    from ..resilience.chaos import get_fault_injector

    return get_fault_injector()


# ----------------------------------------------------------------------
# durable small-file IO

def _write_json_durable(path: str, obj: Any) -> None:
    """Commit-protocol JSON: temp + fsync + atomic rename."""
    write_json_atomic(path, obj, fsync=True, indent=2)


def _file_crc32(path: str) -> int:
    crc = 0
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            crc = zlib.crc32(chunk, crc)
    return crc & 0xFFFFFFFF


def _walk_files(root: str) -> List[str]:
    """Relative paths of every regular file under ``root``, sorted."""
    out = []
    for dirpath, _dirnames, filenames in os.walk(root):
        for name in filenames:
            full = os.path.join(dirpath, name)
            out.append(os.path.relpath(full, root))
    return sorted(out)


# ----------------------------------------------------------------------
# tag validity

def build_manifest(path: str) -> Dict[str, Any]:
    """Per-file checksum manifest over everything currently in ``path``
    (the manifest and marker themselves excluded)."""
    files: Dict[str, Dict[str, Any]] = {}
    for rel in _walk_files(path):
        if rel in (MANIFEST_FILE, COMMITTED_FILE):
            continue
        full = os.path.join(path, rel)
        files[rel] = {"size": os.path.getsize(full),
                      "crc32": _file_crc32(full)}
    return {"version": 1, "files": files}


def verify_tag(path: str, checksums: bool = True) -> Tuple[bool, str]:
    """Is the tag at ``path`` a complete, committed checkpoint?

    Returns (ok, reason). A tag dir written before this commit protocol
    existed (state/ + meta.json, no marker) is accepted as legacy — the
    atomic rename guarantees any *new-protocol* tag at its final path is
    complete, so a markerless dir cannot be a torn new-protocol save.
    """
    if not os.path.isdir(path):
        return False, "missing"
    committed = os.path.join(path, COMMITTED_FILE)
    manifest_path = os.path.join(path, MANIFEST_FILE)
    if not os.path.isfile(committed):
        if os.path.isdir(os.path.join(path, "state")):
            logger.warning(f"checkpoint {path}: pre-protocol tag (no "
                           f"{COMMITTED_FILE} marker) — accepting as legacy")
            return True, "legacy"
        return False, f"no {COMMITTED_FILE} marker and no state dir"
    if not os.path.isfile(manifest_path):
        return False, f"{COMMITTED_FILE} present but {MANIFEST_FILE} missing"
    try:
        with open(manifest_path) as f:
            manifest = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        return False, f"unreadable manifest: {e}"
    for rel, info in manifest.get("files", {}).items():
        full = os.path.join(path, rel)
        if not os.path.isfile(full):
            return False, f"missing file {rel}"
        if os.path.getsize(full) != info["size"]:
            return False, (f"size mismatch for {rel}: "
                           f"{os.path.getsize(full)} != {info['size']}")
        if checksums and _file_crc32(full) != info["crc32"]:
            return False, f"checksum mismatch for {rel}"
    return True, "ok"


def _commit_time(path: str) -> float:
    marker = os.path.join(path, COMMITTED_FILE)
    try:
        with open(marker) as f:
            return float(json.load(f).get("time", 0.0))
    except (OSError, ValueError, json.JSONDecodeError):
        try:
            return os.path.getmtime(path)
        except OSError:
            return 0.0


def list_tags(save_dir: str) -> List[str]:
    """Candidate tags under ``save_dir``, newest committed first (commit
    time from the COMMITTED marker; dir mtime for legacy tags). Temp dirs
    are never candidates."""
    if not os.path.isdir(save_dir):
        return []
    cands = []
    for name in os.listdir(save_dir):
        if name.startswith(TMP_PREFIX) or name == LATEST_FILE:
            continue
        path = os.path.join(save_dir, name)
        if os.path.isdir(path):
            cands.append((_commit_time(path), name))
    return [name for _t, name in sorted(cands, reverse=True)]


def tag_model_version(path: str) -> Optional[int]:
    """The ``model_version`` a tag's meta records (None for tags saved
    before the field existed, or with no version stamped). ``path`` is
    the tag directory — pair with :func:`verify_tag`/:func:`find_valid_tag`;
    this reads identity only, it does not validate."""
    meta_path = os.path.join(path, "meta.json")
    try:
        with open(meta_path) as f:
            v = json.load(f).get("model_version")
    except (OSError, ValueError, json.JSONDecodeError):
        return None
    return int(v) if v is not None else None


def find_valid_tag(save_dir: str, checksums: bool = True) -> Optional[str]:
    """Newest tag that passes :func:`verify_tag`. Scans commit-time order
    rather than trusting the ``latest`` pointer — a crash between commit
    and pointer update leaves the pointer stale, not the data."""
    for tag in list_tags(save_dir):
        ok, reason = verify_tag(_ckpt_dir(save_dir, tag), checksums=checksums)
        if ok:
            return tag
        logger.warning(f"checkpoint tag '{tag}' skipped: {reason}")
    return None


class CheckpointEngine:
    """Orbax-backed sharded checkpoint engine with atomic commits.

    Layout under ``save_dir/tag/``:
      state/         — orbax tree of {params, opt_state, scaler, step, ...}
      meta.json      — config snapshot + pytree structure info + client state
      manifest.json  — per-file {size, crc32} over state/ + meta.json
      COMMITTED      — commit marker {tag, time, n_files}
    ``save_dir/latest`` holds the most recent tag (reference engine.py:3206),
    written by rank 0 after a barrier, via temp + rename.
    """

    def __init__(self, async_save: bool = False, keep_last_n: int = 0,
                 verify_checksums: bool = True):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self._async_save = async_save
        self.keep_last_n = int(keep_last_n)
        self.verify_checksums = bool(verify_checksums)
        self._ckptr = ocp.StandardCheckpointer() if not async_save else ocp.AsyncCheckpointer(
            ocp.StandardCheckpointHandler())
        # tags are immutable once committed: path -> commit time verified
        # OK, so GC never re-checksums a tag it (or save) already verified
        self._verified: Dict[str, float] = {}

    @staticmethod
    def _barrier() -> None:
        if _is_multiprocess():
            from ..comm.comm import barrier

            barrier()

    # ------------------------------------------------------------------
    def _write_state(self, state_path: str, state: Dict[str, Any]) -> None:
        if os.path.exists(state_path):
            shutil.rmtree(state_path)
        self._ckptr.save(os.path.abspath(state_path), state)
        # orbax may finalize in the background even on the "sync" path (the
        # state dir appears as *.orbax-checkpoint-tmp until renamed) — wait
        # so the manifest below hashes the finalized files
        if hasattr(self._ckptr, "wait_until_finished"):
            self._ckptr.wait_until_finished()
        for _ in range(600):
            if os.path.isdir(state_path):
                return
            time.sleep(0.05)
        raise RuntimeError(f"checkpoint finalize timed out: {state_path}")

    @staticmethod
    def _clean_stale_tmp(save_dir: str) -> None:
        """Remove temp dirs abandoned by crashed saves (they are the torn
        checkpoints this protocol turns into harmless garbage)."""
        for name in os.listdir(save_dir):
            if name.startswith(TMP_PREFIX):
                logger.warning(f"removing stale checkpoint temp dir {name}")
                shutil.rmtree(os.path.join(save_dir, name),
                              ignore_errors=True)

    def save(self, save_dir: str, tag: str, state: Dict[str, Any],
             client_state: Optional[Dict[str, Any]] = None,
             config_snapshot: Optional[Dict[str, Any]] = None,
             model_version: Optional[int] = None) -> str:
        tag = str(tag)
        os.makedirs(save_dir, exist_ok=True)
        rank0 = jax.process_index() == 0
        final = _ckpt_dir(save_dir, tag)
        # ONE shared temp dir across processes (orbax's multihost save is
        # collective — every rank's shards must land in the directory the
        # commit below publishes); rank 0 prepares it, a barrier keeps
        # other ranks from writing into a dir being cleaned
        tmp = os.path.join(save_dir, f"{TMP_PREFIX}{tag}")
        if rank0:
            self._clean_stale_tmp(save_dir)
            os.makedirs(tmp, exist_ok=True)
        self._barrier()
        state_path = os.path.join(tmp, "state")
        budget = RetryBudget(_FS_BUDGET_PER_OP)
        if _is_multiprocess():
            # the orbax multihost save is COLLECTIVE: one rank retrying it
            # alone would desynchronize the processes and hang the barrier
            # below — a failed collective write needs a restart, not a
            # retry (resilience/retry.py's own contract)
            self._write_state(state_path, state)
        else:
            retry_call(self._write_state, state_path, state,
                       policy=_FS_RETRY, op="checkpoint_save", budget=budget)
        meta = {
            "tag": tag,
            "client_state": client_state or {},
            "config": config_snapshot or {},
            "version": 2,
        }
        if model_version is not None:
            # rollout identity (serving/rollout.py): which MODEL version
            # these weights are — hot_swap_checkpoint reads it back so a
            # weight flip stamps the replica with the version it actually
            # loaded, not the version it was told to expect. Optional
            # field, not a meta version bump (same discipline as the
            # telemetry record schemas).
            meta["model_version"] = int(model_version)
        if rank0:
            retry_call(_write_json_durable, os.path.join(tmp, "meta.json"),
                       meta, policy=_FS_RETRY, op="checkpoint_fs",
                       budget=budget)
        # every rank's shards must be durable before rank 0 hashes them
        # into the manifest and publishes the tag
        self._barrier()

        inj = _chaos()
        if inj is not None:
            inj.on_save_phase("before_commit", tag)

        if rank0:
            self._commit(tmp, final, budget)
        self._barrier()

        corrupted = False
        if inj is not None:
            # a crash here lands AFTER the durable commit: the tag must
            # survive and auto-resume must find it even though the latest
            # pointer below was never updated
            inj.on_save_phase("after_commit", tag)
            corrupted = inj.maybe_corrupt(final)
        if rank0 and not corrupted:
            # the just-committed tag was hashed while building its
            # manifest — remember it as verified so GC never re-reads it
            # (seeded only after the chaos window: an injected corruption
            # must not ride the memo past GC's checksum gate)
            self._verified[final] = _commit_time(final)

        # 'latest' pointer: rank 0 only, after the barrier above (every
        # process has finished its shard writes), via temp + atomic rename —
        # a crash mid-update can no longer leave a truncated pointer
        if rank0:
            retry_call(self._write_latest, save_dir, tag,
                       policy=_FS_RETRY, op="checkpoint_fs", budget=budget)
            self._gc(save_dir, just_saved=tag)
        from ..telemetry.registry import get_registry

        get_registry().counter("checkpoint/saves").inc()
        log_dist(f"Saved checkpoint {final} (committed)")
        return final

    def _commit(self, tmp: str, final: str,
                budget: Optional[RetryBudget] = None) -> None:
        """Manifest + marker + fsync + atomic publish."""
        manifest = build_manifest(tmp)
        retry_call(_write_json_durable, os.path.join(tmp, MANIFEST_FILE),
                   manifest, policy=_FS_RETRY, op="checkpoint_fs",
                   budget=budget)
        marker = {"tag": os.path.basename(final), "time": time.time(),
                  "n_files": len(manifest["files"])}
        retry_call(_write_json_durable, os.path.join(tmp, COMMITTED_FILE),
                   marker, policy=_FS_RETRY, op="checkpoint_fs",
                   budget=budget)
        _fsync_dir(tmp)
        trash = None
        if os.path.exists(final):
            # replacing an existing tag: move the old one aside first (the
            # new tag is already complete in tmp, so no crash window loses
            # both), publish, then drop the old. The trash name carries
            # TMP_PREFIX so a crash before the rmtree leaves it invisible
            # to list_tags/GC and _clean_stale_tmp reaps it next save.
            trash = os.path.join(
                os.path.dirname(final) or ".",
                f"{TMP_PREFIX}{os.path.basename(final)}-replaced")
            shutil.rmtree(trash, ignore_errors=True)
            os.rename(final, trash)
        os.rename(tmp, final)
        _fsync_dir(os.path.dirname(final) or ".")
        if trash is not None:
            shutil.rmtree(trash, ignore_errors=True)

    @staticmethod
    def _write_latest(save_dir: str, tag: str) -> None:
        tmp = os.path.join(save_dir, LATEST_FILE + ".tmp")
        with open(tmp, "w") as f:
            f.write(str(tag))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(save_dir, LATEST_FILE))

    def _verified_for_keep(self, path: str) -> bool:
        """Checksum verification with a memo: committed tags are immutable,
        so a tag this engine verified once (or just wrote — save() seeds
        the memo from the manifest it built) is never re-read. Keeps the
        per-save GC cost at one checksum pass per NEW tag, not
        keep_last_n x checkpoint-size of read I/O every save."""
        t = _commit_time(path)
        if self._verified.get(path) == t and t > 0:
            return True
        ok = verify_tag(path, checksums=self.verify_checksums)[0]
        if ok:
            self._verified[path] = t
        return ok

    def _gc(self, save_dir: str, just_saved: Optional[str] = None) -> None:
        """Keep the newest ``keep_last_n`` *valid* tags. The tags being
        counted toward the keep quota are CHECKSUM-verified, memoized (a
        bit-flipped tag must not pass for the last good checkpoint and
        license deleting the real one); invalid tags are left in place as
        evidence; the only valid checkpoint is never deleted regardless
        of config."""
        if self.keep_last_n <= 0:
            return
        keep = max(self.keep_last_n, 1)
        confirmed = 0
        doomed: List[str] = []
        for tag in list_tags(save_dir):
            path = _ckpt_dir(save_dir, tag)
            if confirmed < keep:
                if self._verified_for_keep(path):
                    confirmed += 1
                # invalid within the keep window: skip, keep scanning
            elif verify_tag(path, checksums=False)[0]:
                doomed.append(tag)
        if confirmed == 0:
            return  # nothing verified: touch nothing
        for tag in doomed:
            if tag == just_saved:  # paranoia: never GC the tag just written
                continue
            logger.info(f"checkpoint GC: removing old tag '{tag}'")
            self._verified.pop(_ckpt_dir(save_dir, tag), None)
            shutil.rmtree(_ckpt_dir(save_dir, tag), ignore_errors=True)
            from ..telemetry.registry import get_registry

            get_registry().counter("checkpoint/gc_removed").inc()

    # ------------------------------------------------------------------
    def load(self, load_dir: str, tag: Optional[str] = None,
             template: Optional[Any] = None) -> Optional[Dict[str, Any]]:
        """Restore. ``template`` is a pytree of ShapeDtypeStruct (or arrays)
        with target shardings — loading re-places shards for the *current*
        mesh, which is the universal-checkpoint reshape path.

        With ``tag=None`` the newest valid tag is chosen (torn, corrupted
        and uncommitted tags are verified against their manifest and
        skipped). An explicit ``tag`` that fails verification returns None
        — no silent substitution when the caller asked for a specific one.
        """
        if not os.path.isdir(load_dir):
            logger.warning(f"checkpoint dir {load_dir} not found; nothing to load")
            return None
        if tag is not None:
            ok, reason = verify_tag(_ckpt_dir(load_dir, str(tag)),
                                    checksums=self.verify_checksums)
            if not ok:
                logger.warning(f"checkpoint tag '{tag}' invalid: {reason}")
                from ..telemetry.registry import get_registry

                get_registry().counter("checkpoint/invalid_tags").inc()
                return None
            return self._restore(load_dir, str(tag), template)
        chosen = find_valid_tag(load_dir, checksums=self.verify_checksums)
        if chosen is None:
            logger.warning(f"no valid checkpoint tag in {load_dir}")
            return None
        return self._restore(load_dir, chosen, template)

    def _restore(self, load_dir: str, tag: str,
                 template: Optional[Any]) -> Optional[Dict[str, Any]]:
        path = _ckpt_dir(load_dir, tag)
        state_path = os.path.join(path, "state")
        if not os.path.isdir(state_path):
            logger.warning(f"Checkpoint dir {state_path} not found")
            return None

        def _do_restore():
            if template is not None:
                return self._ckptr.restore(os.path.abspath(state_path),
                                           target=template)
            return self._ckptr.restore(os.path.abspath(state_path))

        restored = retry_call(_do_restore, policy=_FS_RETRY,
                              op="checkpoint_load",
                              budget=RetryBudget(_FS_BUDGET_PER_OP))
        meta_path = os.path.join(path, "meta.json")
        meta = {}
        if os.path.isfile(meta_path):
            with open(meta_path) as f:
                meta = json.load(f)
        log_dist(f"Loaded checkpoint {path}")
        return {"state": restored, "meta": meta, "tag": tag}


def validate_tag_consistency(tag: str, mode: str = "Warn") -> None:
    """Tag validation across processes (reference engine._checkpoint_tag_validation
    engine.py:2993). With a single JAX controller tags cannot diverge; in
    multi-process mode we broadcast-and-compare via a host callback."""
    if not _is_multiprocess() or mode == "Ignore":
        return
    from jax.experimental import multihost_utils

    try:
        arr = np.frombuffer(tag.encode()[:64].ljust(64, b"\0"), dtype=np.uint8).copy()
        agreed = multihost_utils.broadcast_one_to_all(arr)
        if not np.array_equal(arr, agreed):
            msg = f"Checkpoint tag '{tag}' differs across processes"
            if mode == "Fail":
                raise RuntimeError(msg)
            logger.warning(msg)
    except Exception as e:  # pragma: no cover - defensive on exotic backends
        logger.warning(f"tag validation skipped: {e}")


def consolidate_full_state(params: Any, dtype=None) -> Any:
    """Gather a (possibly sharded) param tree into host numpy arrays —
    parity with zero_to_fp32 / save_16bit_model (engine.py:3492)."""
    def to_host(x):
        arr = np.asarray(jax.device_get(x))
        return arr.astype(dtype) if dtype is not None else arr

    return jax.tree_util.tree_map(to_host, params)
