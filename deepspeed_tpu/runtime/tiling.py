"""Tiled linear layers (reference runtime/zero/tiling.py — TiledLinear
splits one huge matmul into in/out-feature tiles so ZeRO-3 can fetch one
tile's params at a time).

On TPU the memory motivation maps to sharding, not manual tiling — a big
linear is sharded over the ``model`` axis and GSPMD streams it — but the
capability is preserved for parity and for the genuinely-huge-single-layer
case (embedding/vocab projections beyond one core's HBM): the tile loop is
a ``lax.map`` over parameter slices, so only one tile's output is live at a
time and remat keeps backward memory flat.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class TiledLinear:
    """init/apply functional layer computing x @ W + b with W stored as
    [out_splits, in_splits, in/in_splits, out/out_splits].

    Reference parity: in_splits/out_splits args, input_is_already_split /
    combine_out_splits behaviors (TiledLinear forward, tiling.py).
    """

    def __init__(self, in_features: int, out_features: int,
                 in_splits: int = 1, out_splits: int = 1, use_bias: bool = True):
        assert in_features % in_splits == 0, (in_features, in_splits)
        assert out_features % out_splits == 0, (out_features, out_splits)
        self.in_features = in_features
        self.out_features = out_features
        self.in_splits = in_splits
        self.out_splits = out_splits
        self.use_bias = use_bias

    def init(self, rng) -> Dict[str, Any]:
        w = jax.random.normal(
            rng, (self.out_splits, self.in_splits,
                  self.in_features // self.in_splits,
                  self.out_features // self.out_splits),
            jnp.float32) / np.sqrt(self.in_features)
        params = {"w": w}
        if self.use_bias:
            params["b"] = jnp.zeros((self.out_features,), jnp.float32)
        return params

    def apply(self, params, x, input_is_already_split: bool = False,
              combine_out_splits: bool = True):
        """x: [..., in_features] (or a tuple of in_splits chunks)."""
        if input_is_already_split:
            xs = jnp.stack(x, axis=0)  # [in_splits, ..., in/in_splits]
        else:
            xs = jnp.stack(jnp.split(x, self.in_splits, axis=-1), axis=0)

        def out_tile(w_out):  # w_out: [in_splits, in_t, out_t]
            # sum over input tiles; lax.map keeps one tile live at a time
            def in_tile(acc_w):
                acc, (w, xt) = acc_w
                return acc + xt @ w

            parts = jax.vmap(lambda w, xt: xt @ w)(w_out, xs)  # [in_splits, ..., out_t]
            return jnp.sum(parts, axis=0)

        outs = jax.lax.map(out_tile, params["w"])  # [out_splits, ..., out_t]
        if combine_out_splits:
            out = jnp.concatenate(list(outs), axis=-1)
            if self.use_bias:
                out = out + params["b"]
            return out
        return [outs[i] for i in range(self.out_splits)]

    def full_weight(self, params) -> jnp.ndarray:
        """Reassemble [in_features, out_features] (reference
        copy_params_from inverse)."""
        w = params["w"]  # [os, is, in_t, out_t]
        return jnp.concatenate(
            [jnp.concatenate(list(w[o]), axis=0) for o in range(self.out_splits)],
            axis=1)
