"""Training engine.

Capability parity with the reference's ``DeepSpeedEngine``
(``runtime/engine.py:175`` — forward :1761 / backward :1902 / step :2100,
gradient accumulation, allreduce, mixed precision, checkpoint save/load,
monitor + timer integration), redesigned TPU-first:

* The fwd/bwd/step trio and all of ZeRO's hook machinery compile into ONE
  jitted, donated ``train_step`` containing a ``lax.scan`` over gradient-
  accumulation microbatches, gradient sharding constraints (ZeRO), global-
  norm clipping, loss scaling, and the fused optimizer update. XLA inserts
  and overlaps every collective the reference issues by hand.
* DeepSpeed's imperative micro-batch API (``forward``/``backward``/``step``
  per microbatch with ``is_gradient_accumulation_boundary``) is preserved as
  a compatibility path that accumulates gradient shards across jitted calls
  and applies the same update at the boundary.
* ZeRO stages 0-3 are placement policies from ``parallel/zero.py`` — there
  is no separate optimizer wrapper class per stage (reference
  stage_1_and_2.py / stage3.py / bf16_optimizer.py / fused_optimizer.py all
  collapse here).

Mixed precision follows the BF16_Optimizer design (reference
runtime/bf16_optimizer.py:30): fp32 master params live in the (ZeRO-sharded)
param tree; compute casts to bf16/fp16 at the loss-fn boundary. fp16 adds
dynamic loss scaling (runtime/fp16/loss_scaler.py parity in
``runtime/loss_scaler.py``).
"""

from __future__ import annotations

import os
import threading
import time
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from ..config import Config
from ..parallel.mesh import Topology
from ..parallel.zero import ZeroShardingRules
from ..utils.logging import log_dist, logger
from ..utils.timer import SynchronizedWallClockTimer, ThroughputTimer
from . import loss_scaler as ls
from .checkpoint import CheckpointEngine, consolidate_full_state, validate_tag_consistency
from .lr_schedules import Schedule, build_schedule, constant_lr
from .optimizers import Transform, as_transform, build_optimizer

LossFn = Callable[..., Any]  # (params, batch, rng) -> loss | (loss, aux)


def _normalize_loss_fn(loss_fn: LossFn) -> Callable[[Any, Any, Any], Tuple[Any, Dict[str, Any]]]:
    def wrapped(params, batch, rng):
        out = loss_fn(params, batch, rng)
        if isinstance(out, tuple):
            loss, aux = out
            if not isinstance(aux, dict):
                aux = {"aux": aux}
        else:
            loss, aux = out, {}
        return loss, aux

    return wrapped


def _cast_tree(tree: Any, dtype) -> Any:
    def cast(x):
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(dtype)
        return x

    return jax.tree_util.tree_map(cast, tree)


def _jit_cache_size(fn: Any) -> int:
    """Compiled-entry count of a jitted callable (0 when unbuilt or the
    running JAX hides the counter)."""
    if fn is None:
        return 0
    try:
        return int(fn._cache_size())
    except Exception:
        return 0


def _batch_abstract(batch: Any) -> Any:
    """ShapeDtypeStruct tree for AOT lowering: jax.Arrays keep their
    sharding, ShapeDtypeStructs pass through, host arrays lower with
    unspecified placement."""
    def leaf(x):
        if isinstance(x, jax.ShapeDtypeStruct):
            return x
        sharding = getattr(x, "sharding", None)
        x = np.asarray(x) if not hasattr(x, "shape") else x
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sharding)

    return jax.tree_util.tree_map(leaf, batch)


def _batch_signature(batch: Any) -> tuple:
    """Hashable (shape, dtype) signature of a batch pytree — the part of
    the jit cache key a dataloader can change between steps."""
    return tuple(
        (tuple(getattr(x, "shape", ())), str(getattr(x, "dtype", type(x).__name__)))
        for x in jax.tree_util.tree_leaves(batch))


def host_memory_kind() -> str:
    """The host memory space name for offload shardings. Accelerator
    backends expose ``pinned_host``; the CPU backend (and some older
    runtimes) only ``unpinned_host`` — probing keeps ZeRO-Offload
    functional on both instead of silently disabling itself."""
    try:
        kinds = {m.kind for m in jax.devices()[0].addressable_memories()}
    except Exception:
        return "pinned_host"
    for kind in ("pinned_host", "unpinned_host"):
        if kind in kinds:
            return kind
    return "pinned_host"


def global_norm(tree: Any) -> jnp.ndarray:
    leaves = [jnp.sum(jnp.square(g.astype(jnp.float32))) for g in jax.tree_util.tree_leaves(tree)]
    if not leaves:
        return jnp.zeros([], jnp.float32)
    return jnp.sqrt(jnp.asarray(leaves).sum())


class TrainEngine:
    """The TPU-native DeepSpeedEngine."""

    def __init__(self, *,
                 loss_fn: LossFn,
                 params: Any,
                 config: Config,
                 topology: Optional[Topology] = None,
                 optimizer: Optional[Any] = None,
                 lr_scheduler: Optional[Any] = None,
                 tp_specs: Optional[Any] = None,
                 model: Optional[Any] = None,
                 donate: bool = True):
        self.config = config
        self.model = model
        # hpZ / MiCS factor the data-parallel dimension into data × zshard
        # (inner = fast-ICI slice); see parallel/mesh.py MESH_AXES.
        zero_inner = config.zero.zero_inner_size()
        self.topo = topology or Topology.build(config.mesh, zero_inner=zero_inner)
        if zero_inner > 1 and self.topo.zero_secondary_size == 1:
            logger.warning(
                f"hpz/mics inner partition size {zero_inner} requested but the "
                f"provided topology has no zshard axis — running without it")
        self._raw_loss_fn = loss_fn
        self.loss_fn = _normalize_loss_fn(loss_fn)
        self.tp_specs = tp_specs
        self._donate = donate

        # -- pipeline parallelism: GAS micro-batches flow through the
        # rotating-microbatch executor inside ONE loss call instead of the
        # outer accumulation scan (reference: PipelineEngine.train_batch,
        # runtime/pipe/engine.py:312, where GAS == in-flight micro-batches)
        self._pipelined = self.topo.pipe_parallel_size > 1
        if self._pipelined:
            if model is None or not hasattr(model, "pipeline_loss"):
                raise ValueError(
                    "mesh has pipe axis > 1 but the model does not expose "
                    "pipeline_loss(params, batch, rng, num_microbatches)")

            def pipe_loss(p, batch, rng):
                # read GAS at call time: resolve_batch_config (below) may
                # derive it from train_batch/micro_batch after this closure
                # is created
                return model.pipeline_loss(p, batch, rng,
                                           config.gradient_accumulation_steps)

            self.loss_fn = _normalize_loss_fn(pipe_loss)

        # -- batch arithmetic (reference config._configure_train_batch_size)
        config.resolve_batch_config(self.topo.data_parallel_size)
        log_dist(
            f"batch config: train_batch={config.train_batch_size} "
            f"micro_batch={config.train_micro_batch_size_per_gpu} "
            f"gas={config.gradient_accumulation_steps} dp={self.topo.data_parallel_size}"
        )

        # -- ZeRO placement rules
        self.zero_rules = ZeroShardingRules(self.topo, config.zero)
        param_shapes = jax.eval_shape(lambda p: p, params)
        # fp32 gradient-tree bytes: the per-step cross-'data' reduction
        # payload the telemetry comm breakdown reports (_grad_reduce_comm)
        self._grad_bytes = int(sum(
            np.prod(l.shape) for l in jax.tree_util.tree_leaves(param_shapes)
            if hasattr(l, "shape")) * 4)
        self.param_shardings = self.zero_rules.param_shardings(param_shapes, tp_specs)
        self.grad_shardings = self.zero_rules.grad_shardings(param_shapes, tp_specs)

        # -- ZeRO++ (reference runtime/engine.py:836-845 keys):
        #   qwZ  — the stage-3 weight gather at the compute-cast boundary
        #          moves blockwise-int8 payloads (partition_parameters.py:679)
        #   hpZ  — compute copy sharded over the inner 'zshard' axes only, so
        #          per-layer all-gathers stay on fast ICI (:883)
        #   qgZ  — gradients reduced across the outer 'data' axis through the
        #          hierarchical quantized collective (comm/compressed.py)
        # The comm_compression block (docs/communication.md) makes both
        # quantized legs the DEFAULT above its mesh-size threshold; the
        # explicit zero_optimization knobs opt individual legs in below it.
        self._cc = config.comm_compression
        cc_on = self._cc.resolve_enabled(self.topo.data_parallel_size)
        # kernel backend of the facade (comm/backends.py): "auto" keeps
        # the plain XLA collectives off-TPU, so CPU meshes are unchanged;
        # "pallas" opts the staged schedule into the fused
        # compute-collective kernels (interpret mode off-TPU)
        from ..comm.backends import resolve_backend

        self._comm_backend = resolve_backend(self._cc.kernel_backend)
        self._qwz = ((bool(config.zero.zero_quantized_weights) or cc_on)
                     and config.zero.stage >= 3)
        self._qgz = (bool(config.zero.zero_quantized_gradients)
                     or (cc_on and config.zero.stage >= 2))
        self._hpz = self.zero_rules.hpz
        # manual shard_map axes of the facade-routed grad/weight paths:
        # the factored data-parallel dimension (outer 'data' = the slow
        # inter-slice hop, inner 'zshard' = fast ICI)
        self._dp_manual_axes = tuple(
            a for a in ("data", "zshard") if self.topo.axis_size(a) > 1)
        # T3-style staged block schedule (parallel/zero.py): models
        # exposing zero3_blocks get per-block eager collective issue
        # inside the fused step; "serial" keeps just-in-time issue (A/B).
        # Only when the engine trains the MODEL'S OWN loss: the staged
        # path computes loss from zero3_blocks' loss_tail, so silently
        # engaging it under a user-supplied loss_fn would optimize a
        # different objective than the one passed to initialize().
        self._staged_mode = None
        if (config.zero.stage >= 3 and not self._pipelined
                and self._dp_manual_axes
                and model is not None and hasattr(model, "zero3_blocks")
                and self._cc.overlap != "off"):
            if self._raw_loss_fn == getattr(model, "loss", None):
                self._staged_mode = self._cc.overlap
            else:
                logger.warning(
                    "staged ZeRO-3 overlap disabled: a custom loss_fn was "
                    "supplied, but the model's zero3_blocks defines its own "
                    "loss_tail — training proceeds on the (unstaged) facade "
                    "path with the custom loss")
        # quant-error stats only exist where a quantized facade path runs
        self._wants_quant_err = bool(
            self._cc.error_stats
            and (self._staged_mode is not None
                 or (self._qgz and self._dp_manual_axes)))
        self._secondary_shardings = None
        if self._hpz or (self._qwz and self.zero_rules.zero_size > 1):
            self._secondary_shardings = self.zero_rules.secondary_param_shardings(
                param_shapes, tp_specs)

        # master params: fp32 (BF16_Optimizer design); compute dtype applied in loss
        params = _cast_tree(params, jnp.float32)
        self.params = jax.device_put(params, self.param_shardings)

        # -- ZeRO-3 param offload (reference runtime/zero/stage3.py:558 +
        # partitioned_param_swapper.py): master param shards parked in
        # pinned host memory ("cpu") or on disk via the aio engine ("nvme")
        # between steps; uploaded around each step. The compute copy inside
        # the step is unchanged (bf16, per-layer gathers).
        self._param_offload_device = (config.zero.offload_param.device
                                      if config.zero.stage >= 3 else "none")
        self._param_host_shardings = None
        self._param_nvme_swapper = None
        if self._param_offload_device == "cpu":
            # pinned-host shardings gate only the 'cpu' mode — the nvme path
            # never uses them (it stages through the aio swapper)
            try:
                host_kind = host_memory_kind()
                self._param_host_shardings = jax.tree_util.tree_map(
                    lambda sh, x: (sh.with_memory_kind(host_kind)
                                   if getattr(x, "ndim", 0) >= 1 else sh),
                    self.param_shardings, self.params)
            except Exception as e:  # platform without host memory space
                logger.warning(f"param offload unavailable: {e}")
                self._param_offload_device = "none"
        if self._param_offload_device == "nvme":
            from .swap_tensor import OptimizerSwapper

            path = (config.zero.offload_param.nvme_path
                    or "/tmp/ds_tpu_param_swap")
            self._param_nvme_swapper = OptimizerSwapper(path)
        # actual parking happens after optimizer-state init below
        # (the optimizer init consumes the device-resident params)

        # -- optimizer + schedule
        base_lr = float(config.optimizer.params.get("lr", 1e-3))
        if lr_scheduler is not None and callable(lr_scheduler):
            self.lr_schedule: Schedule = lr_scheduler
        elif config.scheduler.type:
            self.lr_schedule = build_schedule(config.scheduler.type, config.scheduler.params, base_lr)
        else:
            self.lr_schedule = constant_lr(base_lr)
        if optimizer is not None:
            self.optimizer: Transform = as_transform(optimizer)
        else:
            self.optimizer = build_optimizer(config.optimizer.type, config.optimizer.params,
                                             lr_schedule=self.lr_schedule)

        opt_shape = jax.eval_shape(self.optimizer.init, params)
        self.opt_state_shardings = self.zero_rules.opt_state_shardings(opt_shape)

        # -- optimizer-state offload (ZeRO-Offload / Infinity parity:
        # reference runtime/zero/offload_config.py + swap_tensor stack).
        # "cpu": state parked in pinned host memory between steps; uploaded
        #        to device around each step (the reference's pinned-buffer
        #        copy engine analog).
        # "nvme": state lives on disk between steps via the native aio
        #        engine (csrc/aio), host RAM as staging.
        self._offload_device = config.zero.offload_optimizer.device
        self._opt_host_shardings = None
        self._nvme_swapper = None
        if self._offload_device in ("cpu", "nvme"):
            try:
                # scalars (step counters) stay in device memory — XLA's SPMD
                # partitioner rejects host placement on replicated scalars,
                # and there is nothing to save by offloading them
                host_kind = host_memory_kind()
                self._opt_host_shardings = jax.tree_util.tree_map(
                    lambda s, shape: (s.with_memory_kind(host_kind)
                                      if len(shape.shape) >= 1 else s),
                    self.opt_state_shardings, opt_shape)
            except Exception as e:  # platform without host memory space
                logger.warning(f"optimizer offload unavailable: {e}")
                self._offload_device = "none"
        if self._offload_device == "nvme":
            from .swap_tensor import OptimizerSwapper

            path = config.zero.offload_optimizer.nvme_path or "/tmp/ds_tpu_swap"
            self._nvme_swapper = OptimizerSwapper(path)

        self.opt_state = jax.jit(
            self.optimizer.init, out_shardings=self.opt_state_shardings
        )(self.params)
        # struct-only checkpoint template captured while everything is still
        # device-resident: load_checkpoint must not have to swap offloaded
        # state in from disk just to learn the tree structure
        self._params_struct = jax.eval_shape(lambda p: p, self.params)
        self._opt_struct = jax.eval_shape(lambda o: o, self.opt_state)
        if self._opt_host_shardings is not None:
            # park in host memory outside jit (memory-kind out_shardings on
            # scalar leaves trip the SPMD partitioner)
            self.opt_state = jax.device_put(self.opt_state,
                                            self._opt_host_shardings)
        if self._offload_device == "nvme":
            self._nvme_swapper.swap_out(self.opt_state)
            self.opt_state = None  # lives on disk between steps
        self._params_to_offload()

        # -- loss scaling state
        if config.fp16.enabled:
            if config.fp16.dynamic_loss_scale:
                self.scaler_state = ls.make_state(config.fp16.initial_scale_power, config.fp16.hysteresis)
            else:
                self.scaler_state = ls.static_state(config.fp16.loss_scale)
        else:
            self.scaler_state = ls.static_state(1.0)

        self.compute_dtype = config.compute_dtype
        self.global_steps = 0
        self.micro_steps = 0
        self.skipped_steps = 0  # via the lazy property below
        self.rng = jax.random.PRNGKey(config.train_seed)
        # commit the small carried states (scaler, rng) to the replicated
        # sharding they come back with after a step: uncommitted first-call
        # avals would miss the jit cache on step 2 and compile the whole
        # train step a SECOND time (trace-stability contract: one compile
        # per program — tests/test_perf_pipeline.py pins it)
        repl = self.topo.replicated()
        self.scaler_state = jax.device_put(self.scaler_state, repl)
        self.rng = jax.device_put(self.rng, repl)

        # -- bookkeeping / observability
        self.timers = SynchronizedWallClockTimer()
        self.tput = ThroughputTimer(batch_size=config.train_batch_size,
                                    steps_per_output=config.steps_per_print,
                                    monitor_memory=config.memory_breakdown)
        self.monitor = None
        if config.monitor.enabled:
            from ..monitor.monitor import MonitorMaster

            self.monitor = MonitorMaster(config.monitor)
        # unified telemetry: the monitor (when enabled) is one sink among
        # several; with telemetry AND monitor off, wants_step_records is
        # False and the step path keeps the seed's sync discipline exactly
        from ..telemetry import Telemetry

        self.telemetry = Telemetry(config.telemetry, monitor=self.monitor)
        if config.telemetry.enabled:
            from ..resilience import restart_count_from_env
            from ..telemetry import set_telemetry

            # share the pipeline with the comm facade / inference engines
            set_telemetry(self.telemetry)
            restart_count_from_env()
        if config.comms_logger.enabled or config.telemetry.enabled:
            # trace-time recording is free at steady state; telemetry needs
            # it on for the StepStats comm breakdown
            from ..comm.comm import configure_comms_logger

            configure_comms_logger(enabled=True,
                                   verbose=config.comms_logger.verbose)
        self._step_flops: Optional[float] = None  # per-step, from XLA cost analysis
        self._peak_flops: Optional[float] = None
        self._tokens_per_batch: Optional[int] = None
        self._comm_totals_prev: Dict[str, Dict[str, float]] = {}
        self._grad_comm_noted = False
        self._closed = False
        self.ckpt_engine = CheckpointEngine(
            async_save=config.checkpoint.async_save,
            keep_last_n=config.checkpoint.keep_last_n,
            verify_checksums=config.checkpoint.verify_checksums)

        # -- fault tolerance (docs/fault_tolerance.md). When every knob is
        # off, _ft_active stays False and the step path performs exactly
        # the same host synchronizations as before — the guards' cost
        # exists only when a guard does.
        rcfg = config.resilience
        self._step_hooks: list = []
        self._nan_skip_traced = rcfg.divergence.nan_action == "skip"
        self._divergence = None
        if rcfg.divergence.wants_host_check:
            from ..resilience.divergence import DivergenceGuard

            self._divergence = DivergenceGuard(
                nan_action=rcfg.divergence.nan_action,
                spike_action=rcfg.divergence.spike_action,
                spike_factor=rcfg.divergence.spike_factor,
                window=rcfg.divergence.window,
                warmup_steps=rcfg.divergence.warmup_steps)
        self.preemption_guard = None
        self._stop_reason: Optional[str] = None
        self._dataloader = None  # bound loader whose position checkpoints carry
        self._rollback_streak = 0   # rollbacks without progress past...
        self._ft_high_step = 0      # ...this high-water step
        self._ckpt_save_dir = config.checkpoint.save_dir
        self._ft_active = (self._divergence is not None
                           or bool(self._ckpt_save_dir
                                   and config.checkpoint.save_interval > 0))
        if rcfg.chaos.enabled:
            from ..resilience.chaos import FaultInjector, install_fault_injector

            inj = install_fault_injector(FaultInjector(rcfg.chaos))
            self.register_step_hook(lambda _eng, step: inj.on_step(step))

        # compat micro-step accumulation state
        self._acc_grads: Optional[Any] = None
        self._acc_add_fn = None   # cached jitted accumulator (one trace)
        self._last_loss = None

        # optional traced transform applied to the compute-copy params
        # (compression QAT / pruning masks — compression/compress.py)
        self._param_transform: Optional[Callable[[Any], Any]] = None

        self._train_step_fn = None
        self._eval_step_fn = None
        self._micro_grad_fn = None
        self._apply_update_fn = None

        # -- async/compiled dispatch machinery (docs/performance.md)
        self._train_step_raw = None          # unjitted step body (scanned by train_steps)
        self._train_steps_fns: Dict[int, Any] = {}  # k -> jitted k-step scan
        self._train_step_aot = None          # AOT executable from warmup()
        self._warmup_thread: Optional[threading.Thread] = None
        self._loader_iter = None             # persistent iterator for train_steps(k)
        self._loader_iter_src = None
        self._steps_fallback_logged: set = set()
        # recompile guard: batch signatures seen per compiled program
        self._seen_batch_sigs: Dict[str, set] = {}
        self._recompile_warned = False
        # trace counters: the step bodies bump these at TRACE time (the
        # Python in a jitted function only runs while JAX (re)traces it),
        # so each count is one program construction — the honest
        # "compiles per program" number the trace-stability tests pin.
        # (pjit's _cache_size() over-counts: it keys fastpath entries on
        # argument committed-ness and can hold 2 entries for 1 executable.)
        from collections import Counter as _Counter

        self._trace_counts: Dict[str, int] = _Counter()
        # host-overhead ledger clocks
        self._last_call_end_t: Optional[float] = None
        self._data_wait_prev_s = 0.0
        if config.compile.cache_dir:
            from .compile_cache import enable_persistent_cache

            enable_persistent_cache(config.compile.cache_dir,
                                    config.compile.min_compile_time_s)

    # ==================================================================
    # properties (parity with engine.py:468-:869 accessors)
    @property
    def skipped_steps(self) -> int:
        """Steps dropped by the loss scaler. Resolved lazily: the per-step
        overflow flag stays a device scalar accumulated with an async add —
        fetching it eagerly would block the host on every step (through the
        axon relay, a full round trip) and serialize dispatch."""
        if self._skipped_dev is not None:
            self._skipped_base += int(jax.device_get(self._skipped_dev))
            self._skipped_dev = None
        return self._skipped_base

    @skipped_steps.setter
    def skipped_steps(self, value: int) -> None:
        self._skipped_base = int(value)
        self._skipped_dev = None

    def _note_skipped(self, skipped) -> None:
        s = jnp.asarray(skipped).astype(jnp.int32)
        self._skipped_dev = s if self._skipped_dev is None else self._skipped_dev + s

    @property
    def train_batch_size(self) -> int:
        return self.config.train_batch_size

    @property
    def train_micro_batch_size_per_gpu(self) -> int:
        return self.config.train_micro_batch_size_per_gpu

    @property
    def gradient_accumulation_steps(self) -> int:
        return self.config.gradient_accumulation_steps

    @property
    def zero_optimization_stage(self) -> int:
        return self.config.zero.stage

    @property
    def data_parallel_world_size(self) -> int:
        return self.topo.data_parallel_size

    @property
    def world_size(self) -> int:
        return self.topo.world_size

    @property
    def gradient_clipping(self) -> float:
        return self.config.gradient_clipping

    def get_lr(self) -> float:
        return float(self.lr_schedule(jnp.asarray(self.global_steps)))

    def get_loss_scale(self) -> float:
        return float(self.scaler_state.scale)

    def is_gradient_accumulation_boundary(self) -> bool:
        return (self.micro_steps + 1) % self.gradient_accumulation_steps == 0

    # ==================================================================
    # core jitted programs
    def _compute_copy(self, params):
        """Compute-dtype copy of the fp32 master params with the ZeRO++
        transforms applied at this boundary: qwZ fake-quantizes through the
        facade's STE gather (comm/compressed.py — the int8 tensor carries
        the gather placement, so the cross-'data' all-gather moves
        1 byte/elt), hpZ re-shards onto the inner axes only (per-layer
        gathers stay on fast ICI). The facade shard_map paths use
        :meth:`_facade_compute_copy` instead, which keeps the sharded
        layout so the gather happens inside the metered region."""
        pc = _cast_tree(params, self.compute_dtype)
        if self._param_transform is not None:
            pc = self._param_transform(pc)
        if self._secondary_shardings is None:
            return pc
        from ..comm.compressed import QuantSpec, ste_quant_gather

        wq = QuantSpec(self._cc.weight_bits, self._cc.weight_block)

        # no 'data' hop (e.g. hpZ partition == dp): the re-shard moves
        # nothing across a slow link, so fake-quantizing it would pay the
        # bracket + error with no wire to save (intra-slice stays dense,
        # docs/communication.md)
        qwz_here = self._qwz and "data" in self._dp_manual_axes

        def leaf(x, sh):
            if not (hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)):
                return x
            if qwz_here and x.size % wq.block == 0 and x.size >= 4096:
                return ste_quant_gather(x, sh, wq, self.compute_dtype)
            return jax.lax.with_sharding_constraint(x, sh)

        return jax.tree_util.tree_map(leaf, pc, self._secondary_shardings)

    def _loss_and_grads(self, params, batch, rng, scale):
        """One microbatch: grads of (scaled) loss wrt fp32 master params,
        computed in the compute dtype. Dispatch: the staged block schedule
        (T3 overlap) when the model exposes it, else the facade qgZ path
        when quantized gradients are on, else the plain GSPMD path."""
        if self._staged_mode is not None:
            return self._loss_and_grads_staged(params, batch, rng, scale)
        if self._qgz and self._dp_manual_axes:
            return self._loss_and_grads_qgz(params, batch, rng, scale)

        def scaled_loss(p):
            loss, aux = self.loss_fn(self._compute_copy(p), batch, rng)
            return loss.astype(jnp.float32) * scale, (loss, aux)

        grads, (loss, aux) = jax.grad(scaled_loss, has_aux=True)(params)
        return grads, loss, aux

    @staticmethod
    def _strip_spec_to_axes(spec: PartitionSpec, keep) -> PartitionSpec:
        """Project a PartitionSpec onto a subset of mesh axes (for partial-
        manual shard_map in_specs, which may only name manual axes)."""
        out = []
        for e in spec:
            if e is None:
                out.append(None)
            elif isinstance(e, tuple):
                kept = tuple(a for a in e if a in keep)
                out.append(kept[0] if len(kept) == 1 else (kept or None))
            else:
                out.append(e if e in keep else None)
        return PartitionSpec(*out)

    def _facade_compute_copy(self, params):
        """Compute-dtype copy for the facade shard_map paths: keeps the
        stage-3 SHARDED layout so the per-leaf (quantized) gather happens
        INSIDE the shard_map region where the facade can meter it. Under
        hpZ the secondary (inner-sharded) copy is used instead, with the
        STE fake-quant booking the one outer hop at the cast boundary —
        the facade then only issues the fast-ICI inner gathers.
        Returns (pc, pc_shardings)."""
        if self._hpz and self._secondary_shardings is not None:
            return self._compute_copy(params), self._secondary_shardings
        pc = _cast_tree(params, self.compute_dtype)
        if self._param_transform is not None:
            pc = self._param_transform(pc)
        pc = jax.lax.with_sharding_constraint(pc, self.param_shardings)
        return pc, self.param_shardings

    def _facade_axes(self):
        """(outer, outer_world, inner, inner_world) of the hierarchical
        comm layout: 'data' is the slow inter-slice hop, 'zshard' the
        fast-ICI intra-slice hop when the mesh factors it out. When the
        whole DP group is the inner slice (data=1, e.g. hpZ partition ==
        dp), there IS no slow hop: outer comes back None/world-1 so every
        quantized leg degrades to the dense fast-ICI path — the contract
        ("the intra-slice hop always reduces dense fp",
        docs/communication.md) must hold on degenerate meshes too."""
        axes = self._dp_manual_axes
        outer = "data" if "data" in axes else None
        inner = "zshard" if "zshard" in axes else None
        return (outer, self.topo.axis_size("data") if outer else 1,
                inner, self.topo.axis_size("zshard") if inner else 1)

    def _facade_qspecs(self):
        from ..comm.compressed import QuantSpec

        wq = (QuantSpec(self._cc.weight_bits, self._cc.weight_block)
              if self._qwz else None)
        gq = (QuantSpec(self._cc.grad_bits, self._cc.grad_block)
              if self._qgz else None)
        return wq, gq

    def _facade_prelude(self, params, batch):
        """Shared setup of the facade shard_map paths (qgZ + staged):
        axis layout, quant specs, sharded compute copy, stripped in/out
        specs. One site to change when the facade contract moves."""
        axes = self._dp_manual_axes
        outer, outer_world, inner, inner_world = self._facade_axes()
        wq, gq = self._facade_qspecs()
        pc, pc_shardings = self._facade_compute_copy(params)
        is_spec = lambda x: isinstance(x, PartitionSpec)  # noqa: E731
        pc_specs = jax.tree_util.tree_map(
            lambda sh: self._strip_spec_to_axes(sh.spec, set(axes)),
            pc_shardings)
        bspec = PartitionSpec(axes[0] if len(axes) == 1 else axes)
        batch_specs = jax.tree_util.tree_map(lambda _: bspec, batch)
        rep = PartitionSpec()
        rep_tree = jax.tree_util.tree_map(lambda _: rep, pc_specs,
                                          is_leaf=is_spec)
        return dict(axes=axes, outer=outer, outer_world=outer_world,
                    inner=inner, inner_world=inner_world, wq=wq, gq=gq,
                    pc=pc, pc_specs=pc_specs, batch_specs=batch_specs,
                    rep=rep, rep_tree=rep_tree, is_spec=is_spec)

    @staticmethod
    def _facade_err_scalar(stats, axes):
        """Replicated max quantization error: each rank's local max must
        be pmax-reduced over the manual axes before the out_spec declares
        it replicated — otherwise the host reads an arbitrary shard's
        value and a single-rank bound violation is invisible."""
        from ..comm import compressed as ccomm

        local = (jnp.max(jnp.stack(stats)) if stats
                 else jnp.zeros([], jnp.float32))
        return ccomm.pmax(local, axes)

    def _run_facade_spmd(self, spmd, env, batch, rng, scale, aux_spec):
        """jit-traceable shard_map wrapper shared by the facade paths:
        manual over the factored DP axes, replicated outputs, fp32 grad
        cast (the linear master->compute chain rule)."""
        from ..parallel.mesh import shard_map_compat

        grads_c, loss, aux = shard_map_compat(
            spmd, mesh=self.topo.mesh, axis_names=set(env["axes"]),
            in_specs=(env["pc_specs"], env["batch_specs"], env["rep"],
                      env["rep"]),
            out_specs=(env["rep_tree"], env["rep"], aux_spec),
            check_vma=False)(env["pc"], batch, rng, scale)
        grads = jax.tree_util.tree_map(lambda g: g.astype(jnp.float32),
                                       grads_c)
        return grads, loss, aux

    def _loss_and_grads_qgz(self, params, batch, rng, scale):
        """qgZ/qwZ through the compressed-collectives facade
        (docs/communication.md): the stage-3 weight fetch is a facade
        all-gather per sharded leaf — quantized across the outer 'data'
        hop when qwZ is on, dense across the fast-ICI 'zshard' hop — and
        the cross-replica gradient reduction is the hierarchical chunked
        mean (fp reduce-scatter inside the slice, int8/int4 exchange on
        the chunk across slices, fp all-gather back). Runs under
        shard_map with the factored data-parallel axes manual; model/seq
        axes stay on their GSPMD placement as before."""
        from ..comm import compressed as ccomm

        env = self._facade_prelude(params, batch)
        wants_err = self._wants_quant_err

        def spmd(pc, mb, rng, scale):
            stats = [] if wants_err else None
            pc_full = jax.tree_util.tree_map(
                lambda x, spec: ccomm.gather_param_leaf(
                    x, spec,
                    outer_axes=(env["outer"],) if env["outer"] else (),
                    qspec=env["wq"], stats=stats),
                pc, env["pc_specs"], is_leaf=env["is_spec"])

            def scaled_loss(p):
                loss, aux = self.loss_fn(p, mb, rng)
                return loss.astype(jnp.float32) * scale, (loss, aux)

            grads, (loss, aux) = jax.grad(scaled_loss, has_aux=True)(pc_full)
            grads = ccomm.tree_hierarchical_pmean(
                grads, outer_axis=env["outer"],
                outer_world=env["outer_world"], inner_axis=env["inner"],
                inner_world=env["inner_world"], qspec=env["gq"],
                stats=stats)
            loss = ccomm.pmean(loss, env["axes"])
            if wants_err:
                aux = dict(aux)
                aux["quant_rel_err"] = self._facade_err_scalar(
                    stats, env["axes"])
            return grads, loss, aux

        return self._run_facade_spmd(spmd, env, batch, rng, scale,
                                     aux_spec=env["rep"])

    def _loss_and_grads_staged(self, params, batch, rng, scale):
        """T3-style staged ZeRO-3 step (parallel/zero.py
        Zero3BlockSchedule): the model's sequential blocks run with
        per-block facade collectives — block i+1's weight all-gather
        issued before block i's forward, the backward re-gathers each
        block (2-gather schedule, bounded param residency) and defers
        the previous block's gradient reduce behind the current block's
        compute — so the compiler can hide the ZeRO-3 comm behind
        compute. Serial mode ("comm_compression.overlap": "serial")
        issues each collective just-in-time instead; both orders are
        bit-exact to each other (identical dataflow) and that is pinned
        by tests."""
        from ..comm import compressed as ccomm
        from ..parallel.zero import Zero3BlockSchedule

        env = self._facade_prelude(params, batch)
        # per-block spec subtrees: zero3_blocks is structural in params
        prog_struct = self.model.zero3_blocks(env["pc_specs"], None)
        block_specs = prog_struct.blocks
        overlapped = self._staged_mode == "staged"
        wants_err = self._wants_quant_err
        # kernel-backend seam: blocks whose MatmulBlockSpec weight is
        # sharded exactly on the matmul's OUTPUT dim over the single
        # quantized outer axis can fuse gather-into-matmul and
        # reduce-into-epilogue (comm/backends.py); everything else —
        # contraction-dim shards, multi-axis leaves, the XLA backend —
        # keeps the generic per-block gather/reduce path below
        fusable = {}
        mm_specs = getattr(prog_struct, "matmul_blocks", None)
        if (self._comm_backend.name == "pallas" and mm_specs
                and env["outer"] and env["outer_world"] > 1):
            for i, ms in enumerate(mm_specs):
                if ms is None or not isinstance(block_specs[i], dict):
                    continue
                wspec = block_specs[i].get(ms.weight)
                if not isinstance(wspec, PartitionSpec):
                    continue
                entries = [(d, e if not (isinstance(e, tuple) and
                                         len(e) == 1) else e[0])
                           for d, e in enumerate(tuple(wspec))
                           if e is not None]
                if entries == [(1, env["outer"])]:
                    fusable[i] = ms

        def spmd(pc, mb, rng, scale):
            stats = [] if wants_err else None
            prog = self.model.zero3_blocks(pc, mb, rng)

            def gather(i, blk):
                return jax.tree_util.tree_map(
                    lambda x, spec: ccomm.gather_param_leaf(
                        x, spec,
                        outer_axes=(env["outer"],) if env["outer"] else (),
                        qspec=env["wq"], stats=stats),
                    blk, block_specs[i], is_leaf=env["is_spec"])

            def reduce(i, g):
                return ccomm.tree_hierarchical_pmean(
                    g, outer_axis=env["outer"],
                    outer_world=env["outer_world"],
                    inner_axis=env["inner"],
                    inner_world=env["inner_world"], qspec=env["gq"],
                    stats=stats)

            fused_ops = {i: self._fused_block_ops(ms, block_specs[i], env,
                                                  stats)
                         for i, ms in fusable.items()}
            sched = Zero3BlockSchedule(gather, reduce, overlapped=overlapped,
                                       fused=fused_ops or None)
            loss, block_grads = sched.loss_and_grads(prog, scale)
            grads = prog.merge(block_grads)
            loss = ccomm.pmean(loss.astype(jnp.float32), env["axes"])
            aux = {}
            if wants_err:
                aux["quant_rel_err"] = self._facade_err_scalar(
                    stats, env["axes"])
            return grads, loss, aux

        aux_spec = {"quant_rel_err": env["rep"]} if wants_err else {}
        return self._run_facade_spmd(spmd, env, batch, rng, scale,
                                     aux_spec=aux_spec)

    def _fused_block_ops(self, ms, spec_tree, env, stats):
        """FusedBlockOps for one matmul-annotated block of the staged
        schedule: the forward runs the weight's all-gather INSIDE the
        consuming matmul (per-tile ring dequant+multiply), the backward
        fuses the weight-grad reduce-scatter into the grad matmul's
        epilogue (in-kernel blockwise quantization); non-matmul leaves
        (biases) keep the generic facade gather/reduce. Dataflow is
        identical to the generic path — output tiles only ever split
        non-contraction matmul dims — so the fused engine stays
        bit-exact to the XLA-backend engine (pinned by
        tests/test_fused_collectives.py and the run_tests.sh gate)."""
        from ..comm import compressed as ccomm
        from ..parallel.zero import FusedBlockOps

        backend = self._comm_backend
        wkey = ms.weight
        outer = env["outer"]
        wq, gq = env["wq"], env["gq"]
        w_spec = spec_tree[wkey]
        rest_specs = {k: v for k, v in spec_tree.items() if k != wkey}
        # same small-leaf floor the generic reduce path applies
        # (tree_hierarchical_pmean), so fallbacks line up
        min_size = 4 * env["outer_world"] * (gq.block if gq else 1)

        def gather_rest(blk):
            rest = {k: v for k, v in blk.items() if k != wkey}
            return jax.tree_util.tree_map(
                lambda x, sp: ccomm.gather_param_leaf(
                    x, sp, outer_axes=(outer,), qspec=wq, stats=stats),
                rest, rest_specs, is_leaf=env["is_spec"])

        def forward(blk, h):
            rest_full = gather_rest(blk)
            y = backend.all_gather_matmul(h, blk[wkey], outer, dim=1,
                                          qspec=wq, stats=stats)
            return ms.epilogue(y, rest_full, h)

        def backward(blk, h_in, g_out):
            # the schedule's second gather: rebuild W for the data-path
            # cotangent (bit-identical values to the fused forward
            # gather) and recompute y for the epilogue vjp — activation
            # checkpointing at block boundaries, same as the generic
            # backward's recompute
            w_full = ccomm.gather_param_leaf(
                blk[wkey], w_spec, outer_axes=(outer,), qspec=wq,
                stats=stats)
            rest_full = gather_rest(blk)
            y = jax.lax.dot_general(
                h_in, w_full, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32).astype(h_in.dtype)
            _, evjp = jax.vjp(ms.epilogue, y, rest_full, h_in)
            g_y, g_rest, g_h_epi = evjp(g_out)
            g_w = backend.matmul_reduce_scatter(
                h_in, g_y, outer_axis=outer,
                outer_world=env["outer_world"], inner_axis=env["inner"],
                inner_world=env["inner_world"], qspec=gq,
                min_quant_size=min_size, stats=stats)
            g_rest_red = ccomm.tree_hierarchical_pmean(
                g_rest, outer_axis=outer, outer_world=env["outer_world"],
                inner_axis=env["inner"], inner_world=env["inner_world"],
                qspec=gq, stats=stats)
            g_h = g_h_epi + jax.lax.dot_general(
                g_y, w_full, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32).astype(h_in.dtype)
            grads = dict(g_rest_red)
            grads[wkey] = g_w.astype(jnp.float32)
            return grads, g_h

        return FusedBlockOps(forward=forward, backward=backward)

    def _build_train_step(self):
        cfg = self.config
        # pipelined: micro-batching happens inside pipeline_loss
        gas = 1 if self._pipelined else cfg.gradient_accumulation_steps
        clip = cfg.gradient_clipping
        fp16 = cfg.fp16.enabled
        dynamic = fp16 and cfg.fp16.dynamic_loss_scale
        optimizer = self.optimizer

        def train_step(params, opt_state, scaler_state, rng, batch):
            self._trace_counts["train_step"] += 1  # dslint: disable=trace-hygiene -- deliberate trace-time counter: bumps once per (re)trace, which IS the recompile telemetry
            scale = scaler_state.scale if fp16 else jnp.ones([], jnp.float32)

            wants_err = self._wants_quant_err

            def micro(carry, mb):
                acc, rng = carry
                rng, sub = jax.random.split(rng)
                grads, loss, _aux = self._loss_and_grads(params, mb, sub, scale)
                grads = jax.lax.with_sharding_constraint(grads, self.grad_shardings)
                acc_g, acc_loss = acc
                acc_g = jax.tree_util.tree_map(lambda a, g: a + g.astype(jnp.float32), acc_g, grads)
                err = _aux.get("quant_rel_err") if wants_err else None
                return ((acc_g, acc_loss + loss.astype(jnp.float32)), rng), err

            quant_err = None
            if gas > 1:
                # [global_batch, ...] -> [gas, global_batch/gas, ...]
                mb_batch = jax.tree_util.tree_map(
                    lambda x: x.reshape((gas, x.shape[0] // gas) + x.shape[1:]), batch)
                zero_acc = jax.tree_util.tree_map(
                    lambda s: jnp.zeros(s.shape, jnp.float32), jax.eval_shape(lambda p: p, params))
                zero_acc = jax.lax.with_sharding_constraint(zero_acc, self.grad_shardings)
                (carry, rng), errs = jax.lax.scan(
                    micro, ((zero_acc, jnp.zeros([], jnp.float32)), rng), mb_batch)
                grads, loss_sum = carry
                inv = 1.0 / gas
                grads = jax.tree_util.tree_map(lambda g: g * inv, grads)
                loss = loss_sum * inv
                if wants_err:
                    quant_err = jnp.max(errs)
            else:
                rng, sub = jax.random.split(rng)
                grads, loss, _aux = self._loss_and_grads(params, batch, sub, scale)
                grads = jax.lax.with_sharding_constraint(
                    jax.tree_util.tree_map(lambda g: g.astype(jnp.float32), grads),
                    self.grad_shardings)
                if wants_err:
                    quant_err = _aux["quant_rel_err"]

            new_params, new_opt, new_scaler, gnorm, skipped = self._update(
                params, opt_state, scaler_state, grads, scale,
                clip=clip, fp16=fp16, dynamic=dynamic, optimizer=optimizer,
                nan_skip=self._nan_skip_traced)
            metrics = {
                "loss": loss,
                "grad_norm": gnorm,
                "loss_scale": new_scaler.scale,
                "skipped": skipped,
            }
            if wants_err:
                # max local quantization round-trip rel error across this
                # step's facade collectives (docs/communication.md)
                metrics["quant_rel_err"] = quant_err
            return new_params, new_opt, new_scaler, rng, metrics

        self._train_step_raw = train_step  # dslint: disable=races -- warmup-join synchronization: _build_train_step runs on main or on the warmup thread, never concurrently (_ensure_train_step_fn joins a pending warmup first; warmup_async is called once from initialize)
        donate = (0, 1, 2) if self._donate else ()
        return jax.jit(train_step, donate_argnums=donate,
                       out_shardings=self._step_out_shardings())

    def _step_out_shardings(self):
        """Output shardings pinning the engine state to exactly the
        shardings it entered with. Left unspecified, GSPMD may hand the
        carried state back under an equivalent-but-unequal sharding
        representation, and the NEXT call's avals miss the jit cache —
        the whole step program compiles a second time (trace-stability
        contract, tests/test_perf_pipeline.py)."""
        repl = self.topo.replicated()
        scaler_sh = jax.tree_util.tree_map(lambda _: repl, self.scaler_state)
        metrics_sh = {"loss": repl, "grad_norm": repl, "loss_scale": repl,
                      "skipped": repl}
        if self._wants_quant_err:
            metrics_sh["quant_rel_err"] = repl
        return (self.param_shardings, self.opt_state_shardings, scaler_sh,
                repl, metrics_sh)

    def _ensure_train_step_fn(self):
        """The jitted single-step program, building it on first use. Joins
        a pending AOT warmup thread first so a warmup-compiled executable
        (and its persistent-cache entry) is never raced by a second
        compile of the same program."""
        if self._warmup_thread is not None:
            self._warmup_thread.join()
            self._warmup_thread = None
        if self._train_step_fn is None:
            self._train_step_fn = self._build_train_step()  # dslint: disable=races -- warmup-join synchronization: the join two lines up establishes happens-before with the warmup thread's write; no other writer exists
        return self._train_step_fn

    # ==================================================================
    # AOT warmup (docs/performance.md): compile the fused step during
    # initialize(), overlapped with the input pipeline's warm fill
    def warmup(self, batch: Any) -> bool:
        """AOT-compile the fused train step against ``batch`` — a real
        batch or a ``jax.ShapeDtypeStruct`` tree (see
        ``DataLoader.batch_struct``; no data movement needed). The
        compiled executable serves subsequent ``train_batch`` calls whose
        batch signature matches, and with the persistent compilation
        cache enabled the compile is also written to disk, so even a
        signature miss only pays a cache read. Returns False (warned,
        engine fully functional on the lazy-jit path) on any failure."""
        if self._offload_device != "none" or self._param_offload_device != "none":
            logger.warning("AOT warmup skipped: offload parks state between"
                           " steps (no stable arguments to lower against)")
            return False
        try:
            if self._train_step_fn is None:
                self._train_step_fn = self._build_train_step()
            struct = _batch_abstract(batch)
            lowered = self._train_step_fn.lower(
                self.params, self.opt_state, self.scaler_state, self.rng,
                struct)
            self._train_step_aot = lowered.compile()  # dslint: disable=races -- warmup-join synchronization: train_batch reaches its _train_step_aot read only after _ensure_train_step_fn joined this thread
            return True
        except Exception as e:  # noqa: BLE001 — warmup must never kill init
            logger.warning(f"AOT warmup failed (lazy jit path unaffected): {e}")
            return False

    def warmup_async(self, batch: Any) -> threading.Thread:
        """Run :meth:`warmup` in a background thread (XLA compilation
        releases the GIL), overlapping the compile with the caller's own
        warm-up work — e.g. the prefetch pipeline's first fills. The first
        ``train_batch``/``train_steps`` joins it."""
        t = threading.Thread(target=self.warmup, args=(batch,),
                             name="dst-aot-warmup", daemon=True)
        self._warmup_thread = t
        t.start()
        return t

    def _update(self, params, opt_state, scaler_state, grads, scale, *,
                clip, fp16, dynamic, optimizer, nan_skip=False):
        """Unscale, clip, step — shared by fused and compat paths.

        ``nan_skip`` (divergence.nan_action == "skip") reuses the fp16
        overflow machinery for full-precision runs: a non-finite gradient
        tree keeps the old params/opt state ON DEVICE — the NaN guard
        compiles into the step and costs zero extra host syncs."""
        cfg = self.config
        if fp16:
            grads = jax.tree_util.tree_map(lambda g: g / scale, grads)
            finite = ls.grads_finite(grads)
        elif nan_skip:
            finite = ls.grads_finite(grads)
        else:
            finite = jnp.asarray(True)
        gnorm = global_norm(grads)
        if clip > 0:
            factor = jnp.minimum(1.0, clip / (gnorm + 1e-6))
            grads = jax.tree_util.tree_map(lambda g: g * factor, grads)
        updates, new_opt = optimizer.update(grads, opt_state, params)
        new_params = jax.tree_util.tree_map(lambda p, u: p + u, params, updates)
        # overflow / injected NaN => keep old params/opt state
        # (reference: skipped step)
        if fp16 or nan_skip:
            new_params = jax.tree_util.tree_map(
                lambda n, o: jnp.where(finite, n, o), new_params, params)
            new_opt = jax.tree_util.tree_map(
                lambda n, o: jnp.where(finite, n, o) if hasattr(n, "dtype") else n,
                new_opt, opt_state)
        new_scaler = ls.update(
            scaler_state, finite, dynamic=dynamic,
            scale_window=cfg.fp16.loss_scale_window,
            min_scale=cfg.fp16.min_loss_scale,
            consecutive_hysteresis=cfg.fp16.consecutive_hysteresis,
            init_hysteresis=cfg.fp16.hysteresis)
        new_params = jax.lax.with_sharding_constraint(new_params, self.param_shardings)
        skipped = jnp.logical_not(finite)
        return new_params, new_opt, new_scaler, gnorm, skipped

    # ==================================================================
    # fused fast path
    def train_batch(self, batch: Any) -> Dict[str, Any]:
        """One full optimizer step over a global batch of
        ``train_batch_size`` samples (parity with PipelineEngine.train_batch
        semantics for the non-pipelined engine)."""
        t_entry = time.perf_counter()
        for hook in self._step_hooks:
            hook(self, self.global_steps)
        fn = self._ensure_train_step_fn()
        self._note_batch_sig(batch)
        self.tput.start()
        if self._offload_device == "nvme":
            # disk -> host staging via the aio engine (reference
            # pipelined_optimizer_swapper), then host -> device
            self.opt_state = self._nvme_swapper.swap_in(self.opt_state_shardings)  # dslint: disable=races -- warmup-join synchronization: warmup only READS engine state, and train_batch joined it (via _ensure_train_step_fn above) before this write; offload engines additionally skip AOT warmup entirely
        elif self._offload_device == "cpu":
            # pinned host -> device upload (the reference offload engine's
            # per-step copy-in)
            self.opt_state = jax.device_put(self.opt_state, self.opt_state_shardings)
        self._params_to_device()
        if self.telemetry.wants_step_records and self._step_flops is None:
            # MFU numerator from HLO cost analysis of the lowered step,
            # measured BEFORE the donated call while the argument buffers
            # are alive (no XLA compile — see _measure_step_flops)
            self._measure_step_flops(batch)
        out = None
        if self._train_step_aot is not None:
            # warmup's AOT executable: same program, dispatched without the
            # jit cache lookup. Any argument mismatch (new batch signature,
            # different sharding) falls back to the lazy jit path for good.
            try:
                out = self._train_step_aot(
                    self.params, self.opt_state, self.scaler_state, self.rng,
                    batch)
            except Exception as e:  # noqa: BLE001 — aval check precedes execution
                logger.warning(f"AOT train step no longer matches the inputs "
                               f"({e}); using the jit path")
                self._train_step_aot = None
        if out is None:
            out = fn(self.params, self.opt_state, self.scaler_state, self.rng,
                     batch)
        self.params, self.opt_state, self.scaler_state, self.rng, metrics = out  # dslint: disable=races -- warmup-join synchronization: the warmup thread's reads of params/opt_state/scaler/rng happen strictly before _ensure_train_step_fn's join at the top of train_batch; after the join, main is the only toucher
        self._params_to_offload()
        if self._offload_device == "nvme":
            self._nvme_swapper.swap_out(self.opt_state)
            self.opt_state = None
        elif self._offload_device == "cpu":
            self.opt_state = jax.device_put(self.opt_state, self._opt_host_shardings)
        # host ledger: everything from entry to here ran on the host while
        # the device was free to execute (dispatch is async) — the per-step
        # dispatch tax the async pipeline + train_steps(k) amortize
        t_dispatched = time.perf_counter()
        self.global_steps += 1
        self.micro_steps += self.gradient_accumulation_steps
        # sync_obj blocks the host until the step completes — honest per-step
        # timing, but it forbids dispatch-ahead pipelining. Only pay for it
        # when the user asked for timing (wall_clock_breakdown), when a
        # telemetry sink will fetch the metrics anyway (so the fetch lands
        # inside the timed region, not the untimed gap), or at the report
        # boundary. Telemetry off + monitor off => same sync points as seed.
        report_boundary = self.tput.will_report_next()
        want_stats = self.telemetry.wants_step_records
        sync = metrics["loss"] if (
            self.config.wall_clock_breakdown or want_stats
            or report_boundary) else None
        step_dt = self.tput.stop(sync_obj=sync, report_speed=True)
        host = None
        if want_stats:
            host = {"host_ms": (t_dispatched - t_entry) * 1e3,
                    "data_wait_ms": self._consume_data_wait_ms(),
                    "dispatch_gap_ms": ((t_entry - self._last_call_end_t) * 1e3
                                        if self._last_call_end_t is not None
                                        else None)}
        self._emit_step(metrics, wall_time_s=step_dt, log_step=report_boundary,
                        host=host)
        self._last_call_end_t = time.perf_counter()
        self._note_skipped(metrics["skipped"])
        self._last_loss = metrics["loss"]
        if self._ft_active or self.preemption_guard is not None:
            self._after_step(metrics)
        if self.config.memory_breakdown and report_boundary:
            # reference see_memory_usage at engine phase boundaries
            # (runtime/utils.py); boundary-only so it never adds a host
            # sync to the steady-state step
            from ..utils.memory import see_memory_usage

            see_memory_usage(f"step {self.global_steps}")
        return metrics

    # ==================================================================
    # compiled multi-step driver (docs/performance.md)
    def train_steps_eligible(self) -> Tuple[bool, Optional[str]]:
        """Whether ``train_steps`` may fuse k steps into one compiled
        program, with the blocking reason when it may not. Anything that
        must interleave HOST work between optimizer steps forces the
        per-step path."""
        if self._offload_device != "none" or self._param_offload_device != "none":
            return False, "zero-offload swaps state around every step"
        if self._step_hooks:
            return False, "per-step hooks registered"
        if self.preemption_guard is not None:
            return False, "preemption-latch polling needs per-step boundaries"
        if self._divergence is not None:
            return False, "host-side divergence guard fetches the loss each step"
        if self._pipelined:
            return False, "pipelined engine schedules micro-batches itself"
        return True, None

    def train_steps(self, batches: Union[int, Sequence[Any]]) -> Dict[str, Any]:
        """Run k optimizer steps as ONE jitted, donated ``lax.scan`` —
        dispatch cost amortized k×, zero host work between the inner
        steps. Bit-exact with k calls to :meth:`train_batch` (the scan
        body IS the single-step program).

        ``batches`` is a sequence of k equal-shaped global batches (e.g.
        pulled from a prefetching loader), or an int k to pull them from
        the bound dataloader (cycling epochs like ``RepeatingLoader``).

        When the engine is ineligible (:meth:`train_steps_eligible` —
        offload, per-step hooks, preemption polling, host divergence
        guards), falls back to per-step ``train_batch`` calls with the
        reason logged once. Returns the last step's metrics plus
        ``losses``, the per-step loss vector."""
        if isinstance(batches, int):
            k, batches = int(batches), None  # pulled below, path-dependent
        else:
            batches = list(batches)
            k = len(batches)
        if k <= 0:
            raise ValueError("train_steps: no batches")
        eligible, reason = self.train_steps_eligible()
        if not eligible or k == 1:
            if not eligible and reason not in self._steps_fallback_logged:
                self._steps_fallback_logged.add(reason)
                log_dist(f"train_steps: fused multi-step path ineligible "
                         f"({reason}); running {k} per-step train_batch calls")
            # pull lazily, one batch per step: the ineligible reasons are
            # exactly the ones that can checkpoint/rollback BETWEEN the
            # inner steps (preemption drain, divergence), and the loader
            # position those paths capture must reflect actual consumption,
            # not a k-batch read-ahead
            losses = []
            metrics: Dict[str, Any] = {}
            for i in range(k):
                if batches is not None:
                    b = batches[i]
                else:
                    pulled = self._pull_batches(1)
                    if not pulled:  # loader is empty
                        break
                    b = pulled[0]
                metrics = self.train_batch(b)
                losses.append(metrics["loss"])
            if not losses:
                raise ValueError("train_steps: no batches")
            out = dict(metrics)
            out["losses"] = jnp.stack([jnp.asarray(l) for l in losses])
            return out
        if batches is None:
            batches = self._pull_batches(k)
            k = len(batches)
            if k == 0:
                raise ValueError("train_steps: no batches")
            if k == 1:  # loader could only supply one batch
                metrics = self.train_batch(batches[0])
                out = dict(metrics)
                out["losses"] = jnp.stack([jnp.asarray(metrics["loss"])])
                return out

        t_entry = time.perf_counter()
        gap_ms = ((t_entry - self._last_call_end_t) * 1e3
                  if self._last_call_end_t is not None else None)
        self._ensure_train_step_fn()  # also builds _train_step_raw
        fn = self._train_steps_fns.get(k)
        if fn is None:
            fn = self._build_train_steps(k)
            self._train_steps_fns[k] = fn
        # the k batches enter the program as a tuple and are stacked INTO
        # the scan's leading dim inside the compiled program — stacking on
        # the host side would pay one dispatch per leaf per block, exactly
        # the tax this driver exists to amortize
        batch_tuple = tuple(batches)
        self._note_batch_sig(batch_tuple, program=f"train_steps_{k}")
        want_stats = self.telemetry.wants_step_records
        if want_stats and self._step_flops is None:
            self._measure_step_flops(batches[0])
        prev_steps = self.global_steps
        self.tput.start()
        self.params, self.opt_state, self.scaler_state, self.rng, ms = fn(
            self.params, self.opt_state, self.scaler_state, self.rng,
            batch_tuple)
        t_dispatched = time.perf_counter()
        self.global_steps += k
        self.micro_steps += k * self.gradient_accumulation_steps
        metrics = {"loss": ms["loss"][-1], "grad_norm": ms["grad_norm"][-1],
                   "loss_scale": ms["loss_scale"][-1],
                   "skipped": ms["skipped"][-1]}
        sync = metrics["loss"] if (self.config.wall_clock_breakdown
                                   or want_stats) else None
        block_dt = self.tput.stop(sync_obj=sync, report_speed=False)
        # keep the throughput aggregates honest: stop() booked one step of
        # batch_size; this block ran k of them
        self.tput.step_count = self.global_steps
        self.tput.total_samples += self.train_batch_size * (k - 1)
        host = None
        if want_stats:
            host = {"host_ms": (t_dispatched - t_entry) * 1e3,
                    "data_wait_ms": self._consume_data_wait_ms(),
                    "dispatch_gap_ms": gap_ms}
        self._emit_step(metrics, wall_time_s=block_dt, log_step=False,
                        host=host, n_steps=k)
        self._last_call_end_t = time.perf_counter()
        self._note_skipped(ms["skipped"].sum())
        self._last_loss = metrics["loss"]
        # periodic auto-save: a block can cross (or land on) a save
        # boundary; preemption/divergence never reach here (ineligible)
        iv = self.config.checkpoint.save_interval
        if (self._ckpt_save_dir and iv > 0
                and self.global_steps // iv != prev_steps // iv):
            self.save_checkpoint(self._ckpt_save_dir)
        out = dict(metrics)
        out["losses"] = ms["loss"]
        return out

    def _build_train_steps(self, k: int):
        raw = self._train_step_raw

        def k_step(params, opt_state, scaler_state, rng, batch_tuple):
            self._trace_counts[f"train_steps_{k}"] += 1  # dslint: disable=trace-hygiene -- deliberate trace-time counter (recompile telemetry)
            stacked = jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs), *batch_tuple)

            def body(carry, mb):
                p, o, s, r = carry
                p, o, s, r, m = raw(p, o, s, r, mb)
                return (p, o, s, r), m

            (p, o, s, r), ms = jax.lax.scan(
                body, (params, opt_state, scaler_state, rng), stacked)
            return p, o, s, r, ms

        donate = (0, 1, 2) if self._donate else ()
        return jax.jit(k_step, donate_argnums=donate,
                       out_shardings=self._step_out_shardings())

    def _pull_batches(self, k: int) -> List[Any]:
        """k batches from the bound dataloader via a persistent iterator,
        advancing epochs like RepeatingLoader when one ends mid-pull."""
        src = self._dataloader
        if src is None:
            raise ValueError(
                "train_steps(k) needs a bound dataloader (bind_dataloader) "
                "or an explicit sequence of batches")
        if self._loader_iter is None or self._loader_iter_src is not src:
            self._loader_iter = iter(src)
            self._loader_iter_src = src
        out: List[Any] = []
        fresh_restarts = 0
        while len(out) < k:
            try:
                out.append(next(self._loader_iter))
                fresh_restarts = 0
            except StopIteration:
                if fresh_restarts:  # empty loader — don't spin forever
                    break
                fresh_restarts += 1
                if hasattr(src, "set_epoch"):
                    src.set_epoch(getattr(src, "epoch", 0) + 1)
                self._loader_iter = iter(src)
        return out

    # ==================================================================
    # trace accounting (docs/performance.md#recompile-guard)
    def trace_count(self, name: str = "train_step") -> int:
        """Times the named program body was traced (each trace constructs
        a new program and, modulo the compilation cache, a new XLA
        compile). 1 at steady state; >1 means shape/type churn retraced
        it. Names: ``train_step``, ``eval_step``, ``train_steps_<k>``."""
        return int(self._trace_counts.get(name, 0))

    def train_step_cache_size(self) -> int:
        """Entry count of the fused train step's pjit call cache. NOTE:
        fastpath entries key on argument committed-ness too, so this can
        exceed :meth:`trace_count` by one without any recompile; use
        trace_count for the one-compile-per-program contract."""
        return _jit_cache_size(self._train_step_fn)

    def eval_step_cache_size(self) -> int:
        return _jit_cache_size(self._eval_step_fn)

    def overlap_report(self, batch: Any, repeats: int = 3,
                       **kwargs) -> Dict[str, Any]:
        """Measured (not modeled) comm-overlap accounting for the staged
        ZeRO-3 schedule (profiling/overlap.py): drives this engine's
        block program eagerly with per-phase fenced timing, joins wire
        bytes from the CommsLogger ledger, and compares measured comm
        exposure against ``modeled_exposure`` under a calibrated
        bandwidth. Requires the staged path (model exposes
        ``zero3_blocks`` and the mesh factors a data-parallel axis);
        never touches the jitted step programs."""
        if self._staged_mode is None:
            raise ValueError(
                "overlap_report needs the staged ZeRO-3 path (stage 3, a "
                "zero3_blocks model, comm_compression.overlap != 'off' "
                "and a >1 data-parallel mesh axis)")
        from ..profiling.overlap import overlap_report

        return overlap_report(self, batch, repeats=repeats, **kwargs)

    def _note_batch_sig(self, batch: Any, program: str = "train_step") -> None:
        """Recompile guard: a batch signature (leaf shapes/dtypes) this
        program has not seen misses its jit cache and compiles a whole new
        XLA program. Count it (``train/recompiles``) and warn once with
        the remedy. Signatures are per program — the k-step driver and the
        single-step program legitimately see different shapes."""
        sig = _batch_signature(batch)
        seen = self._seen_batch_sigs.setdefault(program, set())
        if sig in seen:
            return
        first = not seen
        seen.add(sig)
        if first:
            return
        from ..telemetry.registry import get_registry

        get_registry().counter("train/recompiles").inc()
        if self.config.compile.warn_on_recompile and not self._recompile_warned:
            self._recompile_warned = True
            logger.warning(
                f"train step RETRACED: new batch signature {sig} missed the "
                f"jit cache (curriculum_fn changing seq length? ragged last "
                f"batch?). Every distinct shape compiles a new XLA program — "
                f"pad batches to a small fixed set of bucket shapes "
                f"(docs/performance.md#recompile-guard). Further recompiles "
                f"are counted in train/recompiles without this warning.")

    def _consume_data_wait_ms(self) -> Optional[float]:
        """Delta of the bound loader's cumulative data-wait ledger since
        the last step record (host time the consumer spent waiting for /
        producing batches)."""
        dl = self._dataloader
        cur = getattr(dl, "data_wait_s", None) if dl is not None else None
        if cur is None:
            return None
        d = float(cur) - self._data_wait_prev_s
        self._data_wait_prev_s = float(cur)
        return d * 1e3 if d >= 0 else None

    # ==================================================================
    # fault tolerance (docs/fault_tolerance.md)
    @property
    def should_stop(self) -> bool:
        """True once a preemption was handled (emergency checkpoint saved,
        telemetry flushed) or a guard halted the run — the training loop's
        drain signal."""
        return self._stop_reason is not None

    @property
    def stop_reason(self) -> Optional[str]:
        return self._stop_reason

    def attach_preemption_guard(self, guard: Optional[Any] = None):
        """Wire a PreemptionGuard into the step path: when its signal
        latches, the NEXT step boundary saves an emergency checkpoint
        (into ``checkpoint.save_dir``), flushes telemetry, and sets
        :attr:`should_stop`. Pass an entered guard, or None to construct
        one (caller still manages its context)."""
        if guard is None:
            from ..resilience.preemption import PreemptionGuard

            guard = PreemptionGuard()
        self.preemption_guard = guard
        return guard

    def bind_dataloader(self, loader: Any) -> None:
        """Checkpoints now carry this loader's position (epoch + batch
        index) in client_state, and load_checkpoint restores it — resume
        replays the exact remaining data order. Bind before iterating."""
        self._dataloader = loader
        self._loader_iter = None
        self._loader_iter_src = None
        self._data_wait_prev_s = float(getattr(loader, "data_wait_s", 0.0) or 0.0)

    def _after_step(self, metrics: Dict[str, Any]) -> None:
        """Step-boundary fault-tolerance checks. Never called when every
        knob is off (the zero-extra-host-syncs contract)."""
        step = self.global_steps
        if step > self._ft_high_step:
            # progress past the previous high-water step: any earlier
            # divergence was transient, the rollback did its job
            self._ft_high_step = step
            self._rollback_streak = 0
        if self._divergence is not None:
            # the one host sync the divergence guard costs, documented
            verdict = self._divergence.observe(step, float(metrics["loss"]))
            if verdict is not None:
                kind, action = verdict
                from ..telemetry.registry import get_registry

                get_registry().counter(f"resilience/divergence/{kind}").inc()
                if action == "halt":
                    from ..resilience.divergence import DivergenceError

                    self._stop_reason = f"divergence:{kind}"
                    raise DivergenceError(
                        f"{kind} divergence at step {step} (action=halt)")
                if action == "rollback":
                    self._rollback_streak += 1
                    limit = self.config.resilience.divergence.max_rollbacks
                    if self._rollback_streak > limit:
                        # bit-exact resume replays a deterministic fault
                        # identically — rolling back again would loop
                        # forever; escalate to halt
                        from ..resilience.divergence import DivergenceError

                        self._stop_reason = f"divergence:{kind}:rollback-loop"
                        raise DivergenceError(
                            f"{kind} divergence at step {step} persisted "
                            f"through {limit} rollbacks (deterministic "
                            f"fault?) — halting")
                    self._rollback(kind)
                    return  # don't checkpoint the rolled-back state twice
                # "warn": the guard already logged and counted
        if (self.preemption_guard is not None
                and self.preemption_guard.should_stop
                and self._stop_reason is None):
            self._emergency_checkpoint()
            self._stop_reason = "preempted"
            return
        if (self._ckpt_save_dir and self.config.checkpoint.save_interval > 0
                and step % self.config.checkpoint.save_interval == 0):
            self.save_checkpoint(self._ckpt_save_dir)

    def _rollback(self, kind: str) -> None:
        from ..resilience.counters import record_rollback
        from ..resilience.divergence import DivergenceError

        if not self._ckpt_save_dir:
            raise DivergenceError(
                f"{kind} divergence: rollback requested but "
                f"checkpoint.save_dir is not configured")
        bad_step = self.global_steps
        client = self.load_checkpoint(self._ckpt_save_dir, auto=True)
        if client is None:
            raise DivergenceError(
                f"{kind} divergence at step {bad_step}: no valid "
                f"checkpoint to roll back to")
        self._divergence.reset()
        record_rollback()
        logger.warning(f"divergence ({kind}) at step {bad_step}: rolled "
                       f"back to step {self.global_steps}")

    def _emergency_checkpoint(self) -> None:
        """Preemption drain: checkpoint (if a save_dir is configured) and
        flush every telemetry sink before the SIGKILL deadline."""
        from ..resilience.counters import record_emergency_save

        if self._ckpt_save_dir:
            self.save_checkpoint(self._ckpt_save_dir)
            record_emergency_save()
            log_dist(f"emergency checkpoint at step {self.global_steps} "
                     f"(preemption drain)")
        else:
            logger.warning("preempted with no checkpoint.save_dir — "
                           "draining without an emergency checkpoint")
        self.telemetry.close()

    def register_param_transform(self, fn: Optional[Callable[[Any], Any]]) -> None:
        """Install/replace a traced params transform applied at the
        compute-cast boundary (compression QAT, pruning masks). Invalidates
        compiled step functions — call sparingly (schedule boundaries)."""
        if self._warmup_thread is not None:
            # an in-flight AOT warmup would re-install a pre-transform
            # executable AFTER the reset below; let it land first
            self._warmup_thread.join()
            self._warmup_thread = None
        self._param_transform = fn
        self._train_step_fn = None
        self._train_step_raw = None
        self._train_steps_fns = {}
        self._train_step_aot = None
        self._micro_grad_fn = None
        self._acc_add_fn = None
        self._eval_step_fn = None

    def register_step_hook(self, fn: Callable[["TrainEngine", int], None]) -> None:
        """fn(engine, global_step) before each train_batch (compression
        schedule gating, reference scheduler.py analog)."""
        self._step_hooks.append(fn)
    def _params_to_device(self) -> None:
        if self._param_offload_device == "nvme":
            if self.params is None:
                self.params = self._param_nvme_swapper.swap_in(self.param_shardings)
        elif self._param_offload_device == "cpu":
            self.params = jax.device_put(self.params, self.param_shardings)

    def _params_to_offload(self) -> None:
        if self._param_offload_device == "nvme":
            self._param_nvme_swapper.swap_out(self.params)
            self.params = None
        elif self._param_offload_device == "cpu":
            self.params = jax.device_put(self.params, self._param_host_shardings)

    # ==================================================================
    # DeepSpeed-compatible micro-step path
    def forward(self, batch: Any) -> Any:
        """Compute loss for a microbatch (no grads). Provided for API parity;
        ``backward`` recomputes through ``jax.grad`` (forward+backward fuse
        on TPU, so the split exists only at the Python API level)."""
        self._reject_if_pipelined()
        self._params_to_device()
        # no phase timer here: forward() is an eval op in this engine
        # (backward() recomputes through jax.grad), and it is routinely
        # called for validation between optimizer steps — accumulating it
        # into the next step's phase times would corrupt wall_time_s and
        # trip false stalls
        loss, _aux = self._jitted_eval()(self.params, batch, self._next_rng())
        self._last_loss = loss
        return loss

    def backward(self, batch: Any) -> Any:
        """Accumulate gradient shards for one microbatch (parity with
        engine.backward engine.py:1902 + ZeRO IPG accumulation)."""
        self._reject_if_pipelined()
        self._params_to_device()
        self._note_batch_shape(batch, scale=self.gradient_accumulation_steps)
        if self._micro_grad_fn is None:
            self._micro_grad_fn = jax.jit(
                lambda p, b, r, s: self._loss_and_grads(p, b, r, s)[:2],
                out_shardings=(self.grad_shardings, None))
        scale = self.scaler_state.scale if self.config.fp16.enabled else jnp.ones([], jnp.float32)
        want_stats = self.telemetry.wants_step_records
        if want_stats:
            self.timers("compat/backward").start()
        grads, loss = self._micro_grad_fn(self.params, batch, self._next_rng(), scale)
        if want_stats:
            self.timers("compat/backward").stop(sync_obj=loss)
        if self._acc_grads is None:
            self._acc_grads = grads
        else:
            # cache the jitted accumulator: a fresh jax.jit(lambda ...)
            # per microbatch is a new wrapper with an empty trace cache,
            # i.e. one recompile per accumulation step (dslint
            # recompile-hazard)
            if self._acc_add_fn is None:
                self._acc_add_fn = jax.jit(
                    lambda a, g: jax.tree_util.tree_map(jnp.add, a, g),
                    donate_argnums=(0,))
            self._acc_grads = self._acc_add_fn(self._acc_grads, grads)
        self.micro_steps += 1
        self._last_loss = loss
        return loss

    def _reject_if_pipelined(self) -> None:
        if self._pipelined:
            # reference parity: PipelineEngine only supports train_batch()
            # (pipe/engine.py — forward/backward are schedule instructions,
            # not user API)
            raise RuntimeError("pipelined engine: use train_batch(), not "
                               "forward()/backward()/step()")

    def step(self) -> None:
        """Apply the update at a gradient-accumulation boundary (parity with
        engine.step engine.py:2100: no-op off-boundary)."""
        self._reject_if_pipelined()
        if self.micro_steps % self.gradient_accumulation_steps != 0:
            return
        if self._acc_grads is None:
            logger.warning("step() called with no accumulated gradients")
            return
        if self._apply_update_fn is None:
            optimizer = self.optimizer
            cfg = self.config

            def apply_update(params, opt_state, scaler_state, grads):
                inv = 1.0 / cfg.gradient_accumulation_steps
                grads = jax.tree_util.tree_map(lambda g: g * inv, grads)
                scale = scaler_state.scale if cfg.fp16.enabled else jnp.ones([], jnp.float32)
                return self._update(params, opt_state, scaler_state, grads, scale,
                                    clip=cfg.gradient_clipping, fp16=cfg.fp16.enabled,
                                    dynamic=cfg.fp16.enabled and cfg.fp16.dynamic_loss_scale,
                                    optimizer=optimizer,
                                    nan_skip=self._nan_skip_traced)

            donate = (0, 1, 2, 3) if self._donate else ()
            self._apply_update_fn = jax.jit(apply_update, donate_argnums=donate)

        self._params_to_device()
        want_stats = self.telemetry.wants_step_records
        if want_stats:
            self.timers("compat/optimizer").start()
        self.params, self.opt_state, self.scaler_state, gnorm, skipped = self._apply_update_fn(
            self.params, self.opt_state, self.scaler_state, self._acc_grads)
        if want_stats:
            self.timers("compat/optimizer").stop(sync_obj=gnorm)
        self._acc_grads = None
        self._params_to_offload()
        self.global_steps += 1
        # the compat fwd/bwd/step path drives global_steps without the
        # throughput timer; keep the two counters aligned so a later
        # train_batch's report boundary lands on steps_per_print multiples
        self.tput.step_count = self.global_steps
        self._note_skipped(skipped)
        phase_times = None
        wall = None
        if want_stats:
            # phase wall times accumulated since the last boundary. Only
            # backward/optimizer: forward() is an eval op here (see above),
            # so forward_s stays null on both engine paths
            phase_times = {}
            for phase in ("backward", "optimizer"):
                t = self.timers.timers.get(f"compat/{phase}")
                if t is not None and t.count:
                    phase_times[phase] = t.elapsed_total
                    t.reset()
            wall = sum(phase_times.values()) or None
        self._emit_step({"loss": self._last_loss, "grad_norm": gnorm,
                         "loss_scale": self.scaler_state.scale, "skipped": skipped},
                        wall_time_s=wall, phase_times=phase_times)
        if self._ft_active or self.preemption_guard is not None:
            # the compat path is an optimizer-step boundary too: divergence
            # guards, preemption drain and periodic auto-save all apply
            self._after_step({"loss": self._last_loss, "grad_norm": gnorm,
                              "skipped": skipped})

    # ==================================================================
    def eval_batch(self, batch: Any) -> Any:
        self._params_to_device()
        loss, aux = self._jitted_eval()(self.params, batch, self._next_rng())
        return loss

    def _jitted_eval(self):
        if self._eval_step_fn is None:
            def eval_step(params, batch, rng):
                self._trace_counts["eval_step"] += 1  # dslint: disable=trace-hygiene -- deliberate trace-time counter (recompile telemetry)
                return self.loss_fn(self._compute_copy(params), batch, rng)

            self._eval_step_fn = jax.jit(eval_step)
        return self._eval_step_fn

    def _next_rng(self):
        self.rng, sub = jax.random.split(self.rng)
        return sub

    def _emit_step(self, metrics: Dict[str, Any],
                   wall_time_s: Optional[float] = None,
                   log_step: Optional[bool] = None,
                   phase_times: Optional[Dict[str, float]] = None,
                   host: Optional[Dict[str, Optional[float]]] = None,
                   n_steps: int = 1) -> None:
        """Step-boundary observability: the human log line plus — when any
        telemetry sink is configured (JSONL/Prometheus/monitor) — one
        StepStats span record through the unified pipeline. Replaces the
        seed's ad-hoc ``_write_monitor``: MonitorMaster now receives its
        Train/* events as one telemetry sink among several."""
        # keyed off the throughput timer's boundary when the caller knows it
        # (train_batch) so the blocking float() fetches below never land
        # mid-window on an unsynced step; global_steps fallback for the
        # compat step() path
        if log_step is None:
            log_step = self.global_steps % self.config.steps_per_print == 0
        if log_step:
            log_dist(
                f"step={self.global_steps} loss={float(metrics['loss']):.4f} "
                f"lr={self.get_lr():.3e} grad_norm={float(metrics['grad_norm']):.3f}"
                + (f" loss_scale={float(metrics['loss_scale']):.0f}" if self.config.fp16.enabled else "")
            )
        if not self.telemetry.wants_step_records:
            return
        self.telemetry.record_step(
            self._build_step_stats(metrics, wall_time_s, phase_times,
                                   host=host, n_steps=n_steps))

    def _build_step_stats(self, metrics: Dict[str, Any],
                          wall_time_s: Optional[float],
                          phase_times: Optional[Dict[str, float]] = None,
                          host: Optional[Dict[str, Optional[float]]] = None,
                          n_steps: int = 1):
        from ..telemetry import StepStats

        dt = float(wall_time_s) if wall_time_s else 0.0
        tokens = self._count_batch_tokens() * n_steps
        comm, comm_s = self._comm_step_delta()
        if self.telemetry.enabled:
            from ..utils.memory import device_memory_stats, host_rss_gb

            memory = device_memory_stats()
            rss = host_rss_gb()
            if rss is not None:
                memory["host_rss_gb"] = rss
        else:  # monitor-only: reuse the report-boundary sample, if any
            memory = dict(self.tput.last_memory)
        mfu = 0.0
        if dt > 0 and self._step_flops and self._get_peak_flops():
            mfu = self._step_flops * n_steps / dt / self._get_peak_flops()
        host = host or {}
        # distributed-tracing join: when a tracer is installed, the step
        # lands as one "train/step" span and the record carries its ids
        # (telemetry/tracing.py). Off by default: one attribute check.
        trace_id = span_id = None
        from ..telemetry.tracing import get_tracer

        tracer = get_tracer()
        if tracer.enabled:
            from ..resilience.clock import get_clock

            t_end = get_clock().time()
            sp = tracer.span_complete(
                "train/step", t_end - dt, t_end, track="train",
                step=self.global_steps, n_steps=n_steps)
            trace_id, span_id = sp.trace_id, sp.span_id
        quant_err = None
        if metrics.get("quant_rel_err") is not None:
            # one extra host fetch, paid only when comm_compression.
            # error_stats is on (docs/communication.md#error-bounds)
            quant_err = float(metrics["quant_rel_err"])
            from ..telemetry.registry import get_registry

            get_registry().histogram("comm/quant_rel_err").observe(quant_err)
        return StepStats(
            step=self.global_steps,
            n_steps=n_steps,
            wall_time_s=dt,
            tokens_per_s=tokens / dt if dt > 0 else 0.0,
            samples_per_s=(self.train_batch_size * n_steps / dt
                           if dt > 0 else 0.0),
            host_ms=host.get("host_ms"),
            data_wait_ms=host.get("data_wait_ms"),
            dispatch_gap_ms=host.get("dispatch_gap_ms"),
            mfu=mfu,
            loss=float(metrics["loss"]) if metrics.get("loss") is not None else None,
            grad_norm=float(metrics["grad_norm"]) if metrics.get("grad_norm") is not None else None,
            loss_scale=float(metrics["loss_scale"]) if self.config.fp16.enabled else None,
            lr=self.get_lr(),
            skipped=bool(metrics["skipped"]) if metrics.get("skipped") is not None else None,
            forward_s=(phase_times or {}).get("forward"),
            backward_s=(phase_times or {}).get("backward"),
            optimizer_s=(phase_times or {}).get("optimizer"),
            comm_s=comm_s,
            comm=comm,
            quant_rel_err=quant_err,
            memory=memory,
            trace_id=trace_id,
            span_id=span_id,
        )

    def _count_batch_tokens(self) -> int:
        """Tokens per optimizer step: sequence models carry [batch, seq]
        input_ids; anything else counts samples (tokens == samples for
        non-sequence workloads)."""
        return (self._tokens_per_batch if self._tokens_per_batch is not None
                else self.config.train_batch_size)

    def _note_batch_shape(self, batch: Any, scale: int = 1) -> None:
        """Latch tokens-per-step from the first observed batch. ``scale``
        lifts a micro-batch (compat path) to the full accumulation step."""
        if self._tokens_per_batch is not None:
            return
        if isinstance(batch, dict) and "input_ids" in batch:
            self._tokens_per_batch = int(
                np.prod(batch["input_ids"].shape)) * scale
        else:
            self._tokens_per_batch = self.config.train_batch_size

    def _grad_reduce_comm(self):
        """(op, entry) for this step's gradient-reduction traffic. GSPMD
        inserts the collective inside the compiled step where the facade's
        wrappers cannot see it, but the op and payload are determined by
        the grad shardings: replicated grads (stage 0) reduce with an
        all-reduce of the full fp32 tree; sharded grads (stage >= 1) with
        a reduce-scatter. Recorded with the CommsLogger ONCE (so
        measure_comm_latencies can replay it and log_summary shows one
        row, not one per step) and merged into every step's breakdown
        here; time_s comes from the backfilled record when available."""
        dp = self.topo.data_parallel_size
        if dp <= 1 or not self._grad_bytes:
            return None
        if self._qgz or self._staged_mode is not None:
            # the facade paths record their own (quantized, wire-accurate)
            # ledger entries at trace time — a synthetic dense booking on
            # top would double-count traffic that never happens
            return None
        from ..comm.comm import get_comms_logger

        log = get_comms_logger()
        if not log.enabled:
            return None
        op = "reduce_scatter" if self.config.zero.stage >= 1 else "all_reduce"
        if not self._grad_comm_noted:
            log.append(op, self._grad_bytes, 0.0, dp, "data")
            self._grad_comm_noted = True
        else:
            # append() fed the registry once at the one-time record; keep
            # the exported comm/<op> counters tracking the per-step traffic
            from ..telemetry.registry import get_registry

            reg = get_registry()
            reg.counter(f"comm/{op}/calls").inc()
            reg.counter(f"comm/{op}/bytes").inc(self._grad_bytes)
            reg.counter(f"comm/{op}/wire_bytes").inc(self._grad_bytes)
        durs = log.records.get(op, {}).get(self._grad_bytes, [])
        t = durs[0] if durs and durs[0] > 0 else 0.0
        return op, {"count": 1.0, "bytes": float(self._grad_bytes),
                    "wire_bytes": float(self._grad_bytes), "time_s": t}

    def _comm_step_delta(self):
        """Per-step comm breakdown: delta of the CommsLogger's cumulative
        totals since the last emitted step. Counts/bytes are trace-time
        facts; time_s becomes real once measure_comm_latencies backfills."""
        from ..comm.comm import get_comms_logger

        # the engine's implied gradient reduction happens EVERY step, but
        # its CommsLogger record is a one-time synthetic append (so
        # measure_comm_latencies can replay it). Subtract that record from
        # the cumulative stream — including its possibly-backfilled
        # duration — and re-inject the entry per step below; otherwise the
        # step after a backfill would count the measured latency twice
        # (once via the snapshot jump, once via the merge).
        grad = self._grad_reduce_comm()
        totals = get_comms_logger().snapshot_totals()
        if grad is not None and grad[0] in totals:
            cur = totals[grad[0]]
            for k in ("count", "bytes", "wire_bytes", "time_s"):
                cur[k] = max(0.0, cur.get(k, 0.0) - grad[1].get(k, 0.0))
        delta: Dict[str, Dict[str, float]] = {}
        comm_s = 0.0
        for op, cur in totals.items():
            prev = self._comm_totals_prev.get(op, {})
            d = {k: cur[k] - prev.get(k, 0.0) for k in cur}
            if d["count"] <= 0 and d["time_s"]:
                # duration moved with no new records: that's a
                # measure_comm_latencies backfill rewriting history, not
                # traffic on this step — don't spike this step's comm_s
                d["time_s"] = 0.0
            if any(v for v in d.values()):
                delta[op] = d
                comm_s += d["time_s"]
        self._comm_totals_prev = totals
        if grad is not None:
            op, entry = grad
            if op in delta:
                for k in entry:
                    delta[op][k] += entry[k]
            else:
                delta[op] = dict(entry)
            comm_s += entry["time_s"]
        return delta, (comm_s if comm_s > 0 else None)

    def _measure_step_flops(self, batch: Any) -> None:
        """One-time HLO cost analysis of the fused train step (the flops
        profiler's program counting applied to the real step). Analysis
        runs on the LOWERED module, not a compiled one — ``.compile()``
        here would XLA-compile the step a second time (the AOT executable
        does not populate the jit call cache), doubling time-to-first-step
        for large models. Pre-optimization flops differ negligibly for the
        matmul-dominated MFU numerator."""
        self._note_batch_shape(batch)
        try:
            cost = self._train_step_fn.lower(
                self.params, self.opt_state, self.scaler_state, self.rng,
                batch).cost_analysis()
            if isinstance(cost, list):  # some versions return [dict]
                cost = cost[0] if cost else {}
            f = (cost or {}).get("flops")
            self._step_flops = float(f) if f and f > 0 else 0.0
        except Exception as e:  # backend without cost analysis
            logger.debug(f"train-step cost analysis unavailable: {e}")
            self._step_flops = 0.0

    def _get_peak_flops(self) -> float:
        if self._peak_flops is None:
            from ..profiling.flops_profiler import _peak_flops_per_device

            self._peak_flops = _peak_flops_per_device() * len(jax.devices())
        return self._peak_flops

    def close(self) -> None:
        """Engine shutdown: flush + close every telemetry sink (including
        the MonitorMaster adapter — the TensorBoard writer buffers events
        and loses the run tail if never closed). Idempotent."""
        if self._closed:
            return
        self._closed = True
        if self._warmup_thread is not None:
            self._warmup_thread.join()
            self._warmup_thread = None
        self.telemetry.close()
        from ..telemetry import get_telemetry, set_telemetry

        if get_telemetry() is self.telemetry:
            set_telemetry(None)

    # ==================================================================
    # checkpointing (parity with engine.save_checkpoint engine.py:3010)
    def _materialized_params(self) -> Any:
        """Params for read-out (export/eval/state-dict): swapped in from
        disk under nvme offload WITHOUT mutating the engine's parked state."""
        if self._param_offload_device == "nvme" and self.params is None:
            return self._param_nvme_swapper.swap_in()
        return self.params

    def _state_dict(self) -> Dict[str, Any]:
        opt_state = self.opt_state
        if self._offload_device == "nvme" and opt_state is None:
            opt_state = self._nvme_swapper.swap_in()
        return {
            "params": self._materialized_params(),
            "opt_state": opt_state,
            "scaler": self.scaler_state,
            "step": jnp.asarray(self.global_steps, jnp.int32),
            "rng": self.rng,
        }

    def save_checkpoint(self, save_dir: str, tag: Optional[str] = None,
                        client_state: Optional[Dict[str, Any]] = None,
                        model_version: Optional[int] = None) -> str:
        tag = tag if tag is not None else f"global_step{self.global_steps}"
        validate_tag_consistency(str(tag), self.config.checkpoint.tag_validation)
        client = {**(client_state or {}),
                  "global_steps": self.global_steps,
                  "micro_steps": self.micro_steps,
                  "skipped_steps": self.skipped_steps}
        if self._dataloader is not None and hasattr(self._dataloader,
                                                    "state_dict"):
            # data-pipeline position rides along so resume replays the
            # exact remaining batch order (bit-exact resume contract)
            client["dataloader"] = self._dataloader.state_dict()
        return self.ckpt_engine.save(
            save_dir, str(tag), self._state_dict(),
            client_state=client,
            config_snapshot=self.config.raw,
            model_version=model_version)

    def load_checkpoint(self, load_dir: str, tag: Optional[str] = None,
                        load_optimizer_states: bool = True,
                        auto: bool = False) -> Optional[Dict[str, Any]]:
        """Restore engine state. ``tag=None`` picks the newest VALID tag
        (torn/uncommitted/corrupt tags are verified against their manifest
        and skipped — see runtime/checkpoint.py). ``auto=True`` is the
        resume-after-restart entry point: a missing/empty directory is a
        quiet no-op instead of a warning, so first boot and restart share
        one code path."""
        if auto and not os.path.isdir(load_dir):
            return None
        # struct-only template: never swaps offloaded state in from disk
        # just to learn the tree structure
        template = {
            "params": self._params_struct,
            "opt_state": self._opt_struct,
            "scaler": self.scaler_state,
            "step": jnp.asarray(self.global_steps, jnp.int32),
            "rng": self.rng,
        }
        result = self.ckpt_engine.load(load_dir, tag, template=template)
        if result is None:
            return None
        state = result["state"]
        repl = self.topo.replicated()
        self.params = jax.device_put(state["params"], self.param_shardings)
        self._params_to_offload()
        if load_optimizer_states:
            if self._offload_device == "nvme":
                self._nvme_swapper.swap_out(state["opt_state"])
                self.opt_state = None
            else:
                target = (self._opt_host_shardings
                          if self._opt_host_shardings is not None
                          else self.opt_state_shardings)
                self.opt_state = jax.device_put(state["opt_state"], target)
            self.scaler_state = jax.device_put(
                jax.tree_util.tree_map(jnp.asarray, state["scaler"]), repl)
        self.global_steps = int(state["step"])
        # keep the throughput timer's step counter aligned with
        # global_steps so the report boundary (will_report_next) stays on
        # steps_per_print multiples of the *global* step across resumes
        self.tput.step_count = self.global_steps
        self.rng = jax.device_put(jnp.asarray(state["rng"]), repl)
        client = result["meta"].get("client_state", {})
        self.micro_steps = int(client.get("micro_steps", self.global_steps * self.gradient_accumulation_steps))
        self.skipped_steps = int(client.get("skipped_steps", 0))
        if (self._dataloader is not None and "dataloader" in client
                and hasattr(self._dataloader, "load_state_dict")):
            self._dataloader.load_state_dict(client["dataloader"])
        return client

    def hot_swap_checkpoint(self, load_dir: str,
                            tag: Optional[str] = None,
                            warmup_batch: Optional[Any] = None
                            ) -> Optional[int]:
        """Weight-only swap for zero-downtime rollout (serving/rollout.py).

        Loads ONLY ``params`` from the checkpoint — optimizer state,
        loss-scaler, step counters, rng, and dataloader position are all
        left untouched, because the process keeps serving/training as the
        same logical worker; only the model weights flip. The checkpoint
        is manifest-verified exactly like :meth:`load_checkpoint` — a
        torn or corrupt tag raises instead of half-swapping, so the
        rollout controller's swap-failure path (re-open admission, retry
        or roll back) sees a clean error, never a franken-model.

        ``warmup_batch`` triggers :meth:`warmup_async` on the new weights
        so the first post-swap step does not eat a compile stall.

        Returns the checkpoint's ``model_version`` manifest field (None
        when the checkpoint predates version stamping).
        """
        template = {
            "params": self._params_struct,
            "opt_state": self._opt_struct,
            "scaler": self.scaler_state,
            "step": jnp.asarray(self.global_steps, jnp.int32),
            "rng": self.rng,
        }
        result = self.ckpt_engine.load(load_dir, tag, template=template)
        if result is None:
            raise ValueError(
                f"hot_swap_checkpoint: no valid checkpoint under "
                f"{load_dir!r} (tag={tag!r}) — refusing to swap")
        self.params = jax.device_put(result["state"]["params"],
                                     self.param_shardings)
        self._params_to_offload()
        if warmup_batch is not None:
            self.warmup_async(warmup_batch)
        version = result["meta"].get("model_version")
        return int(version) if version is not None else None

    def save_16bit_model(self, save_dir: str, filename: str = "model_fp16.npz") -> str:
        """Consolidated 16-bit export (reference engine.save_16bit_model
        engine.py:3492 + zero_to_fp32 consolidation)."""
        os.makedirs(save_dir, exist_ok=True)
        flat = consolidate_full_state(
            _cast_tree(self._materialized_params(), jnp.bfloat16))
        leaves, treedef = jax.tree_util.tree_flatten_with_path(flat)
        out = {jax.tree_util.keystr(k): np.asarray(v) for k, v in leaves}
        path = os.path.join(save_dir, filename)
        np.savez(path, **out)
        return path

    def get_fp32_state_dict(self) -> Any:
        return consolidate_full_state(self._materialized_params(),
                                      dtype=np.float32)
