"""Memory-mapped indexed dataset (Megatron/DeepSpeed ``.bin``/``.idx``
binary format).

Reference surface: ``deepspeed/runtime/data_pipeline/data_sampling/
indexed_dataset.py`` (``MMapIndexedDataset`` + builder) — the de-facto
public pretraining-corpus container (magic ``MMIDIDX``): an ``.idx`` file
holding dtype code, per-sequence lengths, byte pointers, and document
boundaries, and a flat ``.bin`` of token payloads. Reading stays mmap'd so
a multi-hundred-GB corpus costs no resident RAM; this matters on TPU VMs
whose host RAM is small relative to the corpus.

This is an independent implementation of the published format (readable by
/ produced for Megatron-family tooling), not a translation of the
reference code.
"""

from __future__ import annotations

import os
import struct
from typing import List, Optional, Sequence

import numpy as np

_MAGIC = b"MMIDIDX\x00\x00"
_VERSION = 1

# dtype codes of the published format
_DTYPES = {1: np.uint8, 2: np.int8, 3: np.int16, 4: np.int32,
           5: np.int64, 6: np.float64, 7: np.float32, 8: np.uint16}
_CODES = {np.dtype(v): k for k, v in _DTYPES.items()}


def data_file_path(prefix: str) -> str:
    return prefix + ".bin"


def index_file_path(prefix: str) -> str:
    return prefix + ".idx"


class MMapIndexedDatasetBuilder:
    """Stream sequences into ``prefix.bin`` and finalize ``prefix.idx``."""

    def __init__(self, out_file: str, dtype=np.int32):
        self._data = open(out_file, "wb")
        self._dtype = np.dtype(dtype)
        self._sizes: List[int] = []
        self._doc_idx: List[int] = [0]

    def add_item(self, tokens: Sequence[int]) -> None:
        arr = np.asarray(tokens, dtype=self._dtype)
        self._data.write(arr.tobytes(order="C"))
        self._sizes.append(arr.size)

    def end_document(self) -> None:
        self._doc_idx.append(len(self._sizes))

    def finalize(self, index_file: str) -> None:
        self._data.close()
        sizes = np.asarray(self._sizes, np.int32)
        itemsize = self._dtype.itemsize
        pointers = np.zeros(len(sizes), np.int64)
        np.cumsum(sizes[:-1] * itemsize, out=pointers[1:])
        with open(index_file, "wb") as f:
            f.write(_MAGIC)
            f.write(struct.pack("<Q", _VERSION))
            f.write(struct.pack("<B", _CODES[self._dtype]))
            f.write(struct.pack("<Q", len(sizes)))
            f.write(struct.pack("<Q", len(self._doc_idx)))
            f.write(sizes.tobytes(order="C"))
            f.write(pointers.tobytes(order="C"))
            f.write(np.asarray(self._doc_idx, np.int64).tobytes(order="C"))


class MMapIndexedDataset:
    """Zero-copy reads: ``ds[i]`` returns a numpy view into the mmap."""

    def __init__(self, prefix: str):
        idx_path = index_file_path(prefix)
        with open(idx_path, "rb") as f:
            magic = f.read(len(_MAGIC))
            if magic != _MAGIC:
                raise ValueError(f"{idx_path}: bad magic {magic!r} "
                                 "(not an MMIDIDX index)")
            (version,) = struct.unpack("<Q", f.read(8))
            if version != _VERSION:
                raise ValueError(f"unsupported index version {version}")
            (code,) = struct.unpack("<B", f.read(1))
            self._dtype = np.dtype(_DTYPES[code])
            (count,) = struct.unpack("<Q", f.read(8))
            (doc_count,) = struct.unpack("<Q", f.read(8))
            offset = f.tell()
        self._index_mmap = np.memmap(idx_path, mode="r", dtype=np.uint8)
        self.sizes = np.frombuffer(self._index_mmap, np.int32, count,
                                   offset=offset)
        offset += count * 4
        self._pointers = np.frombuffer(self._index_mmap, np.int64, count,
                                       offset=offset)
        offset += count * 8
        self.doc_idx = np.frombuffer(self._index_mmap, np.int64, doc_count,
                                     offset=offset)
        self._data_mmap = np.memmap(data_file_path(prefix), mode="r",
                                    dtype=np.uint8)

    def __len__(self) -> int:
        return len(self.sizes)

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(len(self)))]
        size = int(self.sizes[i])
        ptr = int(self._pointers[i])
        return np.frombuffer(self._data_mmap, self._dtype, size, offset=ptr)

    def get(self, i: int, offset: int = 0,
            length: Optional[int] = None) -> np.ndarray:
        """Partial-sequence read without touching the rest (the reference's
        ``get``): mmap means only the needed pages fault in."""
        size = int(self.sizes[i])
        length = size - offset if length is None else length
        ptr = int(self._pointers[i]) + offset * self._dtype.itemsize
        return np.frombuffer(self._data_mmap, self._dtype, length, offset=ptr)

    @property
    def dtype(self):
        return self._dtype


def make_builder(out_prefix: str, dtype=np.int32) -> MMapIndexedDatasetBuilder:
    os.makedirs(os.path.dirname(os.path.abspath(out_prefix)), exist_ok=True)
    return MMapIndexedDatasetBuilder(data_file_path(out_prefix), dtype=dtype)
