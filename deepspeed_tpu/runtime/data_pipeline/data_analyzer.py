"""Offline data analysis for curriculum learning.

Reference surface: ``deepspeed/runtime/data_pipeline/data_sampling/
data_analyzer.py`` (``DataAnalyzer.run_map_reduce``): walk the dataset,
compute per-sample difficulty metrics (seqlen, vocab rarity, custom
functions), and write two artifacts per metric that the curriculum sampler
consumes:

* ``<metric>_sample_to_metric`` — metric value per sample index (mmap'd
  indexed dataset, one int per sample);
* ``<metric>_metric_to_sample`` — for each distinct metric value, the list
  of sample indices at that value (the difficulty buckets).

The reference fans out torch workers + barriers for the map phase and
merges per-worker files in reduce; here the map is chunked numpy on one
host (a TPU-VM host analyzes ~1M samples/min for seqlen-class metrics) and
both artifacts land in the same mmap container (indexed_dataset.py), so
the curriculum sampler streams them without loading anything resident.
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from .indexed_dataset import MMapIndexedDataset, make_builder


def metric_seqlen(batch: List[np.ndarray]) -> np.ndarray:
    """Built-in metric: token count per sample (curriculum 'seqlen')."""
    return np.asarray([len(s) for s in batch], np.int64)


def metric_vocab_rarity(vocab_size: int):
    """Built-in metric factory: mean token frequency rank proxy (rarer
    tokens -> larger metric; reference vocab_rarity analog)."""

    def fn(batch: List[np.ndarray]) -> np.ndarray:
        return np.asarray([int(np.mean(s)) if len(s) else 0 for s in batch],
                          np.int64)

    return fn


class DataAnalyzer:
    """``run_map_reduce`` parity (reference data_analyzer.py)."""

    def __init__(self, dataset: Any,
                 metric_names: Sequence[str],
                 metric_functions: Sequence[Callable],
                 save_path: str,
                 batch_size: int = 1024,
                 metric_types: Optional[Sequence[str]] = None):
        assert len(metric_names) == len(metric_functions)
        self.dataset = dataset
        self.metric_names = list(metric_names)
        self.metric_functions = list(metric_functions)
        self.metric_types = list(metric_types or
                                 ["single_value_per_sample"] * len(metric_names))
        self.save_path = save_path
        self.batch_size = batch_size

    def _iter_chunks(self):
        n = len(self.dataset)
        for start in range(0, n, self.batch_size):
            end = min(start + self.batch_size, n)
            yield start, [np.asarray(self.dataset[i]) for i in range(start, end)]

    def run_map_reduce(self) -> Dict[str, Dict[str, str]]:
        """Returns {metric: {"sample_to_metric": prefix,
        "metric_to_sample": json_path, "min": .., "max": ..}}."""
        os.makedirs(self.save_path, exist_ok=True)
        n = len(self.dataset)
        values = {m: np.zeros(n, np.int64) for m in self.metric_names}
        for start, batch in self._iter_chunks():         # map
            for name, fn in zip(self.metric_names, self.metric_functions):
                out = np.asarray(fn(batch), np.int64)
                values[name][start:start + len(batch)] = out

        result: Dict[str, Dict[str, str]] = {}
        for name in self.metric_names:                    # reduce
            vals = values[name]
            prefix = os.path.join(self.save_path, f"{name}_sample_to_metric")
            builder = make_builder(prefix, dtype=np.int64)
            for v in vals:
                builder.add_item([int(v)])
            builder.end_document()
            builder.finalize(prefix + ".idx")

            buckets: Dict[int, List[int]] = {}
            for i, v in enumerate(vals.tolist()):
                buckets.setdefault(int(v), []).append(i)
            m2s_path = os.path.join(self.save_path,
                                    f"{name}_metric_to_sample.json")
            with open(m2s_path, "w") as f:
                json.dump({str(k): v for k, v in sorted(buckets.items())}, f)
            result[name] = {
                "sample_to_metric": prefix,
                "metric_to_sample": m2s_path,
                "min": int(vals.min()) if n else 0,
                "max": int(vals.max()) if n else 0,
            }
        with open(os.path.join(self.save_path, "analysis_index.json"), "w") as f:
            json.dump(result, f, indent=2)
        return result


def load_sample_to_metric(prefix: str) -> np.ndarray:
    """Read a sample_to_metric artifact back as a flat int64 array."""
    ds = MMapIndexedDataset(prefix)
    return np.asarray([int(ds[i][0]) for i in range(len(ds))], np.int64)


def samples_up_to_difficulty(metric_to_sample_json: str,
                             difficulty: int) -> np.ndarray:
    """Curriculum query: all sample indices whose metric <= difficulty —
    what the CL sampler draws from at a given schedule step."""
    with open(metric_to_sample_json) as f:
        buckets = json.load(f)
    out: List[int] = []
    for k, idxs in buckets.items():
        if int(k) <= difficulty:
            out.extend(idxs)
    return np.asarray(sorted(out), np.int64)
