"""Curriculum learning scheduler.

Parity with reference ``runtime/data_pipeline/curriculum_scheduler.py:11``
(CurriculumScheduler): difficulty ramps by schedule type
``fixed_linear`` / ``fixed_root`` / ``fixed_discrete`` / ``custom``, with
``update_difficulty(global_step)`` / ``get_current_difficulty()`` and
state_dict round-trip. Difficulty typically modulates sequence length
(truncation) — see DataLoader.curriculum hook.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, Optional

FIXED_LINEAR = "fixed_linear"
FIXED_ROOT = "fixed_root"
FIXED_DISCRETE = "fixed_discrete"
CUSTOM = "custom"


class CurriculumScheduler:
    def __init__(self, config: Dict[str, Any]):
        self.state: Dict[str, Any] = {}
        assert "curriculum_type" in config, "curriculum_type required"
        assert "min_difficulty" in config and "max_difficulty" in config
        ctype = config["curriculum_type"]
        self.state["schedule_type"] = ctype
        self.state["min_difficulty"] = config["min_difficulty"]
        self.state["max_difficulty"] = config["max_difficulty"]
        self.state["current_difficulty"] = config["min_difficulty"]
        sched = config.get("schedule_config", {})
        if ctype in (FIXED_LINEAR, FIXED_ROOT):
            assert "total_curriculum_step" in sched
            sched.setdefault("difficulty_step", 1)
            if ctype == FIXED_ROOT:
                sched.setdefault("root_degree", 2)
        elif ctype == FIXED_DISCRETE:
            assert "difficulty" in sched and "max_step" in sched
            assert len(sched["difficulty"]) == len(sched["max_step"]) + 1
        elif ctype == CUSTOM:
            self._custom_fn: Optional[Callable[[int], int]] = sched.get("difficulty_fn")
            assert callable(self._custom_fn), "custom curriculum needs difficulty_fn"
        else:
            raise ValueError(f"unknown curriculum_type {ctype!r}")
        self.state["schedule"] = sched

    # -- reference API --------------------------------------------------
    def get_current_difficulty(self) -> int:
        return self.state["current_difficulty"]

    def set_current_difficulty(self, d: int) -> None:
        self.state["current_difficulty"] = d

    def update_difficulty(self, global_steps: int) -> int:
        self.state["current_difficulty"] = self.__difficulty(global_steps)
        return self.state["current_difficulty"]

    def get_state(self) -> Dict[str, Any]:
        return dict(self.state)

    def set_state(self, state: Dict[str, Any]) -> None:
        self.state.update(state)

    # -- schedules ------------------------------------------------------
    def __difficulty(self, step: int) -> int:
        lo, hi = self.state["min_difficulty"], self.state["max_difficulty"]
        sched = self.state["schedule"]
        ctype = self.state["schedule_type"]
        if ctype == FIXED_LINEAR:
            frac = min(step / sched["total_curriculum_step"], 1.0)
        elif ctype == FIXED_ROOT:
            frac = min((step / sched["total_curriculum_step"]) **
                       (1.0 / sched["root_degree"]), 1.0)
        elif ctype == FIXED_DISCRETE:
            idx = sum(1 for m in sched["max_step"] if step > m)
            return int(sched["difficulty"][idx])
        else:
            return int(self._custom_fn(step))
        d = lo + (hi - lo) * frac
        # round down to difficulty_step granularity (reference behavior)
        q = sched.get("difficulty_step", 1)
        d = int(d // q * q)
        return max(lo, min(hi, d))
