"""Data efficiency suite (reference runtime/data_pipeline/): curriculum
learning scheduler, curriculum-aware data sampler, and random-LTD
(layer-token drop)."""

from .curriculum_scheduler import CurriculumScheduler
from .data_sampler import DeepSpeedDataSampler
from .random_ltd import RandomLTDScheduler, random_ltd_gather, random_ltd_scatter

__all__ = ["CurriculumScheduler", "DeepSpeedDataSampler", "RandomLTDScheduler",
           "random_ltd_gather", "random_ltd_scatter"]
