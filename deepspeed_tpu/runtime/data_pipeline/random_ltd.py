"""Random-LTD: random layerwise token dropping.

Parity with reference ``runtime/data_pipeline/data_routing/`` (basic_layer.py
RandomLayerTokenDrop + scheduler.py RandomLTDScheduler) and its CUDA helpers
``csrc/random_ltd/`` (token_sort.cu, gather_scatter.cu) — on TPU the
gather/scatter is ``jnp.take_along_axis`` with a sorted random index set
(SURVEY.md §2.4 row Random-LTD: "jax.lax.sort/gather — no custom kernel").

Mechanics: middle layers process only a random subset of tokens; the kept
tokens' outputs are scattered back into the full residual stream. The kept
count ramps linearly from ``mini_seq`` to the full sequence over the
schedule, after which the layer reverts to dense.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp


class RandomLTDScheduler:
    """Kept-token-count schedule (reference scheduler.py: update_seq per
    global step, linear ramp seq_begin -> seq_end by step_size)."""

    def __init__(self, total_layers: int, mini_seq: int, full_seq: int,
                 total_steps: int, step_size: int = 16):
        self.total_layers = total_layers
        self.mini_seq = mini_seq
        self.full_seq = full_seq
        self.total_steps = max(total_steps, 1)
        self.step_size = step_size
        self.current_seq = mini_seq

    def update_seq(self, global_step: int) -> int:
        frac = min(global_step / self.total_steps, 1.0)
        seq = int(self.mini_seq + (self.full_seq - self.mini_seq) * frac)
        seq = min(self.full_seq, (seq // self.step_size) * self.step_size)
        self.current_seq = max(self.mini_seq, seq)
        return self.current_seq

    def get_current_seq(self) -> int:
        return self.current_seq

    def state_dict(self):
        return {"current_seq": self.current_seq}

    def load_state_dict(self, sd):
        self.current_seq = sd["current_seq"]


def random_ltd_indices(rng, seq_len: int, keep: int, batch: int) -> jnp.ndarray:
    """[batch, keep] sorted random token indices (reference token_sort.cu:
    random selection that preserves order)."""
    # gumbel top-k without replacement, then sort to preserve token order
    g = jax.random.gumbel(rng, (batch, seq_len))
    _, idx = jax.lax.top_k(g, keep)
    return jnp.sort(idx, axis=-1)


def random_ltd_gather(x: jnp.ndarray, indices: jnp.ndarray) -> jnp.ndarray:
    """[b, s, d] -> [b, keep, d] (reference gather_scatter.cu gather)."""
    return jnp.take_along_axis(x, indices[..., None], axis=1)


def random_ltd_scatter(full: jnp.ndarray, part: jnp.ndarray,
                       indices: jnp.ndarray) -> jnp.ndarray:
    """Scatter processed kept tokens back over the residual stream
    (reference gather_scatter.cu scatter): dropped tokens keep their
    incoming activations (skip connection)."""
    b = full.shape[0]
    batch_idx = jnp.arange(b)[:, None]
    return full.at[batch_idx, indices].set(part)


def apply_random_ltd(layer_fn, x: jnp.ndarray, rng, keep: int):
    """Run ``layer_fn`` on a random token subset; identity elsewhere."""
    b, s, _ = x.shape
    if keep >= s:
        return layer_fn(x)
    idx = random_ltd_indices(rng, s, keep, b)
    sub = random_ltd_gather(x, idx)
    out = layer_fn(sub)
    return random_ltd_scatter(x, out, idx)
