"""Curriculum-aware data sampler.

Parity with reference ``runtime/data_pipeline/data_sampling/data_sampler.py:36``
(DeepSpeedDataSampler): samples are bucketed by a difficulty metric; each
epoch the sampler draws only from buckets at or below the curriculum's
current difficulty, sharded across data-parallel ranks deterministically.
The reference's offline map-reduce ``DataAnalyzer`` reduces here to a
difficulty callable (or precomputed array) — the mmap index machinery is
unnecessary when difficulties fit in one numpy array.
"""

from __future__ import annotations

from typing import Any, Callable, Iterator, Optional, Sequence

import numpy as np

from .curriculum_scheduler import CurriculumScheduler


class DeepSpeedDataSampler:
    def __init__(self, dataset_size: int,
                 difficulties: Sequence[float],
                 curriculum: CurriculumScheduler,
                 batch_size: int,
                 data_parallel_rank: int = 0,
                 data_parallel_size: int = 1,
                 seed: int = 0,
                 drop_last: bool = True):
        assert len(difficulties) == dataset_size
        self.difficulties = np.asarray(difficulties)
        self.dataset_size = dataset_size
        self.curriculum = curriculum
        self.batch_size = batch_size
        self.dp_rank = data_parallel_rank
        self.dp_size = data_parallel_size
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0
        self.global_step = 0
        assert batch_size % data_parallel_size == 0

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def state_dict(self):
        return {"epoch": self.epoch, "global_step": self.global_step,
                "curriculum": self.curriculum.get_state()}

    def load_state_dict(self, state) -> None:
        self.epoch = state["epoch"]
        self.global_step = state["global_step"]
        self.curriculum.set_state(state["curriculum"])

    def __iter__(self) -> Iterator[np.ndarray]:
        rng = np.random.default_rng(self.seed + self.epoch)
        order = rng.permutation(self.dataset_size)
        per_rank = self.batch_size // self.dp_size
        # Samples move exactly once from locked -> queue as the curriculum
        # difficulty grows (the reference appends newly unlocked data the same
        # way); consuming from the queue head can then never re-yield or skip
        # a sample, unlike indexing a recomputed eligible array with a cursor.
        unlocked = np.zeros(self.dataset_size, dtype=bool)
        queue: list = []
        while True:
            difficulty = self.curriculum.update_difficulty(self.global_step)
            newly = order[(self.difficulties[order] <= difficulty) & ~unlocked[order]]
            if newly.size:
                unlocked[newly] = True
                queue.extend(newly.tolist())
            if len(queue) < self.batch_size:
                if self.drop_last or not queue:
                    return
                batch, queue = np.asarray(queue), []
            else:
                batch = np.asarray(queue[:self.batch_size])
                queue = queue[self.batch_size:]
            self.global_step += 1
            yield batch[self.dp_rank * per_rank:(self.dp_rank + 1) * per_rank]

    def __len__(self) -> int:
        return self.dataset_size // self.batch_size
