from .autotuner import Autotuner, TuningConstraints, autotune  # noqa: F401
