"""Autotuner: mesh-shape × micro-batch × remat search via compile-time
analysis.

Reference surface: ``deepspeed/autotuning/autotuner.py:404`` (``tune``) —
the reference launches real training experiments per candidate (ZeRO stage
sweep, micro-batch sweep, per-config trials through the launcher). On TPU
the same search is nearly free: every candidate is AOT-compiled
(``jax.jit(...).lower(...).compile()`` on ShapeDtypeStructs — no params are
ever materialized) and scored from XLA's own ``memory_analysis()`` /
``cost_analysis()``:

* feasibility — peak device bytes (args + temps + outputs) must fit the
  per-chip HBM budget;
* cost — a roofline estimate ``max(flops/peak_flops, bytes/hbm_bw)`` over
  the compiled step.

The candidate step is a faithful proxy of ``TrainEngine``'s fused
train_step (grads in compute dtype + ZeRO sharding constraints + AdamW
update on fp32 master params); its compiled memory/flops profile is what
the real engine step will see.
"""

from __future__ import annotations

import itertools
import json
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config, MeshConfig
from ..parallel.mesh import Topology
from ..parallel.zero import ZeroShardingRules
from ..utils.logging import log_dist


# chip generation -> (bf16 peak FLOP/s, HBM bytes, HBM GB/s)
CHIP_SPECS = {
    "v5e": (197e12, 16e9, 819e9),
    "v5p": (459e12, 95e9, 2765e9),
    "v4": (275e12, 32e9, 1228e9),
    "v6e": (918e12, 32e9, 1640e9),
    "cpu": (1e12, 8e9, 100e9),  # test stand-in
}


@dataclass
class TuningConstraints:
    """Search-space bounds (reference autotuning/config.py analog)."""

    n_devices: Optional[int] = None
    chip: str = "v5e"
    hbm_bytes: Optional[float] = None          # override chip HBM
    global_batch: int = 32
    seq_len: int = 2048
    micro_batches: List[int] = field(default_factory=lambda: [1, 2, 4, 8])
    zero_stages: List[int] = field(default_factory=lambda: [3])
    tp_sizes: Optional[List[int]] = None       # default: divisors of n_devices
    # Ulysses sequence-parallel degrees to try (long-context configs where
    # activations, not params, bound memory); 1 = off
    sp_sizes: List[int] = field(default_factory=lambda: [1])
    remat_options: List[bool] = field(default_factory=lambda: [True, False])


@dataclass
class CandidateResult:
    mesh: Dict[str, int]
    micro_batch: int
    zero_stage: int
    remat: bool
    feasible: bool
    peak_bytes: float
    flops: float
    est_step_s: float
    error: Optional[str] = None

    def config_overrides(self) -> Dict[str, Any]:
        return {
            "mesh": self.mesh,
            "train_micro_batch_size_per_gpu": self.micro_batch,
            "zero_optimization": {"stage": self.zero_stage},
        }


class Autotuner:
    """``tune()`` parity (reference autotuner.py:404) — returns the best
    config plus a ranked report of every candidate."""

    def __init__(self, model_factory: Callable[..., Any],
                 constraints: TuningConstraints,
                 base_config: Optional[Dict[str, Any]] = None):
        self.model_factory = model_factory
        self.c = constraints
        self.base_config = dict(base_config or {})
        n = self.c.n_devices or len(jax.devices())
        self.n_devices = n
        peak, hbm, bw = CHIP_SPECS.get(self.c.chip, CHIP_SPECS["v5e"])
        self.peak_flops, self.hbm_bw = peak, bw
        self.hbm_bytes = self.c.hbm_bytes if self.c.hbm_bytes else hbm

    # -- candidate enumeration -----------------------------------------
    def candidates(self) -> List[Dict[str, Any]]:
        n = self.n_devices
        tps = self.c.tp_sizes or [t for t in (1, 2, 4, 8) if n % t == 0 and t <= n]
        out = []
        for tp, sp, mb, stage, remat in itertools.product(
                tps, self.c.sp_sizes, self.c.micro_batches,
                self.c.zero_stages, self.c.remat_options):
            if n % (tp * sp):
                continue
            dp = n // (tp * sp)
            if self.c.global_batch % (dp * mb):
                continue
            mesh = {"data": dp, "model": tp}
            if sp > 1:
                mesh["seq"] = sp
            out.append({"mesh": mesh, "micro_batch": mb,
                        "zero_stage": stage, "remat": remat})
        return out

    # -- per-candidate compile + analysis ------------------------------
    def evaluate(self, cand: Dict[str, Any]) -> CandidateResult:
        try:
            return self._evaluate(cand)
        except Exception as e:  # infeasible shapes, partitioner errors, ...
            return CandidateResult(
                mesh=cand["mesh"], micro_batch=cand["micro_batch"],
                zero_stage=cand["zero_stage"], remat=cand["remat"],
                feasible=False, peak_bytes=float("inf"), flops=0.0,
                est_step_s=float("inf"), error=f"{type(e).__name__}: {e}")

    def _evaluate(self, cand: Dict[str, Any]) -> CandidateResult:
        model = self.model_factory(remat=cand["remat"])
        topo = Topology.build(MeshConfig(**cand["mesh"]),
                              devices=jax.devices()[:self.n_devices])
        cfg = Config.from_any({**self.base_config,
                               "train_batch_size": self.c.global_batch,
                               **{k: v for k, v in
                                  {"zero_optimization":
                                   {"stage": cand["zero_stage"]}}.items()}})
        rules = ZeroShardingRules(topo, cfg.zero)

        rng = jax.random.PRNGKey(0)
        param_struct = jax.eval_shape(model.init, rng)
        tp_specs = (model.partition_specs(param_struct, topo)
                    if hasattr(model, "partition_specs") else None)
        if hasattr(model, "bind_topology"):
            model.bind_topology(topo)
        p32 = jax.tree_util.tree_map(
            lambda s: jax.ShapeDtypeStruct(s.shape, jnp.float32), param_struct)
        param_sh = rules.param_shardings(p32, tp_specs)
        grad_sh = rules.grad_shardings(p32, tp_specs)

        dp = topo.data_parallel_size
        mb = cand["micro_batch"]
        batch_struct = {"input_ids": jax.ShapeDtypeStruct(
            (dp * mb, self.c.seq_len), jnp.int32)}
        batch_sh = {"input_ids": topo.batch_sharding(2)}

        # proxy of TrainEngine's fused step: bf16 grads + ZeRO constraints +
        # AdamW(fp32 master) update — same compiled memory/flops profile
        def step(params, mu, nu, batch, rng):
            def loss_fn(p):
                pc = jax.tree_util.tree_map(
                    lambda x: x.astype(jnp.bfloat16)
                    if jnp.issubdtype(x.dtype, jnp.floating) else x, p)
                return model.loss(pc, batch, rng)

            grads = jax.grad(loss_fn)(params)
            grads = jax.lax.with_sharding_constraint(grads, grad_sh)
            t = jax.tree_util.tree_map
            mu = t(lambda m, g: 0.9 * m + 0.1 * g, mu, grads)
            nu = t(lambda v, g: 0.99 * v + 0.01 * g * g, nu, grads)
            params = t(lambda p, m, v: p - 1e-4 * m / (jnp.sqrt(v) + 1e-8),
                       params, mu, nu)
            return (jax.lax.with_sharding_constraint(params, param_sh),
                    mu, nu)

        opt_sh = rules.opt_state_shardings(p32)
        lowered = jax.jit(
            step,
            in_shardings=(param_sh, opt_sh, opt_sh, batch_sh, None),
            out_shardings=(param_sh, opt_sh, opt_sh),
        ).lower(p32, p32, p32, batch_struct,
                jax.ShapeDtypeStruct((2,), jnp.uint32))
        compiled = lowered.compile()

        mem = compiled.memory_analysis()
        peak = 0.0
        for attr in ("argument_size_in_bytes", "output_size_in_bytes",
                     "temp_size_in_bytes", "generated_code_size_in_bytes"):
            peak += float(getattr(mem, attr, 0.0) or 0.0)
        # analyses report whole-program bytes; per-device = /n for sharded
        peak_per_dev = peak / max(1, self.n_devices)

        cost = compiled.cost_analysis() or {}
        if isinstance(cost, list):  # older jax returns [dict]
            cost = cost[0] if cost else {}
        flops = float(cost.get("flops", 0.0))
        bytes_accessed = float(cost.get("bytes accessed", 0.0))
        gas = self.c.global_batch // (dp * mb)
        per_dev_flops = flops / max(1, self.n_devices)
        est = gas * max(per_dev_flops / self.peak_flops,
                        (bytes_accessed / max(1, self.n_devices)) / self.hbm_bw)
        return CandidateResult(
            mesh=cand["mesh"], micro_batch=mb, zero_stage=cand["zero_stage"],
            remat=cand["remat"], feasible=peak_per_dev <= self.hbm_bytes,
            peak_bytes=peak_per_dev, flops=flops, est_step_s=est)

    # -- search --------------------------------------------------------
    def tune(self) -> Dict[str, Any]:
        results = [self.evaluate(c) for c in self.candidates()]
        feasible = [r for r in results if r.feasible]
        ranked = sorted(feasible, key=lambda r: r.est_step_s)
        report = {
            "n_devices": self.n_devices,
            "chip": self.c.chip,
            "candidates": [r.__dict__ for r in
                           sorted(results, key=lambda r: r.est_step_s)],
            "best": ranked[0].__dict__ if ranked else None,
        }
        if ranked:
            log_dist(f"autotune: best {ranked[0].mesh} mb={ranked[0].micro_batch} "
                     f"remat={ranked[0].remat} est={ranked[0].est_step_s * 1e3:.2f} ms "
                     f"({len(feasible)}/{len(results)} feasible)")
        return report

    def write_report(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.tune(), f, indent=2)


def autotune(model_factory: Callable[..., Any],
             constraints: Optional[TuningConstraints] = None,
             base_config: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """One-call tuner: returns the winning config overrides dict (merge into
    your training config) plus the full report under ``"report"``."""
    tuner = Autotuner(model_factory, constraints or TuningConstraints(),
                      base_config)
    report = tuner.tune()
    if report["best"] is None:
        raise RuntimeError("autotune: no feasible candidate "
                           f"(tried {len(report['candidates'])})")
    best = report["best"]
    return {"mesh": best["mesh"],
            "train_micro_batch_size_per_gpu": best["micro_batch"],
            "zero_optimization": {"stage": best["zero_stage"]},
            "remat": best["remat"],
            "report": report}
