from .transformer import Transformer, TransformerConfig  # noqa: F401
from .llama import Llama, llama_config  # noqa: F401
from .gpt2 import GPT2, OPT, GPTNeo, gpt2_config, opt_config, gpt_neo_config  # noqa: F401
from .bert import Bert, DistilBert, bert_config, distilbert_config  # noqa: F401
from .clip import CLIP, CLIPConfig, CLIPVision, clip_text_config, clip_vision_config  # noqa: F401
from .moe import GPTMoE, MoETransformer, MoETransformerConfig, gpt_moe_config  # noqa: F401
from .api import FromFlax, from_flax  # noqa: F401
from .diffusion import (AutoencoderKL, UNet2DCondition, UNetConfig,  # noqa: F401
                        VAEConfig)
