"""Model-protocol adapters.

``initialize()`` accepts any object with ``.init(rng) -> params`` and
``.loss(params, batch, rng) -> scalar`` (plus optional
``.partition_specs`` / ``.bind_topology``). These adapters wrap foreign
model definitions into that protocol — the analog of the reference
accepting any ``nn.Module`` (runtime/engine.py:175 wraps the client
module directly).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple


class FromFlax:
    """Wrap a flax ``nn.Module`` into the native model protocol.

    ``loss_fn(logits_or_output, batch) -> scalar`` defines the objective on
    the module's output; by default the module's output is assumed to be
    the scalar loss itself when called as ``module.apply(variables, batch)``.
    """

    def __init__(self, module: Any, example_batch: Any = None,
                 loss_fn: Optional[Callable] = None,
                 init_args: Tuple = (), apply_kwargs: Optional[dict] = None):
        self.module = module
        self.example_batch = example_batch
        self.loss_fn = loss_fn
        self.init_args = init_args
        self.apply_kwargs = apply_kwargs or {}

    def init(self, rng, *args):
        batch = args[0] if args else self.example_batch
        assert batch is not None, \
            "FromFlax.init needs an example batch (pass example_batch=...)"
        return self.module.init(rng, batch, *self.init_args)

    def loss(self, params, batch, rng=None):
        out = self.module.apply(params, batch, *self.init_args,
                                **self.apply_kwargs)
        if self.loss_fn is not None:
            return self.loss_fn(out, batch)
        return out


def from_flax(module: Any, example_batch: Any = None,
              loss_fn: Optional[Callable] = None, **kw) -> FromFlax:
    """One-line flax adapter: ``initialize(model=from_flax(mod, batch, ce))``."""
    return FromFlax(module, example_batch, loss_fn, **kw)
