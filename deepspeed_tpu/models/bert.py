"""BERT / DistilBERT encoder family configs.

Parity target: reference containers for the encoder models
(``module_inject/containers/bert.py``, ``distil_bert.py``; policy classes
``module_inject/replace_policy.py``) and the BERT-era fused training layer
(``csrc/transformer/ds_transformer_cuda.cpp`` ``BertTransformerLayer``) —
here the same shared Transformer core serves them with post-LN
(``prenorm=False``) bidirectional (``causal=False``) blocks, so the flash /
XLA attention path and all parallelism specs carry over unchanged.

BERT specifics on the core: learned positions + segment (token-type)
embeddings normalized together (``embed_norm``), exact-erf GELU, MLM head
(dense + gelu + LN + tied decoder + vocab bias) and the [CLS] tanh pooler.
DistilBERT drops token types and the pooler.
"""

from __future__ import annotations

from .transformer import Transformer, TransformerConfig


def bert_config(size: str = "base", **overrides) -> TransformerConfig:
    presets = {
        "tiny": dict(d_model=128, n_layers=2, n_heads=2),
        "mini": dict(d_model=256, n_layers=4, n_heads=4),
        "base": dict(d_model=768, n_layers=12, n_heads=12),
        "large": dict(d_model=1024, n_layers=24, n_heads=16),
    }
    if size not in presets:
        raise ValueError(f"unknown bert size '{size}'; have {sorted(presets)}")
    kw = dict(presets[size])
    kw.update(vocab_size=30522, max_seq_len=512, norm="layer",
              activation="gelu_exact", position="learned",
              causal=False, prenorm=False, embed_norm=True,
              type_vocab_size=2, mlm_head=True, pooler=True,
              tie_embeddings=True, use_bias=True, norm_eps=1e-12)
    kw.update(overrides)
    return TransformerConfig(**kw)


def distilbert_config(size: str = "base", **overrides) -> TransformerConfig:
    presets = {
        "tiny": dict(d_model=128, n_layers=2, n_heads=2),
        "base": dict(d_model=768, n_layers=6, n_heads=12),
    }
    if size not in presets:
        raise ValueError(f"unknown distilbert size '{size}'; have {sorted(presets)}")
    kw = dict(presets[size])
    kw.update(vocab_size=30522, max_seq_len=512, norm="layer",
              activation="gelu_exact", position="learned",
              causal=False, prenorm=False, embed_norm=True,
              type_vocab_size=0, mlm_head=True, pooler=False,
              tie_embeddings=True, use_bias=True, norm_eps=1e-12)
    kw.update(overrides)
    return TransformerConfig(**kw)


def Bert(size: str = "base", **overrides) -> Transformer:
    return Transformer(bert_config(size, **overrides))


def DistilBert(size: str = "base", **overrides) -> Transformer:
    return Transformer(distilbert_config(size, **overrides))
