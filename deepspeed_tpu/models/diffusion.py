"""Stable-diffusion model surface: UNet2DCondition + AutoencoderKL.

Capability parity with the reference's diffusers serving stack:
``module_inject/containers/unet.py:1`` / ``vae.py:1`` (injection policies),
``model_implementations/diffusers/unet.py:1`` / ``vae.py:1`` (DSUNet/DSVAE
cuda-graph wrappers) and the fused spatial kernel
``csrc/spatial/csrc/opt_bias_add.cu:1``. TPU-first redesign:

* NHWC feature maps / HWIO conv kernels — the layouts XLA tiles onto the
  MXU convolution units (the reference forces torch ``channels_last`` for
  the same reason, model_implementations/diffusers/unet.py:22).
* The cuda-graph replay machinery collapses into ``jax.jit``: the whole
  denoise step (and the full sampling loop, see inference/diffusion.py)
  is one compiled program.
* The fused bias-add+residual kernel is XLA's bread-and-butter elementwise
  fusion — no custom kernel needed.

The parameter pytree mirrors diffusers' module tree (down_blocks[i]
.resnets[j], mid_block, up_blocks[i], ...) so checkpoint ingestion
(checkpoint/diffusers.py) is name mapping + layout transposes, and the
tests can drive torch mirrors of the same blocks weight-for-weight.

Architecture follows diffusers' UNet2DConditionModel / AutoencoderKL as
used by Stable Diffusion 1.x/2.x: ResnetBlock2D (GroupNorm32 + SiLU +
3x3 conv + time-embedding add), Transformer2DModel (GroupNorm + 1x1
proj_in + BasicTransformerBlock(self-attn, cross-attn, GEGLU ff) + 1x1
proj_out, spatial residual), sinusoidal timestep embedding with a 2-layer
SiLU MLP, stride-2 conv downsampling, nearest-2x + conv upsampling.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.norms import group_norm

# ----------------------------------------------------------------------
# primitives (NHWC / HWIO)

_DN = ("NHWC", "HWIO", "NHWC")


def conv2d(x, p, stride: int = 1, padding: int = 1):
    y = jax.lax.conv_general_dilated(
        x, p["kernel"].astype(x.dtype), window_strides=(stride, stride),
        padding=((padding, padding), (padding, padding)),
        dimension_numbers=_DN)
    return y + p["bias"].astype(x.dtype)


def linear(x, p):
    y = x @ p["kernel"].astype(x.dtype)
    if "bias" in p:
        y = y + p["bias"].astype(x.dtype)
    return y


def _silu(x):
    return x * jax.nn.sigmoid(x)


def timestep_embedding(t, dim: int, max_period: float = 10000.0):
    """diffusers ``Timesteps`` with flip_sin_to_cos=True,
    downscale_freq_shift=0 (the SD configuration): [cos | sin] halves."""
    half = dim // 2
    freqs = jnp.exp(-math.log(max_period) *
                    jnp.arange(half, dtype=jnp.float32) / half)
    args = t.astype(jnp.float32)[:, None] * freqs[None, :]
    return jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)


# ----------------------------------------------------------------------
# blocks


def resnet_block(x, temb, p, groups: int = 32, eps: float = 1e-5):
    """diffusers ResnetBlock2D: pre-GN+SiLU convs with the time embedding
    added between them; 1x1 shortcut when channels change."""
    h = _silu(group_norm(x, p["norm1"]["scale"], p["norm1"]["bias"],
                         groups=groups, eps=eps))
    h = conv2d(h, p["conv1"])
    if temb is not None and "time_emb_proj" in p:
        h = h + linear(_silu(temb), p["time_emb_proj"])[:, None, None, :].astype(h.dtype)
    h = _silu(group_norm(h, p["norm2"]["scale"], p["norm2"]["bias"],
                         groups=groups, eps=eps))
    h = conv2d(h, p["conv2"])
    if "conv_shortcut" in p:
        x = conv2d(x, p["conv_shortcut"], padding=0)
    return x + h


def _attention(q_in, kv_in, p, heads: int):
    """diffusers Attention: to_q/k/v (no bias in SD), per-head softmax,
    to_out[0] with bias. Shapes [b, n, c] / [b, m, c_kv]."""
    b, n, _ = q_in.shape
    q = linear(q_in, p["to_q"])
    k = linear(kv_in, p["to_k"])
    v = linear(kv_in, p["to_v"])
    d = q.shape[-1] // heads
    q = q.reshape(b, n, heads, d).transpose(0, 2, 1, 3)
    k = k.reshape(b, -1, heads, d).transpose(0, 2, 1, 3)
    v = v.reshape(b, -1, heads, d).transpose(0, 2, 1, 3)
    logits = jnp.einsum("bhnd,bhmd->bhnm", q, k).astype(jnp.float32) / math.sqrt(d)
    attn = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    out = jnp.einsum("bhnm,bhmd->bhnd", attn, v)
    out = out.transpose(0, 2, 1, 3).reshape(b, n, heads * d)
    return linear(out, p["to_out"])


def _geglu_ff(x, p):
    """diffusers FeedForward with GEGLU: net[0] = GEGLU proj (2x inner dim,
    gelu on the gate half), net[2] = output linear."""
    h = linear(x, p["proj"])
    h, gate = jnp.split(h, 2, axis=-1)
    h = h * jax.nn.gelu(gate.astype(jnp.float32), approximate=False).astype(h.dtype)
    return linear(h, p["out"])


def _layer_norm(x, p, eps: float = 1e-5):
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mean), axis=-1, keepdims=True)
    y = (x32 - mean) / jnp.sqrt(var + eps)
    return (y * p["scale"].astype(jnp.float32)
            + p["bias"].astype(jnp.float32)).astype(x.dtype)


def transformer_2d(x, ctx, p, heads: int, groups: int = 32):
    """diffusers Transformer2DModel (SD: one BasicTransformerBlock):
    GN -> 1x1 proj_in -> [self-attn, cross-attn, GEGLU ff with LN-pre
    residuals] -> 1x1 proj_out -> + residual."""
    b, h, w, c = x.shape
    residual = x
    y = group_norm(x, p["norm"]["scale"], p["norm"]["bias"],
                   groups=groups, eps=1e-6)
    y = conv2d(y, p["proj_in"], padding=0)
    y = y.reshape(b, h * w, c)
    for blk in p["blocks"]:
        y = y + _attention(_layer_norm(y, blk["norm1"]),
                           _layer_norm(y, blk["norm1"]), blk["attn1"], heads)
        y = y + _attention(_layer_norm(y, blk["norm2"]), ctx,
                           blk["attn2"], heads)
        y = y + _geglu_ff(_layer_norm(y, blk["norm3"]), blk["ff"])
    y = y.reshape(b, h, w, c)
    y = conv2d(y, p["proj_out"], padding=0)
    return y + residual


def downsample(x, p):
    """UNet Downsample2D: symmetric padding=1 stride-2 conv."""
    return conv2d(x, p["conv"], stride=2, padding=1)


def downsample_asym(x, p):
    """VAE-encoder Downsample2D: diffusers uses padding=0 with an
    asymmetric right/bottom pad (F.pad (0,1,0,1)) before the stride-2
    conv — NOT the UNet's symmetric padding."""
    x = jnp.pad(x, ((0, 0), (0, 1), (0, 1), (0, 0)))
    return conv2d(x, p["conv"], stride=2, padding=0)


def upsample(x, p):
    b, h, w, c = x.shape
    x = jax.image.resize(x, (b, 2 * h, 2 * w, c), method="nearest")
    return conv2d(x, p["conv"])


# ----------------------------------------------------------------------
# UNet2DCondition


@dataclass
class UNetConfig:
    """Subset of diffusers UNet2DConditionModel config that SD uses."""

    sample_size: int = 64
    in_channels: int = 4
    out_channels: int = 4
    block_out_channels: Tuple[int, ...] = (320, 640, 1280, 1280)
    layers_per_block: int = 2
    cross_attention_dim: int = 768
    # diffusers bug-compat: UNet2DConditionModel's attention_head_dim is
    # actually the NUMBER of heads (num_attention_heads defaults to it);
    # int or per-down-block tuple (SD2: (5, 10, 20, 20))
    attention_head_dim: Any = 8
    down_block_types: Tuple[str, ...] = ("CrossAttnDownBlock2D",) * 3 + ("DownBlock2D",)
    up_block_types: Tuple[str, ...] = ("UpBlock2D",) + ("CrossAttnUpBlock2D",) * 3
    norm_num_groups: int = 32

    def param_count(self, params=None) -> int:
        if params is None:
            return 0
        return sum(int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(params))


class UNet2DCondition:
    """Jittable conditional UNet: ``apply(params, sample, t, ctx)`` with
    sample [b, h, w, c_in] (NHWC), t [b], ctx [b, seq, cross_dim]."""

    def __init__(self, config: UNetConfig):
        self.config = config

    # -- init ----------------------------------------------------------
    def init(self, rng, dtype=jnp.float32) -> Dict[str, Any]:
        c = self.config
        key = [rng]

        def nk():
            key[0], sub = jax.random.split(key[0])
            return sub

        def conv(cin, cout, k=3):
            scale = 1.0 / math.sqrt(cin * k * k)
            return {"kernel": jax.random.uniform(
                        nk(), (k, k, cin, cout), dtype, -scale, scale),
                    "bias": jnp.zeros((cout,), dtype)}

        def lin(cin, cout, bias=True):
            scale = 1.0 / math.sqrt(cin)
            p = {"kernel": jax.random.uniform(nk(), (cin, cout), dtype,
                                              -scale, scale)}
            if bias:
                p["bias"] = jnp.zeros((cout,), dtype)
            return p

        def norm(ch):
            return {"scale": jnp.ones((ch,), dtype),
                    "bias": jnp.zeros((ch,), dtype)}

        def resnet(cin, cout, temb):
            p = {"norm1": norm(cin), "conv1": conv(cin, cout),
                 "time_emb_proj": lin(temb, cout),
                 "norm2": norm(cout), "conv2": conv(cout, cout)}
            if cin != cout:
                p["conv_shortcut"] = conv(cin, cout, k=1)
            return p

        def attn(ch, kv_dim):
            return {"to_q": lin(ch, ch, bias=False),
                    "to_k": lin(kv_dim, ch, bias=False),
                    "to_v": lin(kv_dim, ch, bias=False),
                    "to_out": lin(ch, ch)}

        def tblock(ch):
            inner = 4 * ch
            return {"norm1": norm(ch), "attn1": attn(ch, ch),
                    "norm2": norm(ch), "attn2": attn(ch, c.cross_attention_dim),
                    "norm3": norm(ch),
                    "ff": {"proj": lin(ch, 2 * inner), "out": lin(inner, ch)}}

        def t2d(ch):
            return {"norm": norm(ch), "proj_in": conv(ch, ch, k=1),
                    "blocks": [tblock(ch)], "proj_out": conv(ch, ch, k=1)}

        temb_dim = 4 * c.block_out_channels[0]
        params: Dict[str, Any] = {
            "conv_in": conv(c.in_channels, c.block_out_channels[0]),
            "time_embedding": {
                "linear_1": lin(c.block_out_channels[0], temb_dim),
                "linear_2": lin(temb_dim, temb_dim)},
        }

        down = []
        ch = c.block_out_channels[0]
        for i, btype in enumerate(c.down_block_types):
            cout = c.block_out_channels[i]
            blk: Dict[str, Any] = {"resnets": [], "attentions": []}
            for j in range(c.layers_per_block):
                blk["resnets"].append(resnet(ch if j == 0 else cout, cout,
                                             temb_dim))
            if btype == "CrossAttnDownBlock2D":
                blk["attentions"] = [t2d(cout)
                                     for _ in range(c.layers_per_block)]
            if i < len(c.down_block_types) - 1:
                blk["downsamplers"] = [{"conv": conv(cout, cout)}]
            down.append(blk)
            ch = cout
        params["down_blocks"] = down

        mid_ch = c.block_out_channels[-1]
        params["mid_block"] = {
            "resnets": [resnet(mid_ch, mid_ch, temb_dim),
                        resnet(mid_ch, mid_ch, temb_dim)],
            "attentions": [t2d(mid_ch)]}

        up = []
        rev = list(reversed(c.block_out_channels))
        ch = rev[0]
        for i, btype in enumerate(c.up_block_types):
            cout = rev[i]
            cskip_end = rev[min(i + 1, len(rev) - 1)]
            blk = {"resnets": [], "attentions": []}
            for j in range(c.layers_per_block + 1):
                skip = cskip_end if j == c.layers_per_block else cout
                cin = (ch if j == 0 else cout) + skip
                blk["resnets"].append(resnet(cin, cout, temb_dim))
            if btype == "CrossAttnUpBlock2D":
                blk["attentions"] = [t2d(cout)
                                     for _ in range(c.layers_per_block + 1)]
            if i < len(c.up_block_types) - 1:
                blk["upsamplers"] = [{"conv": conv(cout, cout)}]
            up.append(blk)
            ch = cout
        params["up_blocks"] = up

        params["conv_norm_out"] = norm(c.block_out_channels[0])
        params["conv_out"] = conv(c.block_out_channels[0], c.out_channels)
        return params

    # -- forward -------------------------------------------------------
    def apply(self, params, sample, timesteps, encoder_hidden_states):
        """sample [b,h,w,c] NHWC, timesteps [b] (or scalar), ctx [b,s,d]."""
        c = self.config
        g = c.norm_num_groups
        if timesteps.ndim == 0:
            timesteps = jnp.broadcast_to(timesteps, (sample.shape[0],))
        temb = timestep_embedding(timesteps, c.block_out_channels[0])
        temb = linear(temb, params["time_embedding"]["linear_1"])
        temb = linear(_silu(temb), params["time_embedding"]["linear_2"])
        temb = temb.astype(sample.dtype)
        ctx = encoder_hidden_states

        hd = c.attention_head_dim
        n_down = len(c.block_out_channels)
        heads_per_block = (tuple(hd) if isinstance(hd, (tuple, list))
                           else (hd,) * n_down)

        x = conv2d(sample, params["conv_in"])
        skips = [x]
        for i, blk in enumerate(params["down_blocks"]):
            has_attn = len(blk["attentions"]) > 0
            for j, rp in enumerate(blk["resnets"]):
                x = resnet_block(x, temb, rp, groups=g)
                if has_attn:
                    x = transformer_2d(x, ctx, blk["attentions"][j],
                                       heads_per_block[i], groups=g)
                skips.append(x)
            if "downsamplers" in blk:
                x = downsample(x, blk["downsamplers"][0])
                skips.append(x)

        mid = params["mid_block"]
        x = resnet_block(x, temb, mid["resnets"][0], groups=g)
        x = transformer_2d(x, ctx, mid["attentions"][0],
                           heads_per_block[-1], groups=g)
        x = resnet_block(x, temb, mid["resnets"][1], groups=g)

        for i, blk in enumerate(params["up_blocks"]):
            has_attn = len(blk["attentions"]) > 0
            for j, rp in enumerate(blk["resnets"]):
                skip = skips.pop()
                x = jnp.concatenate([x, skip], axis=-1)
                x = resnet_block(x, temb, rp, groups=g)
                if has_attn:
                    x = transformer_2d(x, ctx, blk["attentions"][j],
                                       heads_per_block[n_down - 1 - i],
                                       groups=g)
            if "upsamplers" in blk:
                x = upsample(x, blk["upsamplers"][0])

        x = _silu(group_norm(x, params["conv_norm_out"]["scale"],
                             params["conv_norm_out"]["bias"], groups=g))
        return conv2d(x, params["conv_out"])

    __call__ = apply


# ----------------------------------------------------------------------
# AutoencoderKL


@dataclass
class VAEConfig:
    in_channels: int = 3
    out_channels: int = 3
    latent_channels: int = 4
    block_out_channels: Tuple[int, ...] = (128, 256, 512, 512)
    layers_per_block: int = 2
    norm_num_groups: int = 32
    scaling_factor: float = 0.18215


def _vae_attn(x, p, groups: int):
    """VAE mid-block attention (diffusers Attention over spatial tokens,
    single head, GN pre-norm, residual)."""
    b, h, w, c = x.shape
    y = group_norm(x, p["group_norm"]["scale"], p["group_norm"]["bias"],
                   groups=groups, eps=1e-6)
    y = y.reshape(b, h * w, c)
    y = _attention(y, y, p, heads=1)
    return x + y.reshape(b, h, w, c)


class AutoencoderKL:
    """encode() -> (mean, logvar); decode(latents) -> image. NHWC."""

    def __init__(self, config: VAEConfig):
        self.config = config

    def init(self, rng, dtype=jnp.float32) -> Dict[str, Any]:
        c = self.config
        key = [rng]

        def nk():
            key[0], sub = jax.random.split(key[0])
            return sub

        def conv(cin, cout, k=3):
            scale = 1.0 / math.sqrt(cin * k * k)
            return {"kernel": jax.random.uniform(
                        nk(), (k, k, cin, cout), dtype, -scale, scale),
                    "bias": jnp.zeros((cout,), dtype)}

        def lin(cin, cout):
            scale = 1.0 / math.sqrt(cin)
            return {"kernel": jax.random.uniform(nk(), (cin, cout), dtype,
                                                 -scale, scale),
                    "bias": jnp.zeros((cout,), dtype)}

        def norm(ch):
            return {"scale": jnp.ones((ch,), dtype),
                    "bias": jnp.zeros((ch,), dtype)}

        def resnet(cin, cout):
            p = {"norm1": norm(cin), "conv1": conv(cin, cout),
                 "norm2": norm(cout), "conv2": conv(cout, cout)}
            if cin != cout:
                p["conv_shortcut"] = conv(cin, cout, k=1)
            return p

        def attn(ch):
            return {"group_norm": norm(ch), "to_q": lin(ch, ch),
                    "to_k": lin(ch, ch), "to_v": lin(ch, ch),
                    "to_out": lin(ch, ch)}

        enc_blocks = []
        ch = c.block_out_channels[0]
        for i, cout in enumerate(c.block_out_channels):
            blk = {"resnets": [resnet(ch if j == 0 else cout, cout)
                               for j in range(c.layers_per_block)]}
            if i < len(c.block_out_channels) - 1:
                blk["downsamplers"] = [{"conv": conv(cout, cout)}]
            enc_blocks.append(blk)
            ch = cout
        mid_ch = c.block_out_channels[-1]
        encoder = {
            "conv_in": conv(c.in_channels, c.block_out_channels[0]),
            "down_blocks": enc_blocks,
            "mid_block": {"resnets": [resnet(mid_ch, mid_ch),
                                      resnet(mid_ch, mid_ch)],
                          "attentions": [attn(mid_ch)]},
            "conv_norm_out": norm(mid_ch),
            "conv_out": conv(mid_ch, 2 * c.latent_channels),
        }

        dec_blocks = []
        rev = list(reversed(c.block_out_channels))
        ch = rev[0]
        for i, cout in enumerate(rev):
            blk = {"resnets": [resnet(ch if j == 0 else cout, cout)
                               for j in range(c.layers_per_block + 1)]}
            if i < len(rev) - 1:
                blk["upsamplers"] = [{"conv": conv(cout, cout)}]
            dec_blocks.append(blk)
            ch = cout
        decoder = {
            "conv_in": conv(c.latent_channels, rev[0]),
            "mid_block": {"resnets": [resnet(rev[0], rev[0]),
                                      resnet(rev[0], rev[0])],
                          "attentions": [attn(rev[0])]},
            "up_blocks": dec_blocks,
            "conv_norm_out": norm(c.block_out_channels[0]),
            "conv_out": conv(c.block_out_channels[0], c.out_channels),
        }
        return {"encoder": encoder,
                "quant_conv": conv(2 * c.latent_channels,
                                   2 * c.latent_channels, k=1),
                "post_quant_conv": conv(c.latent_channels,
                                        c.latent_channels, k=1),
                "decoder": decoder}

    def encode(self, params, x):
        """image [b,h,w,3] -> (mean, logvar) each [b,h/8,w/8,latent]."""
        c = self.config
        g = c.norm_num_groups
        e = params["encoder"]
        h = conv2d(x, e["conv_in"])
        for blk in e["down_blocks"]:
            for rp in blk["resnets"]:
                h = resnet_block(h, None, rp, groups=g, eps=1e-6)
            if "downsamplers" in blk:
                h = downsample_asym(h, blk["downsamplers"][0])
        m = e["mid_block"]
        h = resnet_block(h, None, m["resnets"][0], groups=g, eps=1e-6)
        h = _vae_attn(h, m["attentions"][0], groups=g)
        h = resnet_block(h, None, m["resnets"][1], groups=g, eps=1e-6)
        h = _silu(group_norm(h, e["conv_norm_out"]["scale"],
                             e["conv_norm_out"]["bias"], groups=g, eps=1e-6))
        h = conv2d(h, e["conv_out"])
        h = conv2d(h, params["quant_conv"], padding=0)
        mean, logvar = jnp.split(h, 2, axis=-1)
        return mean, jnp.clip(logvar, -30.0, 20.0)

    def sample_latents(self, params, x, rng):
        mean, logvar = self.encode(params, x)
        eps = jax.random.normal(rng, mean.shape, mean.dtype)
        return (mean + jnp.exp(0.5 * logvar) * eps) * self.config.scaling_factor

    def decode(self, params, z):
        c = self.config
        g = c.norm_num_groups
        d = params["decoder"]
        z = z / c.scaling_factor
        h = conv2d(z, params["post_quant_conv"], padding=0)
        h = conv2d(h, d["conv_in"])
        m = d["mid_block"]
        h = resnet_block(h, None, m["resnets"][0], groups=g, eps=1e-6)
        h = _vae_attn(h, m["attentions"][0], groups=g)
        h = resnet_block(h, None, m["resnets"][1], groups=g, eps=1e-6)
        for blk in d["up_blocks"]:
            for rp in blk["resnets"]:
                h = resnet_block(h, None, rp, groups=g, eps=1e-6)
            if "upsamplers" in blk:
                h = upsample(h, blk["upsamplers"][0])
        h = _silu(group_norm(h, d["conv_norm_out"]["scale"],
                             d["conv_norm_out"]["bias"], groups=g, eps=1e-6))
        return conv2d(h, d["conv_out"])
