"""GPT-2 / OPT family configs.

Parity target: reference containers for gpt2/gptj/gptneo/opt
(module_inject/containers/) and the OPT FastGen implementation
(inference/v2/model_implementations/opt). LayerNorm + learned positions +
GELU MLP + biases + tied embeddings on the shared Transformer core.
"""

from __future__ import annotations

from .transformer import Transformer, TransformerConfig


def gpt2_config(size: str = "small", **overrides) -> TransformerConfig:
    presets = {
        "tiny": dict(vocab_size=50257, d_model=256, n_layers=4, n_heads=8, max_seq_len=512),
        "small": dict(vocab_size=50257, d_model=768, n_layers=12, n_heads=12, max_seq_len=1024),
        "medium": dict(vocab_size=50257, d_model=1024, n_layers=24, n_heads=16, max_seq_len=1024),
        "large": dict(vocab_size=50257, d_model=1280, n_layers=36, n_heads=20, max_seq_len=1024),
        "xl": dict(vocab_size=50257, d_model=1600, n_layers=48, n_heads=25, max_seq_len=1024),
    }
    if size not in presets:
        raise ValueError(f"unknown gpt2 size '{size}'; have {sorted(presets)}")
    kw = dict(presets[size])
    kw.update(norm="layer", activation="gelu", position="learned",
              tie_embeddings=True, use_bias=True, norm_eps=1e-5)
    kw.update(overrides)
    return TransformerConfig(**kw)


def opt_config(size: str = "1.3b", **overrides) -> TransformerConfig:
    presets = {
        "125m": dict(vocab_size=50272, d_model=768, n_layers=12, n_heads=12),
        "1.3b": dict(vocab_size=50272, d_model=2048, n_layers=24, n_heads=32),
        "6.7b": dict(vocab_size=50272, d_model=4096, n_layers=32, n_heads=32),
        "13b": dict(vocab_size=50272, d_model=5120, n_layers=40, n_heads=40),
        "30b": dict(vocab_size=50272, d_model=7168, n_layers=48, n_heads=56),
    }
    if size not in presets:
        raise ValueError(f"unknown opt size '{size}'; have {sorted(presets)}")
    kw = dict(presets[size])
    kw.update(max_seq_len=2048, norm="layer", activation="gelu", position="learned",
              tie_embeddings=True, use_bias=True, norm_eps=1e-5)
    kw.update(overrides)
    return TransformerConfig(**kw)


def gpt_neo_config(size: str = "125m", **overrides) -> TransformerConfig:
    """GPT-Neo: alternating global/local causal attention (window 256),
    UNSCALED attention logits, qkv projections without bias.
    Parity: reference module_inject/containers/gptneo.py."""
    presets = {
        "tiny": dict(vocab_size=50257, d_model=256, n_layers=4, n_heads=8,
                     max_seq_len=512),
        "125m": dict(vocab_size=50257, d_model=768, n_layers=12, n_heads=12,
                     max_seq_len=2048),
        "1.3b": dict(vocab_size=50257, d_model=2048, n_layers=24, n_heads=16,
                     max_seq_len=2048),
        "2.7b": dict(vocab_size=50257, d_model=2560, n_layers=32, n_heads=20,
                     max_seq_len=2048),
    }
    if size not in presets:
        raise ValueError(f"unknown gpt_neo size '{size}'; have {sorted(presets)}")
    kw = dict(presets[size])
    n = kw["n_layers"]
    kw.update(norm="layer", activation="gelu", position="learned",
              tie_embeddings=True, use_bias=True, qkv_bias=False,
              attn_scale=1.0,
              attn_windows=tuple(0 if i % 2 == 0 else 256 for i in range(n)),
              use_flash=False,  # window masks need the jnp attention path
              norm_eps=1e-5)
    kw.update(overrides)
    return TransformerConfig(**kw)


def GPT2(size: str = "small", **overrides) -> Transformer:
    return Transformer(gpt2_config(size, **overrides))


def GPTNeo(size: str = "125m", **overrides) -> Transformer:
    return Transformer(gpt_neo_config(size, **overrides))


def OPT(size: str = "1.3b", **overrides) -> Transformer:
    return Transformer(opt_config(size, **overrides))
