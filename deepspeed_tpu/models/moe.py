"""MoE transformer model family.

Parity target: DeepSpeed-MoE models (reference ``deepspeed/moe/layer.py``
MoE facade + GPT-MoE configurations from BASELINE.json configs[4]). Every
layer's FFN is an expert bank routed by top-k gating
(:mod:`deepspeed_tpu.parallel.moe`); expert weights are stacked
``[n_layers, E, ...]`` and sharded over the ``expert`` (and ``model``) mesh
axes, composing with ZeRO <=2 over ``data`` — the same composition rule as
the reference (stage_1_and_2.py:566 _configure_moe_settings: MoE requires
ZeRO <= 2).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..parallel.moe import GateConfig, MoELayer
from .transformer import Transformer, TransformerConfig


@dataclass
class MoETransformerConfig(TransformerConfig):
    n_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    min_capacity: int = 4
    aux_loss_weight: float = 0.01
    noisy_gate_policy: Optional[str] = None

    def gate_config(self) -> GateConfig:
        return GateConfig(
            n_experts=self.n_experts, top_k=self.top_k,
            capacity_factor=self.capacity_factor, min_capacity=self.min_capacity,
            aux_loss_weight=self.aux_loss_weight,
            noisy_gate_policy=self.noisy_gate_policy)

    def param_count(self) -> int:
        d, f, n = self.d_model, self.d_ff, self.n_layers
        n_mats = 3 if self.activation == "silu_glu" else 2
        moe = self.n_experts * n_mats * d * f + d * self.n_experts
        return self._shared_param_count() + n * moe

    def active_param_count(self) -> int:
        """Parameters a single token actually exercises (top_k experts)."""
        d, f, n = self.d_model, self.d_ff, self.n_layers
        n_mats = 3 if self.activation == "silu_glu" else 2
        active_moe = self.top_k * n_mats * d * f + d * self.n_experts
        return self._shared_param_count() + n * active_moe

    def flops_per_token(self, seq_len: int) -> float:
        """MoE FLOPs count only the experts a token routes through (and
        the shared window-aware attention term)."""
        return 6.0 * self.active_param_count() \
            + 12.0 * self.d_model * self._attn_flop_len(seq_len)


class MoETransformer(Transformer):
    """Transformer with MoE FFN in every block."""

    def __init__(self, config: MoETransformerConfig):
        super().__init__(config)
        self.moe = MoELayer(config.d_model, config.d_ff, config.gate_config(),
                            activation=config.activation,
                            use_bias=config.use_bias)

    def init(self, rng, dtype=jnp.float32) -> Dict[str, Any]:
        k_dense, k_moe = jax.random.split(rng)
        params = super().init(k_dense, dtype)
        # replace dense FFN weights with the expert bank
        for key in ("w_up", "w_down", "w_gate", "b_up", "b_down"):
            params["layers"].pop(key, None)
        params["layers"].update(
            self.moe.init(k_moe, dtype, n_layers=self.config.n_layers))
        return params

    def _mlp(self, h, lp, rng=None, training=False):
        moe_params = {k: lp[k] for k in ("wg", "w_up", "w_down", "w_gate",
                                         "b_up", "b_down") if k in lp}
        out, aux = self.moe.apply(moe_params, h, rng=rng, training=training)
        return out, aux * self.config.aux_loss_weight

    def partition_specs(self, params, topo=None) -> Dict[str, Any]:
        specs = super(MoETransformer, self).partition_specs(
            {k: v for k, v in params.items()}, topo)
        layer_specs = dict(specs["layers"])
        for key in ("w_up", "w_down", "w_gate", "b_up", "b_down"):
            layer_specs.pop(key, None)
        pipe_size = topo.pipe_parallel_size if topo is not None else self._pipe_size
        layer_specs.update(self.moe.partition_specs(
            n_layers=self.config.n_layers,
            pipe="pipe" if pipe_size > 1 else None))
        specs["layers"] = layer_specs
        return specs


def gpt_moe_config(size: str = "350m", n_experts: int = 8, **overrides) -> MoETransformerConfig:
    """GPT-MoE presets (reference DeepSpeed-MoE GPT family)."""
    presets = {
        "tiny": dict(d_model=128, n_layers=2, n_heads=4, max_seq_len=256, vocab_size=1024),
        "350m": dict(d_model=1024, n_layers=24, n_heads=16, max_seq_len=2048, vocab_size=50257),
        "1.3b": dict(d_model=2048, n_layers=24, n_heads=32, max_seq_len=2048, vocab_size=50257),
    }
    if size not in presets:
        raise ValueError(f"unknown gpt-moe size '{size}'; have {sorted(presets)}")
    kw = dict(presets[size])
    kw.update(norm="layer", activation="gelu", position="learned", use_bias=False,
              tie_embeddings=True, n_experts=n_experts, norm_eps=1e-5)
    kw.update(overrides)
    return MoETransformerConfig(**kw)


def GPTMoE(size: str = "350m", n_experts: int = 8, **overrides) -> MoETransformer:
    return MoETransformer(gpt_moe_config(size, n_experts, **overrides))
