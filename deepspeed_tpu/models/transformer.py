"""Decoder-only transformer core.

This is the model substrate the reference gets from HuggingFace + kernel
injection (``deepspeed/module_inject/containers/{llama,gptneo,opt,...}`` and
FastGen's ``inference/v2/model_implementations/``). Built TPU-first:

* **Stacked layer parameters + ``lax.scan`` over depth** — one compiled
  block regardless of layer count (compile time O(1) in depth, XLA pipelines
  the scan); the reference's per-layer Python modules have no TPU analog.
* **Tensor parallelism as PartitionSpecs** — Megatron-style column/row
  sharding over the ``model`` mesh axis is *data placement* here, not code:
  :meth:`Transformer.partition_specs` returns the spec tree and GSPMD
  inserts the one all-reduce per block the reference's AutoTP patches into
  forward (module_inject/auto_tp.py).
* **Sequence parallelism (Ulysses)** via ``parallel/ulysses.py`` — enabled
  when the mesh's ``seq`` axis > 1.
* fp32 accumulation in norms/softmax/logits; bf16 everywhere else.

Families supported via :class:`TransformerConfig`: Llama/Mistral-style
(RMSNorm + RoPE + gated-SiLU MLP + GQA), GPT-2/OPT-style (LayerNorm +
learned positions + GELU MLP, optional biases), with tied or untied
embeddings.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..ops.attention import dot_product_attention, flash_attention
from ..ops.norms import layer_norm, rms_norm
from ..ops.rotary import alibi_slopes, apply_rotary, rope_frequencies


@dataclass
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_layers: int = 4
    n_heads: int = 8
    n_kv_heads: Optional[int] = None  # GQA; None => MHA
    d_ff: Optional[int] = None        # default 4*d (gelu) or 8/3*d rounded (glu)
    max_seq_len: int = 2048
    norm: str = "rms"                 # rms | layer
    activation: str = "silu_glu"      # silu_glu | gelu | relu
    position: str = "rope"            # rope | learned
    rope_theta: float = 10000.0
    tie_embeddings: bool = True
    use_bias: bool = False
    norm_eps: float = 1e-6
    remat: bool = True                # activation checkpointing per block
    remat_policy: str = "full"        # full | selective | selective_flash
    #                                 # | dots_with_no_batch_dims | nothing
    use_flash: bool = True
    logits_softcap: float = 0.0
    z_loss: float = 0.0
    # chunked cross entropy: tokens per head+CE chunk (0 = whole batch).
    # Bounds the fp32 logits transient to [chunk, vocab] instead of
    # [b*s, vocab] (2.1 GB at b8 s2048 v32k) — the backward recomputes each
    # chunk's logits from the (small) hidden states via jax.checkpoint
    loss_chunk_size: int = 0
    # sequence-parallel attention when the mesh's seq axis > 1:
    # "auto" = ulysses when n_heads divides the seq axis, else ring
    sp_attention: str = "auto"        # auto | ulysses | ring
    # family coverage knobs (Bloom / GPT-J / GPT-NeoX):
    rope_pct: float = 1.0             # fraction of head_dim rotated (NeoX)
    rope_interleaved: bool = False    # GPT-J pairing instead of half-split
    parallel_residual: bool = False   # x + attn(ln1 x) + mlp(ln2 x)
    embed_norm: bool = False          # LayerNorm after token embed (Bloom)
    # encoder-family knobs (BERT / DistilBERT; reference
    # module_inject/containers/{bert,distil_bert}.py):
    causal: bool = True               # False = bidirectional encoder
    prenorm: bool = True              # False = post-LN (x = LN(x + sub(x)))
    type_vocab_size: int = 0          # >0 adds segment (token-type) embeddings
    mlm_head: bool = False            # BERT MLM head: dense+gelu+LN+decoder+bias
    pooler: bool = False              # [CLS] dense+tanh pooler
    # Sliding-window knobs (GPT-Neo alternating local layers, Mistral/
    # Mixtral uniform windows): per-layer window sizes, 0 = global causal.
    # At seq <= window the window is statically elided (flash path kept).
    # A BINDING uniform window dispatches the banded flash kernel
    # (O(s*window) compute, below-band tiles skipped); per-layer-VARYING
    # windows use the masked jnp path (O(s^2) score memory — GPT-Neo's
    # windows are small). attn_scale overrides the logit scale (GPT-Neo
    # uses UNSCALED qk^T, i.e. attn_scale=1.0).
    attn_windows: Optional[Tuple[int, ...]] = None
    attn_scale: Optional[float] = None
    qkv_bias: Optional[bool] = None   # None -> follow use_bias (Neo: False)
    # InternLM: attention projections carry biases (incl. o_proj) while the
    # gated MLP does not — reference module_inject/containers/internlm.py:20
    attn_o_bias: Optional[bool] = None  # None -> follow use_bias

    def __post_init__(self):
        if self.n_kv_heads is None:
            self.n_kv_heads = self.n_heads
        if self.qkv_bias is None:
            self.qkv_bias = self.use_bias
        if self.attn_o_bias is None:
            self.attn_o_bias = self.use_bias
        if self.attn_windows is not None:
            self.attn_windows = tuple(int(w) for w in self.attn_windows)
            assert len(self.attn_windows) == self.n_layers, (
                f"attn_windows has {len(self.attn_windows)} entries for "
                f"{self.n_layers} layers")
            if not self.causal:
                # every window path (banded kernel, masks, paged gather)
                # implements the CAUSAL band k > q - w; a bidirectional
                # model would silently get causal attention
                raise ValueError(
                    "attn_windows requires a causal model "
                    "(sliding windows are a decoder feature)")
        if self.d_ff is None:
            if self.activation == "silu_glu":
                self.d_ff = int(8 * self.d_model / 3 / 128 + 1) * 128
            else:
                self.d_ff = 4 * self.d_model
        assert self.d_model % self.n_heads == 0

    def window_binds(self, length: int) -> bool:
        """True if any per-layer sliding window actually trims attention
        at this sequence/context length (w == length attends everything)."""
        return self.attn_windows is not None \
            and any(0 < w < length for w in self.attn_windows)

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def rotary_dim(self) -> int:
        """Rotated dims per head (GPT-NeoX rope_pct), even-rounded."""
        return int(self.head_dim * self.rope_pct) // 2 * 2

    def _shared_param_count(self) -> int:
        """Attention + norms + embeddings (everything but the FFN)."""
        d, v, n = self.d_model, self.vocab_size, self.n_layers
        hd = self.head_dim
        attn = d * self.n_heads * hd + 2 * d * self.n_kv_heads * hd + self.n_heads * hd * d
        if self.qkv_bias:
            attn += self.n_heads * hd + 2 * self.n_kv_heads * hd
        if self.attn_o_bias:
            attn += d
        norms = (2 * d) * n + (d if self.prenorm else 0)
        if self.norm == "layer":
            norms *= 2  # weights + biases
        emb = v * d * (1 if self.tie_embeddings else 2)
        if self.position == "learned":
            emb += self.max_seq_len * d
        emb += self.type_vocab_size * d
        if self.embed_norm:
            emb += 2 * d
        head = 0
        if self.mlm_head:
            head += d * d + d + 2 * d + v  # transform + LN + decoder bias
        if self.pooler:
            head += d * d + d
        return n * attn + norms + emb + head

    def param_count(self) -> int:
        d, f, n = self.d_model, self.d_ff, self.n_layers
        mlp = (3 if self.activation == "silu_glu" else 2) * d * f
        if self.use_bias:
            mlp += f + d
        return self._shared_param_count() + n * mlp

    def _attn_flop_len(self, seq_len: int) -> int:
        """Summed per-layer attention lengths: sliding-window layers attend
        at most ``window`` keys, so min(seq, window) — keeps MFU honest for
        windowed models (shared by the dense and MoE flops counts)."""
        if self.attn_windows is not None:
            return sum(min(seq_len, w) if w > 0 else seq_len
                       for w in self.attn_windows)
        return self.n_layers * seq_len

    def flops_per_token(self, seq_len: int) -> float:
        """Forward+backward FLOPs/token (standard 6N + attention term)."""
        return 6.0 * self.param_count() \
            + 12.0 * self.d_model * self._attn_flop_len(seq_len)


class Transformer:
    """Functional model: ``init`` -> params pytree; ``apply`` -> logits;
    ``loss`` -> scalar; ``partition_specs`` -> TP placement."""

    def __init__(self, config: TransformerConfig):
        self.config = config
        self._mesh = None
        self._seq_size = 1
        self._tp_size = 1
        self._pipe_size = 1
        self._comm_backend = None

    def bind_comm_backend(self, backend) -> "Transformer":
        """Attach a fused kernel backend (comm/backends.py). The TP
        decode path's MLP down-projection then runs its partial matmul
        and all-reduce fused (``matmul_all_reduce``) instead of leaving
        GSPMD's psum as pure exposed latency after the matmul — see
        :meth:`_down_proj`. Called by the inference engine when its
        ``kernel_backend`` resolves to a fused backend."""
        self._comm_backend = backend
        return self

    def bind_topology(self, topo) -> "Transformer":
        """Attach the device mesh; activates Ulysses/ring sequence-parallel
        attention when the topology's seq axis > 1 (called by
        ``deepspeed_tpu.initialize``)."""
        self._mesh = topo.mesh
        self._seq_size = topo.sequence_parallel_size
        self._tp_size = topo.model_parallel_size
        self._pipe_size = topo.pipe_parallel_size
        self._batch_axes = topo.data_axes()
        if self._pipe_size > 1:
            assert self.config.n_layers % self._pipe_size == 0, (
                f"n_layers={self.config.n_layers} not divisible by "
                f"pipeline stages={self._pipe_size}")
        if self._seq_size > 1:
            impl = self.config.sp_attention
            if impl == "auto":
                # under TP the heads dim is already sharded over 'model', so
                # ulysses scatters the LOCAL n_heads/tp heads over the seq axis
                local_heads = self.config.n_heads // self._tp_size
                impl = "ulysses" if local_heads % self._seq_size == 0 else "ring"
            self._sp_impl = impl
        return self

    # ------------------------------------------------------------------
    def init(self, rng, dtype=jnp.float32) -> Dict[str, Any]:
        c = self.config
        hd = c.head_dim
        k = iter(jax.random.split(rng, 16))

        def dense(key, shape, scale=None):
            scale = scale if scale is not None else 1.0 / np.sqrt(shape[-2] if len(shape) > 1 else shape[-1])
            return (jax.random.normal(key, shape, jnp.float32) * scale).astype(dtype)

        n = c.n_layers
        layers: Dict[str, Any] = {
            "attn_norm_w": jnp.ones((n, c.d_model), dtype),
            "wq": dense(next(k), (n, c.d_model, c.n_heads * hd)),
            "wk": dense(next(k), (n, c.d_model, c.n_kv_heads * hd)),
            "wv": dense(next(k), (n, c.d_model, c.n_kv_heads * hd)),
            "wo": dense(next(k), (n, c.n_heads * hd, c.d_model), scale=1.0 / np.sqrt(c.d_model * 2 * n)),
            "mlp_norm_w": jnp.ones((n, c.d_model), dtype),
            "w_up": dense(next(k), (n, c.d_model, c.d_ff)),
            "w_down": dense(next(k), (n, c.d_ff, c.d_model), scale=1.0 / np.sqrt(c.d_ff * 2 * n)),
        }
        if c.activation == "silu_glu":
            layers["w_gate"] = dense(next(k), (n, c.d_model, c.d_ff))
        if c.norm == "layer":
            layers["attn_norm_b"] = jnp.zeros((n, c.d_model), dtype)
            layers["mlp_norm_b"] = jnp.zeros((n, c.d_model), dtype)
        if c.qkv_bias:
            layers["bq"] = jnp.zeros((n, c.n_heads * hd), dtype)
            layers["bk"] = jnp.zeros((n, c.n_kv_heads * hd), dtype)
            layers["bv"] = jnp.zeros((n, c.n_kv_heads * hd), dtype)
        if c.attn_o_bias:
            layers["bo"] = jnp.zeros((n, c.d_model), dtype)
        if c.use_bias:
            layers["b_up"] = jnp.zeros((n, c.d_ff), dtype)
            layers["b_down"] = jnp.zeros((n, c.d_model), dtype)

        params: Dict[str, Any] = {
            "tok_embed": dense(next(k), (c.vocab_size, c.d_model), scale=0.02),
            "layers": layers,
        }
        if c.prenorm:  # post-LN blocks end in their own norm — no final norm
            params["final_norm_w"] = jnp.ones((c.d_model,), dtype)
            if c.norm == "layer":
                params["final_norm_b"] = jnp.zeros((c.d_model,), dtype)
        if c.position == "learned":
            params["pos_embed"] = dense(next(k), (c.max_seq_len, c.d_model), scale=0.02)
        if c.type_vocab_size > 0:
            params["type_embed"] = dense(next(k), (c.type_vocab_size, c.d_model), scale=0.02)
        if c.embed_norm:
            params["embed_norm_w"] = jnp.ones((c.d_model,), dtype)
            params["embed_norm_b"] = jnp.zeros((c.d_model,), dtype)
        if not c.tie_embeddings:
            params["lm_head"] = dense(next(k), (c.d_model, c.vocab_size))
        if c.mlm_head:
            params["mlm_dense_w"] = dense(next(k), (c.d_model, c.d_model))
            params["mlm_dense_b"] = jnp.zeros((c.d_model,), dtype)
            params["mlm_norm_w"] = jnp.ones((c.d_model,), dtype)
            params["mlm_norm_b"] = jnp.zeros((c.d_model,), dtype)
            params["mlm_bias"] = jnp.zeros((c.vocab_size,), dtype)
        if c.pooler:
            params["pooler_w"] = dense(next(k), (c.d_model, c.d_model))
            params["pooler_b"] = jnp.zeros((c.d_model,), dtype)
        return params

    # ------------------------------------------------------------------
    def _norm(self, x, w, b=None):
        if self.config.norm == "rms":
            return rms_norm(x, w, self.config.norm_eps)
        return layer_norm(x, w, b, self.config.norm_eps)

    def _local_flash(self, q, k, v, *, causal, scale=None, window=0):
        """Flash attention that stays device-local on multi-device meshes.

        GSPMD cannot partition a ``pallas_call`` — with batch/head-sharded
        operands it would replicate the kernel (silent pod-scale perf
        cliff). Standard practice: run the kernel INSIDE a shard_map whose
        specs name the operands' existing sharding (batch over the data
        axes, heads over 'model'), so each device runs the kernel on its
        local shard with zero collectives. Single-device (the bench) and
        unbound-mesh paths call the dispatcher directly."""
        from ..ops.attention import flash_attention as fa

        kw = {"causal": causal, "scale": scale}
        if window:
            kw["window"] = window
        mesh = self._mesh
        multi = mesh is not None and any(
            mesh.shape[a] > 1 for a in ("data", "zshard", "model")
            if a in mesh.shape)
        if not multi:
            return fa(q, k, v, **kw)
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P_

        batch_axes = getattr(self, "_batch_axes", None) or ()
        tp = self._tp_size
        dp = 1
        for a in batch_axes:
            dp *= mesh.shape.get(a, 1)
        # the wrapper needs every named dim to divide its axes; GQA counts
        # must shard TOGETHER (sharding q but replicating kv would invert
        # the local q:kv ratio). Unwrappable corners (kv heads < tp, odd
        # batch) fall back to the jnp path, which GSPMD partitions fine —
        # correctness kept, and still no opaque pallas_call in the graph.
        heads_ok = tp == 1 or (q.shape[2] % tp == 0 and k.shape[2] % tp == 0)
        batch_ok = dp == 1 or q.shape[0] % dp == 0
        if not (heads_ok and batch_ok):
            return dot_product_attention(
                q, k, v, causal=causal, scale=scale, window=window)
        ha = "model" if tp > 1 else None
        spec = P_(tuple(batch_axes) or None, None, ha, None)
        return shard_map(lambda q, k, v: fa(q, k, v, **kw), mesh=mesh,
                         in_specs=(spec, spec, spec),
                         out_specs=spec, check_rep=False)(q, k, v)

    def _sp_attention(self, q, k, v, window=None, causal=True):
        """Sequence-parallel attention over the bound mesh's seq axis."""
        batch_axes = getattr(self, "_batch_axes", None) or None
        head_axes = "model" if self._tp_size > 1 else None
        if self._sp_impl == "ring":
            from ..parallel.ring import ring_attention_sharded

            assert window is None and self.config.attn_scale is None \
                and causal, \
                "ring attention is causal-only, no window/scale — caller " \
                "must reject"
            return ring_attention_sharded(q, k, v, self._mesh, causal=True,
                                          batch_axes=batch_axes,
                                          head_axes=head_axes)
        from ..parallel.ulysses import DistributedAttention

        # after the a2a each device holds FULL sequences for a head subset —
        # exactly the flash kernel's shape (so a static sliding window and
        # scale override apply cleanly, and bidirectional encoders work
        # unchanged); the dispatcher falls back to the jnp path off-TPU /
        # on odd shapes
        local_attn = (flash_attention if self.config.use_flash
                      else dot_product_attention)
        kw = {}
        if window is not None:
            kw["window"] = window
        if self.config.attn_scale is not None:
            kw["scale"] = self.config.attn_scale
        if kw:
            local_attn = partial(local_attn, **kw)
        return DistributedAttention(local_attn, self._mesh,
                                    batch_axes=batch_axes,
                                    head_axes=head_axes)(q, k, v,
                                                         causal=causal)

    def _block(self, x, lp, angles, positions, kv_cache=None, rng=None, training=False,
               attn_mask=None, attn_window=None):
        """One transformer block. x: [b, s, d]. Returns (x, new_kv, aux).

        ``attn_mask``: optional [b, s] padding mask (1 = attend) for the
        bidirectional (causal=False) encoder path.
        ``attn_window``: sliding-window size for local attention (<= 0
        means global causal). A STATIC python int (uniform windows,
        Mistral) dispatches the banded flash kernel — keep it static, a
        traced scalar silently falls to the O(s^2) masked path reserved
        for per-layer-varying windows (GPT-Neo)."""
        c = self.config
        hd = c.head_dim
        b, s, _ = x.shape
        if attn_mask is not None and c.causal:
            raise NotImplementedError(
                "attn_mask with a causal model is not supported (padding "
                "masks are an encoder feature; causal batches should pack "
                "or left-trim instead)")

        # pre-LN normalizes the branch input; post-LN (BERT-era,
        # prenorm=False) runs the branch on x and norms AFTER the residual
        h = self._norm(x, lp["attn_norm_w"], lp.get("attn_norm_b")) \
            if c.prenorm else x
        q = h @ lp["wq"]
        kk = h @ lp["wk"]
        vv = h @ lp["wv"]
        if c.qkv_bias:
            q, kk, vv = q + lp["bq"], kk + lp["bk"], vv + lp["bv"]
        q = q.reshape(b, s, c.n_heads, hd)
        kk = kk.reshape(b, s, c.n_kv_heads, hd)
        vv = vv.reshape(b, s, c.n_kv_heads, hd)
        if c.position == "rope":
            # apply_rotary no-ops the partial slice when rotary_dim == hd
            q = apply_rotary(q, angles, positions, rotary_dim=c.rotary_dim,
                             interleaved=c.rope_interleaved)
            kk = apply_rotary(kk, angles, positions, rotary_dim=c.rotary_dim,
                              interleaved=c.rope_interleaved)

        def _alibi_bias(skv):
            # ALiBi (Bloom): logits += slopes * (k_pos - q_pos); the per-row
            # -slopes*q_pos shift is constant along the softmax axis and
            # cancels, so slopes * k_pos alone is exact under row softmax
            slopes = alibi_slopes(c.n_heads)
            return (slopes[:, None, None]
                    * jnp.arange(skv, dtype=jnp.float32)[None, None, :])

        new_kv = None
        if kv_cache is not None:
            ck, cv, cache_pos = kv_cache
            ck = jax.lax.dynamic_update_slice_in_dim(ck, kk, cache_pos, axis=1)
            cv = jax.lax.dynamic_update_slice_in_dim(cv, vv, cache_pos, axis=1)
            new_kv = (ck, cv)
            # query i sits at absolute position cache_pos + i: it may attend
            # every cache slot up to and including itself (this also masks
            # the unwritten zero tail of the cache)
            q_abs = cache_pos + jnp.arange(s)                   # [s]
            k_pos = jnp.arange(ck.shape[1])                     # [max_len]
            mask = k_pos[None, :] <= q_abs[:, None]             # [s, max_len]
            if attn_window is not None:  # local layers trim the left edge
                mask = mask & ((attn_window <= 0)
                               | (k_pos[None, :] > q_abs[:, None] - attn_window))
            bias = _alibi_bias(ck.shape[1]) if c.position == "alibi" else None
            attn = dot_product_attention(q, ck, cv, causal=False,
                                         mask=mask[None, None], bias=bias,
                                         scale=c.attn_scale)
        elif self._seq_size > 1:
            if c.position == "alibi":
                raise NotImplementedError(
                    "ALiBi + sequence-parallel attention not supported yet")
            # attn_window is None here whenever no window binds at this
            # length (_encode elides them). Ulysses supports static
            # (uniform) binding windows, scale overrides, and
            # bidirectional encoders — the a2a yields full local
            # sequences; traced per-layer windows and the (causal-only)
            # ring path do not.
            if attn_window is not None and not isinstance(attn_window, int):
                raise NotImplementedError(
                    "per-layer-varying attention windows + sequence-"
                    "parallel attention not supported")
            if (attn_window is not None or c.attn_scale is not None
                    or not c.causal) and self._sp_impl != "ulysses":
                raise NotImplementedError(
                    "binding attention windows / scale overrides / "
                    "bidirectional encoders require ulysses sequence "
                    "parallelism (ring is causal-only)")
            if not c.causal and attn_mask is not None:
                raise NotImplementedError(
                    "encoder padding masks not threaded through sequence-"
                    "parallel attention yet — drop the seq axis or pack "
                    "unpadded batches")
            attn = self._sp_attention(q, kk, vv, window=attn_window,
                                      causal=c.causal)
        elif c.position == "alibi":
            # flash kernel carries no additive bias — use the jnp path
            attn = dot_product_attention(q, kk, vv, causal=True,
                                         bias=_alibi_bias(s))
        elif not c.causal and attn_mask is not None:
            # encoder with padding: keys at padded positions are masked for
            # every query ([b, 1, 1, s] broadcast)
            key_mask = attn_mask.astype(bool)[:, None, None, :]
            attn = dot_product_attention(q, kk, vv, causal=False, mask=key_mask,
                                         scale=c.attn_scale)
        elif attn_window is not None and isinstance(attn_window, int):
            # uniform static window (Mistral/Mixtral): banded flash kernel
            # on TPU (tiles below the band skipped), banded jnp otherwise
            if c.use_flash:
                attn = self._local_flash(q, kk, vv, causal=True,
                                         scale=c.attn_scale,
                                         window=attn_window)
            else:
                attn = dot_product_attention(q, kk, vv, causal=True,
                                             scale=c.attn_scale,
                                             window=attn_window)
        elif attn_window is not None:
            # per-layer-varying (traced) windows — alternating global/local
            # causal attention (GPT-Neo): numeric banded mask
            q_pos = jnp.arange(s)[:, None]
            k_pos = jnp.arange(s)[None, :]
            m = (k_pos <= q_pos) & ((attn_window <= 0)
                                    | (k_pos > q_pos - attn_window))
            attn = dot_product_attention(q, kk, vv, causal=False,
                                         mask=m[None, None], scale=c.attn_scale)
        elif c.use_flash:
            attn = self._local_flash(q, kk, vv, causal=c.causal,
                                     scale=c.attn_scale)
        else:
            attn = dot_product_attention(q, kk, vv, causal=c.causal,
                                         scale=c.attn_scale)

        attn = attn.reshape(b, s, c.n_heads * hd) @ lp["wo"]
        if c.attn_o_bias:
            attn = attn + lp["bo"]

        if c.parallel_residual:
            # GPT-J / GPT-NeoX: both branches read the SAME input x
            # (GPT-J's single shared LN arrives as duplicated norm params)
            h2 = self._norm(x, lp["mlp_norm_w"], lp.get("mlp_norm_b"))
            down, aux = self._mlp(h2, lp, rng, training)
            return x + attn + down, new_kv, aux

        if not c.prenorm:  # post-LN: norm AFTER each residual add
            x = self._norm(x + attn, lp["attn_norm_w"], lp.get("attn_norm_b"))
            down, aux = self._mlp(x, lp, rng, training)
            return self._norm(x + down, lp["mlp_norm_w"], lp.get("mlp_norm_b")), new_kv, aux

        x = x + attn
        h = self._norm(x, lp["mlp_norm_w"], lp.get("mlp_norm_b"))
        down, aux = self._mlp(h, lp, rng, training)
        return x + down, new_kv, aux

    def _mlp(self, h, lp, rng=None, training=False):
        """Dense FFN. Subclasses (MoE) override; returns (out, aux_loss)."""
        c = self.config
        if c.activation == "silu_glu":
            up = jax.nn.silu(h @ lp["w_gate"]) * (h @ lp["w_up"])
        else:
            up = h @ lp["w_up"]
            if c.use_bias:
                up = up + lp["b_up"]
            if c.activation == "relu":
                up = jax.nn.relu(up)
            elif c.activation == "gelu_exact":   # erf GELU (GPT-NeoX/Pythia)
                up = jax.nn.gelu(up, approximate=False)
            elif c.activation == "quick_gelu":   # x*sigmoid(1.702x) (CLIP)
                up = up * jax.nn.sigmoid(1.702 * up)
            else:
                up = jax.nn.gelu(up)             # tanh approx (GPT-2 family)
        down = self._down_proj(up, lp["w_down"])
        if c.use_bias:
            down = down + lp["b_down"]
        return down, jnp.zeros((), jnp.float32)

    def _down_proj(self, up, w_down):
        """Row-parallel MLP down-projection. On the TP decode path (one
        query position in flight) with a fused kernel backend bound, the
        partial matmul and its all-reduce run fused inside a shard_map
        (``matmul_all_reduce``: the matmul epilogue produces the chunks
        of a deterministic rank-ordered chunked all-reduce, per-tile
        overlapped) — at decode the all-reduce is otherwise pure exposed
        latency after a tiny matmul (docs/performance.md). Prefill,
        training, unwrappable shapes and the default backend keep the
        plain matmul and let GSPMD insert the psum."""
        backend = self._comm_backend
        mesh = self._mesh
        tp = self._tp_size
        if (backend is None or tp <= 1 or mesh is None
                or up.ndim != 3 or up.shape[1] != 1):
            return up @ w_down
        batch_axes = tuple(getattr(self, "_batch_axes", None) or ())
        dp = 1
        for a in batch_axes:
            dp *= mesh.shape.get(a, 1)
        b, _, f = up.shape
        d = w_down.shape[-1]
        if (dp > 1 and b % dp) or f % tp:
            return up @ w_down
        from ..parallel.mesh import shard_map_compat
        from jax.sharding import PartitionSpec as P_

        def fused(u, w):
            y = backend.matmul_all_reduce(u.reshape(-1, u.shape[-1]), w,
                                          "model", out_dtype=u.dtype)
            return y.reshape(u.shape[0], 1, d)

        return shard_map_compat(
            fused, mesh=mesh,
            in_specs=(P_(batch_axes or None, None, "model"),
                      P_("model", None)),
            out_specs=P_(batch_axes or None, None, None),
            axis_names=set(batch_axes) | {"model"},
            check_vma=False)(up, w_down)

    def _encode(self, params, x, angles=None, positions=None, rng=None,
                training=False, attn_mask=None):
        """Scan the block stack over already-embedded inputs x: [b, s, d].
        Returns (hidden, summed aux loss). Shared by the token path
        (:meth:`apply`) and non-token towers (vision patch embeddings)."""
        c = self.config
        layer_rng = rng if rng is not None else jax.random.PRNGKey(0)
        # when no window binds at this (static) length, windowed causal ==
        # plain causal: keep the flash path (Mistral at seq <= window).
        # A BINDING uniform window stays a static python int so _block can
        # dispatch the banded flash kernel; only per-layer-varying windows
        # (GPT-Neo) ride the scan as traced scalars.
        aw = c.attn_windows if c.window_binds(x.shape[1]) else None
        static_window = None
        if aw is not None and len(set(aw)) == 1:
            static_window, aw = aw[0], None
        windows = jnp.asarray(aw, jnp.int32) if aw is not None else None

        def block(x, lp, r, w):
            return self._block(x, lp, angles, positions, None, r, training,
                               attn_mask, static_window if w is None else w)

        if c.remat:
            from ..runtime.activation_checkpointing import checkpoint_wrapper

            block = checkpoint_wrapper(block, policy=c.remat_policy)

        def scan_fn(carry, xs):
            y, r = carry
            lp, w = (xs, None) if windows is None else xs
            r, sub = jax.random.split(r)
            y, _, aux = block(y, lp, sub, w)
            return (y, r), aux

        xs = params["layers"] if windows is None else (params["layers"], windows)
        (x, _), auxes = jax.lax.scan(scan_fn, (x, layer_rng), xs)
        return x, jnp.sum(auxes)

    def apply(self, params, tokens, positions=None, kv_caches=None, cache_pos=None,
              rng=None, training=False, return_aux=False, last_token_only=False,
              return_hidden=False, token_type_ids=None, attn_mask=None):
        """Forward. tokens: [b, s] int32 -> logits [b, s, vocab] (fp32).

        ``kv_caches``: optional stacked (k,v) cache [n_layers, b, max_s, hkv, hd]
        pair for decode; returns (logits, new_caches) then.
        ``return_aux``: also return the summed auxiliary loss (MoE load
        balancing) accumulated across layers.
        ``return_hidden``: return the pre-head hidden states [b, s, d]
        instead of logits (the chunked-CE loss runs the head itself).
        ``token_type_ids``: [b, s] segment ids (encoder families; defaults
        to zeros when the config has type embeddings).
        ``attn_mask``: [b, s] padding mask for the bidirectional path.
        """
        c = self.config
        if kv_caches is not None and not c.causal:
            raise ValueError("KV-cache decode requires a causal model")
        x = self._embed(params, tokens, positions, token_type_ids)  # [b, s, d]
        angles = rope_frequencies(c.rotary_dim, c.max_seq_len, c.rope_theta) \
            if c.position == "rope" else None

        aux_total = jnp.zeros((), jnp.float32)
        if kv_caches is None:
            x, aux_total = self._encode(params, x, angles, positions, rng,
                                        training, attn_mask)
            new_caches = None
        else:
            ks, vs = kv_caches
            windows = jnp.asarray(c.attn_windows, jnp.int32) \
                if c.attn_windows is not None else None

            def scan_fn(carry, layer_in):
                if windows is None:
                    (lp, ck, cv), w = layer_in, None
                else:
                    lp, ck, cv, w = layer_in
                y, (nk, nv), _aux = self._block(
                    carry, lp, angles, positions, (ck, cv, cache_pos),
                    attn_window=w)
                return y, (nk, nv)

            xs = (params["layers"], ks, vs) if windows is None \
                else (params["layers"], ks, vs, windows)
            x, (nks, nvs) = jax.lax.scan(scan_fn, x, xs)
            new_caches = (nks, nvs)

        if last_token_only:
            x = x[:, -1:]
        if return_hidden:
            out = x
        else:
            out = self._head(params, x)
        if new_caches is not None:
            return out, new_caches
        if return_aux:
            return out, aux_total
        return out

    # ------------------------------------------------------------------
    def _targets_from_batch(self, batch):
        """(inputs, targets, mask) for next-token CE. batch:
        {"input_ids": [b, s]} with optional "labels" (shifted internally when
        absent) and "loss_mask"."""
        tokens = batch["input_ids"]
        if "labels" in batch:
            mask = batch.get("loss_mask")
            if mask is not None:
                mask = mask.astype(jnp.float32)
            return tokens, batch["labels"], mask
        if not self.config.causal:
            # next-token shift is degenerate under bidirectional attention
            # (position i sees token i+1 directly — loss collapses to a
            # copy task); encoders must train on explicit labels (MLM)
            raise ValueError(
                "bidirectional (causal=False) models require explicit "
                "'labels' (+ 'loss_mask') in the batch — next-token "
                "prediction is not a valid encoder objective")
        # keep the full sequence length (it must stay divisible by the
        # seq mesh axis); predict shift-left targets and mask the final
        # position instead of slicing
        targets = jnp.concatenate([tokens[:, 1:], tokens[:, :1]], axis=1)
        last_off = jnp.ones_like(tokens, jnp.float32).at[:, -1].set(0.0)
        mask = batch.get("loss_mask")
        mask = last_off if mask is None else mask.astype(jnp.float32) * last_off
        return tokens, targets, mask

    def _ce_terms(self, logits, targets, mask):
        """(weighted nll sum, weight sum, z-loss sum) for one [b, s, v]
        logits block — fp32 accumulation."""
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        if mask is not None:
            mask = mask[:, : nll.shape[1]].astype(jnp.float32)
            nll_sum = jnp.sum(nll * mask)
            denom = jnp.sum(mask)
        else:
            nll_sum = jnp.sum(nll)
            denom = jnp.asarray(float(np.prod(nll.shape)), jnp.float32)
        z_sum = jnp.zeros([], jnp.float32)
        if self.config.z_loss > 0:
            z = jnp.square(jax.scipy.special.logsumexp(logits, axis=-1))
            if mask is not None:
                z = z * mask
            z_sum = jnp.sum(z)
        return nll_sum, denom, z_sum

    def loss(self, params, batch, rng=None):
        """Next-token (or masked-LM, via explicit labels) cross entropy
        (+ z-loss + MoE aux). Encoder batches may carry "attention_mask"
        (padding) and "token_type_ids" (segments); both flow into the
        forward."""
        inputs, targets, mask = self._targets_from_batch(batch)
        # only encoder configs consume these; causal models ignore them the
        # way HF-tokenizer batches expect (all-ones attention_mask is the
        # decoder norm and must not trip the causal+mask guard)
        fwd_kw = {}
        if not self.config.causal and "attention_mask" in batch:
            fwd_kw["attn_mask"] = batch["attention_mask"]
        if self.config.type_vocab_size > 0 and "token_type_ids" in batch:
            fwd_kw["token_type_ids"] = batch["token_type_ids"]
        cs = self.config.loss_chunk_size
        if cs > 0:
            x, aux = self.apply(params, inputs, rng=rng, training=True,
                                return_aux=True, return_hidden=True, **fwd_kw)
            nll_sum, denom, z_sum = self._ce_chunked(params, x, targets, mask, cs)
        else:
            logits, aux = self.apply(params, inputs, rng=rng, training=True,
                                     return_aux=True, **fwd_kw)
            nll_sum, denom, z_sum = self._ce_terms(logits, targets, mask)
        loss = nll_sum / jnp.maximum(denom, 1.0)
        if self.config.z_loss > 0:
            loss = loss + self.config.z_loss * z_sum / jnp.maximum(denom, 1.0)
        return loss + aux

    def _ce_chunked(self, params, x, targets, mask, chunk):
        """Head + CE over flattened token chunks under a scan, so the full
        [b*s, vocab] fp32 logits never materialize; ``jax.checkpoint`` on
        the body makes the backward recompute each chunk's logits from its
        [chunk, d] hidden slice instead of storing them."""
        d = x.shape[-1]
        xf = x.reshape(-1, d)
        tf = targets.reshape(-1)
        mf = jnp.ones_like(tf, jnp.float32) if mask is None \
            else mask.reshape(-1).astype(jnp.float32)
        n = xf.shape[0]
        pad = (-n) % chunk
        if pad:
            xf = jnp.pad(xf, ((0, pad), (0, 0)))
            tf = jnp.pad(tf, (0, pad))
            mf = jnp.pad(mf, (0, pad))  # padded lanes carry zero weight
        xc = xf.reshape(-1, 1, chunk, d)
        tc = tf.reshape(-1, 1, chunk)
        mc = mf.reshape(-1, 1, chunk)

        @jax.checkpoint
        def body(carry, xtm):
            xcb, tcb, mcb = xtm
            logits = self._head(params, xcb)          # [1, chunk, vocab] fp32
            ns, dn, zs = self._ce_terms(logits, tcb, mcb)
            a, b, c_ = carry
            return (a + ns, b + dn, c_ + zs), None

        init = (jnp.zeros([], jnp.float32),) * 3
        (nll_sum, denom, z_sum), _ = jax.lax.scan(body, init, (xc, tc, mc))
        return nll_sum, denom, z_sum

    # ------------------------------------------------------------------
    # pipeline-parallel path (reference: runtime/pipe/engine.py train_batch)
    def _embed(self, params, tokens, positions=None, token_type_ids=None):
        """Token (+ learned position) embedding: [b, s] -> [b, s, d] in the
        compute dtype.

        With the table vocab-sharded over 'model' (partition_specs), a plain
        gather forces SPMD "involuntary full rematerialization" (replicate
        the table, then repartition). The one-hot contraction keeps the
        lookup sharded: each shard contracts its vocab slice on the MXU and
        GSPMD inserts one psum of [b, s, d] — never materializing the full
        table on any chip (the Megatron VocabParallelEmbedding semantics,
        expressed as a matmul instead of masked gather + allreduce).
        """
        c = self.config
        compute_dtype = params["layers"]["wq"].dtype
        if self._tp_size > 1:
            # clip for parity with the gather branch (jnp indexing clamps
            # out-of-range ids; unclipped one_hot would zero them instead)
            safe = jnp.clip(tokens, 0, c.vocab_size - 1)
            one_hot = jax.nn.one_hot(safe, c.vocab_size, dtype=compute_dtype)
            x = one_hot @ params["tok_embed"].astype(compute_dtype)
        else:
            x = params["tok_embed"][tokens]
        x = x.astype(compute_dtype)
        if c.position == "learned":
            s = tokens.shape[-1]
            pos_emb = params["pos_embed"][:s] if positions is None else params["pos_embed"][positions]
            x = x + pos_emb.astype(compute_dtype)
        if c.type_vocab_size > 0:
            # segment embeddings (BERT); embed_norm below then normalizes
            # the SUM of word+position+type, matching BertEmbeddings
            tt = jnp.zeros_like(tokens) if token_type_ids is None else token_type_ids
            x = x + params["type_embed"][tt].astype(compute_dtype)
        if c.embed_norm:
            x = layer_norm(x, params["embed_norm_w"], params["embed_norm_b"],
                           c.norm_eps)
        return x

    def _head(self, params, x):
        """Final norm + LM head: [..., s, d] -> fp32 logits [..., s, vocab].

        Encoder MLM head (mlm_head): dense + gelu + LN transform before the
        tied decoder, plus a vocab bias (BertLMPredictionHead)."""
        c = self.config
        if c.prenorm:
            x = self._norm(x, params["final_norm_w"], params.get("final_norm_b"))
        if c.mlm_head:
            x = x @ params["mlm_dense_w"].astype(x.dtype) + params["mlm_dense_b"].astype(x.dtype)
            # HF BertPredictionHeadTransform reuses config.hidden_act —
            # follow the model's FFN activation, not a hardcoded GELU
            if c.activation == "relu":
                x = jax.nn.relu(x)
            else:
                x = jax.nn.gelu(x, approximate=(c.activation != "gelu_exact"))
            x = layer_norm(x, params["mlm_norm_w"], params["mlm_norm_b"], c.norm_eps)
        w_out = params["tok_embed"].T if c.tie_embeddings else params["lm_head"]
        logits = (x @ w_out.astype(x.dtype)).astype(jnp.float32)
        if c.mlm_head:
            logits = logits + params["mlm_bias"].astype(jnp.float32)
        if "lm_head_b" in params:  # GPT-J carries an LM-head bias
            logits = logits + params["lm_head_b"].astype(jnp.float32)
        if c.logits_softcap > 0:
            logits = jnp.tanh(logits / c.logits_softcap) * c.logits_softcap
        return logits

    def pooled(self, params, hidden):
        """BertPooler: tanh dense on the [CLS] (first) token of the final
        hidden states ([b, s, d] from apply(..., return_hidden=True))."""
        if not self.config.pooler:
            raise ValueError("model config has pooler=False")
        cls = hidden[:, 0]
        return jnp.tanh(cls @ params["pooler_w"].astype(cls.dtype)
                        + params["pooler_b"].astype(cls.dtype))

    def pipeline_loss(self, params, batch, rng, num_microbatches: int):
        """Pipelined training loss over the whole global batch.

        Splits the batch into ``num_microbatches`` (= gradient-accumulation
        steps, as in the reference PipelineEngine where GAS is the number of
        in-flight micro-batches), embeds, pipelines the block stack over the
        ``pipe`` mesh axis via the rotating-microbatch executor, then runs
        the head + CE per micro-batch under a scan (so full-batch logits are
        never materialized at once).
        """
        from ..parallel.pipeline import microbatch, pipeline_apply, stack_stage_params

        c = self.config
        assert self._pipe_size > 1 and self._mesh is not None, \
            "pipeline_loss requires a bound topology with pipe axis > 1"
        if self._seq_size > 1:
            raise NotImplementedError(
                "pipe x seq parallel composition not supported yet; "
                "use Ulysses/ring SP without the pipe axis")
        if not self.config.causal and (
                "attention_mask" in batch or "token_type_ids" in batch):
            raise NotImplementedError(
                "encoder attention_mask/token_type_ids not plumbed through "
                "the pipeline path yet — drop the pipe axis for BERT-style "
                "training")
        if rng is None:
            rng = jax.random.PRNGKey(0)

        inputs, targets, mask = self._targets_from_batch(batch)
        if self.config.window_binds(inputs.shape[1]):
            # the stage scan does not thread per-layer windows; a window
            # that never binds at this length is plain causal and fine
            raise NotImplementedError(
                "binding attention windows not plumbed through the "
                "pipeline stage scan yet — drop the pipe axis or keep "
                "seq_len <= window")
        mb = microbatch(
            {"inputs": inputs, "targets": targets,
             **({"mask": mask} if mask is not None else {})},
            num_microbatches)
        # lax.map (sequential) under TP bounds the one-hot embed transient to
        # one micro-batch's [b/M, s, vocab]; vmap would materialize all M at
        # once — a ~vocab/d_model blowup at the pipeline entrance
        if self._tp_size > 1:
            xs = jax.lax.map(lambda t: self._embed(params, t), mb["inputs"])
        else:
            xs = jax.vmap(lambda t: self._embed(params, t))(mb["inputs"])
        # xs: [M, b/M, s, d]
        angles = rope_frequencies(c.rotary_dim, c.max_seq_len, c.rope_theta) \
            if c.position == "rope" else jnp.zeros((1, 1), jnp.float32)
        stage_params = stack_stage_params(params["layers"], self._pipe_size)

        # fp32 at the pipe boundary: inter-stage transfers and the
        # replicated-input cotangent reductions shard_map's autodiff inserts
        # accumulate in fp32 (sub-fp32 psum also miscompiles on XLA:CPU);
        # block compute stays in the params' compute dtype.
        compute_dtype = params["layers"]["wq"].dtype
        xs = xs.astype(jnp.float32)

        def stage_fn(lp_stage, x, consts, sub_rng, valid):
            x = x.astype(compute_dtype)

            def body(carry, lp):
                y, r = carry
                r, sub = jax.random.split(r)
                y, _, aux = self._block(y, lp, consts["angles"], None, None, sub, True)
                return (y, r), aux

            (y, _), auxes = jax.lax.scan(body, (x, sub_rng), lp_stage)
            return y.astype(jnp.float32), jnp.sum(auxes)

        ys, aux = pipeline_apply(
            stage_fn, stage_params, xs, rng, self._mesh,
            consts={"angles": angles}, remat=c.remat)

        # head + CE per micro-batch, scanned to bound logits memory
        def head_ce(carry, mb_t):
            logits = self._head(params, mb_t["x"].astype(compute_dtype))
            nll_sum, denom, z_sum = self._ce_terms(
                logits, mb_t["targets"], mb_t.get("mask"))
            nll_acc, den_acc, z_acc = carry
            return (nll_acc + nll_sum, den_acc + denom, z_acc + z_sum), None

        head_ce = jax.checkpoint(head_ce)
        zeros = (jnp.zeros([], jnp.float32),) * 3
        scan_in = {"x": ys, "targets": mb["targets"]}
        if mask is not None:
            scan_in["mask"] = mb["mask"]
        (nll_sum, denom, z_sum), _ = jax.lax.scan(head_ce, zeros, scan_in)
        loss = nll_sum / jnp.maximum(denom, 1.0)
        if c.z_loss > 0:
            loss = loss + c.z_loss * z_sum / jnp.maximum(denom, 1.0)
        return loss + aux

    # ------------------------------------------------------------------
    def partition_specs(self, params, topo=None) -> Dict[str, Any]:
        """Tensor-parallel PartitionSpecs over the 'model' axis.

        Megatron-style: column-parallel QKV/up/gate (shard output features),
        row-parallel O/down (shard input features), vocab-sharded embedding.
        This is the training-TP capability the reference delegates to an
        external mpu (SURVEY.md §2.2 "TP (training)") and implements for
        inference as AutoTP (module_inject/auto_tp.py) — here it is native.
        """
        c = self.config
        # pipeline parallelism: the stacked-layer leading dim is sharded over
        # 'pipe' so each stage group holds only its layers (reference:
        # PipelineModule assigns layer ranges to stage ranks, module.py:86)
        pipe_size = topo.pipe_parallel_size if topo is not None else self._pipe_size
        pipe = "pipe" if pipe_size > 1 else None
        layer_specs = {
            "attn_norm_w": P(pipe, None),
            "wq": P(pipe, None, "model"),
            "wk": P(pipe, None, "model"),
            "wv": P(pipe, None, "model"),
            "wo": P(pipe, "model", None),
            "mlp_norm_w": P(pipe, None),
            "w_up": P(pipe, None, "model"),
            "w_down": P(pipe, "model", None),
        }
        if c.activation == "silu_glu":
            layer_specs["w_gate"] = P(pipe, None, "model")
        if c.norm == "layer":
            layer_specs["attn_norm_b"] = P(pipe, None)
            layer_specs["mlp_norm_b"] = P(pipe, None)
        if c.qkv_bias:
            layer_specs.update({
                "bq": P(pipe, "model"), "bk": P(pipe, "model"),
                "bv": P(pipe, "model"),
            })
        if c.attn_o_bias:
            layer_specs["bo"] = P(pipe, None)
        if c.use_bias:
            layer_specs.update({
                "b_up": P(pipe, "model"), "b_down": P(pipe, None),
            })
        specs: Dict[str, Any] = {
            "tok_embed": P("model", None),
            "layers": layer_specs,
        }
        if c.prenorm:
            specs["final_norm_w"] = P(None)
            if c.norm == "layer":
                specs["final_norm_b"] = P(None)
        if c.position == "learned":
            specs["pos_embed"] = P(None, None)
        if c.type_vocab_size > 0:
            specs["type_embed"] = P(None, None)
        if c.embed_norm:
            specs["embed_norm_w"] = P(None)
            specs["embed_norm_b"] = P(None)
        if not c.tie_embeddings:
            specs["lm_head"] = P(None, "model")
            if isinstance(params, dict) and "lm_head_b" in params:
                specs["lm_head_b"] = P("model")  # GPT-J ingests carry one
        if c.mlm_head:
            # transform stays replicated (its output feeds a LayerNorm over
            # full d); the vocab bias follows the vocab-sharded embedding
            specs.update({"mlm_dense_w": P(None, None), "mlm_dense_b": P(None),
                          "mlm_norm_w": P(None), "mlm_norm_b": P(None),
                          "mlm_bias": P("model")})
        if c.pooler:
            specs["pooler_w"] = P(None, None)
            specs["pooler_b"] = P(None)
        return specs
