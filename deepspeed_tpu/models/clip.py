"""CLIP family: contrastive text + vision towers.

Parity target: reference ``module_inject/containers/clip.py`` (CLIP layer
policy) and the stable-diffusion serving path's text encoder
(``model_implementations/``). Both towers run on the shared Transformer
core:

* **text tower** — causal pre-LN encoder with learned positions and
  quick-GELU; features are the final-LN hidden state at the EOS position,
  projected without bias (HF ``CLIPTextTransformer`` semantics).
* **vision tower** — a ViT on the same block stack: non-overlapping patch
  embedding expressed as a reshape + one MXU matmul (equivalent to the
  stride-p conv), a learned class token, ``embed_norm`` standing in for
  HF's ``pre_layrnorm`` and ``final_norm`` for ``post_layernorm``.

The contrastive head L2-normalizes both embeddings and scales by
``exp(logit_scale)`` (CLIPModel.forward).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..ops.norms import layer_norm
from .transformer import Transformer, TransformerConfig


def clip_text_config(vocab_size=49408, d_model=512, n_layers=12, n_heads=8,
                     d_ff=2048, max_seq_len=77, **overrides) -> TransformerConfig:
    kw = dict(vocab_size=vocab_size, d_model=d_model, n_layers=n_layers,
              n_heads=n_heads, d_ff=d_ff, max_seq_len=max_seq_len,
              norm="layer", activation="quick_gelu", position="learned",
              causal=True, tie_embeddings=True, use_bias=True, norm_eps=1e-5)
    kw.update(overrides)
    return TransformerConfig(**kw)


def clip_vision_config(d_model=768, n_layers=12, n_heads=12, d_ff=3072,
                       **overrides) -> TransformerConfig:
    kw = dict(vocab_size=1,  # token table unused by the pixel path
              d_model=d_model, n_layers=n_layers, n_heads=n_heads, d_ff=d_ff,
              max_seq_len=1, norm="layer", activation="quick_gelu",
              position="none",  # learned positions are added in apply_pixels
              causal=False, embed_norm=True, tie_embeddings=True,
              use_bias=True, norm_eps=1e-5)
    kw.update(overrides)
    return TransformerConfig(**kw)


class CLIPVision(Transformer):
    """ViT tower: pixels [b, 3, H, W] -> (hidden [b, 1+n, d], pooled [b, d])."""

    def __init__(self, config: TransformerConfig, image_size: int = 224,
                 patch_size: int = 32, n_channels: int = 3):
        super().__init__(config)
        assert image_size % patch_size == 0
        self.image_size = image_size
        self.patch_size = patch_size
        self.n_channels = n_channels
        self.n_patches = (image_size // patch_size) ** 2

    def init(self, rng, dtype=jnp.float32) -> Dict[str, Any]:
        c = self.config
        params = super().init(rng, dtype)
        k1, k2, k3 = jax.random.split(jax.random.fold_in(rng, 7), 3)
        pdim = self.n_channels * self.patch_size ** 2
        params["patch_w"] = (jax.random.normal(k1, (pdim, c.d_model), jnp.float32)
                             / np.sqrt(pdim)).astype(dtype)
        params["cls_embed"] = (jax.random.normal(k2, (c.d_model,), jnp.float32)
                               * 0.02).astype(dtype)
        params["pos_embed"] = (jax.random.normal(
            k3, (self.n_patches + 1, c.d_model), jnp.float32) * 0.02).astype(dtype)
        return params

    def apply_pixels(self, params, pixels, rng=None, training=False):
        """pixels: [b, 3, H, W] float. The stride-p conv is a reshape into
        (c, ph, pw)-ordered patch vectors + one matmul — identical math,
        MXU-shaped."""
        c = self.config
        p = self.patch_size
        b, ch, H, W = pixels.shape
        assert ch == self.n_channels and H == W == self.image_size, (
            f"expected [b, {self.n_channels}, {self.image_size}, "
            f"{self.image_size}], got {pixels.shape}")
        hp = H // p
        compute_dtype = params["layers"]["wq"].dtype
        patches = pixels.reshape(b, ch, hp, p, hp, p) \
                        .transpose(0, 2, 4, 1, 3, 5) \
                        .reshape(b, hp * hp, ch * p * p).astype(compute_dtype)
        x = patches @ params["patch_w"].astype(compute_dtype)
        cls = jnp.broadcast_to(params["cls_embed"].astype(compute_dtype),
                               (b, 1, c.d_model))
        x = jnp.concatenate([cls, x], axis=1)
        x = x + params["pos_embed"].astype(compute_dtype)
        x = layer_norm(x, params["embed_norm_w"], params["embed_norm_b"],
                       c.norm_eps)  # HF pre_layrnorm
        h, _ = self._encode(params, x, rng=rng, training=training)
        pooled = layer_norm(h[:, 0], params["final_norm_w"],
                            params["final_norm_b"], c.norm_eps)  # post_layernorm
        return h, pooled

    def partition_specs(self, params, topo=None) -> Dict[str, Any]:
        specs = super().partition_specs(params, topo)
        specs["tok_embed"] = P(None, None)  # unused 1-row table: replicate
        specs["patch_w"] = P(None, None)
        specs["cls_embed"] = P(None)
        specs["pos_embed"] = P(None, None)
        return specs


@dataclass
class CLIPConfig:
    text: TransformerConfig
    vision: TransformerConfig
    proj_dim: int = 512
    image_size: int = 224
    patch_size: int = 32
    n_channels: int = 3
    eos_token_id: Optional[int] = None  # None -> argmax pooling (pre-HF4.30)


class CLIP:
    """Two-tower contrastive model (reference CLIPModel surface)."""

    def __init__(self, config: CLIPConfig):
        self.config = config
        self.text = Transformer(config.text)
        self.vision = CLIPVision(config.vision, config.image_size,
                                 config.patch_size, config.n_channels)

    def bind_topology(self, topo) -> "CLIP":
        self.text.bind_topology(topo)
        self.vision.bind_topology(topo)
        return self

    def init(self, rng, dtype=jnp.float32) -> Dict[str, Any]:
        kt, kv, kp1, kp2 = jax.random.split(rng, 4)
        c = self.config
        return {
            "text": self.text.init(kt, dtype),
            "vision": self.vision.init(kv, dtype),
            "text_proj": (jax.random.normal(
                kp1, (c.text.d_model, c.proj_dim), jnp.float32)
                / np.sqrt(c.text.d_model)).astype(dtype),
            "vision_proj": (jax.random.normal(
                kp2, (c.vision.d_model, c.proj_dim), jnp.float32)
                / np.sqrt(c.vision.d_model)).astype(dtype),
            "logit_scale": jnp.asarray(np.log(1 / 0.07), dtype),
        }

    def encode_text(self, params, tokens):
        """tokens [b, s] -> projected text embedding [b, proj]. Pools the
        final-LN hidden state at the EOS position (eos_token_id match, or
        argmax like original CLIP where EOS is the highest id)."""
        tp = params["text"]
        h = self.text.apply(tp, tokens, return_hidden=True)
        h = layer_norm(h, tp["final_norm_w"], tp["final_norm_b"],
                       self.config.text.norm_eps)
        if self.config.eos_token_id is not None:
            eos = jnp.argmax((tokens == self.config.eos_token_id)
                             .astype(jnp.int32), axis=-1)
        else:
            eos = jnp.argmax(tokens, axis=-1)
        pooled = h[jnp.arange(h.shape[0]), eos]
        return pooled @ params["text_proj"].astype(pooled.dtype)

    def encode_image(self, params, pixels):
        """pixels [b, 3, H, W] -> projected image embedding [b, proj]."""
        _, pooled = self.vision.apply_pixels(params["vision"], pixels)
        return pooled @ params["vision_proj"].astype(pooled.dtype)

    def similarity(self, params, tokens, pixels):
        """Returns (logits_per_text [bt, bi], logits_per_image [bi, bt])."""
        t = self.encode_text(params, tokens)
        v = self.encode_image(params, pixels)
        t = t / jnp.linalg.norm(t, axis=-1, keepdims=True)
        v = v / jnp.linalg.norm(v, axis=-1, keepdims=True)
        scale = jnp.exp(params["logit_scale"]).astype(t.dtype)
        lpt = (t @ v.T) * scale
        return lpt, lpt.T

    def loss(self, params, batch, rng=None):
        """Symmetric InfoNCE over in-batch pairs (CLIP training objective)."""
        lpt, lpi = self.similarity(params, batch["input_ids"],
                                   batch["pixel_values"])
        n = lpt.shape[0]
        labels = jnp.arange(n)
        lt = -jnp.take_along_axis(jax.nn.log_softmax(lpt, -1),
                                  labels[:, None], -1).mean()
        li = -jnp.take_along_axis(jax.nn.log_softmax(lpi, -1),
                                  labels[:, None], -1).mean()
        return 0.5 * (lt + li)

    def partition_specs(self, params, topo=None) -> Dict[str, Any]:
        return {
            "text": self.text.partition_specs(params.get("text"), topo),
            "vision": self.vision.partition_specs(params.get("vision"), topo),
            "text_proj": P(None, None),
            "vision_proj": P(None, None),
            "logit_scale": P(),
        }
