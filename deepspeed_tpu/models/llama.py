"""Llama-2 family configs (flagship model).

Parity target: the reference serves Llama via HF + kernel injection
(module_inject/containers/llama.py, inference/v2/model_implementations/
llama_v2) — here Llama is a first-class native model on the shared
:class:`~deepspeed_tpu.models.transformer.Transformer` core (RMSNorm + RoPE
+ gated-SiLU + GQA, pre-norm, tied-or-untied head).
"""

from __future__ import annotations

from .transformer import Transformer, TransformerConfig


def llama_config(size: str = "7b", **overrides) -> TransformerConfig:
    presets = {
        "tiny": dict(vocab_size=32000, d_model=256, n_layers=4, n_heads=8, n_kv_heads=8,
                     max_seq_len=512),
        "160m": dict(vocab_size=32000, d_model=768, n_layers=12, n_heads=12, n_kv_heads=12,
                     max_seq_len=2048),
        "1b": dict(vocab_size=32000, d_model=2048, n_layers=22, n_heads=32, n_kv_heads=4,
                   d_ff=5632, max_seq_len=2048),
        "7b": dict(vocab_size=32000, d_model=4096, n_layers=32, n_heads=32, n_kv_heads=32,
                   d_ff=11008, max_seq_len=4096),
        "13b": dict(vocab_size=32000, d_model=5120, n_layers=40, n_heads=40, n_kv_heads=40,
                    d_ff=13824, max_seq_len=4096),
        "70b": dict(vocab_size=32000, d_model=8192, n_layers=80, n_heads=64, n_kv_heads=8,
                    d_ff=28672, max_seq_len=4096),
    }
    if size not in presets:
        raise ValueError(f"unknown llama size '{size}'; have {sorted(presets)}")
    kw = dict(presets[size])
    kw.update(norm="rms", activation="silu_glu", position="rope",
              tie_embeddings=False, use_bias=False)
    kw.update(overrides)
    return TransformerConfig(**kw)


def Llama(size: str = "7b", **overrides) -> Transformer:
    return Transformer(llama_config(size, **overrides))
