"""Elastic training configuration.

Reference surface: deepspeed/elasticity/ — ``compute_elastic_config``
(elasticity.py:233, algorithms v0.1 :83 / v0.2 :126),
``ensure_immutable_elastic_config`` (:208), the ``ds_elastic`` CLI, and
``DSElasticAgent`` (elastic_agent.py:28).

TPU-native stance (SURVEY.md §7 "Elasticity"): TPU slices don't do live
membership change — recovery is checkpoint-based resume at a new world size
(the universal/orbax checkpoint reshards automatically). So this module
keeps the *planning* capability (choosing batch configs valid across an
accelerator-count range, enforcing immutability) and maps the agent's
restart loop onto run-loop resume (runtime/engine.load_checkpoint).
"""

from .elasticity import (
    ElasticityConfig,
    ElasticityError,
    ServingElasticityConfig,
    compute_elastic_config,
    compute_serving_replicas,
    ensure_immutable_elastic_config,
    get_compatible_gpus,
    serving_replica_candidates,
)

__all__ = ["compute_elastic_config", "ensure_immutable_elastic_config",
           "get_compatible_gpus", "ElasticityConfig", "ElasticityError",
           "ServingElasticityConfig", "compute_serving_replicas",
           "serving_replica_candidates"]
