"""Elastic capacity planning: training batch geometry AND serving
replica counts (reference elasticity/elasticity.py, extended).

Two consumers share the same candidate-enumeration discipline:

* **training** — given a target global-batch range, candidate micro-batch
  sizes, and a min/max accelerator count, find the global batch size (and
  per-count micro-batch + GAS) that stays valid across every admissible
  accelerator count, so a job can resume from checkpoint at a different
  slice size without changing the effective batch
  (:func:`compute_elastic_config`);
* **serving** — given the live pressure signals (queue depth, in-SLA
  ratio, KV occupancy), size the replica fleet by walking the admissible
  replica-count candidates for the smallest count that absorbs the load
  (:func:`compute_serving_replicas`). The fleet autoscaler calls this —
  policy lives HERE, not hard-coded in the fleet loop, so training and
  serving elasticity stay one subsystem with one config surface.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import hashlib
import json

from ..utils.logging import log_dist


class ElasticityError(Exception):
    pass


@dataclass
class ElasticityConfig:
    """Reference elasticity config schema (elasticity/config.py):
    max_train_batch_size, micro_batch_sizes, min/max_gpus,
    prefer_larger_batch, version, ignore_non_elastic_batch_info."""

    enabled: bool = False
    max_train_batch_size: int = 2048
    micro_batch_sizes: Sequence[int] = (2, 4, 6)
    min_gpus: int = 1
    max_gpus: int = 1024
    min_time: int = 0
    prefer_larger_batch: bool = True
    version: float = 0.2
    ignore_non_elastic_batch_info: bool = False

    @classmethod
    def from_dict(cls, d: Optional[Dict]) -> "ElasticityConfig":
        if not d:
            return cls()
        return cls(**{k: v for k, v in d.items() if k in cls.__dataclass_fields__})


def _candidate_batches(max_batch: int, micro_batches: Sequence[int]) -> List[int]:
    """All batch sizes of the form micro * k <= max_batch (reference
    _get_candidate_batch_sizes)."""
    out = set()
    for mb in micro_batches:
        b = mb
        while b <= max_batch:
            out.add(b)
            b += mb
    return sorted(out)


def get_compatible_gpus(batch: int, micro_batches: Sequence[int],
                        min_gpus: int, max_gpus: int) -> List[int]:
    """Accelerator counts that evenly fit ``batch`` with some micro-batch
    (reference _get_compatible_gpus_v01)."""
    ok = []
    for n in range(min_gpus, max_gpus + 1):
        if any(batch % (mb * n) == 0 for mb in micro_batches):
            ok.append(n)
    return ok


def compute_elastic_config(ds_config: Dict, target_deepspeed_version: str = "",
                           world_size: int = 0,
                           return_microbatch: bool = False):
    """Pick the final batch config (reference compute_elastic_config :233).

    Returns (final_batch_size, valid_gpus[, micro_batch]) — with
    ``world_size`` > 0 also resolves the micro-batch for that size.
    """
    econf = ElasticityConfig.from_dict(ds_config.get("elasticity"))
    if not ds_config.get("elasticity"):
        raise ElasticityError("'elasticity' section missing from config")
    if not econf.enabled:
        raise ElasticityError("elasticity.enabled is false")

    best_batch, best_gpus = 0, []
    for batch in _candidate_batches(econf.max_train_batch_size,
                                    econf.micro_batch_sizes):
        gpus = get_compatible_gpus(batch, econf.micro_batch_sizes,
                                   econf.min_gpus, econf.max_gpus)
        better = (len(gpus), batch) > (len(best_gpus), best_batch) \
            if econf.prefer_larger_batch else (len(gpus), -batch) > (len(best_gpus), -best_batch)
        if gpus and better:
            best_batch, best_gpus = batch, gpus

    if not best_gpus:
        raise ElasticityError(
            f"no batch size <= {econf.max_train_batch_size} is compatible with "
            f"gpu range [{econf.min_gpus}, {econf.max_gpus}] and micro-batches "
            f"{list(econf.micro_batch_sizes)}")
    log_dist(f"elastic config: batch={best_batch} valid_gpus={best_gpus[:8]}"
             + ("..." if len(best_gpus) > 8 else ""))

    if world_size > 0:
        if world_size not in best_gpus:
            raise ElasticityError(
                f"world size {world_size} not in valid elastic gpu counts")
        micro = max(mb for mb in econf.micro_batch_sizes
                    if best_batch % (mb * world_size) == 0)
        return best_batch, best_gpus, micro
    if return_microbatch:
        return best_batch, best_gpus, None
    return best_batch, best_gpus


# ----------------------------------------------------------------------
# serving-fleet sizing (consumed by serving/fleet.py's autoscaler)

@dataclass
class ServingElasticityConfig:
    """Replica-count policy for the serving fleet autoscaler.

    ``scale_up_queue_per_replica`` is the sustained queue depth one
    replica is allowed to carry before the policy asks for more;
    ``scale_down_queue_per_replica`` is the (strictly lower) depth below
    which a replica is considered idle — the gap between the two is the
    hysteresis band that keeps the fleet from flapping. ``kv_high`` and
    ``sla_low`` are pressure overrides: a fleet whose KV pools run hot or
    whose in-SLA ratio sags grows even when the queue looks shallow
    (queue depth lags both). ``max_step`` bounds replicas added/removed
    per decision so one noisy sample can never double or halve a fleet.
    """

    min_replicas: int = 1
    max_replicas: int = 8
    scale_up_queue_per_replica: float = 8.0
    scale_down_queue_per_replica: float = 1.0
    kv_high: float = 0.85
    sla_low: float = 0.90
    max_step: int = 1

    def __post_init__(self):
        if self.min_replicas < 1:
            raise ElasticityError(
                f"min_replicas must be >= 1, got {self.min_replicas}")
        if self.max_replicas < self.min_replicas:
            raise ElasticityError(
                f"max_replicas {self.max_replicas} < min_replicas "
                f"{self.min_replicas}")
        if self.scale_down_queue_per_replica > self.scale_up_queue_per_replica:
            raise ElasticityError(
                "scale_down_queue_per_replica must not exceed "
                "scale_up_queue_per_replica (the gap is the hysteresis band)")
        if self.max_step < 1:
            raise ElasticityError(
                f"max_step must be >= 1, got {self.max_step}")

    @classmethod
    def from_dict(cls, d: Optional[Dict]) -> "ServingElasticityConfig":
        if not d:
            return cls()
        return cls(**{k: v for k, v in d.items()
                      if k in cls.__dataclass_fields__})


def serving_replica_candidates(config: ServingElasticityConfig) -> List[int]:
    """Admissible replica counts, smallest first — the serving analog of
    ``_candidate_batches``: the policy walks these for the first count
    that absorbs the offered load."""
    return list(range(config.min_replicas, config.max_replicas + 1))


def compute_serving_replicas(current: int, *,
                             queue_depth: float,
                             kv_occupancy: float = 0.0,
                             in_sla_ratio: Optional[float] = None,
                             config: Optional[ServingElasticityConfig] = None
                             ) -> int:
    """Target replica count from live pressure signals.

    Sizing: the smallest candidate count keeping per-replica queue depth
    at or under ``scale_up_queue_per_replica``; KV or SLA pressure at the
    current size bumps the target one above ``current`` even when the
    queue looks absorbed (both signals lead the queue under bursty
    arrivals). Shrinking additionally requires the queue to sit under the
    *down* threshold at the SMALLER size — the hysteresis that keeps a
    fleet at the load boundary from oscillating. Movement per call is
    clamped to ``max_step`` and the result always lands in
    ``[min_replicas, max_replicas]``. Pure and deterministic: the fleet
    autoscaler (and its tests) call it with measured signals.
    """
    cfg = config or ServingElasticityConfig()
    current = max(cfg.min_replicas, min(cfg.max_replicas, int(current)))
    candidates = serving_replica_candidates(cfg)
    target = next((n for n in candidates
                   if queue_depth <= n * cfg.scale_up_queue_per_replica),
                  cfg.max_replicas)
    pressured = (kv_occupancy >= cfg.kv_high
                 or (in_sla_ratio is not None
                     and in_sla_ratio < cfg.sla_low))
    if pressured:
        # the bump also pins target >= current, so pressure inherently
        # vetoes shrinking — the hysteresis check below only ever sees
        # unpressured fleets
        target = max(target, min(current + 1, cfg.max_replicas))
    if target > current:
        target = min(target, current + cfg.max_step)
    elif target < current:
        # hysteresis judged at the size actually stepped to: judged at
        # the unclamped target, a single queued request (> down * 1)
        # would freeze an arbitrarily oversized fleet forever instead of
        # letting it shrink stepwise
        stepped = max(target, current - cfg.max_step)
        target = (current
                  if queue_depth > stepped * cfg.scale_down_queue_per_replica
                  else stepped)
    return max(cfg.min_replicas, min(cfg.max_replicas, target))


def elasticity_fingerprint(ds_config: Dict) -> str:
    e = ds_config.get("elasticity", {})
    return hashlib.sha256(json.dumps(e, sort_keys=True).encode()).hexdigest()


_frozen: Dict[str, str] = {}


def ensure_immutable_elastic_config(runtime_elastic_config_dict: Dict) -> None:
    """Reference :208 — the elastic config may not change once scheduled
    (resources were provisioned against it)."""
    fp = elasticity_fingerprint({"elasticity": runtime_elastic_config_dict})
    prev = _frozen.get("fp")
    if prev is not None and prev != fp:
        raise ElasticityError("elastic config changed after scheduling — "
                              "the batch contract is immutable")
    _frozen["fp"] = fp
