"""Elastic batch-size planning (reference elasticity/elasticity.py).

Given a target global-batch range, candidate micro-batch sizes, and a min/max
accelerator count, find the global batch size (and per-count micro-batch +
GAS) that stays valid across every admissible accelerator count — so a job
can resume from checkpoint at a different slice size without changing the
effective batch.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import hashlib
import json

from ..utils.logging import log_dist


class ElasticityError(Exception):
    pass


@dataclass
class ElasticityConfig:
    """Reference elasticity config schema (elasticity/config.py):
    max_train_batch_size, micro_batch_sizes, min/max_gpus,
    prefer_larger_batch, version, ignore_non_elastic_batch_info."""

    enabled: bool = False
    max_train_batch_size: int = 2048
    micro_batch_sizes: Sequence[int] = (2, 4, 6)
    min_gpus: int = 1
    max_gpus: int = 1024
    min_time: int = 0
    prefer_larger_batch: bool = True
    version: float = 0.2
    ignore_non_elastic_batch_info: bool = False

    @classmethod
    def from_dict(cls, d: Optional[Dict]) -> "ElasticityConfig":
        if not d:
            return cls()
        return cls(**{k: v for k, v in d.items() if k in cls.__dataclass_fields__})


def _candidate_batches(max_batch: int, micro_batches: Sequence[int]) -> List[int]:
    """All batch sizes of the form micro * k <= max_batch (reference
    _get_candidate_batch_sizes)."""
    out = set()
    for mb in micro_batches:
        b = mb
        while b <= max_batch:
            out.add(b)
            b += mb
    return sorted(out)


def get_compatible_gpus(batch: int, micro_batches: Sequence[int],
                        min_gpus: int, max_gpus: int) -> List[int]:
    """Accelerator counts that evenly fit ``batch`` with some micro-batch
    (reference _get_compatible_gpus_v01)."""
    ok = []
    for n in range(min_gpus, max_gpus + 1):
        if any(batch % (mb * n) == 0 for mb in micro_batches):
            ok.append(n)
    return ok


def compute_elastic_config(ds_config: Dict, target_deepspeed_version: str = "",
                           world_size: int = 0,
                           return_microbatch: bool = False):
    """Pick the final batch config (reference compute_elastic_config :233).

    Returns (final_batch_size, valid_gpus[, micro_batch]) — with
    ``world_size`` > 0 also resolves the micro-batch for that size.
    """
    econf = ElasticityConfig.from_dict(ds_config.get("elasticity"))
    if not ds_config.get("elasticity"):
        raise ElasticityError("'elasticity' section missing from config")
    if not econf.enabled:
        raise ElasticityError("elasticity.enabled is false")

    best_batch, best_gpus = 0, []
    for batch in _candidate_batches(econf.max_train_batch_size,
                                    econf.micro_batch_sizes):
        gpus = get_compatible_gpus(batch, econf.micro_batch_sizes,
                                   econf.min_gpus, econf.max_gpus)
        better = (len(gpus), batch) > (len(best_gpus), best_batch) \
            if econf.prefer_larger_batch else (len(gpus), -batch) > (len(best_gpus), -best_batch)
        if gpus and better:
            best_batch, best_gpus = batch, gpus

    if not best_gpus:
        raise ElasticityError(
            f"no batch size <= {econf.max_train_batch_size} is compatible with "
            f"gpu range [{econf.min_gpus}, {econf.max_gpus}] and micro-batches "
            f"{list(econf.micro_batch_sizes)}")
    log_dist(f"elastic config: batch={best_batch} valid_gpus={best_gpus[:8]}"
             + ("..." if len(best_gpus) > 8 else ""))

    if world_size > 0:
        if world_size not in best_gpus:
            raise ElasticityError(
                f"world size {world_size} not in valid elastic gpu counts")
        micro = max(mb for mb in econf.micro_batch_sizes
                    if best_batch % (mb * world_size) == 0)
        return best_batch, best_gpus, micro
    if return_microbatch:
        return best_batch, best_gpus, None
    return best_batch, best_gpus


def elasticity_fingerprint(ds_config: Dict) -> str:
    e = ds_config.get("elasticity", {})
    return hashlib.sha256(json.dumps(e, sort_keys=True).encode()).hexdigest()


_frozen: Dict[str, str] = {}


def ensure_immutable_elastic_config(runtime_elastic_config_dict: Dict) -> None:
    """Reference :208 — the elastic config may not change once scheduled
    (resources were provisioned against it)."""
    fp = elasticity_fingerprint({"elasticity": runtime_elastic_config_dict})
    prev = _frozen.get("fp")
    if prev is not None and prev != fp:
        raise ElasticityError("elastic config changed after scheduling — "
                              "the batch contract is immutable")
    _frozen["fp"] = fp
