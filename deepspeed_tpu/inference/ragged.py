"""Ragged / continuous-batching inference with a paged KV cache
(FastGen v2 parity).

Reference surface (deepspeed/inference/v2/):
* ``InferenceEngineV2.put(uids, tokens)`` ragged decode step (engine_v2.py:107)
  and the ``query`` / ``can_schedule`` / ``flush`` scheduling API (:153-:228),
* ``DSStateManager`` + ``DSSequenceDescriptor`` (ragged/ragged_manager.py:19,
  ragged/sequence_descriptor.py),
* ``BlockedAllocator`` paged-KV block pool (ragged/blocked_allocator.py),
* the ragged-batch atom building the reference does in C++
  (ragged/csrc/fast_host_buffer.cpp) — here plain numpy on the host feeding
  ONE jitted step with static shapes,
* Dynamic-SplitFuse token scheduling (the FastGen blog's core idea):
  every step packs all pending decodes (1 token each) plus as many prompt
  tokens as fit into a fixed token budget, so the compiled program sees one
  shape regardless of the prefill/decode mix.

TPU-first redesign: CUDA FastGen builds variable "ragged atoms" per step and
launches paged-attention kernels over them. Under XLA every shape must be
static, so the step program is fixed at ``[token_budget]`` tokens and
``[max_seqs]`` sequence slots; inactive lanes are masked. Paged attention
dispatches to the Pallas kernel with scalar-prefetched block tables
(``ops/pallas/paged_attention.py``) on TPU; elsewhere a jnp gather
formulation with identical semantics serves as fallback and oracle.
"""

from __future__ import annotations

import functools
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec

from ..ops.norms import layer_norm, rms_norm
from ..ops.ragged_host import build_batch, fill_tables
from ..ops.rotary import apply_rotary, rope_frequencies
from ..utils.logging import log_dist
from .engine import _sample


def _use_pallas_paged(head_dim: int, block: int, dtype,
                      scalar_ints: int = 0) -> bool:
    """Pallas paged kernel eligibility: real TPU + tileable page shape +
    prefetched scalars (per-seq tables, slots, positions) fitting in SMEM
    (1 MB/core; keep them under half). DST_RAGGED_FORCE_GATHER=1 pins the
    XLA gather path (serve-bench A/B lever)."""
    import os

    from ..ops.attention import _on_tpu

    if os.environ.get("DST_RAGGED_FORCE_GATHER") == "1":
        return False
    if not _on_tpu():
        return False
    if scalar_ints * 4 > 512 * 1024:
        return False
    sublane = 32 // jnp.dtype(dtype).itemsize  # 8 fp32 / 16 any 16-bit dtype
    return head_dim in (64, 128, 256) and block % sublane == 0


# ----------------------------------------------------------------------
# host-side state (reference: ragged/blocked_allocator.py, ragged_manager.py)

class PoolExhausted(RuntimeError):
    """The KV page pool cannot satisfy a schedule's block demand.
    A dedicated type so recovery code (the serving driver preempts a
    decode and retries) can distinguish this RECOVERABLE condition from
    arbitrary device RuntimeErrors — substring-matching the message
    would misfire on e.g. XLA's 'Resource exhausted' device OOM."""

class BlockedAllocator:
    """Refcounted free-list allocator over ``n_blocks`` KV pages
    (reference blocked_allocator.py — same capability, python list instead
    of a torch tensor free-list; refcounts added for prefix-cache block
    sharing: a page returns to the free list only when every holder —
    sequences and the cache — has released it)."""

    def __init__(self, n_blocks: int):
        self.n_blocks = n_blocks
        self._free: List[int] = list(range(n_blocks))
        self._ref: Dict[int, int] = {}

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    def allocate(self, n: int) -> List[int]:
        if n > len(self._free):
            raise PoolExhausted(
                f"KV pool exhausted: need {n}, have {len(self._free)}")
        out, self._free = self._free[:n], self._free[n:]
        for b in out:
            self._ref[b] = 1
        return out

    def retain(self, blocks: Sequence[int]) -> None:
        for b in blocks:
            self._ref[int(b)] += 1

    def release(self, blocks: Sequence[int]) -> None:
        for b in blocks:
            b = int(b)
            self._ref[b] -= 1
            if self._ref[b] == 0:
                del self._ref[b]
                self._free.append(b)

    def refcount(self, block: int) -> int:
        return self._ref.get(int(block), 0)

    # historical name used throughout the engine/tests: a release, not an
    # unconditional free — shared pages survive until the last holder
    free = release


class PrefixCache:
    """LRU cache of computed KV pages keyed by full-block token prefixes.

    Beyond-reference capability (FastGen recomputes every prompt; vLLM
    calls this automatic prefix caching): when a sequence is flushed, its
    full KV blocks are published under the token prefix they encode; a
    new prompt sharing that prefix adopts the pages (refcounted via
    :class:`BlockedAllocator`) and skips their prefill. Correctness rests
    on immutability of shared pages: sharing covers FULL blocks only and
    is capped at ``len(prompt) - 1`` tokens, so the engine's scatters only
    ever write positions at-or-after the shared region's end — except the
    benign case of re-writing the final shared position with bit-identical
    K/V (same tokens, same absolute positions, same params)."""

    def __init__(self, block_size: int, on_evict=None):
        import collections

        self.block_size = block_size
        # prefix tuple -> list of block ids (cache holds one retain each);
        # ordered oldest-used first: O(1) LRU via move_to_end/popitem
        self._entries: "collections.OrderedDict[Tuple[int, ...], List[int]]" \
            = collections.OrderedDict()
        # per-block count of CACHE references (across nested entries) —
        # lets reclaimable_blocks() tell cache-only pages from shared ones
        self._block_refs: Dict[int, int] = {}
        self.hits = 0
        self.misses = 0
        # optional eviction hook ``(key_tuple, blocks) -> None`` fired
        # BEFORE the evicted entry's refs release (its pages are still
        # valid to read) — the global KV tier's directory-invalidate +
        # cold-spill seam. None (the default) changes nothing.
        self.on_evict = on_evict

    def __len__(self) -> int:
        return len(self._entries)

    def match(self, prompt: Sequence[int]) -> Tuple[int, List[int]]:
        """Longest cached full-block prefix of ``prompt``, capped so at
        least one prompt token remains to prefill (its logits seed
        generation). Returns (shared_token_count, blocks) — blocks are NOT
        yet retained for the caller."""
        bs = self.block_size
        for k in range((len(prompt) - 1) // bs, 0, -1):
            key = tuple(int(t) for t in prompt[: k * bs])
            ent = self._entries.get(key)
            if ent is not None:
                self._entries.move_to_end(key)
                self.hits += 1
                return k * bs, ent
        self.misses += 1
        return 0, []

    def lookup(self, tokens: Sequence[int]) -> Tuple[Optional[Tuple[int, ...]],
                                                     List[int]]:
        """Longest full-block prefix ENTRY covering ``tokens`` — unlike
        :meth:`match` there is no leave-one-token-to-prefill cap, because
        adoption/export wants whole cache entries (the requester's
        routing key is already a full-block prefix). Refreshes LRU
        recency (a donor should not evict what it is donating) but does
        not count hits/misses. Returns (key, blocks) or (None, [])."""
        bs = self.block_size
        for k in range(len(tokens) // bs, 0, -1):
            key = tuple(int(t) for t in tokens[: k * bs])
            ent = self._entries.get(key)
            if ent is not None:
                self._entries.move_to_end(key)
                return key, ent
        return None, []

    def _hold(self, key, blocks, allocator: BlockedAllocator) -> None:
        allocator.retain(blocks)
        for b in blocks:
            self._block_refs[b] = self._block_refs.get(b, 0) + 1
        self._entries[key] = blocks

    def publish(self, tokens: Sequence[int], blocks: Sequence[int], seen: int,
                allocator: BlockedAllocator) -> None:
        """Offer a flushed sequence's full blocks to the cache (the cache
        retains them; the sequence's own refs are released separately)."""
        bs = self.block_size
        k = min(seen, len(tokens)) // bs
        if k <= 0:
            return
        key = tuple(int(t) for t in tokens[: k * bs])
        if key in self._entries:
            self._entries.move_to_end(key)
            return
        held = [int(b) for b in blocks[:k]]
        self._hold(key, held, allocator)
        # keys are exact tuples, so a shorter shared prefix needs its own
        # entry — publish every nested full-block level too (same pages,
        # one retain per level)
        for kk in range(k - 1, 0, -1):
            kkey = key[: kk * bs]
            if kkey in self._entries:
                break
            self._hold(kkey, held[:kk], allocator)

    def _evict_one(self, allocator: BlockedAllocator) -> None:
        key, blocks = self._entries.popitem(last=False)   # LRU
        if self.on_evict is not None:
            # hook runs while the entry's pages are still referenced:
            # the cold-spill copy must read them before they can return
            # to the free list and be overwritten
            self.on_evict(key, blocks)
        allocator.release(blocks)
        for b in blocks:
            self._block_refs[b] -= 1
            if self._block_refs[b] == 0:
                del self._block_refs[b]

    def evict_for(self, allocator: BlockedAllocator, need: int) -> None:
        """LRU-evict entries until ``need`` blocks are free (or empty)."""
        while allocator.free_blocks < need and self._entries:
            self._evict_one(allocator)

    def reclaimable_blocks(self, allocator: BlockedAllocator) -> int:
        """Distinct pages that would return to the free list if the whole
        cache dropped: pages whose every reference is the cache's own.
        Admission checks (can_schedule/query) count these as available —
        without this, a cache that has absorbed the pool starves admission
        forever while _check_pool could evict its way out."""
        return sum(1 for b, n in self._block_refs.items()
                   if allocator.refcount(b) == n)

    def drop_all(self, allocator: BlockedAllocator) -> None:
        while self._entries:
            self._evict_one(allocator)


def block_balance_report(engine) -> Dict[str, Any]:
    """Audit the engine's KV-page accounting: every page must be exactly
    one of free / sequence-held / cache-held, and the allocator's
    refcount for each held page must equal the number of holders
    (sequence occurrences + prefix-cache entry references).

    Returns ``{"free": int, "held": int, "problems": [str, ...]}`` —
    ``problems`` empty means zero leaks and exact refcount balance. The
    serving drain check and the cancellation tests assert on this; it is
    pure host-side dict walking (never touches the device)."""
    alloc = engine.allocator
    free = set(alloc._free)
    held = set(alloc._ref)
    problems: List[str] = []
    if len(free) != len(alloc._free):
        problems.append("duplicate pages in the free list")
    overlap = free & held
    if overlap:
        problems.append(f"pages both free and referenced: "
                        f"{sorted(overlap)[:8]}")
    vanished = set(range(alloc.n_blocks)) - free - held
    if vanished:
        problems.append(f"pages leaked (not free, not referenced): "
                        f"{sorted(vanished)[:8]}")
    expected: Dict[int, int] = {}
    for seq in engine.seqs.values():
        for b in seq.blocks:
            expected[int(b)] = expected.get(int(b), 0) + 1
    if engine.prefix_cache is not None:
        for b, n in engine.prefix_cache._block_refs.items():
            expected[int(b)] = expected.get(int(b), 0) + n
    for b in sorted(held | set(expected)):
        have, want = alloc._ref.get(b, 0), expected.get(b, 0)
        if have != want:
            problems.append(f"page {b}: allocator refcount {have} != "
                            f"{want} holders")
    return {"free": len(free), "held": len(held), "problems": problems}


def assert_block_balance(engine, expect_free: Optional[int] = None) -> None:
    """Raise AssertionError on any block-accounting imbalance (and, when
    given, on ``free != expect_free``)."""
    rep = block_balance_report(engine)
    if rep["problems"]:
        raise AssertionError("KV block balance violated: "
                             + "; ".join(rep["problems"]))
    if expect_free is not None and rep["free"] != expect_free:
        raise AssertionError(
            f"KV free-page count {rep['free']} != expected {expect_free} "
            f"({rep['held']} pages still referenced)")


def kv_page_bytes(model_config, ragged_config) -> int:
    """Bytes ONE KV page (K + V, all layers) occupies in the pool under
    ``ragged_config.kv_quant`` — payload plus per-row fp32 scales. The
    capacity arithmetic behind "quantization roughly doubles concurrent
    sequences per pool": size two pools to the same byte budget with
    :func:`kv_blocks_for_bytes` and the int8 pool holds ~2x the pages."""
    import jax.numpy as _jnp

    c, cfg = model_config, ragged_config
    rows = c.n_layers * c.n_kv_heads * cfg.kv_block_size      # per K or V
    bits = {"none": 0, "int8": 8, "int4": 4}[cfg.kv_quant]
    if bits == 0:
        return 2 * rows * c.head_dim * _jnp.dtype(cfg.dtype).itemsize
    # payload + per-head-vector scale bytes: the ONE audited byte
    # arithmetic (ops/quantizer.quantized_nbytes, block = head_dim)
    from ..ops.quantizer import quantized_nbytes

    return 2 * quantized_nbytes(rows * c.head_dim, bits, c.head_dim)


def kv_blocks_for_bytes(budget_bytes: int, model_config,
                        ragged_config) -> int:
    """Pages a ``budget_bytes`` KV pool holds under the config's
    ``kv_quant`` mode (the fixed-byte-budget sizing the serve bench's
    kv-quant leg and capacity tests use)."""
    return max(1, int(budget_bytes)
               // kv_page_bytes(model_config, ragged_config))


def _prompt_lookup(ctx: Sequence[int], ngram: int, k: int) -> List[int]:
    """Prompt-lookup drafting: if the trailing ``ngram`` of ``ctx`` occurred
    earlier, propose the (up to ``k``) tokens that followed its most recent
    earlier occurrence. The zero-cost draft model of prompt-lookup /
    n-gram speculative decoding — strong on the summarization/code/RAG
    workloads where outputs quote their inputs."""
    if k <= 0 or ngram <= 0 or len(ctx) <= ngram:
        return []
    arr = np.asarray(ctx, np.int32)
    pat = arr[-ngram:]
    win = np.lib.stride_tricks.sliding_window_view(arr[:-1], ngram)
    hits = np.nonzero((win == pat).all(axis=1))[0]
    if len(hits) == 0:
        return []
    # prefer the most recent occurrence that still has k continuation
    # tokens; fall back to whichever hit offers the longest continuation
    cont_len = np.minimum(len(arr) - (hits + ngram), k)
    full = np.nonzero(cont_len == k)[0]
    j = int(hits[full[-1]] if len(full) else hits[np.argmax(cont_len)])
    return arr[j + ngram: j + ngram + k].tolist()


class NgramIndex:
    """Incremental n-gram position index over one sequence's token stream
    — the memoized form of :func:`_prompt_lookup`, bit-identical in what
    it proposes but O(new tokens) per draft round instead of O(context):
    every fully-formed window's start position is recorded once (dict
    key -> ascending position list) as the stream grows, and a trim of
    the stream's tail pops exactly the invalidated entries off an
    append-ordered stack. ``lookup`` then answers "most recent earlier
    occurrence of the trailing n-gram with a k-token continuation, else
    the earliest occurrence" with two bisects plus an O(ngram + extra)
    scan of the windows that overlap the virtual ``extra`` suffix."""

    def __init__(self, ngram: int):
        self.ngram = int(ngram)
        self._toks: List[int] = []
        self._pos: Dict[Tuple[int, ...], List[int]] = {}
        self._order: List[Tuple[int, Tuple[int, ...]]] = []  # (start, key)

    def sync(self, tokens: Sequence[int]) -> None:
        """Index tokens appended since the last call. The caller
        guarantees the previously-indexed prefix is unchanged — the
        engine's only tail mutation (``trim``) calls :meth:`truncate`."""
        n = self.ngram
        if len(tokens) < len(self._toks):        # untracked truncation
            self.truncate(len(tokens))
        self._toks.extend(int(t) for t in tokens[len(self._toks):])
        start = self._order[-1][0] + 1 if self._order else 0
        for h in range(start, len(self._toks) - n + 1):
            key = tuple(self._toks[h:h + n])
            self._pos.setdefault(key, []).append(h)
            self._order.append((h, key))

    def truncate(self, length: int) -> None:
        """Drop the stream's tail: O(removed) — pops only entries whose
        window extends past ``length``."""
        del self._toks[length:]
        n = self.ngram
        while self._order and self._order[-1][0] + n > length:
            h, key = self._order.pop()
            lst = self._pos[key]
            lst.pop()                            # ascending: h is last
            if not lst:
                del self._pos[key]

    def lookup(self, extra: Sequence[int], k: int) -> List[int]:
        """Draft proposal for the stream + virtual ``extra`` suffix —
        exactly :func:`_prompt_lookup`'s answer for
        ``ctx = tokens + extra`` without rescanning ``tokens``."""
        import bisect

        n = self.ngram
        toks = self._toks
        ctx_len = len(toks) + len(extra)
        if k <= 0 or n <= 0 or ctx_len <= n:
            return []

        def at(i: int) -> int:
            return toks[i] if i < len(toks) else int(extra[i - len(toks)])

        pat = tuple(at(ctx_len - n + j) for j in range(n))
        limit = ctx_len - 1 - n          # last admissible window start
        base = self._pos.get(pat, [])
        hi = bisect.bisect_right(base, min(limit, len(toks) - n))
        # windows overlapping ``extra`` (or the trailing pattern region)
        # are not in the index — check the handful directly
        manual = [h for h in range(max(0, len(toks) - n + 1), limit + 1)
                  if all(at(h + j) == pat[j] for j in range(n))]
        if hi == 0 and not manual:
            return []
        # prefer the most recent start with a full k-token continuation;
        # manual starts are all later than indexed ones
        full_limit = ctx_len - n - k
        j = next((h for h in reversed(manual) if h <= full_limit), None)
        if j is None:
            idx = bisect.bisect_right(base, full_limit, 0, hi)
            if idx:
                j = base[idx - 1]
        if j is None:                    # no full hit: longest continuation
            j = base[0] if hi else manual[0]
        return [at(i) for i in range(j + n, min(j + n + k, ctx_len))]


@dataclass
class KVExport:
    """Host-side snapshot of one sequence's KV state, the unit of the
    disaggregated prefill→decode hand-off (``export_kv``/``import_kv``).
    Today the pages travel as numpy arrays (CPU copy); the dataclass is
    the explicit seam where an ICI transfer replaces the host hop later —
    importers validate geometry, never provenance."""

    uid: int
    tokens: List[int]          # fed context (prompt + any decoded tokens)
    seen: int                  # tokens whose KV the pages actually hold
    prompt_len: int
    kv_block_size: int
    n_layers: int
    n_kv_heads: int
    head_dim: int
    dtype: str
    k_pages: np.ndarray        # [n_layers, n_pages, hkv, block, hd]
    v_pages: np.ndarray
    # quantized hand-off (kv_quant != "none"): k/v_pages hold the POOL's
    # quantized payload (int8, or int4 nibble-packed uint8 [.., hd//2])
    # and the per-row fp32 scales ride along — the wire moves ~half
    # (int8) / ~quarter (int4) the fp bytes, and the importer adopts the
    # payload bit-identically (no re-quantization, no extra error)
    kv_quant: str = "none"
    k_scales: Optional[np.ndarray] = None   # [n_layers, n_pages, hkv, block]
    v_scales: Optional[np.ndarray] = None

    @property
    def n_pages(self) -> int:
        return int(self.k_pages.shape[1])

    @property
    def nbytes(self) -> int:
        n = int(self.k_pages.nbytes + self.v_pages.nbytes)
        if self.k_scales is not None:
            n += int(self.k_scales.nbytes + self.v_scales.nbytes)
        return n


@dataclass
class SequenceDescriptor:
    """Reference DSSequenceDescriptor: uid, slot, tokens seen/scheduled,
    owned KV blocks."""

    uid: int
    slot: int
    tokens: List[int] = field(default_factory=list)  # full known token stream
    seen: int = 0                                    # tokens already in KV
    blocks: List[int] = field(default_factory=list)
    # telemetry clocks: t_admitted is cleared once TTFT is recorded;
    # t_created survives until flush() reports end-to-end latency
    t_admitted: Optional[float] = None
    t_created: Optional[float] = None
    prompt_len: int = 0

    @property
    def pending(self) -> int:
        return len(self.tokens) - self.seen


@dataclass
class RaggedConfig:
    """Knobs mirroring reference DSStateManagerConfig + RaggedBatchConfig
    (inference/v2/ragged/manager_configs.py): max_ragged_batch_size =
    token_budget, max_tracked_sequences = max_seqs, memory_config block
    count/size."""

    token_budget: int = 256
    max_seqs: int = 8
    kv_block_size: int = 16
    n_kv_blocks: int = 256
    max_context: int = 2048
    dtype: Any = jnp.bfloat16
    # sampling (parity: FastGen sampler / v1 engine _sample); 0.0 = greedy
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    # automatic prefix caching (beyond the reference: FastGen has no KV
    # reuse across requests): completed sequences publish their full KV
    # blocks into an LRU cache keyed by the token prefix; new prompts
    # sharing a full-block prefix skip its prefill entirely. Shared pages
    # are refcounted; cache entries are evicted under pool pressure.
    enable_prefix_cache: bool = False
    # quantized KV storage ("none" | "int8" | "int4"): pages hold
    # blockwise-quantized payloads (one fp32 scale per K/V head-vector,
    # ops/quantizer.quantize_kv) — quantize on page write, dequantize in
    # the paged-attention read path. At a fixed pool BYTE budget this
    # roughly doubles (int8) / quadruples (int4) the page count, i.e.
    # concurrent sequences; export_kv/import_kv move the quantized
    # payload + scales on the wire (docs/serving.md "KV quantization")
    kv_quant: str = "none"


class RaggedInferenceEngine:
    """Continuous-batching engine over a deepspeed_tpu Transformer.

    ``put(uids, tokens)`` runs ONE compiled ragged step mixing prefill
    chunks and decodes (Dynamic SplitFuse); returns next-token logits per
    uid (NaN rows for uids whose prompt is still being prefilled across
    steps). ``generate`` drives put/flush to completion.
    """

    def __init__(self, model, config: Optional[RaggedConfig] = None,
                 params: Any = None, rng: Any = None, topology=None):
        self.config = config or RaggedConfig()
        self.model = model
        self.topo = topology
        c = model.config
        tp = topology.model_parallel_size if topology is not None else 1
        if tp > 1 and c.n_kv_heads % tp:
            raise ValueError(
                f"n_kv_heads {c.n_kv_heads} not divisible by the model "
                f"axis {tp} — TP serving shards the KV pool by head")
        self._tp_size = tp
        if self.config.max_context > c.max_seq_len:
            raise ValueError(
                f"max_context {self.config.max_context} exceeds model "
                f"max_seq_len {c.max_seq_len} (RoPE/position table bound)")
        if c.position == "alibi" or getattr(c, "parallel_residual", False):
            # the ragged step inlines the block math without ALiBi bias /
            # parallel-residual wiring; loud failure beats wrong logits
            raise NotImplementedError(
                "RaggedInferenceEngine does not support ALiBi or parallel-"
                "residual families yet; use InferenceEngine (dense KV cache)")
        if getattr(c, "attn_scale", None) is not None:
            raise NotImplementedError(
                "RaggedInferenceEngine does not support attention-scale "
                "overrides (GPT-Neo); use InferenceEngine (dense KV cache)")
        if c.window_binds(self.config.max_context):
            log_dist("RaggedInferenceEngine: binding sliding window — "
                     "banded paged kernel on TPU, banded gather elsewhere")
        if self.config.max_context % self.config.kv_block_size != 0:
            raise ValueError(
                f"max_context {self.config.max_context} must be a multiple of "
                f"kv_block_size {self.config.kv_block_size}")
        if self.config.kv_quant not in ("none", "int8", "int4"):
            raise ValueError(
                f"kv_quant must be 'none', 'int8' or 'int4', got "
                f"'{self.config.kv_quant}'")
        self._kv_bits = {"none": 0, "int8": 8, "int4": 4}[self.config.kv_quant]
        if self._kv_bits == 4 and c.head_dim % 2:
            raise ValueError(
                f"kv_quant='int4' packs two channels per byte and needs an "
                f"even head_dim, got {c.head_dim}")
        self.params = params if params is not None else model.init(
            rng if rng is not None else jax.random.PRNGKey(0))
        self.params = jax.tree_util.tree_map(
            lambda x: x.astype(self.config.dtype)
            if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating) else x,
            self.params)
        if topology is not None and topology.world_size > 1:
            # sharded serving (FastGen v2's TP configuration, plus expert
            # parallelism for MoE): place params under the model's
            # partition specs; GSPMD shards every projection + the vocab
            # head (and routes expert dispatch over the 'expert' axis) and
            # inserts the collectives. The KV pool shards by head below
            # when a 'model' axis is present.
            from jax.sharding import NamedSharding

            specs = model.partition_specs(self.params, topology)
            self.params = jax.device_put(
                self.params,
                jax.tree_util.tree_map(
                    lambda sp: NamedSharding(topology.mesh, sp), specs,
                    is_leaf=lambda x: isinstance(x, PartitionSpec)))
        cfg = self.config
        self.allocator = BlockedAllocator(cfg.n_kv_blocks)
        self.prefix_cache = (PrefixCache(cfg.kv_block_size)
                             if cfg.enable_prefix_cache else None)
        self.seqs: Dict[int, SequenceDescriptor] = {}
        self._free_slots = list(range(cfg.max_seqs))
        # uids whose next admission is a RESUME (post-preempt/discard):
        # their fresh descriptors must not re-record TTFT/latency — the
        # serving layer's request spans carry the true end-to-end numbers
        self._resume_uids: set = set()
        self.max_pages = cfg.max_context // cfg.kv_block_size
        # paged KV pool: per-layer tuples of [n_blocks + 1, hkv, block, hd]
        # (last page = scratch sink for masked-out batch lanes; duplicate
        # scatters with mixed old/new values are undefined — inactive lanes
        # must never alias a live page). One array PER LAYER, not a stacked
        # [L, pages, ...] tensor: stacked, every layer's update is a
        # pool-sized dynamic-slice copy-out/copy-in (the whole KV pool
        # re-written L times per step — measured 100 ms/decode-step); flat
        # [(L)*(P+1), ...] with offset tables avoids the slices but XLA then
        # materializes pool-sized scatter copies (measured 16-18 GB compile
        # OOM on a 4.3 GB pool). Per-layer leaves keep every scatter's
        # worst-case transient to one leaf. (block, hd) stay minor-most so
        # each page is a native VMEM tile for the Pallas kernel
        # kv_quant stores pages as blockwise payload + per-row fp32 scales
        # (scale block = one K/V head-vector): int8 payload [.., hd] or
        # int4 nibble-packed uint8 [.., hd//2], scale leaf [P+1, hkv, bs].
        # The sink page's zeros dequantize to zeros, so masked-lane
        # scatters stay harmless exactly as in the fp layout.
        if self._kv_bits == 4:
            leaf_shape = (cfg.n_kv_blocks + 1, c.n_kv_heads,
                          cfg.kv_block_size, c.head_dim // 2)
            leaf_dtype = jnp.uint8
        elif self._kv_bits == 8:
            leaf_shape = (cfg.n_kv_blocks + 1, c.n_kv_heads,
                          cfg.kv_block_size, c.head_dim)
            leaf_dtype = jnp.int8
        else:
            leaf_shape = (cfg.n_kv_blocks + 1, c.n_kv_heads,
                          cfg.kv_block_size, c.head_dim)
            leaf_dtype = cfg.dtype
        scale_shape = (cfg.n_kv_blocks + 1, c.n_kv_heads, cfg.kv_block_size)
        if tp > 1:
            from jax.sharding import NamedSharding

            pool_sh = NamedSharding(topology.mesh,
                                    PartitionSpec(None, "model", None, None))
            scale_sh = NamedSharding(topology.mesh,
                                     PartitionSpec(None, "model", None))

            def _zeros(_):
                return jax.device_put(jnp.zeros(leaf_shape, leaf_dtype),
                                      pool_sh)

            def _zero_scales(_):
                return jax.device_put(jnp.zeros(scale_shape, jnp.float32),
                                      scale_sh)
        else:
            def _zeros(_):
                return jnp.zeros(leaf_shape, leaf_dtype)

            def _zero_scales(_):
                return jnp.zeros(scale_shape, jnp.float32)
        self.kv_pool = (
            tuple(_zeros(i) for i in range(c.n_layers)),
            tuple(_zeros(i) for i in range(c.n_layers)))
        if self._kv_bits:
            self.kv_pool = self.kv_pool + (
                tuple(_zero_scales(i) for i in range(c.n_layers)),
                tuple(_zero_scales(i) for i in range(c.n_layers)))
        self._step_fn = None
        self._core_fn = None
        self._decode_fn = None
        self._copy_page_fn = None
        self._import_fn = None
        self._verify_fn = None
        # speculative-decoding acceptance stats (generate_speculative and
        # the serving tick's verify rounds; mirrored into the shared
        # MetricsRegistry by record_spec)
        self.spec_stats = {"proposed": 0, "accepted": 0, "rounds": 0}
        # per-uid memoized n-gram draft indices (draft_tokens): extended
        # lazily on append, truncated by trim(), dropped on flush/discard
        self._ngram_idx: Dict[int, NgramIndex] = {}
        # global-KV-tier seams (docs/serving.md "Global KV tier"), all
        # inert until enable_kv_tier() attaches them: the fleet's
        # host-memory cold tier, the directory-invalidate callback
        # (fired synchronously on eviction so a directory entry never
        # outlives its pages), and the per-engine adoption counters the
        # DST auditor reads
        self._cold_tier = None
        self._on_prefix_invalidate = None
        self._kv_tier_member = ""
        self.kvtier_cold_spills = 0
        self.kvtier_cold_readmits = 0
        self.kvtier_adopt_imports = 0
        self.kvtier_corrupt_landed = 0
        # sampling streams: decode steps fold a GLOBAL step counter into the
        # decode key, so sampled output is invariant to how decode_steps
        # calls chunk the token budget; prefill first-tokens get their own
        # stream (counter per put-round)
        base = rng if rng is not None else jax.random.PRNGKey(0)
        self._rng_prefill, self._rng_decode = jax.random.split(
            jax.random.fold_in(base, 7919))
        self._decode_step_counter = 0
        self._prefill_round_counter = 0
        # ragged-step token buckets (ascending, capped by the budget): a
        # decode-heavy step compiles + runs at the smallest fitting width
        self._buckets = [b for b in (64, 256, 1024) if b < cfg.token_budget] \
            + [cfg.token_budget]
        log_dist(f"RaggedInferenceEngine: budget={cfg.token_budget} "
                 f"blocks={cfg.n_kv_blocks}x{cfg.kv_block_size}")

    @property
    def _telemetry(self):
        # resolved per call: the global pipeline may be installed after
        # this engine is constructed
        from ..telemetry import get_telemetry

        return get_telemetry()

    # -- scheduling API (parity engine_v2.query/can_schedule) -----------
    def query(self, uid: int) -> Tuple[int, int]:
        """(max new tokens schedulable for uid now, free kv blocks) —
        reference engine_v2.query :153. Accounts for the uid's remaining
        context window and the blocks it could still claim."""
        seen = self.seqs[uid].seen if uid in self.seqs else 0
        owned = len(self.seqs[uid].blocks) if uid in self.seqs else 0
        ctx_room = self.config.max_context - seen
        slack_in_blocks = owned * self.config.kv_block_size - seen
        avail = self._available_blocks()
        kv_room = slack_in_blocks + avail * self.config.kv_block_size
        return (max(0, min(self.config.token_budget, ctx_room, kv_room)),
                avail)

    def _available_blocks(self) -> int:
        """Free pages plus cache-only-held pages (_check_pool evicts those
        on demand, so admission must count them or it starves once the
        prefix cache has absorbed the pool)."""
        free = self.allocator.free_blocks
        if self.prefix_cache is not None:
            free += self.prefix_cache.reclaimable_blocks(self.allocator)
        return free

    def blocks_needed(self, n_tokens: int) -> int:
        """KV pages a fresh sequence of ``n_tokens`` is charged at
        admission (its pages at full length, +1 write scratch). The ONE
        place this formula lives: the serving layer's admission oracle
        and submit-time over-pool reject must agree with the allocator,
        or admission either over-rejects feasible requests or admits
        requests that hit PoolExhausted mid-decode every tick."""
        return -(-int(n_tokens) // self.config.kv_block_size) + 1

    def can_schedule(self, uids: Sequence[int], lengths: Sequence[int]) -> bool:
        """Whether prompts of the given lengths fit (slots + kv blocks) —
        reference engine_v2.can_schedule :179."""
        bs = self.config.kv_block_size
        new = [u for u in uids if u not in self.seqs]
        need_blocks = 0
        for uid, length in zip(uids, lengths):
            if uid in self.seqs:
                seq = self.seqs[uid]
                total = seq.seen + length
                need_blocks += max(0, -(-total // bs) - len(seq.blocks))
            else:
                need_blocks += self.blocks_needed(length)
        return (len(new) <= len(self._free_slots)
                and need_blocks <= self._available_blocks())

    def flush(self, uids: Sequence[int]) -> None:
        """Release sequence state + KV blocks (reference engine_v2.flush :228).
        With the prefix cache on, the sequence's full KV blocks are
        published (cache-retained) before its own refs drop."""
        now = time.perf_counter()
        for uid in uids:
            seq = self.seqs.pop(uid, None)
            self._ngram_idx.pop(uid, None)
            if seq is not None:
                if seq.t_created is not None:
                    # request retires here: end-to-end latency + tokens the
                    # engine generated beyond the admitted prompt
                    self._telemetry.record_request(
                        latency_s=now - seq.t_created,
                        new_tokens=max(0, len(seq.tokens) - seq.prompt_len))
                if self.prefix_cache is not None:
                    self.prefix_cache.publish(seq.tokens, seq.blocks,
                                              seq.seen, self.allocator)
                self.allocator.free(seq.blocks)
                self._free_slots.append(seq.slot)

    def preempt(self, uid: int) -> List[int]:
        """Release ``uid``'s slot + KV blocks WITHOUT retiring it as a
        completed request (no latency record) — the serving layer's
        eviction hook. Full KV blocks are published into the prefix cache
        first (when enabled), so the preempted prompt + generated tokens
        re-prefill mostly from cached pages on resume. Returns the
        KV-backed token stream (tokens actually prefilled/decoded; a
        mid-prefill tail that never reached the KV pool is excluded)."""
        seq = self.seqs.get(uid)
        if seq is None:
            return []
        toks = list(seq.tokens[:seq.seen])
        seq.t_created = None          # suppress request-retired telemetry
        self.flush([uid])
        self._resume_uids.add(uid)
        return toks

    def discard(self, uid: int) -> None:
        """Drop ``uid`` releasing its blocks + slot while publishing
        NOTHING into the prefix cache — the recovery hook for a failed
        step whose KV integrity is unknown (``seen`` may have advanced
        without the scatter landing). Zero-leak either way."""
        seq = self.seqs.pop(uid, None)
        self._ngram_idx.pop(uid, None)
        if seq is None:
            return
        self.allocator.free(seq.blocks)
        self._free_slots.append(seq.slot)
        self._resume_uids.add(uid)

    def clear_resume(self, uid: int) -> None:
        """Forget a ``preempt()``/``discard()`` resume marker for a uid
        that will never be re-admitted (it went terminal in the serving
        layer). Without this, a LATER unrelated sequence reusing the uid
        would silently skip its TTFT/latency telemetry, and the marker
        set would grow without bound under preempt-then-cancel churn."""
        self._resume_uids.discard(uid)

    # -- speculative drafting -------------------------------------------
    def draft_tokens(self, uid: int, next_token: Optional[int],
                     ngram: int, k: int) -> List[int]:
        """Prompt-lookup draft for ``uid``'s next decode step: up to ``k``
        proposal tokens continuing ``tokens + [next_token]`` (the not-yet-
        fed pending token rides as a virtual suffix). Memoized per uid:
        the n-gram index extends incrementally on append and truncates on
        ``trim``, so a draft round costs O(new tokens), not O(context)."""
        seq = self.seqs[uid]
        idx = self._ngram_idx.get(uid)
        if idx is None or idx.ngram != int(ngram):
            idx = NgramIndex(ngram)
            self._ngram_idx[uid] = idx
        idx.sync(seq.tokens)
        return idx.lookup([] if next_token is None else [int(next_token)], k)

    def record_spec(self, proposed: int = 0, accepted: int = 0,
                    rounds: int = 0) -> None:
        """Fold one speculative verify outcome into ``spec_stats`` AND the
        shared MetricsRegistry (inference/spec_* counters + acceptance
        gauge) — the one place the stats dict and the registry stay in
        sync. Host-side only; called by generate_speculative and the
        serving tick's verify dispatch."""
        s = self.spec_stats
        s["proposed"] += int(proposed)
        s["accepted"] += int(accepted)
        s["rounds"] += int(rounds)
        t = self._telemetry
        if not t.enabled:
            return
        r = t.registry
        if rounds:
            r.counter("inference/spec_rounds").inc(rounds)
        if proposed:
            r.counter("inference/spec_proposed").inc(proposed)
        if accepted:
            r.counter("inference/spec_accepted").inc(accepted)
        if s["proposed"]:
            r.gauge("inference/spec_acceptance").set(
                s["accepted"] / s["proposed"])

    # -- KV export/import (disaggregated prefill/decode hand-off) --------
    def export_kv(self, uid: int) -> "KVExport":
        """Snapshot ``uid``'s KV pages + token stream for hand-off to
        ANOTHER engine (disaggregated serving: a prefill replica computes
        the KV, a decode replica continues the stream). Host copy today —
        this is the explicit seam where an ICI/DMA page transfer plugs in
        later; the importer's accounting is identical either way.

        The sequence must be fully prefilled (``pending == 0``): exporting
        mid-prefill would hand off context whose tail has no KV. The
        export does NOT release anything — the caller decides whether to
        ``preempt`` (publish into this engine's prefix cache) or
        ``discard`` the local copy afterwards."""
        seq = self.seqs.get(uid)
        if seq is None:
            raise KeyError(f"uid {uid} has no live sequence to export")
        if seq.pending:
            raise ValueError(
                f"uid {uid}: {seq.pending} tokens still pending prefill — "
                "a mid-prefill export would hand off torn context")
        if seq.seen == 0 or not seq.blocks:
            raise ValueError(f"uid {uid}: nothing prefilled yet")
        c = self.model.config
        idx = jnp.asarray(np.asarray(seq.blocks, np.int32))
        # one device gather per layer leaf, then host transfer; rows past
        # ``seen`` in the last page are never-read scratch and ride along
        k = np.stack([np.asarray(leaf[idx]) for leaf in self.kv_pool[0]])
        v = np.stack([np.asarray(leaf[idx]) for leaf in self.kv_pool[1]])
        ks = vs = None
        if self._kv_bits:
            ks = np.stack([np.asarray(leaf[idx]) for leaf in self.kv_pool[2]])
            vs = np.stack([np.asarray(leaf[idx]) for leaf in self.kv_pool[3]])
        export = KVExport(uid=uid, tokens=list(seq.tokens), seen=seq.seen,
                          prompt_len=seq.prompt_len,
                          kv_block_size=self.config.kv_block_size,
                          n_layers=c.n_layers, n_kv_heads=c.n_kv_heads,
                          head_dim=c.head_dim,
                          dtype=str(jnp.dtype(self.config.dtype)),
                          k_pages=k, v_pages=v,
                          kv_quant=self.config.kv_quant,
                          k_scales=ks, v_scales=vs)
        t = self._telemetry
        if t.enabled:
            t.registry.counter("inference/kv_exports").inc()
            t.registry.counter("inference/kv_export_pages").inc(
                len(seq.blocks))
            t.registry.counter("inference/kv_export_bytes").inc(
                export.nbytes)
        # bytes-on-wire ledger (comm/comm.py): the hand-off is a wire
        # transfer like any collective — logical = what an fp export of
        # the same pages would move, wire = the (quantized) payload +
        # scales actually shipped, so the disaggregated hand-off's
        # compression ratio is auditable next to the collective ops'
        from ..comm.comm import record_collective

        logical = (2 * len(seq.blocks) * c.n_layers * c.n_kv_heads
                   * self.config.kv_block_size * c.head_dim
                   * jnp.dtype(self.config.dtype).itemsize)
        record_collective("kv_handoff", logical, export.nbytes)
        return export

    def import_kv(self, uid: int, export: "KVExport") -> None:
        """Adopt an exported sequence: allocate pages from THIS engine's
        pool (evicting cached prefixes under pressure, same discipline as
        admission), scatter the pages in, and create a live descriptor at
        ``seen`` — so the next ``put(uid, [next_token])`` continues the
        stream bit-exactly without re-prefilling. Pages are charged and
        refcounted exactly like locally-computed ones: ``seq.blocks``
        holds one allocator ref each and ``assert_block_balance`` holds.

        Raises :class:`PoolExhausted` (recoverable — the caller can fall
        back to the re-prefill resume path) or ``ValueError`` on geometry
        mismatch. On any failure nothing is mutated."""
        cfg = self.config
        c = self.model.config
        if uid in self.seqs:
            raise ValueError(f"uid {uid} already live in this engine")
        want = (cfg.kv_block_size, c.n_layers, c.n_kv_heads, c.head_dim,
                str(jnp.dtype(cfg.dtype)), cfg.kv_quant)
        have = (export.kv_block_size, export.n_layers, export.n_kv_heads,
                export.head_dim, export.dtype, export.kv_quant)
        if want != have:
            raise ValueError(
                f"KV geometry mismatch: engine (block,layers,hkv,hd,dtype,"
                f"kv_quant)={want} vs export {have}")
        if self._kv_bits and (export.k_scales is None
                              or export.v_scales is None):
            raise ValueError(
                f"export tagged kv_quant={export.kv_quant} carries no scales")
        if export.seen != len(export.tokens):
            raise ValueError(
                f"export seen {export.seen} != tokens {len(export.tokens)}")
        if export.seen > cfg.max_context:
            raise ValueError(
                f"export context {export.seen} exceeds max_context "
                f"{cfg.max_context}")
        need = export.n_pages
        if need != -(-export.seen // cfg.kv_block_size):
            raise ValueError(
                f"export carries {need} pages for {export.seen} tokens")
        if not self._free_slots:
            raise RuntimeError("no free sequence slots; flush() first")
        if need > self.allocator.free_blocks and self.prefix_cache is not None:
            self.prefix_cache.evict_for(self.allocator, need)
        blocks = self.allocator.allocate(need)        # may raise PoolExhausted
        try:
            # pow2-bucket the page count (one compiled writer per bucket,
            # not one per hand-off length); padding lanes scatter zeros
            # into the sink page, which is never read
            B = 1
            while B < need:
                B *= 2
            B = min(B, self.max_pages)
            dst = np.full((B,), cfg.n_kv_blocks, np.int32)
            dst[:need] = blocks
            k, v = export.k_pages, export.v_pages
            ks, vs = export.k_scales, export.v_scales
            if B > need:
                pad = np.zeros((k.shape[0], B - need) + k.shape[2:], k.dtype)
                k = np.concatenate([k, pad], axis=1)
                v = np.concatenate([v, pad], axis=1)
                if self._kv_bits:
                    spad = np.zeros((ks.shape[0], B - need) + ks.shape[2:],
                                    ks.dtype)
                    ks = np.concatenate([ks, spad], axis=1)
                    vs = np.concatenate([vs, spad], axis=1)
            if self._kv_bits:
                self.kv_pool = self._write_pages(
                    self.kv_pool, jnp.asarray(dst), jnp.asarray(k),
                    jnp.asarray(v), jnp.asarray(ks), jnp.asarray(vs))
            else:
                self.kv_pool = self._write_pages(
                    self.kv_pool, jnp.asarray(dst), jnp.asarray(k),
                    jnp.asarray(v))
        except BaseException:
            self.allocator.release(blocks)
            raise
        # telemetry suppressed like a resume: the serving layer's request
        # span owns the end-to-end TTFT/latency story for handed-off work
        self.seqs[uid] = SequenceDescriptor(
            uid=uid, slot=self._free_slots.pop(),
            tokens=[int(t) for t in export.tokens], seen=int(export.seen),
            blocks=blocks, t_admitted=None, t_created=None,
            prompt_len=int(export.prompt_len))
        self._resume_uids.discard(uid)
        t = self._telemetry
        if t.enabled:
            t.registry.counter("inference/kv_imports").inc()

    # -- global KV tier (docs/serving.md "Global KV tier") ---------------
    def enable_kv_tier(self, *, member: str = "", cold_tier=None,
                       on_invalidate=None) -> None:
        """Attach this engine to the fleet's global KV tier:
        ``cold_tier`` receives evicted prefixes (host-memory spill),
        ``on_invalidate(hash)`` drops the directory entry synchronously
        at eviction time (an entry must never outlive its pages). Both
        hooks are leaf-locked, so firing them under the driver's
        serving lock is legal in the documented lock order."""
        self._kv_tier_member = str(member)
        self._cold_tier = cold_tier
        self._on_prefix_invalidate = on_invalidate
        if self.prefix_cache is not None and (
                cold_tier is not None or on_invalidate is not None):
            self.prefix_cache.on_evict = self._on_prefix_evict

    def _on_prefix_evict(self, key: Tuple[int, ...],
                         blocks: List[int]) -> None:
        """PrefixCache eviction hook: directory invalidation FIRST (the
        entry must be gone before the pages can be reused), then the
        cold-tier spill (a host copy gathered while the evicted entry's
        refs still pin the pages)."""
        if self._on_prefix_invalidate is not None:
            from ..serving.kvtier import prefix_hash

            self._on_prefix_invalidate(prefix_hash(key))
        cold = self._cold_tier
        if cold is not None:
            export = self._gather_prefix_export(key, list(blocks))
            if cold.put(export):
                self.kvtier_cold_spills += 1

    def prefix_residency_hashes(self) -> List[int]:
        """Hashes of every resident prefix-cache entry — the residency
        set a replica publishes into the fleet's prefix directory.
        Driver-thread only (reads the cache's entry map directly)."""
        if self.prefix_cache is None:
            return []
        from ..serving.kvtier import prefix_hash

        return [prefix_hash(k) for k in self.prefix_cache._entries]

    def _gather_prefix_export(self, key: Tuple[int, ...],
                              blocks: List[int]):
        """Host-copy ``blocks`` (one gather per layer leaf, quantized
        payload + scales exactly as pooled) into a checksummed
        :class:`~deepspeed_tpu.serving.kvtier.PrefixExport`."""
        from ..serving.kvtier import PrefixExport

        c = self.model.config
        cfg = self.config
        idx = jnp.asarray(np.asarray(blocks, np.int32))
        k = np.stack([np.asarray(leaf[idx]) for leaf in self.kv_pool[0]])
        v = np.stack([np.asarray(leaf[idx]) for leaf in self.kv_pool[1]])
        scales = None
        if self._kv_bits:
            ks = np.stack([np.asarray(leaf[idx])
                           for leaf in self.kv_pool[2]])
            vs = np.stack([np.asarray(leaf[idx])
                           for leaf in self.kv_pool[3]])
            scales = (ks, vs)
        wire = int(k.nbytes + v.nbytes)
        if scales is not None:
            wire += int(scales[0].nbytes + scales[1].nbytes)
        # logical = the dense (unquantized) bytes the same pages would
        # move — the CommsLogger compression-ratio denominator
        logical = (2 * len(blocks) * c.n_layers * c.n_kv_heads
                   * cfg.kv_block_size * c.head_dim
                   * jnp.dtype(cfg.dtype).itemsize)
        return PrefixExport(
            tokens=key, n_pages=len(blocks),
            block_size=cfg.kv_block_size, n_layers=c.n_layers,
            n_kv_heads=c.n_kv_heads, head_dim=c.head_dim,
            dtype=str(jnp.dtype(cfg.dtype)), kv_quant=cfg.kv_quant,
            pages=(k, v), scales=scales,
            wire_bytes=wire, logical_bytes=logical,
            source=self._kv_tier_member)

    def export_prefix(self, tokens: Sequence[int]):
        """Snapshot the longest cached full-block prefix of ``tokens``
        for cross-replica adoption (quantized pages + scales on the
        wire, ZeRO++-style). Returns None on a cache miss. The pages
        are retained across the host gather so an eviction mid-export
        cannot free them under the copy; the transfer lands in the
        bytes-on-wire ledger as a ``kv_adopt`` row next to the
        disaggregated hand-off's ``kv_handoff``."""
        if self.prefix_cache is None:
            return None
        key, blocks = self.prefix_cache.lookup(tokens)
        if not blocks:
            return None
        blocks = list(blocks)
        self.allocator.retain(blocks)
        try:
            export = self._gather_prefix_export(key, blocks)
        finally:
            self.allocator.release(blocks)
        t = self._telemetry
        if t.enabled:
            t.registry.counter("inference/prefix_exports").inc()
            t.registry.counter("inference/prefix_export_pages").inc(
                len(blocks))
            t.registry.counter("inference/prefix_export_bytes").inc(
                export.wire_bytes)
        from ..comm.comm import record_collective

        record_collective("kv_adopt", export.logical_bytes,
                          export.wire_bytes)
        from ..resilience.chaos import get_fault_injector

        inj = get_fault_injector()
        if inj is not None and inj.on_prefix_export():
            # adoption-wire corruption: flip one token AFTER the
            # checksum was stamped — the importer's verify() must catch
            # the mismatch and fall back to local prefill
            export.tokens = ((export.tokens[0] ^ 0x1,)
                             + export.tokens[1:])
        return export

    def import_prefix(self, export) -> bool:
        """Adopt an exported PREFIX into this engine's prefix cache (no
        live sequence — the counterpart of :meth:`import_kv` for the
        global KV tier; cold-tier re-admission uses the same path).
        Verifies the checksum FIRST (a corrupted adoption must never
        land — DST invariant #19), then geometry, then allocates,
        scatters and publishes; the cache ends holding the only refs,
        so ``block_balance_report`` stays exact. Returns False when the
        prefix is already resident; raises ValueError / PoolExhausted
        (recoverable: the caller degrades to local prefill)."""
        if self.prefix_cache is None:
            raise ValueError("prefix cache disabled; nothing to adopt into")
        cfg = self.config
        c = self.model.config
        if not export.verify():
            if not getattr(self, "_kvtier_skip_verify", False):
                from ..serving.kvtier import CorruptExport
                raise CorruptExport(
                    "prefix export failed checksum verification "
                    "(corrupted in transit)")
            # planted-bug seam (tests/DST only): verification disabled —
            # the landed-corruption counter is invariant #19's witness
            self.kvtier_corrupt_landed += 1
        want = (cfg.kv_block_size, c.n_layers, c.n_kv_heads, c.head_dim,
                str(jnp.dtype(cfg.dtype)), cfg.kv_quant)
        if want != export.geometry():
            raise ValueError(
                f"prefix KV geometry mismatch: engine (block,layers,hkv,"
                f"hd,dtype,kv_quant)={want} vs export {export.geometry()}")
        if self._kv_bits and export.scales is None:
            raise ValueError(
                f"export tagged kv_quant={export.kv_quant} carries no "
                f"scales")
        need = export.n_pages
        if need <= 0 or need != len(export.tokens) // cfg.kv_block_size \
                or len(export.tokens) % cfg.kv_block_size:
            raise ValueError(
                f"prefix export carries {need} pages for "
                f"{len(export.tokens)} tokens (full blocks required)")
        if len(export.tokens) > cfg.max_context:
            raise ValueError(
                f"prefix length {len(export.tokens)} exceeds max_context "
                f"{cfg.max_context}")
        if tuple(export.tokens) in self.prefix_cache._entries:
            return False            # already resident
        if need > self.allocator.free_blocks:
            self.prefix_cache.evict_for(self.allocator, need)
        blocks = self.allocator.allocate(need)    # may raise PoolExhausted
        try:
            B = 1
            while B < need:
                B *= 2
            B = min(B, self.max_pages)
            dst = np.full((B,), cfg.n_kv_blocks, np.int32)
            dst[:need] = blocks
            k, v = export.pages
            ks = vs = None
            if self._kv_bits:
                ks, vs = export.scales
            if B > need:
                pad = np.zeros((k.shape[0], B - need) + k.shape[2:],
                               k.dtype)
                k = np.concatenate([k, pad], axis=1)
                v = np.concatenate([v, pad], axis=1)
                if self._kv_bits:
                    spad = np.zeros((ks.shape[0], B - need) + ks.shape[2:],
                                    ks.dtype)
                    ks = np.concatenate([ks, spad], axis=1)
                    vs = np.concatenate([vs, spad], axis=1)
            if self._kv_bits:
                self.kv_pool = self._write_pages(
                    self.kv_pool, jnp.asarray(dst), jnp.asarray(k),
                    jnp.asarray(v), jnp.asarray(ks), jnp.asarray(vs))
            else:
                self.kv_pool = self._write_pages(
                    self.kv_pool, jnp.asarray(dst), jnp.asarray(k),
                    jnp.asarray(v))
        except BaseException:
            self.allocator.release(blocks)
            raise
        # publish takes the cache's own retains (one per nested level),
        # then the allocation ref drops — the cache holds the ONLY refs
        self.prefix_cache.publish(export.tokens, blocks,
                                  len(export.tokens), self.allocator)
        self.allocator.release(blocks)
        self.kvtier_adopt_imports += 1
        t = self._telemetry
        if t.enabled:
            t.registry.counter("inference/prefix_imports").inc()
            t.registry.counter("inference/prefix_import_pages").inc(need)
        return True

    def _cold_readmit(self, tokens: Sequence[int]) -> None:
        """Probe the cold tier for the longest spilled full-block prefix
        of ``tokens`` that is not already device-resident, and re-admit
        it through :meth:`import_prefix` (the same checksum/geometry
        path as remote adoption) so the admission match finds it.
        Best-effort: pool pressure or a failed verify degrades to plain
        prefill — degraded, never lost."""
        bs = self.config.kv_block_size
        for k in range((len(tokens) - 1) // bs, 0, -1):
            key = tuple(int(t) for t in tokens[: k * bs])
            if key in self.prefix_cache._entries:
                return              # device cache already at least as good
            export = self._cold_tier.get(key)
            if export is None:
                continue
            try:
                if self.import_prefix(export):
                    self.kvtier_cold_readmits += 1
                    t = self._telemetry
                    if t.enabled:
                        t.registry.counter(
                            "inference/prefix_cold_readmits").inc()
            except (ValueError, RuntimeError):
                # PoolExhausted / corrupted entry: drop to plain prefill
                pass
            return

    def _write_pages(self, pools, dst, k, v, ks=None, vs=None):
        """Scatter imported pages into every layer's K/V leaf (one jitted
        donated program; the import-side half of the hand-off seam). With
        kv_quant on, the quantized payload AND its scale pages scatter in
        the same program — the import is bit-identical pool state, never
        a requantization."""
        if self._import_fn is None:
            if self._kv_bits:
                @functools.partial(jax.jit, donate_argnums=(0,))
                def imp_q(pools, dst, k, v, ks, vs):
                    kp = tuple(leaf.at[dst].set(k[i].astype(leaf.dtype))
                               for i, leaf in enumerate(pools[0]))
                    vp = tuple(leaf.at[dst].set(v[i].astype(leaf.dtype))
                               for i, leaf in enumerate(pools[1]))
                    ksp = tuple(leaf.at[dst].set(ks[i])
                                for i, leaf in enumerate(pools[2]))
                    vsp = tuple(leaf.at[dst].set(vs[i])
                                for i, leaf in enumerate(pools[3]))
                    return (kp, vp, ksp, vsp)

                self._import_fn = imp_q
            else:
                @functools.partial(jax.jit, donate_argnums=(0,))
                def imp(pools, dst, k, v):
                    kp = tuple(leaf.at[dst].set(k[i].astype(leaf.dtype))
                               for i, leaf in enumerate(pools[0]))
                    vp = tuple(leaf.at[dst].set(v[i].astype(leaf.dtype))
                               for i, leaf in enumerate(pools[1]))
                    return (kp, vp)

                self._import_fn = imp
        if self._kv_bits:
            return self._import_fn(pools, dst, k, v, ks, vs)
        return self._import_fn(pools, dst, k, v)

    def trim(self, uid: int, length: int) -> None:
        """Rewind ``uid`` to its first ``length`` tokens, freeing now-unused
        KV blocks. Attention reads are position-bounded, so stale KV past
        the trim point is never read; the next put()/decode overwrites it.
        Use after observing EOS inside a ``decode_steps`` chunk when the
        sequence will keep being served (post-EOS tokens were admitted by
        that chunk and would otherwise pollute further continuations)."""
        seq = self.seqs[uid]
        if not 0 <= length <= seq.seen:
            raise ValueError(
                f"uid {uid}: trim length {length} outside [0, seen={seq.seen}]")
        bs = self.config.kv_block_size
        keep = -(-length // bs) if length else 0
        # prefix-cache copy-on-write: after a mid-block trim the next
        # scatter targets rows INSIDE the boundary block; if that page is
        # shared (cache or another sequence holds it), writing would
        # corrupt the other holders — give this sequence a private copy.
        # Allocate it BEFORE mutating any state (evicting LRU prefixes if
        # the pool is dry): a failed trim must leave the sequence intact,
        # never pointed at a still-shared page it will scatter into.
        cow_new = None
        if (length % bs and keep <= len(seq.blocks)
                and self.allocator.refcount(seq.blocks[keep - 1]) > 1):
            if (self.allocator.free_blocks < 1
                    and self.prefix_cache is not None):
                self.prefix_cache.evict_for(self.allocator, 1)
            # eviction may have dropped the cache's own ref on the
            # boundary page, making it private — re-check before copying
            if self.allocator.refcount(seq.blocks[keep - 1]) > 1:
                cow_new = self.allocator.allocate(1)[0]   # may raise: state
                # untouched so far
        seq.tokens = seq.tokens[:length]
        seq.seen = length
        ngi = self._ngram_idx.get(uid)
        if ngi is not None:
            ngi.truncate(length)
        if keep < len(seq.blocks):
            self.allocator.free(seq.blocks[keep:])
            del seq.blocks[keep:]
        if cow_new is not None:
            old = seq.blocks[keep - 1]
            self.kv_pool = self._copy_page(self.kv_pool, old, cow_new)
            self.allocator.release([old])
            seq.blocks[keep - 1] = cow_new

    def _copy_page(self, pools, src: int, dst: int):
        """Device-side page copy across every layer's K/V leaf (one jitted
        donated program; used by trim's copy-on-write)."""
        if self._copy_page_fn is None:
            @functools.partial(jax.jit, donate_argnums=(0,))
            def cp(pools, src, dst):
                return jax.tree_util.tree_map(
                    lambda p: p.at[dst].set(p[src]), pools)

            self._copy_page_fn = cp
        return self._copy_page_fn(pools, jnp.int32(src), jnp.int32(dst))

    # -- step ------------------------------------------------------------
    def _admit_tokens(self, uids: Sequence[int],
                      tokens: Sequence[Sequence[int]]) -> None:
        """Admit new tokens into sequence descriptors — put()'s first
        phase, shared with :meth:`put_spec`: fresh uids get a slot (and
        adopt the longest cached full-block prefix), existing ones append
        their chunk."""
        for uid, toks in zip(uids, tokens):
            new = uid not in self.seqs
            if new:
                if not self._free_slots:
                    raise RuntimeError("no free sequence slots; flush() first")
                now = time.perf_counter()
                resumed = uid in self._resume_uids
                self._resume_uids.discard(uid)
                self.seqs[uid] = SequenceDescriptor(
                    uid=uid, slot=self._free_slots.pop(),
                    t_admitted=None if resumed else now,
                    t_created=None if resumed else now)
            seq = self.seqs[uid]
            seq.tokens.extend(int(t) for t in toks)
            if new:
                seq.prompt_len = len(seq.tokens)
            if new and self.prefix_cache is not None and seq.tokens:
                if self._cold_tier is not None:
                    # cold-tier re-admission first, so the match below
                    # can adopt a spilled prefix the device pool lost
                    self._cold_readmit(seq.tokens)
                # adopt the longest cached full-block prefix: its KV pages
                # are shared (retained), and prefill starts past them
                shared, blocks = self.prefix_cache.match(seq.tokens)
                if shared:
                    self.allocator.retain(blocks)
                    seq.blocks = list(blocks)
                    seq.seen = shared

    def _pack_splitfuse(self) -> List[Tuple[SequenceDescriptor, int]]:
        """Dynamic SplitFuse packing: decodes (and short prompt tails)
        first, then the longest-pending prefill fills the leftover
        budget."""
        sched: List[Tuple[SequenceDescriptor, int]] = []
        budget = self.config.token_budget
        pending = sorted((s for s in self.seqs.values() if s.pending > 0),
                         key=lambda s: s.pending)
        for seq in pending:
            take = min(seq.pending, budget)
            if take == 0:
                break
            sched.append((seq, take))
            budget -= take
        return sched

    def put(self, uids: Sequence[int], tokens: Sequence[Sequence[int]]) -> np.ndarray:
        """Admit new tokens for ``uids`` and run one ragged step.

        Returns [len(uids), vocab] fp32 logits of each sequence's latest
        processed token; rows are NaN while a long prompt is still
        mid-prefill (call put(uid, []) again to continue it).
        """
        cfg = self.config
        self._admit_tokens(uids, tokens)
        sched = self._pack_splitfuse()
        if not sched:
            raise ValueError("put() called with no pending tokens")

        # ---- validate + allocate for the WHOLE schedule before mutating any
        # sequence state, so an exhausted pool leaves every descriptor
        # consistent (seen never advances without its KV being written)
        needs = self._validate_sched(sched)
        flat_tokens, flat_slot, flat_pos, last_idx = \
            self._allocate_and_build(sched, needs)
        last_index = {}  # uid -> index in flat batch of its last token
        for (seq, take), li in zip(sched, last_idx):
            seq.seen += take
            last_index[seq.uid] = int(li)

        block_tables = self._host_tables()

        # per-slot index of the row whose logits we need (sequences not in
        # this schedule keep a harmless 0 — their rows are never read)
        sel_idx = np.zeros((cfg.max_seqs,), np.int32)
        for uid, idx in last_index.items():
            sel_idx[self.seqs[uid].slot] = idx

        if self._step_fn is None:
            self._step_fn = self._build_step()
        logits, self.kv_pool = self._step_fn(
            self.params, self.kv_pool, jnp.asarray(flat_tokens),
            jnp.asarray(flat_slot), jnp.asarray(flat_pos),
            jnp.asarray(block_tables), jnp.asarray(sel_idx),
            self._live_pages_bucket())
        logits = np.asarray(logits)                    # [max_seqs, vocab]

        out = np.full((len(uids), logits.shape[-1]), np.nan, np.float32)
        now = time.perf_counter()
        for i, uid in enumerate(uids):
            seq = self.seqs[uid]
            if seq.pending == 0 and uid in last_index:
                out[i] = logits[seq.slot]
                if seq.t_admitted is not None:
                    # prompt fully prefilled and first logits on host: TTFT.
                    # End-to-end latency is reported at flush(), when the
                    # request actually completes.
                    self._telemetry.record_request(
                        ttft_s=now - seq.t_admitted)
                    seq.t_admitted = None
        self._record_step_telemetry(sched)
        return out

    def put_spec(self, uids: Sequence[int], tokens: Sequence[Sequence[int]],
                 drafts: Sequence[Sequence[int]]
                 ) -> Tuple[np.ndarray, Dict[int, Tuple[List[int], np.ndarray]]]:
        """One ragged step that ALSO verifies speculative draft chains —
        the serving tick's spec-decode entry point: prefill chunks,
        plain decodes and draft-extended decodes all pack into the ONE
        static verify shape (a superset of put()'s program returning
        per-chain-row logits).

        ``drafts[i]`` proposes continuation tokens AFTER ``tokens[i]``
        (which must then be exactly one pending decode token). Returns
        ``(out, verified)``: ``out`` is put()'s [len(uids), vocab]
        last-row logits (NaN mid-prefill rows unchanged); ``verified``
        maps each drafted uid to ``(chain, rows)`` — the chain actually
        scheduled (first element = the fed next token) and fp32 logits
        [len(chain), vocab] for every chain position. The caller accepts
        the longest greedy-matching prefix and MUST ``trim`` the
        rejected tail before the uid's next step.

        Chains are all-or-strip under the token budget: a chain the
        budget cannot hold whole is SHORTENED (unscheduled proposals are
        stripped from the stream), never split into fake pending
        context. On PoolExhausted every remaining draft token is
        stripped before the raise, so the recovery retry (plain ``put``
        with empty chunks) sees exactly put()'s admitted state."""
        cfg = self.config
        self._admit_tokens(uids, tokens)
        # validate EVERY chain before appending ANY draft token: a raise
        # mid-append would leave earlier uids' unverified drafts in their
        # streams, and the next plain put() would schedule them as real
        # context
        for uid, d in zip(uids, drafts):
            if d and self.seqs[uid].pending != 1:
                raise ValueError(
                    f"uid {uid}: a draft chain continues exactly one "
                    f"pending decode token, found "
                    f"pending={self.seqs[uid].pending}")
        appended: Dict[int, int] = {}     # uid -> draft tokens on the stream
        for uid, d in zip(uids, drafts):
            if not d:
                continue
            self.seqs[uid].tokens.extend(int(t) for t in d)
            appended[uid] = len(d)
        try:
            sched = self._pack_splitfuse()
            if not sched:
                raise ValueError("put_spec() called with no pending tokens")
            # all-or-strip: drop draft proposals the budget left behind
            take_of = {seq.uid: take for seq, take in sched}
            for uid in list(appended):
                seq = self.seqs[uid]
                chain_len = 1 + appended[uid]
                take = take_of.get(uid, 0)
                if take < chain_len:
                    strip = chain_len - max(take, 1)
                    if strip:
                        del seq.tokens[len(seq.tokens) - strip:]
                        appended[uid] -= strip
                    if appended[uid] <= 0:
                        appended.pop(uid)
            sched = [(seq, min(take, seq.pending))
                     for seq, take in sched if seq.pending > 0]
            needs = self._validate_sched(sched)
        except BaseException:
            for uid, n in appended.items():
                seq = self.seqs[uid]
                del seq.tokens[len(seq.tokens) - n:]
            raise
        flat_tokens, flat_slot, flat_pos, last_idx = \
            self._allocate_and_build(sched, needs)
        k_max = 1
        for seq, take in sched:
            if seq.uid in appended:
                while k_max < take:
                    k_max *= 2
        sel_rows = np.zeros((cfg.max_seqs, k_max), np.int32)
        last_index: Dict[int, int] = {}
        for (seq, take), li in zip(sched, last_idx):
            li = int(li)
            sel_rows[seq.slot, :] = li        # padding rows: never read
            if seq.uid in appended:
                sel_rows[seq.slot, :take] = np.arange(li - take + 1, li + 1)
            seq.seen += take
            last_index[seq.uid] = li
        if self._verify_fn is None:
            self._verify_fn = self._build_verify()
        logits, self.kv_pool = self._verify_fn(
            self.params, self.kv_pool, jnp.asarray(flat_tokens),
            jnp.asarray(flat_slot), jnp.asarray(flat_pos),
            jnp.asarray(self._host_tables()), jnp.asarray(sel_rows),
            self._live_pages_bucket())
        logits = np.asarray(logits)           # [max_seqs, k_max, vocab]

        out = np.full((len(uids), logits.shape[-1]), np.nan, np.float32)
        now = time.perf_counter()
        for i, uid in enumerate(uids):
            seq = self.seqs[uid]
            if seq.pending == 0 and uid in last_index:
                # sel_rows[slot, -1] is the last scheduled row whether or
                # not the slot carried a chain — put()'s contract holds
                out[i] = logits[seq.slot, -1]
                if seq.t_admitted is not None:
                    self._telemetry.record_request(
                        ttft_s=now - seq.t_admitted)
                    seq.t_admitted = None
        verified: Dict[int, Tuple[List[int], np.ndarray]] = {}
        for seq, take in sched:
            if seq.uid in appended:
                chain = [int(t) for t in seq.tokens[seq.seen - take:
                                                    seq.seen]]
                verified[seq.uid] = (chain, logits[seq.slot, :take])
        self._record_step_telemetry(sched)
        return out, verified

    def kv_occupancy(self) -> float:
        """Fraction of the paged KV pool currently held by live sequences
        or the prefix cache (1.0 = exhausted)."""
        return 1.0 - self.allocator.free_blocks / self.allocator.n_blocks

    def kv_demand(self) -> float:
        """Fraction of the pool that live DEMAND holds: pages the cache
        could reclaim on allocation pressure don't count. This is the
        capacity-planning signal (a warm LRU cache legitimately absorbs
        the whole pool at idle — raw ``kv_occupancy`` would read that as
        permanent pressure and an autoscaler could never scale down)."""
        return 1.0 - self._available_blocks() / self.allocator.n_blocks

    def _record_step_telemetry(self, sched) -> None:
        """Per-ragged-step series: scheduled tokens + pool occupancy. Host
        dict updates only — nothing here touches the device."""
        t = self._telemetry
        if not t.enabled:
            return
        r = t.registry
        r.counter("inference/ragged_steps").inc()
        r.counter("inference/scheduled_tokens").inc(
            sum(take for _, take in sched))
        r.gauge("inference/kv_occupancy").set(self.kv_occupancy())
        r.gauge("inference/live_sequences").set(len(self.seqs))

    def _validate_sched(self, sched) -> List[int]:
        """Validate a (seq, take) schedule WITHOUT mutating anything:
        context bound, pool demand (evicting cached prefixes if needed),
        and batch-width fit. Returns per-entry new-block needs."""
        cfg = self.config
        needs = []
        for seq, take in sched:
            new_total = seq.seen + take
            if new_total > cfg.max_context:
                raise ValueError(
                    f"uid {seq.uid}: context {new_total} exceeds "
                    f"max_context {cfg.max_context}")
            needs.append(-(-new_total // cfg.kv_block_size) - len(seq.blocks))
        self._check_pool(needs)
        scheduled = sum(take for _, take in sched)
        if scheduled > cfg.token_budget:
            raise ValueError(f"scheduled tokens {scheduled} exceed "
                             f"token_budget {cfg.token_budget}")
        return needs

    def _allocate_and_build(self, sched, needs):
        """Grant blocks and build the flat step batch (reference: C++
        fast_host_buffer). T rounds the scheduled token count up to a
        bucket, not the full budget: a pure-decode step with 32 live seqs
        must not pay a 4096-lane forward (one compile per bucket, cached
        by jit). The numpy fallback of build_batch is bit-identical to
        the native builder."""
        scheduled = sum(take for _, take in sched)
        T = next(b for b in self._buckets if b >= scheduled)
        chunks, seens_l, slots_l = [], [], []
        for (seq, take), need in zip(sched, needs):
            if need > 0:
                seq.blocks.extend(self.allocator.allocate(need))
            chunks.append(seq.tokens[seq.seen:seq.seen + take])
            seens_l.append(seq.seen)
            slots_l.append(seq.slot)
        return build_batch(chunks, seens_l, slots_l, T)

    def _put_verify(self, uids: Sequence[int],
                    chains: Sequence[List[int]]) -> List[np.ndarray]:
        """Speculative-verify step: admit each uid's token chain and return
        the logits of EVERY chain row (vs put(), which selects only the
        last). One device call verifies all proposals; the caller accepts
        the longest matching prefix and trims the rest. k is pow2-bucketed
        so the jit cache stays O(log k) wide."""
        cfg = self.config
        sched = [(self.seqs[u], len(c)) for u, c in zip(uids, chains)]
        # validate BEFORE touching seq.tokens: a failed round must not
        # leave unverified draft tokens in any sequence's stream
        needs = self._validate_sched(sched)
        for u, c in zip(uids, chains):
            self.seqs[u].tokens.extend(int(t) for t in c)
        flat_tokens, flat_slot, flat_pos, last_idx = \
            self._allocate_and_build(sched, needs)
        k_max = 1
        while k_max < max(take for _, take in sched):
            k_max *= 2
        sel_rows = np.zeros((cfg.max_seqs, k_max), np.int32)
        for (seq, take), li in zip(sched, last_idx):
            li = int(li)
            sel_rows[seq.slot, :take] = np.arange(li - take + 1, li + 1)
            sel_rows[seq.slot, take:] = li      # padding rows: never read
            seq.seen += take
        if self._verify_fn is None:
            self._verify_fn = self._build_verify()
        logits, self.kv_pool = self._verify_fn(
            self.params, self.kv_pool, jnp.asarray(flat_tokens),
            jnp.asarray(flat_slot), jnp.asarray(flat_pos),
            jnp.asarray(self._host_tables()), jnp.asarray(sel_rows),
            self._live_pages_bucket())
        logits = np.asarray(logits)             # [max_seqs, k_max, vocab]
        return [logits[seq.slot, :take] for seq, take in sched]

    def _build_verify(self):
        core = self._core
        model = self.model

        def step(params, pools, tokens, slots, positions, block_tables,
                 sel_rows, live_pages):
            x, pools = core(params, pools, tokens, slots, positions,
                            block_tables, live_pages)
            x_sel = x[sel_rows.reshape(-1)]                 # [S*k, d]
            logits = model._head(params, x_sel[None, :])[0]
            return logits.reshape(sel_rows.shape + (-1,)), pools

        return jax.jit(step, donate_argnums=(1,), static_argnums=(7,))

    def _check_pool(self, needs) -> None:
        """Admission check shared by put()/decode_steps(): the whole
        schedule's new-block demand must fit the pool before ANY uid is
        granted blocks (two-phase validate-then-allocate). Cache-held
        pages are reclaimable: evict LRU prefixes before giving up."""
        short = sum(n for n in needs if n > 0)
        if short > self.allocator.free_blocks and self.prefix_cache is not None:
            self.prefix_cache.evict_for(self.allocator, short)
        if short > self.allocator.free_blocks:
            raise PoolExhausted(
                f"KV pool exhausted: need {short} blocks, have "
                f"{self.allocator.free_blocks}; flush() finished "
                "sequences first")

    def _host_tables(self) -> np.ndarray:
        live = list(self.seqs.values())
        return fill_tables([s.blocks for s in live], [s.slot for s in live],
                           self.config.max_seqs, self.max_pages)

    def _live_pages_bucket(self) -> int:
        """Static page-walk bound for this step: smallest power of two >=
        the longest live sequence's page count (pow2-bucketed so the jit
        cache holds O(log max_pages) variants, not one per context len)."""
        most = max((len(s.blocks) for s in self.seqs.values()), default=1)
        b = 1
        while b < most:
            b *= 2
        return min(b, self.max_pages)

    def decode_steps(self, first_tokens: Dict[int, int], k: int,
                     eos_token_id: Optional[int] = None) -> Dict[int, List[int]]:
        """Decode ``k`` tokens (greedy or sampled per config) for every uid
        in ``first_tokens`` in ONE device call (see _build_decode).

        ``first_tokens[uid]`` is the next input token (produced by the
        previous step's logits, not yet admitted). Returns uid -> the k
        tokens generated after it; the last one is returned un-processed —
        feed it as the next call's first token (exactly like the
        one-token-at-a-time put() contract). Every uid must be fully
        prefilled (pending == 0).

        EOS caveat: all k tokens are admitted to the sequence's context
        (KV + token stream) before the caller can observe EOS inside the
        chunk. ``generate()`` handles this by flushing finished uids; a
        caller that keeps serving a uid via put()/decode_steps after an
        in-chunk EOS must first ``trim(uid, ...)`` back to the EOS
        position, or the post-EOS tokens become permanent context."""
        cfg = self.config
        if k < 1:
            raise ValueError(f"decode_steps needs k >= 1, got {k}")
        # validate every uid before allocating anything (same two-phase
        # discipline as put()): a rejected uid must not leave earlier uids
        # holding blocks with no KV written
        needs = []
        for uid in first_tokens:
            seq = self.seqs[uid]
            if seq.pending:
                raise ValueError(f"uid {uid} still has pending prefill")
            total = seq.seen + k
            if total > cfg.max_context:
                raise ValueError(
                    f"uid {uid}: decode chunk to {total} exceeds "
                    f"max_context {cfg.max_context}")
            needs.append(-(-total // cfg.kv_block_size) - len(seq.blocks))
        self._check_pool(needs)
        for uid, need in zip(first_tokens, needs):
            if need > 0:
                self.seqs[uid].blocks.extend(self.allocator.allocate(need))

        S = cfg.max_seqs
        toks = np.zeros((S,), np.int32)
        pos = np.zeros((S,), np.int32)
        slots = np.full((S,), -1, np.int32)
        for uid, first in first_tokens.items():
            seq = self.seqs[uid]
            toks[seq.slot] = first
            pos[seq.slot] = seq.seen
            slots[seq.slot] = seq.slot

        if self._decode_fn is None:
            self._decode_fn = self._build_decode()
        steps_xs = np.arange(self._decode_step_counter,
                             self._decode_step_counter + k, dtype=np.int32)
        self._decode_step_counter += k
        eos = -1 if eos_token_id is None else int(eos_token_id)
        gen, self.kv_pool = self._decode_fn(
            self.params, self.kv_pool, jnp.asarray(toks), jnp.asarray(pos),
            jnp.asarray(slots), jnp.asarray(self._host_tables()),
            jnp.asarray(steps_xs), self._rng_decode,
            self._live_pages_bucket(), eos)
        gen = np.asarray(gen)                                   # [S, k]

        out = {}
        for uid, first in first_tokens.items():
            seq = self.seqs[uid]
            chain = gen[seq.slot].tolist()
            if eos >= 0:
                # device-side freeze: only tokens actually FED are context.
                # first==eos feeds nothing; eos at chain[j] means first +
                # chain[:j] were fed (the EOS itself is emitted, not fed)
                if first == eos:
                    fed = []
                elif eos in chain:
                    j = chain.index(eos)
                    fed = [first] + chain[:j]
                else:
                    fed = [first] + chain[:-1]
                seq.tokens.extend(fed)
                seq.seen += len(fed)
            else:
                # positions seen..seen+k-1 now hold first + chain[:-1]
                seq.tokens.extend([first] + chain[:-1])
                seq.seen += k
            out[uid] = chain
        return out

    # -- generation convenience -----------------------------------------
    def _sample_first(self, rows) -> List[int]:
        """First decode token(s) from resolved prefill logits rows —
        greedy on host, else one sampled draw per prefill round (the
        round counter advances ONLY when sampling, so greedy calls never
        shift the seeded streams of later sampled calls)."""
        if self.config.temperature == 0.0:
            return [int(np.argmax(r)) for r in rows]
        key = jax.random.fold_in(self._rng_prefill,
                                 self._prefill_round_counter)
        self._prefill_round_counter += 1
        toks = np.asarray(_sample(jnp.asarray(np.stack(rows)), key,
                                  self.config.temperature,
                                  self.config.top_k, self.config.top_p))
        return [int(t) for t in toks]

    def stream(self, uid: int, prompt: Sequence[int], *,
               max_new_tokens: int = 128,
               eos_token_id: Optional[int] = None,
               decode_chunk: int = 8):
        """Incremental generation: yields decoded tokens as chunks
        complete (the MII/FastGen streaming-response surface). Drives the
        same put()/decode_steps machinery as generate(); the uid is
        flushed when the stream ends — including early consumer breaks
        and mid-prefill failures (no slot/block leak)."""
        logits = self.put([uid], [list(prompt)])
        try:
            while np.isnan(logits[0]).any():
                logits = self.put([uid], [[]])
            tok = self._sample_first([logits[0]])[0]
            produced = 0
            yield tok
            produced += 1
            if eos_token_id is not None and tok == eos_token_id:
                return
            while produced < max_new_tokens:
                room = self.config.max_context - self.seqs[uid].seen
                if room <= 0:
                    return
                k = max(1, min(decode_chunk, max_new_tokens - produced, room))
                chain = self.decode_steps({uid: tok}, k,
                                          eos_token_id=eos_token_id)[uid]
                for t in chain:
                    yield t
                    produced += 1
                    if eos_token_id is not None and t == eos_token_id:
                        return
                tok = chain[-1]
        finally:
            if uid in self.seqs:
                self.flush([uid])

    def generate(self, prompts: Dict[int, Sequence[int]], max_new_tokens: int = 32,
                 eos_token_id: Optional[int] = None,
                 decode_chunk: int = 16) -> Dict[int, List[int]]:
        """Generation: SplitFuse put() steps until every prompt is
        prefilled, then ``decode_steps`` chunks of up to ``decode_chunk``
        tokens per device call. Greedy when config.temperature == 0, else
        temperature/top-k/top-p sampling (chunk-invariant streams).
        Returns uid -> generated tokens."""
        done: Dict[int, List[int]] = {u: [] for u in prompts}
        first = self._prefill_first(prompts, done)

        live = {u: t for u, t in first.items()
                if len(done[u]) < max_new_tokens
                and not (eos_token_id is not None and t == eos_token_id)}
        while live:
            budget = min(max_new_tokens - len(done[u]) for u in live)
            room = min(self.config.max_context - self.seqs[u].seen
                       for u in live)
            k = max(1, min(decode_chunk, budget, room))
            gens = self.decode_steps(live, k, eos_token_id=eos_token_id)
            nxt = {}
            for u, chain in gens.items():
                stop = False
                for t in chain:
                    done[u].append(t)
                    if eos_token_id is not None and t == eos_token_id:
                        stop = True
                        break
                if (not stop and len(done[u]) < max_new_tokens
                        and self.seqs[u].seen < self.config.max_context):
                    nxt[u] = chain[-1]
            live = nxt
        for u in done:
            done[u] = done[u][:max_new_tokens]
        self.flush(list(prompts))
        return done

    def _prefill_first(self, prompts: Dict[int, Sequence[int]],
                       done: Dict[int, List[int]]) -> Dict[int, int]:
        """Run SplitFuse prefill to completion for ``prompts``, collecting
        each uid's first decode token as its row resolves (long prompts
        span multiple put() steps). Appends the first token to ``done``
        and returns uid -> first token. Shared by generate() and
        generate_speculative() (identical under greedy; sampled first
        tokens ride the seeded prefill stream)."""
        uids = list(prompts)
        logits = self.put(uids, [list(p) for p in prompts.values()])
        first: Dict[int, int] = {}
        while True:
            pending, resolved = [], []
            for u, row in zip(uids, logits):
                if np.isnan(row).any():
                    pending.append(u)
                else:
                    resolved.append((u, row))
            if resolved:
                toks_out = self._sample_first([r for _, r in resolved])
                for (u, _), t in zip(resolved, toks_out):
                    first[u] = t
            if not pending:
                break
            uids = pending
            logits = self.put(pending, [[] for _ in pending])
        for u, t in first.items():
            done[u].append(t)
        return first

    def generate_speculative(self, prompts: Dict[int, Sequence[int]],
                             max_new_tokens: int = 32,
                             eos_token_id: Optional[int] = None,
                             ngram: int = 3,
                             lookahead: int = 4) -> Dict[int, List[int]]:
        """Prompt-lookup speculative decoding (greedy only; beyond the
        reference — FastGen decodes strictly one token per step).

        Each round drafts up to ``lookahead`` continuation tokens per
        sequence by matching its trailing ``ngram`` against earlier
        context (zero-cost n-gram draft; no draft model), verifies the
        whole chain in ONE ragged step via per-row logits, accepts the
        longest matching prefix, and trims the rejected tail's KV.
        Greedy acceptance makes the output TOKEN-IDENTICAL to
        ``generate()`` — acceptance rate only changes how many device
        round trips it takes. Stats land in ``self.spec_stats``.
        """
        if self.config.temperature != 0.0:
            raise NotImplementedError(
                "speculative decoding is greedy-only (temperature == 0); "
                "sampled acceptance needs rejection sampling")
        done: Dict[int, List[int]] = {u: [] for u in prompts}
        first = self._prefill_first(prompts, done)

        live = {u: t for u, t in first.items()
                if len(done[u]) < max_new_tokens
                and not (eos_token_id is not None and t == eos_token_id)}
        while live:
            # fair-share the token budget across live chains so the
            # verify round always fits one step batch
            share = max(1, self.config.token_budget // len(live))
            v_uids, v_chains = [], []
            for u, t0 in live.items():
                seq = self.seqs[u]
                room = self.config.max_context - seq.seen
                if room <= 0:
                    continue
                k = max(0, min(lookahead, room - 1, share - 1,
                               max_new_tokens - len(done[u]) - 1))
                # memoized n-gram draft (NgramIndex): O(new tokens) per
                # round instead of rescanning the whole context
                guesses = self.draft_tokens(u, t0, ngram, k)
                v_uids.append(u)
                v_chains.append([t0] + guesses)
            if not v_uids:
                break
            rows = self._put_verify(v_uids, v_chains)
            round_proposed = round_accepted = 0
            nxt: Dict[int, int] = {}
            for u, chain, lr in zip(v_uids, v_chains, rows):
                a = np.argmax(lr, axis=-1)            # [len(chain)]
                matched = 0
                while (matched < len(chain) - 1
                       and int(a[matched]) == chain[matched + 1]):
                    matched += 1
                round_proposed += len(chain) - 1
                round_accepted += matched
                emitted = [int(x) for x in a[:matched + 1]]
                seq = self.seqs[u]
                seen0 = seq.seen - len(chain)
                stop_at = None
                if eos_token_id is not None and eos_token_id in emitted:
                    stop_at = emitted.index(eos_token_id)
                    emitted = emitted[:stop_at + 1]
                # rewind KV/tokens to the validated prefix (rejected rows
                # are never read — attention is position-bounded — but the
                # token stream must stay clean for further serving)
                keep = seen0 + (stop_at if stop_at is not None
                                else matched) + 1
                if keep < seq.seen:
                    self.trim(u, keep)
                done[u].extend(emitted)
                if (stop_at is None and len(done[u]) < max_new_tokens
                        and seq.seen < self.config.max_context):
                    nxt[u] = emitted[-1]
            self.record_spec(proposed=round_proposed,
                             accepted=round_accepted, rounds=1)
            live = nxt
        for u in done:
            done[u] = done[u][:max_new_tokens]
        self.flush(list(prompts))
        return done

    # -- the compiled ragged step ----------------------------------------
    def _build_core(self):
        """The shared ragged forward: (params, pools, tokens, slots,
        positions, block_tables) -> (hidden [T, d], pools). Traced inside
        both the SplitFuse ``put`` step and the multi-step decode loop."""
        from ..ops.pallas.paged_attention import (paged_attention,
                                                  paged_attention_reference)

        model = self.model
        c = model.config
        cfg = self.config
        bs = cfg.kv_block_size
        # per-layer sliding windows (static tuple; 0 = global causal);
        # binding windows ride the banded Pallas kernel per layer on TPU
        # (window passed statically below) and the banded gather elsewhere
        aw = getattr(c, "attn_windows", None)
        windows = tuple(int(w) if 0 < int(w) < cfg.max_context else 0
                        for w in aw) if aw is not None \
            else (0,) * c.n_layers
        # TP shards the pool/heads. GSPMD cannot partition a pallas_call,
        # so under TP the kernel runs INSIDE a shard_map whose specs name
        # the operands' existing sharding (heads/pool over 'model', tables/
        # positions replicated) — each device runs the kernel on its local
        # head shard with zero collectives, exactly the treatment the
        # training flash wrapper got (models/transformer.py _attention;
        # reference frame: FastGen's TP4 headline,
        # blogs/deepspeed-fastgen/README.md:163). Attention is head-local,
        # so no psum is needed; the o-proj contraction after it is GSPMD's.
        # Binding sliding windows ride the kernel too: the per-layer window
        # is STATIC (the python layer loop is unrolled), and the kernel
        # skips + DMA-dedups chunks below the band (O(window) traffic).
        # DST_RAGGED_FORCE_PALLAS=interpret pins the kernel path in Pallas
        # interpret mode — the CPU-lane token-exactness tests for the
        # sharded kernel ride this.
        import os as _os

        _force = _os.environ.get("DST_RAGGED_FORCE_PALLAS", "")
        interp = _force == "interpret"
        # (no indivisible-heads fallback needed here: __init__ rejects
        # n_kv_heads % tp != 0 outright, and n_heads is a multiple of
        # n_kv_heads, so any engine that reaches this point shards cleanly)
        use_pallas = interp or _use_pallas_paged(
            c.head_dim, bs, self.config.dtype,
            scalar_ints=cfg.max_seqs * self.max_pages + 2 * cfg.token_budget)

        kv_bits = self._kv_bits

        def _paged_attn_sharded(q, kp, vp, tables, positions, slots,
                                live_pages, window, ks=None, vs=None):
            """shard_map the paged kernel over the bound mesh: heads and
            pool (payload AND scale leaves) sharded on 'model', scalars
            replicated."""
            from jax.sharding import PartitionSpec as P_

            hspec = P_(None, "model", None)
            pspec = P_(None, "model", None, None)
            sspec = P_(None, "model", None)

            from ..parallel.mesh import shard_map_compat

            if ks is not None:
                def local_q(q, kp, vp, tb, pos, sl, ks, vs):
                    return paged_attention(q, kp, vp, tb, pos, seq_slots=sl,
                                           live_pages=live_pages,
                                           window=window, k_scale=ks,
                                           v_scale=vs, kv_bits=kv_bits,
                                           interpret=interp)

                mapped = shard_map_compat(
                    local_q, mesh=self.topo.mesh, axis_names={"model"},
                    in_specs=(hspec, pspec, pspec, P_(None, None), P_(None),
                              P_(None), sspec, sspec),
                    out_specs=hspec, check_vma=False)
                return mapped(q, kp, vp, tables, positions, slots, ks, vs)

            def local(q, kp, vp, tb, pos, sl):
                return paged_attention(q, kp, vp, tb, pos, seq_slots=sl,
                                       live_pages=live_pages, window=window,
                                       interpret=interp)

            in_specs = (hspec, pspec, pspec, P_(None, None), P_(None),
                        P_(None))
            mapped = shard_map_compat(
                local, mesh=self.topo.mesh, axis_names={"model"},
                in_specs=in_specs, out_specs=hspec, check_vma=False)
            return mapped(q, kp, vp, tables, positions, slots)

        def norm(x, w, b=None):
            return rms_norm(x, w, c.norm_eps) if c.norm == "rms" \
                else layer_norm(x, w, b, c.norm_eps)

        def core(params, pools, tokens, slots, positions, block_tables,
                 live_pages):
            # live_pages: static python int — bounds the kernel's page walk
            # tokens/slots/positions: [T]; embeddings via the model's path
            x = model._embed(params, tokens[None, :],
                             positions=positions[None, :])[0]  # [T, d]
            angles = rope_frequencies(c.rotary_dim, c.max_seq_len, c.rope_theta) \
                if c.position == "rope" else None
            active = slots >= 0                                   # [T]
            safe_slot = jnp.maximum(slots, 0)
            # the Pallas kernel takes the per-seq tables + slot indirection
            # directly (scalar prefetch stays O(seqs * pages), SMEM-sized);
            # only the gather fallback expands to per-token [T, max_pages]
            tables = None if use_pallas else block_tables[safe_slot]

            k_list, v_list = list(pools[0]), list(pools[1])
            ks_list = list(pools[2]) if kv_bits else None
            vs_list = list(pools[3]) if kv_bits else None

            def block(x, li, lp):
                kp, vp = k_list[li], v_list[li]
                h = norm(x, lp["attn_norm_w"], lp.get("attn_norm_b"))
                q = (h @ lp["wq"]).reshape(-1, c.n_heads, c.head_dim)
                kk = (h @ lp["wk"]).reshape(-1, c.n_kv_heads, c.head_dim)
                vv = (h @ lp["wv"]).reshape(-1, c.n_kv_heads, c.head_dim)
                if c.qkv_bias:
                    q = q + lp["bq"].reshape(c.n_heads, c.head_dim)
                    kk = kk + lp["bk"].reshape(c.n_kv_heads, c.head_dim)
                    vv = vv + lp["bv"].reshape(c.n_kv_heads, c.head_dim)
                if c.position == "rope":
                    q = apply_rotary(q[:, None], angles, positions[:, None],
                                     rotary_dim=c.rotary_dim,
                                     interleaved=c.rope_interleaved)[:, 0]
                    kk = apply_rotary(kk[:, None], angles, positions[:, None],
                                      rotary_dim=c.rotary_dim,
                                      interleaved=c.rope_interleaved)[:, 0]
                # scatter new K/V into this layer's pages — one in-place
                # scatter of the touched pages into this layer's leaf:
                # page = table[pos // bs], row = pos % bs
                page = block_tables[safe_slot, positions // bs]   # [T]
                row = positions % bs
                # inactive lanes — and any lane past the context window
                # (possible in the tail of a multi-step decode) — scatter
                # into the scratch sink page, never a live one
                page = jnp.where(active & (positions < cfg.max_context),
                                 page, cfg.n_kv_blocks)
                # pool layout [pages, hkv, block, hd]; kk [T, hkv, hd].
                # kv_quant: quantize each head-vector on the way in (one
                # fp32 scale per row, ops/quantizer.quantize_kv) and
                # scatter payload + scale; reads below dequantize inside
                # the paged-attention path, so fp K/V never round-trips
                # through HBM at full width
                if kv_bits:
                    from ..ops.quantizer import quantize_kv

                    qk, sk = quantize_kv(kk, kv_bits)
                    qv, sv = quantize_kv(vv, kv_bits)
                    kp = kp.at[page, :, row].set(qk)
                    vp = vp.at[page, :, row].set(qv)
                    ksl = ks_list[li].at[page, :, row].set(sk)
                    vsl = vs_list[li].at[page, :, row].set(sv)
                    k_list[li], v_list[li] = kp, vp
                    ks_list[li], vs_list[li] = ksl, vsl
                else:
                    ksl = vsl = None
                    kp = kp.at[page, :, row].set(kk.astype(kp.dtype))
                    vp = vp.at[page, :, row].set(vv.astype(vp.dtype))
                    k_list[li], v_list[li] = kp, vp
                # paged attention: Pallas kernel on TPU (scalar-prefetched
                # block tables, zero gather); jnp gather path elsewhere.
                # (positions <= ctx-1 always, so the causal mask subsumes the
                # context-length mask; inactive lanes produce ignored junk)
                if use_pallas and self._tp_size > 1:
                    attn = _paged_attn_sharded(q, kp, vp, block_tables,
                                               positions, safe_slot,
                                               live_pages, windows[li],
                                               ks=ksl, vs=vsl)
                elif use_pallas:
                    attn = paged_attention(q, kp, vp, block_tables,
                                           positions, seq_slots=safe_slot,
                                           live_pages=live_pages,
                                           window=windows[li],
                                           k_scale=ksl, v_scale=vsl,
                                           kv_bits=kv_bits,
                                           interpret=interp)
                else:
                    attn = paged_attention_reference(q, kp, vp, tables,
                                                     positions,
                                                     window=windows[li],
                                                     k_scale=ksl,
                                                     v_scale=vsl,
                                                     kv_bits=kv_bits)
                attn = attn.astype(x.dtype)
                attn = attn.reshape(-1, c.n_heads * c.head_dim) @ lp["wo"]
                # attn_o_bias, not use_bias: InternLM has use_bias=False
                # with a real o_proj bias (models/transformer.py:500)
                if c.attn_o_bias:
                    attn = attn + lp["bo"]
                x = x + attn
                h = norm(x, lp["mlp_norm_w"], lp.get("mlp_norm_b"))
                # the model's own MLP: honors relu/gelu/gelu_exact/silu_glu
                # and the MoE override (top-k routed experts) uniformly
                down, _ = model._mlp(h[None], lp, None, False)
                return x + down[0]

            # python-unrolled layer loop, NOT lax.scan: a scan would carry
            # the whole pool and either re-slice it per layer (stacked
            # layout) or double-buffer it (flat layout) — see the pool_shape
            # comment in __init__
            for li in range(c.n_layers):
                lp = jax.tree_util.tree_map(lambda a: a[li], params["layers"])
                x = block(x, li, lp)
            out_pools = (tuple(k_list), tuple(v_list))
            if kv_bits:
                out_pools += (tuple(ks_list), tuple(vs_list))
            return x, out_pools

        return core

    @property
    def _core(self):
        if self._core_fn is None:
            self._core_fn = self._build_core()
        return self._core_fn

    def _build_step(self):
        core = self._core
        model = self.model

        def step(params, pools, tokens, slots, positions, block_tables,
                 sel_idx, live_pages):
            x, pools = core(params, pools, tokens, slots, positions,
                            block_tables, live_pages)
            # head only on each sequence's selected (last) token: the full
            # [token_budget, vocab] fp32 logits are 512 MB at T=4096 v=32k
            # and were previously fetched to host every step — select the
            # [max_seqs] rows on-device before the (remote) host transfer
            x_sel = x[sel_idx]                                     # [S, d]
            logits = model._head(params, x_sel[None, :])[0]        # [S, vocab]
            return logits, pools

        return jax.jit(step, donate_argnums=(1,), static_argnums=(7,))

    def _build_decode(self):
        """Multi-step decode entirely on device: one token per live slot
        per step (argmax, or temperature/top-k/top-p sampled), fed straight
        into the next step, KV scattered
        into pre-allocated pages. The host round trip (the dominant cost of
        one-token-at-a-time serving through a remote runtime) amortizes over
        the whole chunk. Reference analog: FastGen schedules one engine call
        per forward (inference/v2/ragged/ragged_manager.py) — on TPU the
        chunked loop is the idiomatic shape."""
        core = self._core
        model = self.model

        cfg = self.config

        def decode(params, pools, tokens0, positions0, slots, block_tables,
                   steps_xs, rng_key, live_pages, eos_id):
            # steps_xs: [k] GLOBAL decode-step ids — the per-step sample key
            # is fold_in(rng_key, global_step), so token streams do not
            # depend on the chunking of decode calls.
            # eos_id >= 0 freezes a lane ON DEVICE once it samples EOS:
            # its token is never fed, its KV scatter routes to the sink
            # page (slot -1), its position stops advancing, and it emits
            # eos fillers — post-EOS context pollution cannot happen
            # (reference ragged manager retires finished sequences
            # host-side per step; the compiled chunk does it in-loop).
            alive0 = slots >= 0
            if eos_id >= 0:
                alive0 = jnp.logical_and(alive0, tokens0 != eos_id)

            def one(carry, step_i):
                pools, toks, pos, alive = carry
                slots_eff = jnp.where(alive, slots, -1)
                x, pools = core(params, pools, toks, slots_eff, pos,
                                block_tables, live_pages)
                logits = model._head(params, x[None, :])[0]    # [S, vocab]
                nxt = _sample(logits, jax.random.fold_in(rng_key, step_i),
                              cfg.temperature, cfg.top_k, cfg.top_p)
                if eos_id >= 0:
                    nxt = jnp.where(alive, nxt, eos_id)
                    new_alive = jnp.logical_and(alive, nxt != eos_id)
                else:
                    new_alive = alive
                pos = pos + alive.astype(pos.dtype)
                return (pools, nxt, pos, new_alive), nxt

            (pools, _, _, _), gen = jax.lax.scan(
                one, (pools, tokens0, positions0, alive0), steps_xs)
            return gen.T, pools                                 # [S, k]

        return jax.jit(decode, donate_argnums=(1,), static_argnums=(8, 9))
