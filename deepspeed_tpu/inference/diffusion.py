"""Stable-diffusion sampling: DDIM denoise loop as ONE compiled program.

The reference accelerates diffusers serving by wrapping the UNet in a
cuda-graph replay (``model_implementations/diffusers/unet.py:35`` —
capture once, replay per step to kill launch overhead). The TPU-native
equivalent is strictly stronger: the ENTIRE sampling loop — classifier-
free guidance, the DDIM update, every UNet call — is a single ``jax.jit``
program (``lax.fori_loop`` over steps), so there is no per-step host
round trip at all, and XLA schedules the whole trajectory.

Scheduler math follows DDIM (Song et al.) with the scaled-linear beta
schedule Stable Diffusion trains with, eta=0 (deterministic), matching
diffusers' ``DDIMScheduler(beta_schedule="scaled_linear")`` defaults.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np


@dataclass
class DDIMSchedule:
    """Precomputed alphas for a truncated DDIM trajectory."""

    num_train_timesteps: int = 1000
    beta_start: float = 0.00085
    beta_end: float = 0.012
    num_inference_steps: int = 50
    # diffusers' SD defaults: timesteps start at 1, and the final step's
    # alpha_prev is alphas_cumprod[0] rather than 1.0 (set_alpha_to_one
    # is False in the SD scheduler config)
    steps_offset: int = 1
    set_alpha_to_one: bool = False

    def __post_init__(self):
        # scaled-linear: betas are squares of a linear sqrt-space ramp
        betas = np.linspace(self.beta_start ** 0.5, self.beta_end ** 0.5,
                            self.num_train_timesteps, dtype=np.float64) ** 2
        self.alphas_cumprod = np.cumprod(1.0 - betas)
        step = self.num_train_timesteps // self.num_inference_steps
        # diffusers "leading" spacing: t = i*step + offset, descending
        self.timesteps = np.clip(
            np.arange(0, self.num_inference_steps)[::-1] * step
            + self.steps_offset, 0, self.num_train_timesteps - 1)

    def arrays(self):
        ts = jnp.asarray(self.timesteps, jnp.int32)
        acp = jnp.asarray(self.alphas_cumprod, jnp.float32)
        step = self.num_train_timesteps // self.num_inference_steps
        prev = ts - step
        final_alpha = 1.0 if self.set_alpha_to_one else float(  # dslint: disable=host-sync -- alphas_cumprod is a host numpy table; this folds to a constant at trace time
            self.alphas_cumprod[0])
        alpha_t = acp[ts]
        alpha_prev = jnp.where(prev >= 0, acp[jnp.maximum(prev, 0)],
                               final_alpha)
        return ts, alpha_t, alpha_prev


class StableDiffusionPipeline:
    """Latent-space text-to-image sampling over native UNet/VAE/CLIP parts.

    Mirrors the surface the reference's injected diffusers pipeline serves
    (UNet + VAE policies, module_inject/containers/unet.py / vae.py);
    text encoding is the native CLIP text tower (models/clip.py) or any
    caller-supplied [b, seq, dim] embedding.
    """

    def __init__(self, unet, vae=None, schedule: Optional[DDIMSchedule] = None,
                 guidance_scale: float = 7.5):
        self.unet = unet
        self.vae = vae
        self.schedule = schedule or DDIMSchedule()
        self.guidance_scale = guidance_scale
        self._sample = jax.jit(self._sample_impl, static_argnames=("shape",))
        self._decode_fn = None   # lazily-jitted VAE decode (one trace)

    # -- one fully-compiled trajectory ---------------------------------
    def _sample_impl(self, unet_params, cond_ctx, uncond_ctx, rng, *,
                     shape):
        ts, alpha_t, alpha_prev = self.schedule.arrays()
        g = jnp.float32(self.guidance_scale)
        latents = jax.random.normal(rng, shape, jnp.float32)

        ctx = jnp.concatenate([uncond_ctx, cond_ctx], axis=0)

        def body(i, lat):
            t = ts[i]
            at, ap = alpha_t[i], alpha_prev[i]
            # classifier-free guidance: one batched UNet call
            lat2 = jnp.concatenate([lat, lat], axis=0)
            tb = jnp.broadcast_to(t, (lat2.shape[0],))
            eps = self.unet.apply(unet_params, lat2, tb, ctx)
            eps_u, eps_c = jnp.split(eps, 2, axis=0)
            eps = eps_u + g * (eps_c - eps_u)
            eps = eps.astype(jnp.float32)
            # DDIM (eta=0): x0-pred then deterministic step
            x0 = (lat - jnp.sqrt(1.0 - at) * eps) / jnp.sqrt(at)
            return jnp.sqrt(ap) * x0 + jnp.sqrt(1.0 - ap) * eps

        return jax.lax.fori_loop(0, len(self.schedule.timesteps), body,
                                 latents)

    def sample_latents(self, unet_params, cond_ctx, uncond_ctx, rng,
                       height: int = 64, width: int = 64):
        b = cond_ctx.shape[0]
        lc = getattr(getattr(self.vae, "config", None), "latent_channels", 4) \
            if self.vae is not None else self.unet.config.in_channels
        shape = (b, height, width, lc)
        return self._sample(unet_params, cond_ctx, uncond_ctx, rng,
                            shape=shape)

    def __call__(self, unet_params, cond_ctx, uncond_ctx, rng,
                 vae_params=None, height: int = 64, width: int = 64):
        """Returns images [b, 8h, 8w, 3] in [-1, 1] (with a VAE) or raw
        latents (without)."""
        lat = self.sample_latents(unet_params, cond_ctx, uncond_ctx, rng,
                                  height, width)
        if self.vae is None or vae_params is None:
            return lat
        # cache the jitted decoder: jax.jit(self.vae.decode) binds a
        # FRESH method object per call, so the wrapper (and its trace
        # cache) would be rebuilt — one VAE recompile per generated
        # image (dslint recompile-hazard)
        if self._decode_fn is None:
            self._decode_fn = jax.jit(self.vae.decode)
        return self._decode_fn(vae_params, lat)
