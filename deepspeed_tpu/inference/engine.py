"""Inference engine (v1): TP-sharded generation with a static KV cache.

Capability parity with the reference's ``InferenceEngine``
(``deepspeed/inference/engine.py:39``) + the injection machinery it drives
(``deepspeed/module_inject/`` auto-TP / kernel containers), redesigned
TPU-first:

* **No module injection.** The reference walks an HF module tree swapping
  layers for fused-kernel containers and patching all-reduces into forward
  (replace_module.py:182, auto_tp.py). Here the model is already functional
  and its :meth:`partition_specs` carry Megatron-style TP placement — GSPMD
  inserts the per-layer collective the reference patches in by hand.
  "Kernel injection" is the flash/paged Pallas attention dispatch inside
  the model.
* **No CUDA-graph capture** (engine.py:517): one jitted, donated decode
  step with a ``lax`` token loop IS the captured graph; XLA replays it.
* KV cache: static ``[n_layers, batch, max_len, kv_heads, head_dim]``
  arrays (shape-stable for jit), sharded over the ``model`` axis on the
  head dim, donated between steps. The ragged/continuous-batching engine
  (FastGen v2 parity) lives in ``inference/ragged.py``.
* Checkpoint-sharded loading (engine.py:324 load_model_with_checkpoint):
  params load through orbax/device_put with the same placement rules.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..parallel.mesh import MESH_AXES, Topology, set_topology
from ..utils.logging import log_dist


@dataclass
class InferenceConfig:
    """Parity with reference ``DeepSpeedInferenceConfig``
    (deepspeed/inference/config.py): dtype, tensor_parallel.tp_size,
    max_out_tokens, replace_with_kernel_inject (accepted, meaningless here),
    quantization hooks."""

    dtype: str = "bfloat16"
    tensor_parallel: int = 1
    max_out_tokens: int = 2048
    min_out_tokens: int = 1
    replace_with_kernel_inject: bool = True   # accepted for API parity
    enable_cuda_graph: bool = False           # accepted; jit is the graph
    max_batch_size: int = 8
    temperature: float = 1.0
    top_k: int = 0                            # 0 = greedy unless temperature>0
    top_p: float = 1.0
    seed: int = 0
    # ZeRO-Inference weight-only quantization (reference
    # inference/quantization/: int8/int4 weights held quantized in HBM,
    # dequantized on the fly per forward): {"enabled": bool, "bits": 8|4,
    # "group_size": int}. Also accepted under the reference's "quant" key.
    quant: Dict[str, Any] = field(default_factory=dict)
    extras: Dict[str, Any] = field(default_factory=dict)

    @classmethod
    def from_any(cls, config: Union[None, Dict[str, Any], "InferenceConfig"],
                 **kwargs) -> "InferenceConfig":
        if isinstance(config, InferenceConfig):
            return config
        d = dict(config or {})
        d.update(kwargs)
        tp = d.pop("tensor_parallel", d.pop("mp_size", 1))
        if isinstance(tp, dict):
            tp = tp.get("tp_size", 1)
        quant = d.pop("quant", d.pop("quantization", {})) or {}
        if quant:
            d["quant"] = dict(quant)
        known = {f for f in cls.__dataclass_fields__ if f != "extras"}
        fields = {k: v for k, v in d.items() if k in known}
        extras = {k: v for k, v in d.items() if k not in known}
        return cls(tensor_parallel=int(tp), extras=extras, **fields)

    @property
    def jnp_dtype(self):
        return {"float32": jnp.float32, "fp32": jnp.float32,
                "float16": jnp.float16, "fp16": jnp.float16, "half": jnp.float16,
                "bfloat16": jnp.bfloat16, "bf16": jnp.bfloat16}[self.dtype]


def _is_wq(x) -> bool:
    return isinstance(x, dict) and "__wq__" in x


class InferenceEngine:
    """Generation engine over a deepspeed_tpu model (Transformer protocol:
    ``init``/``apply(params, tokens, kv_caches=..., cache_pos=...)``)."""

    def __init__(self, model: Any, config: Optional[InferenceConfig] = None,
                 params: Any = None, rng: Any = None):
        self.config = config or InferenceConfig()
        self.model = model
        tp = self.config.tensor_parallel
        n_dev = len(jax.devices())
        if tp > n_dev:
            raise ValueError(f"tensor_parallel={tp} > {n_dev} devices")
        from ..config import MeshConfig

        # inference mesh: model axis = tp, data axis = remaining devices
        self.topo = Topology.build(
            MeshConfig(data=n_dev // tp, model=tp),
            devices=jax.devices()[: (n_dev // tp) * tp])
        set_topology(self.topo)
        if hasattr(model, "bind_topology"):
            model.bind_topology(self.topo)

        if params is None:
            params = model.init(rng if rng is not None else
                                jax.random.PRNGKey(self.config.seed))
        params = jax.tree_util.tree_map(
            lambda x: x.astype(self.config.jnp_dtype)
            if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating) else x,
            params)
        specs = (model.partition_specs(params, self.topo)
                 if hasattr(model, "partition_specs") else None)
        if specs is not None:
            shardings = jax.tree_util.tree_map(
                lambda s: NamedSharding(self.topo.mesh, s), specs,
                is_leaf=lambda x: isinstance(x, P))
            params = jax.device_put(params, shardings)
        # ZeRO-Inference weight-only quantization: params are STORED int8/
        # int4 (+ fp32 block scales) in HBM and dequantized inside each
        # jitted forward — steady-state weight memory drops ~2x (bf16->int8)
        # / ~4x (->int4), the reference's fit-bigger-models win.
        self._quant_enabled = bool(self.config.quant.get("enabled", False))
        self._quant_bits = int(self.config.quant.get("bits", 8))
        self._quant_block = int(self.config.quant.get(
            "group_size", self.config.quant.get("block", 256)))
        if self._quant_enabled:
            params = self._quantize_tree(params)
            n_q = sum(1 for leaf in jax.tree_util.tree_leaves(
                params, is_leaf=_is_wq) if _is_wq(leaf))
            log_dist(f"ZeRO-Inference weight quant: {n_q} tensors at "
                     f"{self._quant_bits} bits, block {self._quant_block}")
        self.params = params
        self._prefill_fn = None
        self._decode_fn = None
        self._fwd_fn = None
        self._rng = jax.random.PRNGKey(self.config.seed)
        self._alloc_fns: Dict[Tuple, Callable] = {}  # avoid re-jit per call
        log_dist(f"InferenceEngine up: tp={tp} dtype={self.config.dtype}")

    # -- weight-only quantization (ZeRO-Inference) ----------------------
    def _quantize_tree(self, params):
        from ..ops.quantizer import quantize_blockwise

        bits, block = self._quant_bits, self._quant_block
        self._wq_shapes: Dict[str, Tuple[int, ...]] = {}

        def leaf(path, x):
            if (hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)
                    and getattr(x, "ndim", 0) >= 2 and x.size % block == 0):
                q, s, _ = quantize_blockwise(x, bits=bits, block=block)
                if bits == 4:
                    # REAL 4-bit residency: two nibbles per byte (int4 values
                    # in int8 storage would burn the same HBM as bits=8)
                    q4 = (q + 8).astype(jnp.uint8).reshape(-1, 2)
                    q = (q4[:, 0] | (q4[:, 1] << 4)).astype(jnp.uint8)
                self._wq_shapes[jax.tree_util.keystr(path)] = tuple(x.shape)
                return {"__wq__": q, "s": s}
            return x

        return jax.jit(
            lambda p: jax.tree_util.tree_map_with_path(leaf, p))(params)

    def _dequant_tree(self, params):
        from ..ops.quantizer import dequantize_blockwise

        if not self._quant_enabled:
            return params
        bits, block, dtype = (self._quant_bits, self._quant_block,
                              self.config.jnp_dtype)
        shapes = self._wq_shapes

        def leaf(path, d):
            if _is_wq(d):
                q = d["__wq__"]
                if bits == 4:
                    lo = (q & 0xF).astype(jnp.int8) - 8
                    hi = (q >> 4).astype(jnp.int8) - 8
                    q = jnp.stack([lo, hi], axis=-1).reshape(-1)
                shape = shapes[jax.tree_util.keystr(path)]
                return dequantize_blockwise(q, d["s"], block=block,
                                            dtype=dtype).reshape(shape)
            return d

        return jax.tree_util.tree_map_with_path(leaf, params, is_leaf=_is_wq)

    def param_bytes(self) -> int:
        """Device bytes of the stored (possibly quantized) weights."""
        total = 0
        for leaf in jax.tree_util.tree_leaves(self.params):
            total += leaf.size * jnp.dtype(leaf.dtype).itemsize
        return total

    # -- cache ---------------------------------------------------------
    def _alloc_cache(self, batch: int, max_len: int):
        c = self.model.config
        shape = (c.n_layers, batch, max_len, c.n_kv_heads, c.head_dim)
        sharding = self.topo.sharding(None, None, None, "model", None) \
            if self.topo.model_parallel_size > 1 and c.n_kv_heads % self.topo.model_parallel_size == 0 \
            else self.topo.replicated()
        alloc = self._alloc_fns.get(shape)
        if alloc is None:
            alloc = jax.jit(lambda: jnp.zeros(shape, self.config.jnp_dtype),
                            out_shardings=sharding)
            self._alloc_fns[shape] = alloc
        return (alloc(), alloc())

    # -- jitted programs ------------------------------------------------
    def _build_prefill(self):
        model = self.model

        def prefill(params, tokens, caches):
            # tokens: [b, s_prompt]; fills cache at [0, s); the head runs on
            # the LAST position only (a full-prompt [b, s, vocab] fp32 logits
            # tensor would be GBs at serving sizes)
            params = self._dequant_tree(params)
            logits, caches = model.apply(params, tokens, kv_caches=caches,
                                         cache_pos=0, last_token_only=True)
            return logits[:, 0, :], caches

        return jax.jit(prefill, donate_argnums=(2,))

    def _build_decode(self):
        model = self.model
        cfg = self.config

        def decode(params, caches, last_tokens, cache_pos, rng):
            # absolute position for RoPE angles / learned position embedding
            params = self._dequant_tree(params)
            positions = cache_pos[None, None]
            logits, caches = model.apply(
                params, last_tokens[:, None], positions=positions,
                kv_caches=caches, cache_pos=cache_pos)
            logits = logits[:, 0, :]
            next_tok = _sample(logits, rng, cfg.temperature, cfg.top_k, cfg.top_p)
            return caches, next_tok

        return jax.jit(decode, donate_argnums=(1,))

    # -- public API (parity: engine.generate / engine.forward) ----------
    def generate(self, input_ids, max_new_tokens: int = 64,
                 eos_token_id: Optional[int] = None) -> np.ndarray:
        """Greedy/sampled decode. input_ids: [b, s] int32 (right-aligned, no
        padding support yet — FastGen-style ragged batching handles mixed
        lengths in inference/ragged.py)."""
        input_ids = jnp.asarray(input_ids, jnp.int32)
        b, s = input_ids.shape
        if max_new_tokens <= 0:
            return np.asarray(input_ids)
        max_len = s + max_new_tokens
        assert max_len <= self.model.config.max_seq_len, (
            f"prompt+new tokens {max_len} exceeds model max_seq_len "
            f"{self.model.config.max_seq_len}")
        if self._prefill_fn is None:
            self._prefill_fn = self._build_prefill()
            self._decode_fn = self._build_decode()
        caches = self._alloc_cache(b, max_len)
        # per-engine RNG stream: successive generate() calls draw fresh keys
        # (the reference engine likewise does not reseed per request)
        self._rng, rng = jax.random.split(self._rng)
        rng, sub = jax.random.split(rng)
        logits, caches = self._prefill_fn(self.params, input_ids, caches)
        next_tok = _sample(logits, sub, self.config.temperature,
                           self.config.top_k, self.config.top_p)
        # per-row EOS: finished rows emit eos (padding) from then on
        finished = np.zeros((b,), bool)
        if eos_token_id is not None:
            finished |= np.asarray(next_tok) == eos_token_id
        out = [np.asarray(next_tok)]
        pos = s
        for i in range(max_new_tokens - 1):
            if finished.all():
                break
            rng, sub = jax.random.split(rng)
            caches, next_tok = self._decode_fn(
                self.params, caches, next_tok, jnp.asarray(pos, jnp.int32), sub)
            step = np.asarray(next_tok)
            if eos_token_id is not None:
                step = np.where(finished, eos_token_id, step)
                finished |= step == eos_token_id
                next_tok = jnp.asarray(step)
            out.append(step)
            pos += 1
        gen = np.stack(out, axis=1)
        return np.concatenate([np.asarray(input_ids), gen], axis=1)

    def forward(self, input_ids, **kw):
        """Raw logits forward (parity with InferenceEngine.forward :577)."""
        if self._fwd_fn is None:
            self._fwd_fn = jax.jit(
                lambda p, t: self.model.apply(self._dequant_tree(p), t))
        return self._fwd_fn(self.params, jnp.asarray(input_ids, jnp.int32))

    __call__ = forward


def _sample(logits, rng, temperature: float, top_k: int, top_p: float):
    """Greedy when temperature==0, else temperature/top-k/top-p sampling."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / jnp.maximum(temperature, 1e-6)
    if top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        cutoff_idx = jnp.sum(cum < top_p, axis=-1, keepdims=True)
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx, axis=-1)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)
