"""Inference engine (v1): TP-sharded generation with a static KV cache.

Capability parity with the reference's ``InferenceEngine``
(``deepspeed/inference/engine.py:39``) + the injection machinery it drives
(``deepspeed/module_inject/`` auto-TP / kernel containers), redesigned
TPU-first:

* **No module injection.** The reference walks an HF module tree swapping
  layers for fused-kernel containers and patching all-reduces into forward
  (replace_module.py:182, auto_tp.py). Here the model is already functional
  and its :meth:`partition_specs` carry Megatron-style TP placement — GSPMD
  inserts the per-layer collective the reference patches in by hand.
  "Kernel injection" is the flash/paged Pallas attention dispatch inside
  the model.
* **No CUDA-graph capture** (engine.py:517): one jitted, donated decode
  step with a ``lax`` token loop IS the captured graph; XLA replays it.
* KV cache: static ``[n_layers, batch, max_len, kv_heads, head_dim]``
  arrays (shape-stable for jit), sharded over the ``model`` axis on the
  head dim, donated between steps. The ragged/continuous-batching engine
  (FastGen v2 parity) lives in ``inference/ragged.py``.
* Checkpoint-sharded loading (engine.py:324 load_model_with_checkpoint):
  params load through orbax/device_put with the same placement rules.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..parallel.mesh import MESH_AXES, Topology, set_topology
from ..utils.logging import log_dist


@dataclass
class InferenceConfig:
    """Parity with reference ``DeepSpeedInferenceConfig``
    (deepspeed/inference/config.py): dtype, tensor_parallel.tp_size,
    max_out_tokens, replace_with_kernel_inject (accepted, meaningless here),
    quantization hooks."""

    dtype: str = "bfloat16"
    tensor_parallel: int = 1
    max_out_tokens: int = 2048
    min_out_tokens: int = 1
    replace_with_kernel_inject: bool = True   # accepted for API parity
    enable_cuda_graph: bool = False           # accepted; jit is the graph
    max_batch_size: int = 8
    temperature: float = 1.0
    top_k: int = 0                            # 0 = greedy unless temperature>0
    top_p: float = 1.0
    seed: int = 0
    # kernel backend of the comm facade (comm/backends.py): "auto" fuses
    # the TP decode MLP's all-reduce into the matmul on TPU (Pallas) and
    # keeps plain GSPMD collectives elsewhere; "pallas"/"xla" force it
    kernel_backend: str = "auto"              # auto | xla | pallas
    # ZeRO-Inference weight-only quantization (reference
    # inference/quantization/: int8/int4 weights held quantized in HBM,
    # dequantized on the fly per forward): {"enabled": bool, "bits": 8|4,
    # "group_size": int}. Also accepted under the reference's "quant" key.
    quant: Dict[str, Any] = field(default_factory=dict)
    extras: Dict[str, Any] = field(default_factory=dict)

    @classmethod
    def from_any(cls, config: Union[None, Dict[str, Any], "InferenceConfig"],
                 **kwargs) -> "InferenceConfig":
        if isinstance(config, InferenceConfig):
            return config
        d = dict(config or {})
        d.update(kwargs)
        tp = d.pop("tensor_parallel", d.pop("mp_size", 1))
        if isinstance(tp, dict):
            tp = tp.get("tp_size", 1)
        quant = d.pop("quant", d.pop("quantization", {})) or {}
        if quant:
            d["quant"] = dict(quant)
        known = {f for f in cls.__dataclass_fields__ if f != "extras"}
        fields = {k: v for k, v in d.items() if k in known}
        extras = {k: v for k, v in d.items() if k not in known}
        return cls(tensor_parallel=int(tp), extras=extras, **fields)

    @property
    def jnp_dtype(self):
        return {"float32": jnp.float32, "fp32": jnp.float32,
                "float16": jnp.float16, "fp16": jnp.float16, "half": jnp.float16,
                "bfloat16": jnp.bfloat16, "bf16": jnp.bfloat16}[self.dtype]


def _is_wq(x) -> bool:
    return isinstance(x, dict) and "__wq__" in x


class InferenceEngine:
    """Generation engine over a deepspeed_tpu model (Transformer protocol:
    ``init``/``apply(params, tokens, kv_caches=..., cache_pos=...)``)."""

    def __init__(self, model: Any, config: Optional[InferenceConfig] = None,
                 params: Any = None, rng: Any = None):
        self.config = config or InferenceConfig()
        self.model = model
        tp = self.config.tensor_parallel
        n_dev = len(jax.devices())
        if tp > n_dev:
            raise ValueError(f"tensor_parallel={tp} > {n_dev} devices")
        from ..config import MeshConfig

        # inference mesh: model axis = tp, data axis = remaining devices
        self.topo = Topology.build(
            MeshConfig(data=n_dev // tp, model=tp),
            devices=jax.devices()[: (n_dev // tp) * tp])
        set_topology(self.topo)
        if hasattr(model, "bind_topology"):
            model.bind_topology(self.topo)
        # fused kernel backend (comm/backends.py): under TP, bind it so
        # the decode MLP's all-reduce runs inside the matmul kernel
        # (models/transformer.py _down_proj) instead of as exposed
        # latency; the default XLA backend changes nothing, so it is
        # never bound
        from ..comm.backends import resolve_backend

        self.comm_backend = resolve_backend(self.config.kernel_backend)
        if (tp > 1 and self.comm_backend.name == "pallas"
                and hasattr(model, "bind_comm_backend")):
            model.bind_comm_backend(self.comm_backend)

        if params is None:
            params = model.init(rng if rng is not None else
                                jax.random.PRNGKey(self.config.seed))
        params = jax.tree_util.tree_map(
            lambda x: x.astype(self.config.jnp_dtype)
            if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating) else x,
            params)
        specs = (model.partition_specs(params, self.topo)
                 if hasattr(model, "partition_specs") else None)
        if specs is not None:
            shardings = jax.tree_util.tree_map(
                lambda s: NamedSharding(self.topo.mesh, s), specs,
                is_leaf=lambda x: isinstance(x, P))
            params = jax.device_put(params, shardings)
        # ZeRO-Inference weight-only quantization: params are STORED int8/
        # int4 (+ fp32 block scales) in HBM and dequantized inside each
        # jitted forward — steady-state weight memory drops ~2x (bf16->int8)
        # / ~4x (->int4), the reference's fit-bigger-models win.
        self._quant_enabled = bool(self.config.quant.get("enabled", False))
        self._quant_bits = int(self.config.quant.get("bits", 8))
        self._quant_block = int(self.config.quant.get(
            "group_size", self.config.quant.get("block", 256)))
        if self._quant_enabled:
            params = self._quantize_tree(params)
            n_q = sum(1 for leaf in jax.tree_util.tree_leaves(
                params, is_leaf=_is_wq) if _is_wq(leaf))
            log_dist(f"ZeRO-Inference weight quant: {n_q} tensors at "
                     f"{self._quant_bits} bits, block {self._quant_block}")
        self.params = params
        self._prefill_fn = None
        self._decode_fn = None
        self._fwd_fn = None
        self._rng = jax.random.PRNGKey(self.config.seed)
        self._alloc_fns: Dict[Tuple, Callable] = {}  # avoid re-jit per call
        log_dist(f"InferenceEngine up: tp={tp} dtype={self.config.dtype}")

    # -- weight-only quantization (ZeRO-Inference) ----------------------
    def _quantize_tree(self, params):
        from ..ops.quantizer import quantize_blockwise

        bits, block = self._quant_bits, self._quant_block
        self._wq_shapes: Dict[str, Tuple[int, ...]] = {}

        def leaf(path, x):
            if (hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)
                    and getattr(x, "ndim", 0) >= 2 and x.size % block == 0):
                q, s, _ = quantize_blockwise(x, bits=bits, block=block)
                if bits == 4:
                    # REAL 4-bit residency: two nibbles per byte (int4 values
                    # in int8 storage would burn the same HBM as bits=8)
                    q4 = (q + 8).astype(jnp.uint8).reshape(-1, 2)
                    q = (q4[:, 0] | (q4[:, 1] << 4)).astype(jnp.uint8)
                self._wq_shapes[jax.tree_util.keystr(path)] = tuple(x.shape)
                return {"__wq__": q, "s": s}
            return x

        return jax.jit(  # dslint: disable=recompile-hazard -- one-shot weight quantization at engine construction
            lambda p: jax.tree_util.tree_map_with_path(leaf, p))(params)

    def _dequant_tree(self, params):
        from ..ops.quantizer import dequantize_blockwise

        if not self._quant_enabled:
            return params
        bits, block, dtype = (self._quant_bits, self._quant_block,
                              self.config.jnp_dtype)
        shapes = self._wq_shapes

        def leaf(path, d):
            if _is_wq(d):
                q = d["__wq__"]
                if bits == 4:
                    lo = (q & 0xF).astype(jnp.int8) - 8
                    hi = (q >> 4).astype(jnp.int8) - 8
                    q = jnp.stack([lo, hi], axis=-1).reshape(-1)
                shape = shapes[jax.tree_util.keystr(path)]
                return dequantize_blockwise(q, d["s"], block=block,
                                            dtype=dtype).reshape(shape)
            return d

        return jax.tree_util.tree_map_with_path(leaf, params, is_leaf=_is_wq)

    def param_bytes(self) -> int:
        """Device bytes of the stored (possibly quantized) weights."""
        total = 0
        for leaf in jax.tree_util.tree_leaves(self.params):
            total += leaf.size * jnp.dtype(leaf.dtype).itemsize
        return total

    # -- cache ---------------------------------------------------------
    def _alloc_cache(self, batch: int, max_len: int):
        c = self.model.config
        shape = (c.n_layers, batch, max_len, c.n_kv_heads, c.head_dim)
        sharding = self.topo.sharding(None, None, None, "model", None) \
            if self.topo.model_parallel_size > 1 and c.n_kv_heads % self.topo.model_parallel_size == 0 \
            else self.topo.replicated()
        alloc = self._alloc_fns.get(shape)
        if alloc is None:
            alloc = jax.jit(lambda: jnp.zeros(shape, self.config.jnp_dtype),
                            out_shardings=sharding)
            self._alloc_fns[shape] = alloc
        return (alloc(), alloc())

    # -- jitted programs ------------------------------------------------
    def _build_prefill(self):
        model = self.model

        def prefill(params, tokens, caches):
            # tokens: [b, s_prompt]; fills cache at [0, s); the head runs on
            # the LAST position only (a full-prompt [b, s, vocab] fp32 logits
            # tensor would be GBs at serving sizes)
            params = self._dequant_tree(params)
            logits, caches = model.apply(params, tokens, kv_caches=caches,
                                         cache_pos=0, last_token_only=True)
            return logits[:, 0, :], caches

        return jax.jit(prefill, donate_argnums=(2,))

    def _build_decode(self):
        model = self.model
        cfg = self.config

        def decode(params, caches, last_tokens, cache_pos, rng):
            # absolute position for RoPE angles / learned position embedding
            params = self._dequant_tree(params)
            positions = cache_pos[None, None]
            logits, caches = model.apply(
                params, last_tokens[:, None], positions=positions,
                kv_caches=caches, cache_pos=cache_pos)
            logits = logits[:, 0, :]
            next_tok = _sample(logits, rng, cfg.temperature, cfg.top_k, cfg.top_p)
            return caches, next_tok

        return jax.jit(decode, donate_argnums=(1,))

    def _build_beam_step(self, beams: int):
        model = self.model

        def step(params, caches, last_tokens, cache_pos, scores):
            # last_tokens/scores: flat [b*beams]. Returns the updated caches
            # (new KV written in the CURRENT beam order) and the top
            # 2*beams candidate (score, beams*V index) per row — enough
            # non-eos candidates to always refill `beams` live beams
            # (HF beam_search's 2k trick).
            params = self._dequant_tree(params)
            logits, caches = model.apply(
                params, last_tokens[:, None], positions=cache_pos[None, None],
                kv_caches=caches, cache_pos=cache_pos)
            logp = jax.nn.log_softmax(logits[:, 0].astype(jnp.float32), -1)
            V = logp.shape[-1]
            total = scores.reshape(-1, beams)[:, :, None] + logp.reshape(-1, beams, V)
            top_scores, top_idx = jax.lax.top_k(
                total.reshape(-1, beams * V), 2 * beams)
            return caches, top_scores, top_idx

        gather = jax.jit(
            lambda caches, idx: jax.tree_util.tree_map(
                lambda c: c[:, idx], caches),
            donate_argnums=(0,))
        return jax.jit(step, donate_argnums=(1,)), gather

    def _generate_beam(self, input_ids, max_new_tokens: int, num_beams: int,
                       eos_token_id: Optional[int],
                       length_penalty: float = 1.0) -> np.ndarray:
        """Deterministic beam search with HF ``generate(num_beams=N)``
        semantics (the reference engine reaches it through the wrapped HF
        module): per row, EOS candidates among the top-2k move to a
        finished-hypothesis pool (kept if the pool has room or they beat
        its worst entry), live beams refill to k from the rest, and rows
        stop when the pool is full and no live beam can still beat it.
        Scores normalize by full sequence length ** length_penalty."""
        k = num_beams
        b, s = input_ids.shape
        max_len = s + max_new_tokens
        assert max_len <= self.model.config.max_seq_len
        if self._prefill_fn is None:
            self._prefill_fn = self._build_prefill()
            self._decode_fn = self._build_decode()
        fns = self._alloc_fns.get(("beam", k))
        if fns is None:
            fns = self._build_beam_step(k)
            self._alloc_fns[("beam", k)] = fns
        beam_step, cache_gather = fns

        caches = self._alloc_cache(b, max_len)
        logits, caches = self._prefill_fn(self.params, input_ids, caches)
        logp0 = jax.nn.log_softmax(logits.astype(jnp.float32), -1)  # [b, V]
        # expand caches to [L, b*k, ...] AFTER the (1x) prefill
        caches = jax.tree_util.tree_map(
            lambda c: jnp.repeat(c, k, axis=1), caches)

        eos = eos_token_id
        lp = length_penalty
        V = self.model.config.vocab_size
        # pools[r]: finished hypotheses (sum_logprobs, gen_tokens WITHOUT
        # the closing eos, norm_len). HF (4.4x) normalization: sum /
        # GENERATED length ** lp, where a pooled hypothesis counts its
        # closing eos and the prompt never counts.
        pools = [[] for _ in range(b)]
        done = np.zeros((b,), bool)
        live_scores = np.zeros((b, k), np.float32)
        live_seqs = np.zeros((b, k, 0), np.int64)

        def norm(score_sum, gen_len):
            return score_sum / float(gen_len) ** lp

        def select(cand_scores, cand_idx):
            """HF BeamSearchScorer.process: walk the 2k candidates per row
            in score order; eos candidates enter the pool (if it has room
            or they beat its worst), others refill k live beams."""
            nonlocal live_scores, live_seqs
            parents = np.zeros((b, k), np.int64)
            new_scores = live_scores.copy()
            new_tokens = np.zeros((b, k), np.int64)
            for r in range(b):
                if done[r]:
                    parents[r] = np.arange(k)   # frozen; results ignored
                    new_tokens[r] = eos if eos is not None else 0
                    continue
                filled = 0
                for rank, (sc, idx) in enumerate(zip(cand_scores[r],
                                                     cand_idx[r])):
                    parent, tok = divmod(int(idx), V)
                    if eos is not None and tok == eos:
                        if rank >= k:  # HF: eos beyond the top-k ranks is
                            continue   # dropped, never pooled
                        hyp = live_seqs[r, parent].copy()
                        nl = len(hyp) + 1  # closing eos counts (HF
                        # process: generated_len = cur_len - prompt_len)
                        if len(pools[r]) < k:
                            pools[r].append((float(sc), hyp, nl))
                        else:
                            worst_i = min(range(k), key=lambda i: norm(
                                pools[r][i][0], pools[r][i][2]))
                            if norm(float(sc), nl) > norm(
                                    pools[r][worst_i][0],
                                    pools[r][worst_i][2]):
                                pools[r][worst_i] = (float(sc), hyp, nl)
                        continue
                    parents[r, filled] = parent
                    new_scores[r, filled] = sc
                    new_tokens[r, filled] = tok
                    filled += 1
                    if filled == k:
                        break
            live_scores = new_scores
            live_seqs = np.take_along_axis(live_seqs, parents[:, :, None],
                                           axis=1)
            live_seqs = np.concatenate([live_seqs, new_tokens[:, :, None]],
                                       axis=2)
            if eos is not None:
                cur = live_seqs.shape[2]
                for r in range(b):
                    if not done[r] and len(pools[r]) >= k:
                        # early_stopping=False heuristic (HF
                        # _check_early_stop_heuristic): stop when the best
                        # RUNNING beam's sum, normalized at the current
                        # generated length, cannot beat the pool's worst
                        # (live_scores[r, 0] is the best non-eos candidate
                        # — selection fills in score order)
                        worst = min(norm(sc, nl) for sc, _, nl in pools[r])
                        done[r] = worst >= norm(float(live_scores[r, 0]),
                                                cur)
            return parents

        # first token step: every beam is identical, so the top-2k of the
        # prefill logits ARE the candidates (HF beam_scores init trick)
        cs0, ci0 = jax.lax.top_k(logp0, 2 * k)
        select(np.asarray(cs0), np.asarray(ci0))  # parents all 0: no gather

        pos = s
        for _ in range(max_new_tokens - 1):
            if done.all():
                break
            caches, cand_scores, cand_idx = beam_step(
                self.params, caches, jnp.asarray(live_seqs[:, :, -1]
                                                 .reshape(-1), jnp.int32),
                jnp.asarray(pos, jnp.int32),
                jnp.asarray(live_scores.reshape(-1), jnp.float32))
            parents = select(np.asarray(cand_scores), np.asarray(cand_idx))
            flat_parent = (np.arange(b)[:, None] * k + parents).reshape(-1)
            if not (flat_parent == np.arange(b * k)).all():
                # identity permutations (stable beams, done rows, and the
                # final iteration) skip the full-cache copy
                caches = cache_gather(caches, jnp.asarray(flat_parent))
            pos += 1

        # finalize (HF): open rows contribute their live beams to the pool;
        # output = gen (+ closing eos if finished) + eos padding
        out = np.full((b, max_new_tokens),
                      eos if eos is not None else 0, np.int64)
        longest = 0
        for r in range(b):
            hyps = [(sc, g, nl, True) for sc, g, nl in pools[r]]
            if len(pools[r]) < k or not done[r]:
                # HF finalize: open live beams normalize by their generated
                # length (no eos to count)
                hyps += [(float(live_scores[r, j]), live_seqs[r, j],
                          live_seqs.shape[2], False) for j in range(k)]
            best = max(hyps, key=lambda h: norm(h[0], h[2]))
            gen = np.asarray(best[1], np.int64)
            if best[3] and eos is not None and len(gen) < max_new_tokens:
                gen = np.append(gen, eos)
            gen = gen[:max_new_tokens]
            out[r, : len(gen)] = gen
            longest = max(longest, len(gen))
        # HF crops the batch to the longest returned generation (rows that
        # finished earlier are eos-padded up to it)
        return np.concatenate([np.asarray(input_ids), out[:, :longest]],
                              axis=1)

    # -- public API (parity: engine.generate / engine.forward) ----------
    def generate(self, input_ids, max_new_tokens: int = 64,
                 eos_token_id: Optional[int] = None, num_beams: int = 1,
                 length_penalty: float = 1.0) -> np.ndarray:
        """Greedy/sampled decode (or beam search when num_beams > 1).
        input_ids: [b, s] int32 (right-aligned, no
        padding support yet — FastGen-style ragged batching handles mixed
        lengths in inference/ragged.py)."""
        input_ids = jnp.asarray(input_ids, jnp.int32)
        if num_beams > 1:  # beam search is deterministic (sampling ignored)
            if max_new_tokens <= 0:
                return np.asarray(input_ids)
            return self._generate_beam(input_ids, max_new_tokens, num_beams,
                                       eos_token_id, length_penalty)
        b, s = input_ids.shape
        if max_new_tokens <= 0:
            return np.asarray(input_ids)
        max_len = s + max_new_tokens
        assert max_len <= self.model.config.max_seq_len, (
            f"prompt+new tokens {max_len} exceeds model max_seq_len "
            f"{self.model.config.max_seq_len}")
        if self._prefill_fn is None:
            self._prefill_fn = self._build_prefill()
            self._decode_fn = self._build_decode()
        # request telemetry: TTFT + decode throughput. The timestamps ride
        # host fetches the loop performs anyway (np.asarray per token), so
        # instrumentation adds no extra device sync either way.
        from ..telemetry import get_telemetry

        telem = get_telemetry()
        t_start = time.perf_counter()
        caches = self._alloc_cache(b, max_len)
        # per-engine RNG stream: successive generate() calls draw fresh keys
        # (the reference engine likewise does not reseed per request)
        self._rng, rng = jax.random.split(self._rng)
        rng, sub = jax.random.split(rng)
        logits, caches = self._prefill_fn(self.params, input_ids, caches)
        next_tok = _sample(logits, sub, self.config.temperature,
                           self.config.top_k, self.config.top_p)
        # per-row EOS: finished rows emit eos (padding) from then on
        finished = np.zeros((b,), bool)
        if eos_token_id is not None:
            finished |= np.asarray(next_tok) == eos_token_id
        out = [np.asarray(next_tok)]
        t_first = time.perf_counter()  # first token materialized on host
        n_generated = b  # real tokens produced (finished rows emit padding)
        pos = s
        for i in range(max_new_tokens - 1):
            if finished.all():
                break
            rng, sub = jax.random.split(rng)
            n_generated += int(b - finished.sum())
            caches, next_tok = self._decode_fn(
                self.params, caches, next_tok, jnp.asarray(pos, jnp.int32), sub)
            step = np.asarray(next_tok)
            if eos_token_id is not None:
                step = np.where(finished, eos_token_id, step)
                finished |= step == eos_token_id
                next_tok = jnp.asarray(step)
            out.append(step)
            pos += 1
        gen = np.stack(out, axis=1)
        if telem.enabled:
            t_end = time.perf_counter()
            decode_s = t_end - t_first
            n_decoded = n_generated - b
            telem.record_request(
                latency_s=t_end - t_start, ttft_s=t_first - t_start,
                new_tokens=n_generated,
                decode_tokens_per_s=(n_decoded / decode_s
                                     if n_decoded and decode_s > 0 else None))
        return np.concatenate([np.asarray(input_ids), gen], axis=1)

    def forward(self, input_ids, **kw):
        """Raw logits forward (parity with InferenceEngine.forward :577)."""
        if self._fwd_fn is None:
            self._fwd_fn = jax.jit(
                lambda p, t: self.model.apply(self._dequant_tree(p), t))
        return self._fwd_fn(self.params, jnp.asarray(input_ids, jnp.int32))

    __call__ = forward


def _sample(logits, rng, temperature: float, top_k: int, top_p: float):
    """Greedy when temperature==0, else temperature/top-k/top-p sampling."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / jnp.maximum(temperature, 1e-6)
    if top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        cutoff_idx = jnp.sum(cum < top_p, axis=-1, keepdims=True)
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx, axis=-1)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)
