"""Inference stack: v1 TP-sharded generation engine (engine.py) and the
FastGen-v2-parity ragged/continuous-batching engine (ragged.py).

Reference surface: deepspeed/inference/ (engine.py, config.py) + v2
(engine_v2.py, ragged/).
"""

from .engine import InferenceConfig, InferenceEngine

__all__ = ["InferenceConfig", "InferenceEngine"]
