"""Device mesh and topology management.

TPU-native replacement for the reference's process-group machinery
(``deepspeed/utils/groups.py`` — data/model/expert/sequence parallel group
creation and caching, plus ``runtime/pipe/topology.py`` ProcessTopology /
PipeModelDataParallelGrid). Instead of creating and caching
``torch.distributed`` groups per parallelism flavor, we build ONE
``jax.sharding.Mesh`` with named axes

    (data, seq, pipe, expert, model)

and every "group" from the reference becomes an axis name (or tuple of axis
names) that collectives/shardings refer to. Hierarchy: the axis order places
``model`` innermost so tensor-parallel collectives ride the fastest ICI
links, matching how the reference nests model-parallel groups inside nodes
(groups.py:64 _create_model_parallel).

The reference's derived groups map as:
  data_parallel group          -> axis 'data'
  model_parallel group         -> axis 'model'
  pipe stages                  -> axis 'pipe'
  expert_parallel group        -> axis 'expert' (reference: _create_expert_and_data_parallel, groups.py:113)
  expert_data_parallel group   -> axes ('data',) with expert folded — see expert_data_axes()
  sequence_parallel group      -> axis 'seq' (groups.py:468 _get_sequence_parallel_group)
  sequence_data_parallel group -> axes ('data','seq') (groups.py:489)
  ZeRO param-partition group   -> axes ('data','seq') — ZeRO shards over all
                                  replica dimensions (engine.py:1122 uses the
                                  seq_data_parallel group as ZeRO's dp group)
"""

from __future__ import annotations

import os
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..config import MeshConfig
from ..utils.logging import log_dist

# Canonical axis order, outermost → innermost. 'zshard' is the secondary
# ZeRO partition axis (size 1 unless ZeRO++ hpZ / MiCS factor the data
# dimension): data-parallel replicas are laid out as data × zshard with
# zshard the *inner* (intra-slice, fast-ICI) factor — the analog of the
# reference's intra-node secondary groups (utils/groups.py:356
# _create_zero_param_parallel_group, runtime/zero/mics.py:55 MiCS_Init).
MESH_AXES: Tuple[str, ...] = ("data", "zshard", "seq", "pipe", "expert", "model")


class Topology:
    """Owns the device mesh and answers every group/rank/size query.

    The reference answers these via cached torch process groups
    (groups.py get_*_parallel_group/rank/world_size); here they are simple
    mesh-shape lookups.
    """

    def __init__(self, mesh: Mesh):
        self.mesh = mesh
        self._sizes = dict(zip(mesh.axis_names, mesh.devices.shape))

    # -- construction ---------------------------------------------------
    @classmethod
    def build(cls, mesh_config: Optional[MeshConfig] = None,
              devices: Optional[Sequence[jax.Device]] = None,
              zero_inner: int = 1) -> "Topology":
        """``zero_inner`` > 1 factors the data-parallel dimension into
        data × zshard (zshard = inner, size ``zero_inner``) for ZeRO++ hpZ /
        MiCS hierarchical sharding."""
        mesh_config = mesh_config or MeshConfig()
        if devices is None:
            devices = jax.devices()
        sizes = mesh_config.resolve(len(devices))
        sizes.setdefault("zshard", 1)
        if zero_inner > 1:
            dp = sizes["data"] * sizes["zshard"]
            if dp % zero_inner != 0:
                raise ValueError(
                    f"zero_inner={zero_inner} must divide the data-parallel "
                    f"size {dp} (hpz_partition_size / mics_shard_size)")
            sizes["data"], sizes["zshard"] = dp // zero_inner, zero_inner
        shape = tuple(sizes[a] for a in MESH_AXES)
        dev_array = np.asarray(devices).reshape(shape)
        mesh = Mesh(dev_array, MESH_AXES)
        log_dist(f"Built device mesh {dict(zip(MESH_AXES, shape))} over {len(devices)} devices")
        return cls(mesh)

    @classmethod
    def build_virtual(cls, sizes: Dict[str, int]) -> "Topology":
        """Build a mesh with explicit axis sizes (tests / dry runs), using
        only as many devices as the axes require. A 'zshard' entry factors
        the data dimension (hpZ / MiCS inner partition)."""
        inner = sizes.get("zshard", 1)
        cfg = MeshConfig(**{a: sizes.get(a, 1) for a in MeshConfig.AXES})
        n = 1
        for a in MeshConfig.AXES:
            n *= sizes.get(a, 1)
        return cls.build(cfg, devices=jax.devices()[:n], zero_inner=inner)

    # -- size / rank queries (parity with groups.py get_* helpers) ------
    def axis_size(self, axis: str) -> int:
        return self._sizes[axis]

    @property
    def world_size(self) -> int:
        return int(np.prod(list(self._sizes.values())))

    @property
    def data_parallel_size(self) -> int:
        return self._sizes["data"] * self._sizes["zshard"]

    @property
    def zero_secondary_size(self) -> int:
        """Size of the inner (hpZ / MiCS) partition factor."""
        return self._sizes["zshard"]

    def data_axes(self) -> Tuple[str, ...]:
        """Mesh axes jointly forming the data-parallel dimension."""
        return ("data", "zshard") if self._sizes["zshard"] > 1 else ("data",)

    @property
    def model_parallel_size(self) -> int:
        return self._sizes["model"]

    @property
    def pipe_parallel_size(self) -> int:
        return self._sizes["pipe"]

    @property
    def expert_parallel_size(self) -> int:
        return self._sizes["expert"]

    @property
    def sequence_parallel_size(self) -> int:
        return self._sizes["seq"]

    @property
    def sequence_data_parallel_size(self) -> int:
        # reference groups.py:489 _get_sequence_data_parallel_group
        return self._sizes["seq"] * self.data_parallel_size

    def zero_partition_axes(self) -> Tuple[str, ...]:
        """Axes ZeRO shards params/grads/optimizer state over.

        The reference uses the (seq-)data-parallel group as ZeRO's dp group
        (engine.py:1122); expert replicas join for non-expert params.
        """
        axes = [a for a in ("data", "zshard", "seq") if self._sizes[a] > 1]
        return tuple(axes) if axes else ("data",)

    def zero_secondary_axes(self) -> Tuple[str, ...]:
        """Inner partition axes for hpZ secondary param shards / MiCS
        sub-group sharding (reference partition_parameters.py:883,
        mics.py:227): the fast-ICI factor of the data dimension (+ seq)."""
        axes = [a for a in ("zshard", "seq") if self._sizes[a] > 1]
        return tuple(axes) if axes else ("zshard",)

    def expert_data_axes(self) -> Tuple[str, ...]:
        """Replica axes for expert parameters (expert-data-parallel group,
        reference groups.py:113)."""
        axes = [a for a in ("data", "zshard", "seq") if self._sizes[a] > 1]
        return tuple(axes) if axes else ("data",)

    # -- sharding helpers ----------------------------------------------
    def sharding(self, *spec) -> NamedSharding:
        return NamedSharding(self.mesh, PartitionSpec(*spec))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, PartitionSpec())

    def data_sharding(self, ndim: int = 1) -> NamedSharding:
        """Batch sharding: leading dim over the data axes — and 'seq' folds
        into batch for the dataloader when sequence parallelism is off."""
        spec: list = [None] * ndim
        axes = self.data_axes()
        # bare name for a single axis: 0.4.x PartitionSpec does not
        # normalize 1-tuples, so ('data',) and 'data' compare unequal
        spec[0] = axes[0] if len(axes) == 1 else axes
        return NamedSharding(self.mesh, PartitionSpec(*spec))

    def batch_sharding(self, ndim: int = 2) -> NamedSharding:
        """[batch, seq, ...] sharding: batch over the data axes, seq over
        'seq'."""
        spec: list = [None] * ndim
        axes = self.data_axes()
        # bare name for a single axis: 0.4.x PartitionSpec does not
        # normalize 1-tuples, so ('data',) and 'data' compare unequal
        spec[0] = axes[0] if len(axes) == 1 else axes
        if ndim > 1 and self._sizes["seq"] > 1:
            spec[1] = "seq"
        return NamedSharding(self.mesh, PartitionSpec(*spec))

    def __repr__(self) -> str:
        return f"Topology({self._sizes})"


# ----------------------------------------------------------------------
# Module-level singleton, mirroring the reference's groups.py module state.
_TOPOLOGY: Optional[Topology] = None


def initialize_topology(mesh_config: Optional[MeshConfig] = None,
                        devices: Optional[Sequence[jax.Device]] = None,
                        force: bool = False) -> Topology:
    global _TOPOLOGY
    if _TOPOLOGY is None or force:
        _TOPOLOGY = Topology.build(mesh_config, devices)
    return _TOPOLOGY


def get_topology() -> Topology:
    if _TOPOLOGY is None:
        return initialize_topology()
    return _TOPOLOGY


def set_topology(topo: Topology) -> None:
    global _TOPOLOGY
    _TOPOLOGY = topo


def reset_topology() -> None:
    global _TOPOLOGY
    _TOPOLOGY = None


def shard_map_compat(f, *, mesh, in_specs, out_specs,
                     axis_names=None, check_vma=None):
    """``jax.shard_map`` with a jax 0.4.x fallback.

    The public ``jax.shard_map`` (and its ``axis_names=``/``check_vma=``
    kwargs) only exists on jax >= 0.5; 0.4.x ships
    ``jax.experimental.shard_map.shard_map`` where the same contract is
    spelled ``auto = mesh axes NOT in axis_names`` and
    ``check_rep = check_vma``. Every shard_map in the package goes
    through here so version skew lives in exactly one place.
    """
    if hasattr(jax, "shard_map"):
        kw = {}
        if axis_names is not None:
            kw["axis_names"] = set(axis_names)
        if check_vma is not None:
            kw["check_vma"] = check_vma
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, **kw)
    from jax.experimental.shard_map import shard_map as _shard_map

    kw = {}
    if check_vma is not None:
        kw["check_rep"] = check_vma
    # NB: axis_names is deliberately DROPPED on 0.4.x (fully-manual
    # mode). The experimental API spells partial-manual as
    # ``auto = complement(axis_names)``, but on 0.4.37 that path is
    # broken for our programs: size-1 auto axes abort XLA CPU outright,
    # and >1 auto axes hit "PartitionId instruction is not supported
    # for SPMD partitioning" wherever the body takes an axis_index.
    # Fully-manual is semantically equivalent for these call sites —
    # unnamed axes appear in no in/out spec and no body collective —
    # and is the spelling the ragged engine's fallback already proved.
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kw)


def collective_axis_size(axis_name: str) -> int:
    """Static size of a named axis INSIDE a shard_map/pmap body.

    ``jax.lax.axis_size`` only exists on jax >= 0.5; on 0.4.x,
    ``psum(1, axis)`` of the literal constant folds to a plain Python
    int under shard_map — the same static value, nothing on the wire.
    """
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)
