"""Ulysses-style sequence parallelism.

Capability parity with the reference's DeepSpeed-Ulysses
(``deepspeed/sequence/layer.py`` — ``DistributedAttention`` wrapping any
local attention with ``_SeqAllToAll``: inputs sharded ``[s/P, b, h]`` are
all-to-all'd to ``[s, b, h/P]`` so attention runs with full sequence but
sharded heads, then transformed back; SURVEY.md §5.7). TPU-native form:
the all-to-all rides the ``seq`` mesh axis via ``jax.lax.all_to_all``
inside ``shard_map``, composing with the batch sharding the engine already
applies ([b/data, s/seq, ...]).

The reference's ``seq_parallel_communication_data_type`` knob
(runtime/config.py:795) maps to ``comm_dtype`` below.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.attention import dot_product_attention
from .mesh import collective_axis_size, shard_map_compat


def _a2a(x, axis_name: str, split_axis: int, concat_axis: int):
    """tiled all-to-all: scatter ``split_axis``, gather ``concat_axis``."""
    return jax.lax.all_to_all(x, axis_name, split_axis=split_axis,
                              concat_axis=concat_axis, tiled=True)


def ulysses_attention(q, k, v, *, axis_name: str = "seq", causal: bool = True,
                      attn_fn: Optional[Callable] = None, comm_dtype=None):
    """Head-scattering attention for seq-sharded inputs.

    Call INSIDE shard_map where q/k/v are local shards [b, s/P, h, d].
    All-to-all swaps seq-sharding for head-sharding ([b, s, h/P, d]),
    runs full-sequence attention on the local heads, and swaps back.
    Requires n_heads % P == 0 (same constraint as the reference,
    sequence/layer.py head-count divisibility).
    """
    attn_fn = attn_fn or partial(dot_product_attention, causal=causal)
    orig_dtype = q.dtype
    if comm_dtype is not None:
        q, k, v = (t.astype(comm_dtype) for t in (q, k, v))
    # GQA: when the local kv-head count doesn't divide the seq axis (e.g.
    # TP already sharded kv heads down to 1), repeat each kv head just
    # enough to scatter — numerics-identical, it's the GQA broadcast done
    # before the a2a instead of inside attention (reference Ulysses does
    # the same for GQA models, sequence/layer.py head-repeat path)
    P_ = collective_axis_size(axis_name)   # 0.4.x: no jax.lax.axis_size
    kvh = k.shape[2]
    if kvh % P_ != 0:
        r = P_ // math.gcd(kvh, P_)
        k = jnp.repeat(k, r, axis=2)
        v = jnp.repeat(v, r, axis=2)
    # [b, s/P, h, d] -> [b, s, h/P, d]
    q, k, v = (_a2a(t, axis_name, split_axis=2, concat_axis=1) for t in (q, k, v))
    if comm_dtype is not None:
        q, k, v = (t.astype(orig_dtype) for t in (q, k, v))
    out = attn_fn(q, k, v)
    if comm_dtype is not None:
        out = out.astype(comm_dtype)
    # [b, s, h/P, d] -> [b, s/P, h, d]
    out = _a2a(out, axis_name, split_axis=1, concat_axis=2)
    return out.astype(orig_dtype)


class DistributedAttention:
    """Module-level parity with the reference's
    ``deepspeed.sequence.layer.DistributedAttention`` (layer.py:61): wraps a
    local attention callable; __call__ takes seq-sharded global arrays and
    runs the a2a dance under shard_map on the given mesh."""

    def __init__(self, local_attention: Callable, mesh: Mesh,
                 scatter_idx: int = 2, gather_idx: int = 1,
                 axis_name: str = "seq", comm_dtype=None,
                 batch_axes=None, head_axes=None):
        self.local_attn = local_attention
        self.mesh = mesh
        self.axis_name = axis_name
        self.comm_dtype = comm_dtype
        # batch/head axes must NAME the activations' existing sharding
        # (batch over the data axes, heads over 'model' under TP) — a spec
        # of None on a sharded dim forces GSPMD to replicate-then-reshard
        # at the shard_map boundary ("involuntary full rematerialization")
        self.batch_axes = batch_axes
        self.head_axes = head_axes
        # scatter/gather idx kept for API parity; fixed [b, s, h, d] layout

    def __call__(self, q, k, v, causal: bool = True):
        spec = P(self.batch_axes, self.axis_name, self.head_axes, None)

        def inner(q, k, v):
            return ulysses_attention(
                q, k, v, axis_name=self.axis_name, causal=causal,
                attn_fn=partial(self.local_attn, causal=causal),
                comm_dtype=self.comm_dtype)

        return shard_map_compat(
            inner, mesh=self.mesh, in_specs=(spec, spec, spec),
            out_specs=spec, check_vma=False)(q, k, v)
