"""Mixture-of-Experts: gating + expert-parallel dispatch.

Capability parity with the reference's ``deepspeed/moe/``:
  - ``TopKGate`` (sharded_moe.py:393; top1gating :184, top2gating :282) —
    top-1/top-2 routing with capacity factor, load-balancing aux loss,
    random token priority, min-capacity floor;
  - ``MOELayer`` (sharded_moe.py:425) — einsum dispatch → ``_AllToAll``
    (:95) over the expert-parallel group → local expert FFNs
    (moe/experts.py) → all-to-all back + weighted combine;
  - drop-token capacity semantics.

TPU-native redesign: the dispatch/combine einsums ARE the GShard dense
formulation, which XLA lowers onto the MXU; expert weights are stacked
``[E, ...]`` and sharded over the ``expert`` mesh axis, so GSPMD inserts
the all-to-alls the reference issues by hand through autograd functions.
No per-expert Python loop exists at any point.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@dataclass
class GateConfig:
    n_experts: int = 8
    top_k: int = 2                    # 1 or 2 (reference supports k in {1,2})
    capacity_factor: float = 1.25     # train capacity (reference default 1.0/1.25)
    eval_capacity_factor: float = 2.0
    min_capacity: int = 4             # reference sharded_moe.py min_capacity
    noisy_gate_policy: Optional[str] = None  # None | 'RSample' | 'Jitter'
    drop_tokens: bool = True
    aux_loss_weight: float = 0.01


def capacity(tokens_per_group: int, cfg: GateConfig, training: bool) -> int:
    if not cfg.drop_tokens:
        # no-drop mode: static shapes force the worst-case bound (every token
        # routed to one expert). The reference grows capacity to the observed
        # max load at runtime (sharded_moe.py drop_tokens=False path); under
        # XLA the conservative static bound is the equivalent guarantee.
        return tokens_per_group
    f = cfg.capacity_factor if training else cfg.eval_capacity_factor
    cap = int(np.ceil(tokens_per_group * f * cfg.top_k / cfg.n_experts))
    return max(cap, cfg.min_capacity)


def _one_hot(idx, n):
    return jax.nn.one_hot(idx, n, dtype=jnp.float32)


def top_k_gating(logits: jnp.ndarray, cfg: GateConfig, cap: int,
                 rng: Optional[jax.Array] = None, training: bool = True
                 ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Compute combine weights + dispatch mask for top-1/top-2 routing.

    logits: [S, E] per-group router logits.
    Returns (combine [S, E, C], dispatch bool [S, E, C], aux_loss scalar).

    Mirrors reference top1gating/top2gating: softmax probs, greedy expert
    choice (optionally noisy), position-in-expert via a cumsum over the
    token dimension, tokens beyond capacity dropped, load-balance loss
    = E * mean(probs_per_expert) . mean(assignment_per_expert).
    """
    S, E = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    if cfg.noisy_gate_policy == "RSample" and training and rng is not None:
        noisy = logits + jax.random.gumbel(rng, logits.shape)
        idx1 = jnp.argmax(noisy, axis=-1)
    else:
        idx1 = jnp.argmax(probs, axis=-1)
    mask1 = _one_hot(idx1, E)                                  # [S, E]

    # load-balancing aux loss (GShard eq.; reference l_aux in top*gating)
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(mask1, axis=0)
    aux = jnp.sum(me * ce) * E

    # position of each token within its expert's queue
    pos1 = jnp.cumsum(mask1, axis=0) - mask1                    # [S, E]
    pos1_tok = jnp.sum(pos1 * mask1, axis=1)                    # [S]
    if cfg.drop_tokens:
        keep1 = pos1_tok < cap
        mask1 = mask1 * keep1[:, None]

    gates1 = jnp.sum(probs * mask1, axis=1)                     # [S]

    if cfg.top_k == 2:
        probs2 = probs * (1.0 - _one_hot(idx1, E))
        idx2 = jnp.argmax(probs2, axis=-1)
        mask2 = _one_hot(idx2, E)
        pos2 = jnp.cumsum(mask2, axis=0) - mask2 + jnp.sum(mask1, axis=0, keepdims=True)
        pos2_tok = jnp.sum(pos2 * mask2, axis=1)
        if cfg.drop_tokens:
            keep2 = pos2_tok < cap
            mask2 = mask2 * keep2[:, None]
        gates2 = jnp.sum(probs * mask2, axis=1)
        denom = jnp.maximum(gates1 + gates2, 1e-9)
        gates1, gates2 = gates1 / denom, gates2 / denom
        combine = (gates1[:, None, None] * mask1[:, :, None] * _one_hot(pos1_tok, cap)[:, None, :]
                   + gates2[:, None, None] * mask2[:, :, None] * _one_hot(pos2_tok, cap)[:, None, :])
    else:
        combine = gates1[:, None, None] * mask1[:, :, None] * _one_hot(pos1_tok, cap)[:, None, :]

    dispatch = combine > 0
    return combine.astype(jnp.float32), dispatch, aux


def no_drop_moe(x_flat: jnp.ndarray, probs: jnp.ndarray, idx: jnp.ndarray,
                params: Dict[str, Any], activation: str) -> jnp.ndarray:
    """Sort-based NO-DROP expert dispatch on grouped GEMMs.

    The TPU analog of FastGen's ``moe_gather``/``moe_scatter`` +
    CUTLASS grouped GEMM (reference
    ``inference/v2/kernels/ragged_ops/{moe_gather,moe_scatter}`` and
    ``kernels/cutlass_ops/moe_gemm``): (token, k) pairs are sorted by
    expert id, each expert's contiguous segment runs through
    ``jax.lax.ragged_dot`` (the MXU grouped GEMM), and outputs
    scatter-add back weighted by the gate. No capacity buffers — no token
    is ever dropped and no [S, E, C] combine tensor exists, so serving
    output is independent of co-scheduled traffic.

    x_flat: [S, d]; probs/idx: [S, k] top-k gate weights / expert ids.
    """
    S, k = idx.shape
    E = params["w_up"].shape[0]
    flat_e = idx.reshape(-1)                          # [S*k]
    order = jnp.argsort(flat_e)                       # stable: tokens in order
    tok = jnp.repeat(jnp.arange(S), k)[order]         # source token per pair
    xs = x_flat[tok]                                  # moe_gather
    group_sizes = jnp.bincount(flat_e, length=E).astype(jnp.int32)

    e_sorted = flat_e[order]                          # expert id per row
    if activation == "silu_glu":
        h = jax.nn.silu(jax.lax.ragged_dot(xs, params["w_gate"], group_sizes)) \
            * jax.lax.ragged_dot(xs, params["w_up"], group_sizes)
    else:
        h = jax.lax.ragged_dot(xs, params["w_up"], group_sizes)
        if "b_up" in params:
            h = h + params["b_up"][e_sorted].astype(h.dtype)
        h = jax.nn.gelu(h)
    ys = jax.lax.ragged_dot(h, params["w_down"], group_sizes)  # [S*k, d]
    if "b_down" in params:
        ys = ys + params["b_down"][e_sorted].astype(ys.dtype)
    w = probs.reshape(-1)[order][:, None].astype(ys.dtype)
    return jnp.zeros_like(x_flat).at[tok].add((ys * w).astype(x_flat.dtype))


class MoELayer:
    """Expert-parallel gated FFN bank.

    Params: {"wg": [d, E], "w_up": [E, d, f], "w_gate": [E, d, f] (glu),
    "w_down": [E, f, d]}. Expert weights shard over ('expert', 'model')
    axes; dispatch einsums produce the all-to-alls under GSPMD. Eval /
    serving routes through :func:`no_drop_moe` — capacity-dropping is a
    training-throughput tradeoff and has no place in inference, where it
    would make a sequence's logits depend on co-scheduled traffic.
    """

    def __init__(self, d_model: int, d_ff: int, gate: GateConfig,
                 activation: str = "silu_glu", use_bias: bool = False):
        self.d_model, self.d_ff, self.gate, self.activation = d_model, d_ff, gate, activation
        # per-expert biases (Megatron-DeepSpeed MoE experts carry
        # dense_h_to_4h/dense_4h_to_h biases; glu llama-style experts don't)
        self.use_bias = use_bias

    def init(self, rng, dtype=jnp.float32, n_layers: Optional[int] = None) -> Dict[str, Any]:
        E, d, f = self.gate.n_experts, self.d_model, self.d_ff
        lead = (n_layers,) if n_layers else ()
        k1, k2, k3, k4 = jax.random.split(rng, 4)

        def dense(key, shape, fan_in):
            return (jax.random.normal(key, lead + shape, jnp.float32) / np.sqrt(fan_in)).astype(dtype)

        p = {
            "wg": dense(k1, (d, E), d),
            "w_up": dense(k2, (E, d, f), d),
            "w_down": dense(k3, (E, f, d), f),
        }
        if self.activation == "silu_glu":
            p["w_gate"] = dense(k4, (E, d, f), d)
        if self.use_bias:
            p["b_up"] = jnp.zeros(lead + (E, f), dtype)
            p["b_down"] = jnp.zeros(lead + (E, d), dtype)
        return p

    def apply(self, params: Dict[str, Any], x: jnp.ndarray,
              rng: Optional[jax.Array] = None, training: bool = True
              ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """x: [b, s, d] -> (out [b, s, d], aux_loss). Token groups = batch
        rows (group-limited routing like the reference's per-group capacity).
        Eval / no-drop uses the sort-based grouped-GEMM path."""
        b, s, d = x.shape
        cfg = self.gate
        if not training or not cfg.drop_tokens:
            logits = x.astype(jnp.float32) @ params["wg"].astype(jnp.float32)
            probs = jax.nn.softmax(logits.reshape(b * s, -1), axis=-1)
            topw, topi = jax.lax.top_k(probs, cfg.top_k)
            topw = topw / jnp.maximum(jnp.sum(topw, axis=-1, keepdims=True),
                                      1e-9)
            out = no_drop_moe(x.reshape(b * s, d), topw, topi, params,
                              self.activation)
            # same load-balance diagnostic as the drop path
            assign = jnp.mean(jax.nn.one_hot(topi[:, 0], cfg.n_experts), axis=0)
            aux = cfg.n_experts * jnp.sum(jnp.mean(probs, axis=0) * assign)
            return out.reshape(b, s, d), aux
        cap = capacity(s, cfg, training)
        if cfg.noisy_gate_policy == "Jitter" and training and rng is not None:
            # multiplicative input jitter (reference multiplicative_jitter,
            # sharded_moe.py): x * U(1-eps, 1+eps) for the router only
            rng, jkey = jax.random.split(rng)
            x_r = x * jax.random.uniform(jkey, x.shape, x.dtype, 0.99, 1.01)
        else:
            x_r = x
        logits = x_r.astype(jnp.float32) @ params["wg"].astype(jnp.float32)  # [b, s, E]

        def per_group(lg, r):
            return top_k_gating(lg, cfg, cap, r, training)

        rngs = jax.random.split(rng, b) if rng is not None else None
        combine, dispatch, aux = jax.vmap(per_group)(
            logits, rngs) if rngs is not None else jax.vmap(lambda lg: per_group(lg, None))(logits)
        aux = jnp.mean(aux)

        # dispatch: [b, s, E, C] x [b, s, d] -> [E, b, C, d]
        disp = dispatch.astype(x.dtype)
        expert_in = jnp.einsum("bsec,bsd->ebcd", disp, x)
        if self.activation == "silu_glu":
            h = jax.nn.silu(jnp.einsum("ebcd,edf->ebcf", expert_in, params["w_gate"])) * \
                jnp.einsum("ebcd,edf->ebcf", expert_in, params["w_up"])
        else:
            h = jnp.einsum("ebcd,edf->ebcf", expert_in, params["w_up"])
            if "b_up" in params:
                h = h + params["b_up"][:, None, None, :].astype(h.dtype)
            h = jax.nn.gelu(h)
        expert_out = jnp.einsum("ebcf,efd->ebcd", h, params["w_down"])
        if "b_down" in params:
            expert_out = expert_out + params["b_down"][:, None, None, :].astype(expert_out.dtype)
        out = jnp.einsum("bsec,ebcd->bsd", combine.astype(x.dtype), expert_out)
        return out, aux

    def partition_specs(self, n_layers: Optional[int] = None,
                        pipe: Optional[str] = None):
        """``pipe``: mesh axis name to shard the stacked-layer leading dim
        over (pipeline stages own their layers' expert banks, matching the
        dense-param placement in Transformer.partition_specs)."""
        from jax.sharding import PartitionSpec as P

        lead = (pipe,) if n_layers else ()
        specs = {
            "wg": P(*lead, None, None),
            "w_up": P(*lead, "expert", None, "model"),
            "w_down": P(*lead, "expert", "model", None),
        }
        if self.activation == "silu_glu":
            specs["w_gate"] = P(*lead, "expert", None, "model")
        if self.use_bias:
            specs["b_up"] = P(*lead, "expert", "model")
            specs["b_down"] = P(*lead, "expert", None)
        return specs
