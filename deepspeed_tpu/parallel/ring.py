"""Ring attention (context parallelism) via collective-permute.

The reference has NO ring/blockwise context parallelism (SURVEY.md §2.2:
Ulysses is its only long-context mechanism) — this is a beyond-parity
capability. Blockwise attention with online softmax: K/V shards rotate
around the ``seq`` mesh axis with ``jax.lax.ppermute`` (riding the ICI
ring) while each device keeps its query shard resident, so sequence length
scales with the number of devices without ever materializing full-sequence
K/V — and without Ulysses' n_heads % P constraint.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from .mesh import collective_axis_size, shard_map_compat


def _block_attn(q, k, v, q_off, k_off, causal, scale):
    """Partial attention of a q block vs one k/v block with global-position
    causal masking. Returns (unnormalized out, running max m, running sum l).
    q: [b, sq, h, d] k/v: [b, sk, h, d]."""
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        q_pos = q_off + jnp.arange(q.shape[1])
        k_pos = k_off + jnp.arange(k.shape[1])
        mask = q_pos[:, None] >= k_pos[None, :]
        logits = jnp.where(mask[None, None], logits, -jnp.inf)
    m = jnp.max(logits, axis=-1)                          # [b, h, q]
    # guard fully-masked rows (no valid key yet): exp(-inf - -inf) -> nan
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(logits - m_safe[..., None])               # [b, h, q, k]
    p = jnp.where(jnp.isfinite(logits), p, 0.0)
    l = jnp.sum(p, axis=-1)                               # [b, h, q]
    out = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)
    return out.astype(jnp.float32), m_safe, l


def _combine(acc_out, acc_m, acc_l, out, m, l):
    """Online-softmax merge of two partial attention results."""
    new_m = jnp.maximum(acc_m, m)
    a = jnp.exp(acc_m - new_m)
    b = jnp.exp(m - new_m)
    new_l = acc_l * a + l * b
    new_out = acc_out * a.transpose(0, 2, 1)[..., None] + out * b.transpose(0, 2, 1)[..., None]
    return new_out, new_m, new_l


def ring_attention(q, k, v, *, axis_name: str = "seq", causal: bool = True,
                   scale: Optional[float] = None):
    """Call INSIDE shard_map. q/k/v: local shards [b, s/P, h, d] where the
    global sequence is contiguously sharded over ``axis_name``."""
    P_ = collective_axis_size(axis_name)   # 0.4.x: no jax.lax.axis_size
    my = jax.lax.axis_index(axis_name)
    b, s_local, h, d = q.shape
    scale = scale if scale is not None else 1.0 / np.sqrt(d)
    if k.shape[2] != h:  # GQA: broadcast kv heads once, locally
        k = jnp.repeat(k, h // k.shape[2], axis=2)
        v = jnp.repeat(v, h // v.shape[2], axis=2)

    q_off = my * s_local
    acc_out = jnp.zeros((b, s_local, h, d), jnp.float32)
    acc_m = jnp.full((b, h, s_local), -jnp.inf, jnp.float32)
    acc_l = jnp.zeros((b, h, s_local), jnp.float32)
    perm = [(i, (i + 1) % P_) for i in range(P_)]

    def body(i, carry):
        acc_out, acc_m, acc_l, kk, vv = carry
        src = (my - i) % P_          # which shard currently holds
        k_off = src * s_local
        out, m, l = _block_attn(q, kk, vv, q_off, k_off, causal, scale)
        # first block initializes the accumulator (acc_m = -inf everywhere)
        acc_out, acc_m, acc_l = _combine(acc_out, acc_m, acc_l, out, m, l)
        kk = jax.lax.ppermute(kk, axis_name, perm)
        vv = jax.lax.ppermute(vv, axis_name, perm)
        return acc_out, acc_m, acc_l, kk, vv

    acc_out, acc_m, acc_l, _, _ = jax.lax.fori_loop(
        0, P_, body, (acc_out, acc_m, acc_l, k, v))
    denom = jnp.maximum(acc_l, 1e-30).transpose(0, 2, 1)[..., None]
    return (acc_out / denom).astype(q.dtype)


def ring_attention_sharded(q, k, v, mesh: Mesh, *, axis_name: str = "seq",
                           causal: bool = True, batch_axes=None,
                           head_axes=None):
    """Global-array wrapper: q/k/v [b, s, h, d] sharded over ``axis_name``
    on the seq dim; runs ring attention under shard_map. ``batch_axes`` /
    ``head_axes`` must name the activations' existing batch/head sharding
    so the shard_map boundary doesn't force a replicate-then-reshard."""
    spec = P(batch_axes, axis_name, head_axes, None)

    def inner(q, k, v):
        return ring_attention(q, k, v, axis_name=axis_name, causal=causal)

    return shard_map_compat(
        inner, mesh=mesh, in_specs=(spec, spec, spec),
        out_specs=spec, check_vma=False)(q, k, v)
