"""Pipeline-parallel executor: rotating-microbatch SPMD pipeline.

TPU-native redesign of the reference's pipeline engine
(``runtime/pipe/engine.py:55`` PipelineEngine + ``runtime/pipe/p2p.py``
send/recv + ``runtime/pipe/schedule.py`` instruction schedules). The
reference drives one process per stage through an interpreted instruction
list (ForwardPass / SendActivation / RecvActivation / BackwardPass / ...)
with explicit point-to-point sends. On TPU the whole schedule compiles into
ONE program:

* the ``pipe`` mesh axis holds one stage per device group,
* stage parameters are *stacked* on a leading axis sharded over ``pipe``,
* a ``lax.scan`` over clock ticks moves micro-batch activations between
  stages with ``lax.ppermute`` (the p2p.send/recv equivalent, riding ICI),
* ``jax.checkpoint`` on the stage body keeps live memory at one activation
  per stage boundary (the reason the reference implements 1F1B),
* reverse-mode autodiff of the scan yields the backward pipeline — the
  drain/fill structure of 1F1B falls out of the chain rule instead of an
  instruction interpreter.

Ticks run ``M + P - 1`` times (M micro-batches, P stages): the classic
fill/steady/drain profile with bubble fraction ``(P-1)/(M+P-1)`` forward —
identical to the reference's TrainSchedule (schedule.py:189).

The executor is *partial-manual*: only ``pipe`` is a manual axis; data /
model / seq / expert axes stay under GSPMD so tensor-parallel matmuls and
ZeRO shardings inside the stage body keep working unchanged.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from .mesh import shard_map_compat

# stage_fn(stage_params, x, consts, rng, valid) -> (y, aux_scalar)
StageFn = Callable[[Any, jnp.ndarray, Any, jnp.ndarray, jnp.ndarray],
                   Tuple[jnp.ndarray, jnp.ndarray]]


def pipeline_apply(stage_fn: StageFn,
                   stage_params: Any,
                   xs: jnp.ndarray,
                   rng: jnp.ndarray,
                   mesh: Mesh,
                   *,
                   consts: Any = None,
                   axis: str = "pipe",
                   remat: bool = True) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Run ``xs`` (``[M, mb, ...]`` micro-batched activations) through the
    pipelined stack.

    ``stage_params``: pytree whose leaves are stacked per-stage with leading
    dim P sharded over ``axis`` (each device sees its own stage's slice).
    ``consts``: pytree of stage-invariant inputs (RoPE angle tables, masks)
    replicated over the pipe axis and handed to every ``stage_fn`` call.
    Returns ``(ys, aux)`` where ``ys`` has the shape of ``xs`` (final-stage
    outputs, broadcast over the pipe axis) and ``aux`` is the mean per-
    microbatch auxiliary loss accumulated across stages (MoE load balancing).
    """
    n_stages = mesh.shape[axis]
    body = jax.checkpoint(stage_fn) if remat else stage_fn

    def spmd(params, xs, consts, rng):
        # params leaves: [1, ...] local stage slice; drop the stage dim.
        params = jax.tree_util.tree_map(lambda x: x[0], params)
        stage = jax.lax.axis_index(axis)
        n_mb = xs.shape[0]
        ticks = n_mb + n_stages - 1
        state = jnp.zeros_like(xs[0])
        ys = jnp.zeros_like(xs)

        def tick(carry, t):
            state, ys, aux_acc = carry
            # stage 0 loads micro-batch t from the data feed; later stages
            # take the activation rotated in from the previous stage
            # (reference: LoadMicroBatch vs RecvActivation, schedule.py:332).
            mb_in = jnp.clip(t, 0, n_mb - 1)
            inp = jnp.where(stage == 0,
                            jax.lax.dynamic_index_in_dim(xs, mb_in, keepdims=False),
                            state)
            # this stage is computing micro-batch (t - stage); it is real
            # work (not fill/drain bubble) iff 0 <= t - stage < M.
            mb_here = t - stage
            valid = jnp.logical_and(mb_here >= 0, mb_here < n_mb)
            sub = jax.random.fold_in(jax.random.fold_in(rng, t), stage)
            out, aux = body(params, inp, consts, sub, valid)
            aux_acc = aux_acc + jnp.where(valid, aux.astype(jnp.float32), 0.0)
            # final stage banks its finished micro-batch (t - (P-1)).
            mb_out = t - (n_stages - 1)
            write = jnp.logical_and(stage == n_stages - 1, mb_out >= 0)
            idx = jnp.clip(mb_out, 0, n_mb - 1)
            cur = jax.lax.dynamic_index_in_dim(ys, idx, keepdims=False)
            ys = jax.lax.dynamic_update_index_in_dim(
                ys, jnp.where(write, out, cur), idx, 0)
            # rotate activations one stage forward (p2p send/recv analog).
            state = jax.lax.ppermute(
                out, axis, [(i, (i + 1) % n_stages) for i in range(n_stages)])
            return (state, ys, aux_acc), None

        init = (state, ys, jnp.zeros([], jnp.float32))
        (state, ys, aux_acc), _ = jax.lax.scan(tick, init, jnp.arange(ticks))
        # outputs live on the last stage only; broadcast to every stage so
        # the (replicated-over-pipe) head/loss can run under plain GSPMD.
        # psum in fp32: fp32 collective accumulation discipline (and XLA's
        # CPU backend miscompiles sub-fp32 psum under partial-manual
        # shard_map — "Invalid binary instruction opcode copy").
        ys_dtype = ys.dtype
        ys = jax.lax.psum(
            jnp.where(stage == n_stages - 1, ys, jnp.zeros_like(ys))
            .astype(jnp.float32), axis).astype(ys_dtype)
        aux = jax.lax.psum(aux_acc, axis) / jnp.maximum(n_mb, 1)
        return ys, aux

    return shard_map_compat(
        spmd, mesh=mesh, axis_names={axis},
        in_specs=(P(axis), P(), P(), P()),
        out_specs=(P(), P()),
        check_vma=False,
    )(stage_params, xs, consts, rng)


def forward_tick_plan(micro_batches: int, stages: int):
    """The executor's forward work map: ``plan[t]`` is the list of
    ``(stage, micro_batch)`` pairs doing *real* work at clock tick ``t``.

    Derived from the SAME predicate the compiled scan body uses
    (``mb_here = t - stage``, valid iff ``0 <= mb_here < M`` — see ``tick``
    above), so tests can assert this plan is equivalent to the reference-
    shaped instruction schedules in ``pipe/schedule.py``: tick-for-step equal
    to InferenceSchedule's ForwardPass stream, and per-stage order-equal to
    TrainSchedule's forward stream (1F1B re-times backward, never forward
    order). That assertion is what makes ``pipe/schedule.py`` a *wired*
    specification of this executor rather than a standalone model.
    """
    n_mb, n_stages = micro_batches, stages
    plan = []
    for t in range(n_mb + n_stages - 1):
        work = [(s, t - s) for s in range(n_stages) if 0 <= t - s < n_mb]
        plan.append(work)
    return plan


def stack_stage_params(layer_params: Any, n_stages: int) -> Any:
    """Reshape stacked-layer params ``[n_layers, ...]`` into per-stage
    ``[n_stages, n_layers/n_stages, ...]``. A metadata-only reshape when the
    leading dim is already sharded over the pipe axis."""

    def reshape(x):
        n = x.shape[0]
        assert n % n_stages == 0, (
            f"layer count {n} not divisible by pipeline stages {n_stages}")
        return x.reshape((n_stages, n // n_stages) + x.shape[1:])

    return jax.tree_util.tree_map(reshape, layer_params)


def microbatch(batch: Any, num_microbatches: int) -> Any:
    """Split a global batch ``[B, ...]`` into ``[M, B/M, ...]`` along dim 0
    (reference: PipelineEngine micro-batch iterator over the data loader)."""

    def split(x):
        b = x.shape[0]
        assert b % num_microbatches == 0, (
            f"batch {b} not divisible by {num_microbatches} microbatches")
        return x.reshape((num_microbatches, b // num_microbatches) + x.shape[1:])

    return jax.tree_util.tree_map(split, batch)
