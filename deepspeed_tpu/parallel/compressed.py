"""Compressed collectives: error-compensated 1-bit / int8 gradient
reduction (the 1-bit optimizer comm layer + ZeRO++ quantized gradients).

Reference surface:
* ``runtime/comm/nccl.py:51`` NcclBackend.compressed_allreduce — the
  error-feedback 1-bit allreduce behind OnebitAdam/OnebitLamb/ZeroOneAdam
  (``runtime/fp16/onebit/``): worker compression -> chunk exchange ->
  server (per-chunk) reduce + second compression -> result broadcast, with
  TWO error buffers (worker_error, server_error) carrying both stages'
  residuals,
* ``runtime/comm/mpi.py`` (same algorithm over mpi4py),
* ZeRO++ quantized gradients over intra-node groups
  (groups.py:356, engine.py:1117).

TPU-first: the reference builds the exchange from igather/isend loops on
side streams; here both phases are XLA collectives inside shard_map —
``all_to_all`` moves int8 sign payloads (1 byte/element instead of 4) so
the wire volume drops ~4x (plus one fp32 scale per chunk), then the
reduced chunk is re-compressed and ``all_gather``-ed. Same convergence
contract, compiler-scheduled transfers riding ICI.

NB: this module is the error-feedback compression layer behind the
1-bit OPTIMIZERS (runtime/onebit.py). The engine's ZeRO-3 qwZ/qgZ hot
path moved to the metered compression facade in ``comm/compressed.py``
(docs/communication.md) — new collective call sites should go there so
the bytes-on-wire ledger and the mesh-size compression policy see them.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from .mesh import shard_map_compat


def _sign_compress(x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-row 1-bit compression: x [rows, m] -> (sign int8, scale [rows]).
    scale = mean |x| per row keeps the decompressed magnitude unbiased."""
    scale = jnp.mean(jnp.abs(x), axis=-1)
    sign = jnp.where(x >= 0, 1, -1).astype(jnp.int8)
    return sign, scale


def onebit_allreduce(x: jnp.ndarray, worker_error: jnp.ndarray,
                     server_error: jnp.ndarray, axis_name: str):
    """Error-compensated 1-bit mean-allreduce of one flat tensor.

    Must run inside shard_map with ``axis_name`` manual. x: [n] with n
    divisible by the axis size. Returns (reduced [n], new_worker_error,
    new_server_error)."""
    world = jax.lax.psum(1, axis_name)
    n = x.shape[0]

    # -- phase 1: worker compression + chunk exchange
    corrected = x + worker_error
    chunks = corrected.reshape(world, -1)                  # [world, m]
    sign, scale = _sign_compress(chunks)                   # int8, [world]
    new_worker_error = (corrected -
                        (sign * scale[:, None]).reshape(-1))
    # each rank receives chunk r of every rank (the igather analog)
    signs_recv = jax.lax.all_to_all(sign, axis_name, split_axis=0,
                                    concat_axis=0, tiled=False)
    scales_recv = jax.lax.all_to_all(scale[:, None], axis_name, split_axis=0,
                                     concat_axis=0, tiled=False)
    # [world, m] / [world, 1]: rank k's view of chunk <self> from all ranks
    signs_recv = signs_recv.reshape(world, -1)
    scales_recv = scales_recv.reshape(world, 1)

    # -- phase 2: server reduce + second compression
    chunk_avg = jnp.mean(signs_recv.astype(jnp.float32) * scales_recv, axis=0)
    corrected2 = chunk_avg + server_error
    sign2, scale2 = _sign_compress(corrected2[None, :])
    new_server_error = corrected2 - (sign2[0] * scale2[0])

    # -- broadcast: all_gather the compressed reduced chunks
    signs_all = jax.lax.all_gather(sign2[0], axis_name)     # [world, m] int8
    scales_all = jax.lax.all_gather(scale2[0], axis_name)   # [world]
    reduced = (signs_all.astype(jnp.float32) * scales_all[:, None]).reshape(n)
    return reduced, new_worker_error, new_server_error


def int8_allreduce(x: jnp.ndarray, worker_error: jnp.ndarray,
                   axis_name: str, block: int = 512):
    """Blockwise-int8 error-compensated allreduce (ZeRO++ gradient
    quantization analog): quantize local contribution to int8 + per-block
    scale, exchange chunks, dense-average, return fp32."""
    from ..ops.quantizer import dequantize_blockwise, quantize_blockwise

    world = jax.lax.psum(1, axis_name)
    n = x.size
    # trace-time divisibility guards (otherwise the reshapes below fail with
    # an opaque error, or scales misalign with payload chunks)
    assert n % (world * block) == 0, (
        f"int8_allreduce: size {n} must be divisible by world*block "
        f"({world}*{block}) — pad the input or use tree_onebit_allreduce's "
        f"dense fallback for small tensors")
    corrected = x + worker_error
    q, s, _ = quantize_blockwise(corrected, bits=8, block=block,
                                 manual_sharding=True)
    deq = dequantize_blockwise(q, s, block=block, manual_sharding=True)
    new_error = corrected - deq
    # chunk exchange of the int8 payload, dequantized server-side
    chunks = q.reshape(world, -1)
    scales = s.reshape(world, -1)
    q_recv = jax.lax.all_to_all(chunks, axis_name, 0, 0, tiled=False)
    s_recv = jax.lax.all_to_all(scales, axis_name, 0, 0, tiled=False)
    q_recv = q_recv.reshape(world, -1, block)
    s_recv = s_recv.reshape(world, -1)
    chunk_avg = jnp.mean(q_recv.astype(jnp.float32) * s_recv[..., None], axis=0)
    reduced = jax.lax.all_gather(chunk_avg.reshape(-1), axis_name).reshape(x.shape)
    return reduced, new_error


def int8_pmean(x: jnp.ndarray, axis_name: str, block: int = 512) -> jnp.ndarray:
    """Stateless blockwise-int8 mean-reduce (ZeRO++ qgZ,
    reference runtime/zero/stage3.py quantized_reduce_scatter path /
    engine keys runtime/engine.py:836): both hops of the hierarchical
    reduction move int8 payloads — local contribution quantized and
    chunk-exchanged via all_to_all, the reduced chunk re-quantized for the
    all_gather — so the wire volume drops ~4x vs fp32. Must run inside
    shard_map with ``axis_name`` manual; x is the rank-local [n] partial
    sum with n divisible by world*block."""
    from ..ops.quantizer import dequantize_blockwise, quantize_blockwise

    world = jax.lax.psum(1, axis_name)
    q, s, _ = quantize_blockwise(x, bits=8, block=block,
                                 manual_sharding=True)
    q_recv = jax.lax.all_to_all(q.reshape(world, -1), axis_name, 0, 0,
                                tiled=False).reshape(world, -1, block)
    s_recv = jax.lax.all_to_all(s.reshape(world, -1), axis_name, 0, 0,
                                tiled=False).reshape(world, -1)
    chunk = jnp.mean(q_recv.astype(jnp.float32) * s_recv[..., None],
                     axis=0).reshape(-1)
    q2, s2, _ = quantize_blockwise(chunk, bits=8, block=block,
                                     manual_sharding=True)
    q_all = jax.lax.all_gather(q2, axis_name).reshape(-1)
    s_all = jax.lax.all_gather(s2, axis_name).reshape(-1)
    return dequantize_blockwise(q_all, s_all, block=block,
                                manual_sharding=True).reshape(x.shape)


def tree_int8_pmean(grads: Any, axis_name: str, world: int,
                    block: int = 512) -> Any:
    """Leaf-wise int8_pmean over a gradient pytree; leaves that don't divide
    world*block (or are tiny) fall back to dense pmean — the reference
    similarly exempts small tensors from quantized collectives."""

    def leaf(g):
        flat = g.reshape(-1).astype(jnp.float32)
        if g.size % (world * block) != 0 or g.size < 4 * world * block:
            return jax.lax.pmean(flat, axis_name).reshape(g.shape)
        return int8_pmean(flat, axis_name, block=block).reshape(g.shape)

    return jax.tree_util.tree_map(leaf, grads)


def tree_onebit_allreduce(grads: Any, worker_errors: Any, server_errors: Any,
                          axis_name: str, world: int):
    """Leaf-wise onebit_allreduce over a gradient pytree. Error buffers are
    PER-RANK state: inside shard_map their leaves arrive as [1, ...] local
    shards of a [world, ...] global array. Leaves whose size doesn't divide
    the axis size fall back to dense psum-mean (the reference similarly
    exempts small tensors)."""

    def leaf(g, we, se):
        n = g.size
        flat = g.reshape(-1).astype(jnp.float32)
        if n % world != 0 or n < 4 * world:
            return jax.lax.pmean(flat, axis_name).reshape(g.shape), we, se
        red, nwe, nse = onebit_allreduce(flat, we[0], se[0], axis_name)
        return red.reshape(g.shape), nwe[None], nse[None]

    flat_g, tree = jax.tree_util.tree_flatten(grads)
    flat_we = jax.tree_util.tree_leaves(worker_errors)
    flat_se = jax.tree_util.tree_leaves(server_errors)
    out = [leaf(g, we, se) for g, we, se in zip(flat_g, flat_we, flat_se)]
    return (jax.tree_util.tree_unflatten(tree, [a for a, _, _ in out]),
            jax.tree_util.tree_unflatten(tree, [b for _, b, _ in out]),
            jax.tree_util.tree_unflatten(tree, [c for _, _, c in out]))


def make_onebit_grad_fn(loss_fn, mesh: Mesh, axis_name: str = "data"):
    """grad_fn(params, batch, worker_err, server_err)
    -> (grads, loss, new_worker_err, new_server_err), with the cross-replica
    gradient reduction going through the error-compensated 1-bit collective
    instead of a dense psum (params replicated over ``axis_name``; batch
    dim 0 sharded over it — the 1-bit optimizers' ZeRO-0/1 layout).

    Error buffers come from :func:`init_error_feedback` and must be placed
    with dim 0 sharded over ``axis_name``.
    """
    world = mesh.shape[axis_name]

    def spmd(params, batch, we, se):
        loss, grads = jax.value_and_grad(
            lambda p: loss_fn(p, batch, None))(params)
        red, nwe, nse = tree_onebit_allreduce(grads, we, se, axis_name, world)
        return red, jax.lax.pmean(loss, axis_name), nwe, nse

    return shard_map_compat(
        spmd, mesh=mesh, axis_names={axis_name},
        in_specs=(P(), P(axis_name), P(axis_name), P(axis_name)),
        out_specs=(P(), P(), P(axis_name), P(axis_name)),
        check_vma=False)


def init_error_feedback(params: Any, axis_size: int) -> Tuple[Any, Any]:
    """(worker_errors, server_errors) zero buffers, one row per rank
    (leading dim = axis_size; shard it over the reduction axis). Server
    errors cover one chunk (1/axis_size of each leaf) — the rank-local
    reduction share. The reference keeps the same two buffers as
    worker_error/server_error tensors per rank."""

    def worker(p):
        return jnp.zeros((axis_size, p.size), jnp.float32)

    def server(p):
        n = p.size
        m = n // axis_size if (n % axis_size == 0 and n >= 4 * axis_size) else n
        return jnp.zeros((axis_size, m), jnp.float32)

    return (jax.tree_util.tree_map(worker, params),
            jax.tree_util.tree_map(server, params))
