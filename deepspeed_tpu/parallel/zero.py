"""ZeRO redundancy elimination as sharding rules.

This module is the TPU-native redesign of the reference's
``runtime/zero/stage_1_and_2.py`` (DeepSpeedZeroOptimizer: flattened bit16
partitions + IPG bucketing + hook-driven reduce-scatter) and
``runtime/zero/stage3.py`` (DeepSpeedZeroOptimizer_Stage3: partitioned
parameters with fetch/release hooks + PartitionedParameterCoordinator
prefetching). Under XLA/GSPMD the entire hook/stream machinery collapses
into *placement*: we emit a ``NamedSharding`` for every parameter, gradient
and optimizer-state leaf, and the compiler inserts + schedules the
all-gathers and reduce-scatters (with latency hiding) that the reference
implements by hand.

Stage semantics (config parity with runtime/zero/config.py):
  stage 0 — params/grads/opt replicated over the ZeRO axes; grads psum.
  stage 1 — optimizer state sharded over the ZeRO axes; grads arrive as
            reduce-scattered shards for the update, updated params
            all-gathered (XLA emits the same reduce-scatter + all-gather
            schedule the reference builds with IPG buckets,
            stage_1_and_2.py:889,:999).
  stage 2 — identical compiled program to stage 1 on TPU (gradient shards
            are never materialized unsharded anyway); kept distinct for
            config parity.
  stage 3 — parameters themselves stored sharded (FSDP); forward/backward
            all-gathers are inserted by GSPMD exactly where the reference's
            pre/post-module hooks fetch/release partitions
            (parameter_offload.py:391, partitioned_param_coordinator.py:256).

Small parameters stay replicated below ``stage3_param_persistence_threshold``
— same knob, same motivation (avoid tiny all-gathers) as the reference's
persistence thresholds (stage3.py / partition_parameters.py).

The ZeRO axes come from :meth:`Topology.zero_partition_axes` — ('data',) or
('data','seq'), mirroring the reference's use of the sequence-data-parallel
group as ZeRO's process group when Ulysses is active (engine.py:1122).
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from ..config import ZeroConfig
from .mesh import Topology


def _spec_to_list(spec: Optional[PartitionSpec], ndim: int) -> list:
    out: list = [None] * ndim
    if spec is None:
        return out
    for i, entry in enumerate(spec):
        if i < ndim:
            out[i] = entry
    return out


def _axes_size(topo: Topology, axes: Tuple[str, ...]) -> int:
    n = 1
    for a in axes:
        n *= topo.axis_size(a)
    return n


def shard_leaf_spec(shape: Tuple[int, ...],
                    zero_axes: Tuple[str, ...],
                    base_spec: Optional[PartitionSpec] = None,
                    threshold: int = 0,
                    axes_size: int = 1,
                    axis_sizes: Optional[dict] = None) -> PartitionSpec:
    """Compute the PartitionSpec for one leaf: start from the tensor-parallel
    spec (if any) and fold the ZeRO axes onto still-unsharded, divisible
    dimensions. Falls back to replicated when nothing fits (tiny or
    odd-shaped leaves — the analog of the reference's persistent params).

    Multi-axis placement puts EACH zero axis on its OWN dimension (largest
    axes first, largest dims first) and NEVER fuses several axes onto one
    dim: XLA's SPMD partitioner cannot efficiently reshard an activation
    tiled over two distinct dims (batch x seq) onto a tensor dim carrying
    the fused product — it falls back to replicate-then-reshard
    ("Involuntary full rematerialization", xla b/433785288), and the
    hazard fires for fused 1-D vector grads just as for fused weight
    grads (an [d] norm grad fused over (data,seq) pressures the [b,s,d]
    cotangent into a feature-dim resharding). Axes that can't get their
    own dim are simply dropped for that leaf (it stays replicated over
    them) — for the 1-D leaves this costs a vector's worth of memory on
    one axis, nothing at scale.
    """
    ndim = len(shape)
    spec = _spec_to_list(base_spec, ndim)
    if ndim == 0 or axes_size == 1:
        return PartitionSpec(*spec)
    if int(np.prod(shape)) < threshold:
        return PartitionSpec(*spec)
    sizes = dict(axis_sizes or {})
    # without per-axis sizes we can only do the fused placement
    live = [] if axis_sizes is None else [a for a in zero_axes if sizes[a] > 1]
    if len(live) > 1:
        placed = 0
        for a in sorted(live, key=lambda a: -sizes[a]):
            n = sizes[a]
            cands = [i for i in range(ndim)
                     if spec[i] is None and shape[i] % n == 0 and shape[i] >= n]
            if cands:
                spec[max(cands, key=lambda i: shape[i])] = a
                placed += 1
        if placed:
            return PartitionSpec(*spec)
        # nothing placeable at all: replicated
        return PartitionSpec(*_spec_to_list(base_spec, ndim))
    # single axis / fused fallback: the product on one divisible dim
    candidates = [i for i in range(ndim) if spec[i] is None and shape[i] % axes_size == 0 and shape[i] >= axes_size]
    if not candidates:
        return PartitionSpec(*spec)
    dim = max(candidates, key=lambda i: shape[i])
    spec[dim] = zero_axes if len(zero_axes) > 1 else zero_axes[0]
    return PartitionSpec(*spec)


class ZeroShardingRules:
    """Produces sharding pytrees for params / grads / optimizer state.

    ``tp_specs`` is an optional pytree (matching params) of PartitionSpecs
    carrying tensor/expert-parallel placement from the model definition; ZeRO
    sharding composes on top (never double-shards a dim).
    """

    def __init__(self, topo: Topology, zero_config: Optional[ZeroConfig] = None):
        self.topo = topo
        self.config = zero_config or ZeroConfig()
        # MiCS (reference runtime/zero/mics.py:55): everything shards within
        # the sub-group (the fast-ICI 'zshard' factor) and REPLICATES across
        # the outer 'data' factor; XLA then emits the hierarchical
        # reduce-scatter(zshard) + all-reduce(data) gradient schedule that
        # mics.py:227 builds by hand.
        self.mics = (self.config.mics_shard_size or 0) > 0
        if self.mics and topo.zero_secondary_size > 1:
            self.zero_axes = topo.zero_secondary_axes()
        else:
            self.zero_axes = topo.zero_partition_axes()
        self.zero_size = _axes_size(topo, self.zero_axes)
        # hpZ (reference partition_parameters.py:883): primary partition over
        # the full ZeRO group (opt state / master params / grads), secondary
        # bf16 compute copy sharded over 'zshard' only so per-layer forward
        # all-gathers never cross the outer axis. The engine applies
        # secondary_param_shardings at the compute-cast boundary.
        self.hpz = (not self.mics
                    and self.config.zero_hpz_partition_size > 1
                    and topo.zero_secondary_size > 1
                    and self.config.stage >= 3)
        self.secondary_axes = topo.zero_secondary_axes()
        self.secondary_size = _axes_size(topo, self.secondary_axes)

    def _axis_sizes(self, axes: Tuple[str, ...]) -> dict:
        return {a: self.topo.axis_size(a) for a in axes}

    # -- per-leaf specs -------------------------------------------------
    def param_spec(self, shape: Tuple[int, ...], base_spec: Optional[PartitionSpec] = None) -> PartitionSpec:
        if self.config.stage < 3:
            return base_spec if base_spec is not None else PartitionSpec()
        return shard_leaf_spec(
            shape, self.zero_axes, base_spec,
            threshold=self.config.stage3_param_persistence_threshold,
            axes_size=self.zero_size, axis_sizes=self._axis_sizes(self.zero_axes),
        )

    def state_spec(self, shape: Tuple[int, ...], base_spec: Optional[PartitionSpec] = None) -> PartitionSpec:
        """Optimizer-state / gradient-shard spec: sharded from stage 1 up."""
        if self.config.stage < 1:
            return base_spec if base_spec is not None else PartitionSpec()
        return shard_leaf_spec(shape, self.zero_axes, base_spec, threshold=0,
                               axes_size=self.zero_size,
                               axis_sizes=self._axis_sizes(self.zero_axes))

    # -- pytree-level ---------------------------------------------------
    def _tree_specs(self, shapes: Any, tp_specs: Optional[Any], leaf_fn) -> Any:
        if tp_specs is None:
            return jax.tree_util.tree_map(lambda s: leaf_fn(tuple(s.shape), None), shapes)
        return jax.tree_util.tree_map(lambda s, t: leaf_fn(tuple(s.shape), t), shapes, tp_specs)

    def secondary_param_spec(self, shape: Tuple[int, ...],
                             base_spec: Optional[PartitionSpec] = None) -> PartitionSpec:
        """hpZ secondary-copy spec: sharded over the inner axes only."""
        return shard_leaf_spec(
            shape, self.secondary_axes, base_spec,
            threshold=self.config.stage3_param_persistence_threshold,
            axes_size=self.secondary_size,
            axis_sizes=self._axis_sizes(self.secondary_axes),
        )

    def param_shardings(self, param_shapes: Any, tp_specs: Optional[Any] = None) -> Any:
        mesh = self.topo.mesh
        specs = self._tree_specs(param_shapes, tp_specs, self.param_spec)
        return jax.tree_util.tree_map(lambda sp: NamedSharding(mesh, sp), specs,
                                      is_leaf=lambda x: isinstance(x, PartitionSpec))

    def secondary_param_shardings(self, param_shapes: Any,
                                  tp_specs: Optional[Any] = None) -> Any:
        """hpZ secondary (compute-copy) shardings — replicated over the outer
        'data' factor, sharded over 'zshard' (+ seq)."""
        mesh = self.topo.mesh
        specs = self._tree_specs(param_shapes, tp_specs, self.secondary_param_spec)
        return jax.tree_util.tree_map(lambda sp: NamedSharding(mesh, sp), specs,
                                      is_leaf=lambda x: isinstance(x, PartitionSpec))

    def grad_shardings(self, param_shapes: Any, tp_specs: Optional[Any] = None) -> Any:
        """Gradient placement: sharded like optimizer state from stage 2 up
        (reduce-scatter), like params otherwise (psum)."""
        mesh = self.topo.mesh
        if self.config.stage >= 2:
            specs = self._tree_specs(param_shapes, tp_specs, self.state_spec)
        else:
            specs = self._tree_specs(param_shapes, tp_specs, self.param_spec)
        return jax.tree_util.tree_map(lambda sp: NamedSharding(mesh, sp), specs,
                                      is_leaf=lambda x: isinstance(x, PartitionSpec))

    def opt_state_shardings(self, opt_state_shapes: Any) -> Any:
        """Sharding pytree for an optax-style optimizer state.

        Any leaf whose shape can host the ZeRO axes gets sharded (master
        weights, Adam moments — the big consumers the reference partitions in
        stage_1_and_2.py:97); scalars (step counts, loss scale) replicate.
        """
        mesh = self.topo.mesh

        def leaf(s):
            shape = tuple(getattr(s, "shape", ()))
            return NamedSharding(mesh, self.state_spec(shape, None))

        return jax.tree_util.tree_map(leaf, opt_state_shapes)


def compute_param_bytes(param_shapes: Any) -> int:
    total = 0
    for leaf in jax.tree_util.tree_leaves(param_shapes):
        total += int(np.prod(leaf.shape)) * jax.numpy.dtype(leaf.dtype).itemsize
    return total
