"""ZeRO redundancy elimination as sharding rules.

This module is the TPU-native redesign of the reference's
``runtime/zero/stage_1_and_2.py`` (DeepSpeedZeroOptimizer: flattened bit16
partitions + IPG bucketing + hook-driven reduce-scatter) and
``runtime/zero/stage3.py`` (DeepSpeedZeroOptimizer_Stage3: partitioned
parameters with fetch/release hooks + PartitionedParameterCoordinator
prefetching). Under XLA/GSPMD the entire hook/stream machinery collapses
into *placement*: we emit a ``NamedSharding`` for every parameter, gradient
and optimizer-state leaf, and the compiler inserts + schedules the
all-gathers and reduce-scatters (with latency hiding) that the reference
implements by hand.

Stage semantics (config parity with runtime/zero/config.py):
  stage 0 — params/grads/opt replicated over the ZeRO axes; grads psum.
  stage 1 — optimizer state sharded over the ZeRO axes; grads arrive as
            reduce-scattered shards for the update, updated params
            all-gathered (XLA emits the same reduce-scatter + all-gather
            schedule the reference builds with IPG buckets,
            stage_1_and_2.py:889,:999).
  stage 2 — identical compiled program to stage 1 on TPU (gradient shards
            are never materialized unsharded anyway); kept distinct for
            config parity.
  stage 3 — parameters themselves stored sharded (FSDP); forward/backward
            all-gathers are inserted by GSPMD exactly where the reference's
            pre/post-module hooks fetch/release partitions
            (parameter_offload.py:391, partitioned_param_coordinator.py:256).

Small parameters stay replicated below ``stage3_param_persistence_threshold``
— same knob, same motivation (avoid tiny all-gathers) as the reference's
persistence thresholds (stage3.py / partition_parameters.py).

The ZeRO axes come from :meth:`Topology.zero_partition_axes` — ('data',) or
('data','seq'), mirroring the reference's use of the sequence-data-parallel
group as ZeRO's process group when Ulysses is active (engine.py:1122).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from ..config import ZeroConfig
from .mesh import Topology


def _spec_to_list(spec: Optional[PartitionSpec], ndim: int) -> list:
    out: list = [None] * ndim
    if spec is None:
        return out
    for i, entry in enumerate(spec):
        if i < ndim:
            out[i] = entry
    return out


def _axes_size(topo: Topology, axes: Tuple[str, ...]) -> int:
    n = 1
    for a in axes:
        n *= topo.axis_size(a)
    return n


def shard_leaf_spec(shape: Tuple[int, ...],
                    zero_axes: Tuple[str, ...],
                    base_spec: Optional[PartitionSpec] = None,
                    threshold: int = 0,
                    axes_size: int = 1,
                    axis_sizes: Optional[dict] = None) -> PartitionSpec:
    """Compute the PartitionSpec for one leaf: start from the tensor-parallel
    spec (if any) and fold the ZeRO axes onto still-unsharded, divisible
    dimensions. Falls back to replicated when nothing fits (tiny or
    odd-shaped leaves — the analog of the reference's persistent params).

    Multi-axis placement puts EACH zero axis on its OWN dimension (largest
    axes first, largest dims first) and NEVER fuses several axes onto one
    dim: XLA's SPMD partitioner cannot efficiently reshard an activation
    tiled over two distinct dims (batch x seq) onto a tensor dim carrying
    the fused product — it falls back to replicate-then-reshard
    ("Involuntary full rematerialization", xla b/433785288), and the
    hazard fires for fused 1-D vector grads just as for fused weight
    grads (an [d] norm grad fused over (data,seq) pressures the [b,s,d]
    cotangent into a feature-dim resharding). Axes that can't get their
    own dim are simply dropped for that leaf (it stays replicated over
    them) — for the 1-D leaves this costs a vector's worth of memory on
    one axis, nothing at scale.
    """
    ndim = len(shape)
    spec = _spec_to_list(base_spec, ndim)
    if ndim == 0 or axes_size == 1:
        return PartitionSpec(*spec)
    if int(np.prod(shape)) < threshold:
        return PartitionSpec(*spec)
    sizes = dict(axis_sizes or {})
    # without per-axis sizes we can only do the fused placement
    live = [] if axis_sizes is None else [a for a in zero_axes if sizes[a] > 1]
    if len(live) > 1:
        placed = 0
        for a in sorted(live, key=lambda a: -sizes[a]):
            n = sizes[a]
            cands = [i for i in range(ndim)
                     if spec[i] is None and shape[i] % n == 0 and shape[i] >= n]
            if cands:
                spec[max(cands, key=lambda i: shape[i])] = a
                placed += 1
        if placed:
            return PartitionSpec(*spec)
        # nothing placeable at all: replicated
        return PartitionSpec(*_spec_to_list(base_spec, ndim))
    # single axis / fused fallback: the product on one divisible dim
    candidates = [i for i in range(ndim) if spec[i] is None and shape[i] % axes_size == 0 and shape[i] >= axes_size]
    if not candidates:
        return PartitionSpec(*spec)
    dim = max(candidates, key=lambda i: shape[i])
    spec[dim] = zero_axes if len(zero_axes) > 1 else zero_axes[0]
    return PartitionSpec(*spec)


class ZeroShardingRules:
    """Produces sharding pytrees for params / grads / optimizer state.

    ``tp_specs`` is an optional pytree (matching params) of PartitionSpecs
    carrying tensor/expert-parallel placement from the model definition; ZeRO
    sharding composes on top (never double-shards a dim).
    """

    def __init__(self, topo: Topology, zero_config: Optional[ZeroConfig] = None):
        self.topo = topo
        self.config = zero_config or ZeroConfig()
        # MiCS (reference runtime/zero/mics.py:55): everything shards within
        # the sub-group (the fast-ICI 'zshard' factor) and REPLICATES across
        # the outer 'data' factor; XLA then emits the hierarchical
        # reduce-scatter(zshard) + all-reduce(data) gradient schedule that
        # mics.py:227 builds by hand.
        self.mics = (self.config.mics_shard_size or 0) > 0
        if self.mics and topo.zero_secondary_size > 1:
            self.zero_axes = topo.zero_secondary_axes()
        else:
            self.zero_axes = topo.zero_partition_axes()
        self.zero_size = _axes_size(topo, self.zero_axes)
        # hpZ (reference partition_parameters.py:883): primary partition over
        # the full ZeRO group (opt state / master params / grads), secondary
        # bf16 compute copy sharded over 'zshard' only so per-layer forward
        # all-gathers never cross the outer axis. The engine applies
        # secondary_param_shardings at the compute-cast boundary.
        self.hpz = (not self.mics
                    and self.config.zero_hpz_partition_size > 1
                    and topo.zero_secondary_size > 1
                    and self.config.stage >= 3)
        self.secondary_axes = topo.zero_secondary_axes()
        self.secondary_size = _axes_size(topo, self.secondary_axes)

    def _axis_sizes(self, axes: Tuple[str, ...]) -> dict:
        return {a: self.topo.axis_size(a) for a in axes}

    # -- per-leaf specs -------------------------------------------------
    def param_spec(self, shape: Tuple[int, ...], base_spec: Optional[PartitionSpec] = None) -> PartitionSpec:
        if self.config.stage < 3:
            return base_spec if base_spec is not None else PartitionSpec()
        return shard_leaf_spec(
            shape, self.zero_axes, base_spec,
            threshold=self.config.stage3_param_persistence_threshold,
            axes_size=self.zero_size, axis_sizes=self._axis_sizes(self.zero_axes),
        )

    def state_spec(self, shape: Tuple[int, ...], base_spec: Optional[PartitionSpec] = None) -> PartitionSpec:
        """Optimizer-state / gradient-shard spec: sharded from stage 1 up."""
        if self.config.stage < 1:
            return base_spec if base_spec is not None else PartitionSpec()
        return shard_leaf_spec(shape, self.zero_axes, base_spec, threshold=0,
                               axes_size=self.zero_size,
                               axis_sizes=self._axis_sizes(self.zero_axes))

    # -- pytree-level ---------------------------------------------------
    def _tree_specs(self, shapes: Any, tp_specs: Optional[Any], leaf_fn) -> Any:
        if tp_specs is None:
            return jax.tree_util.tree_map(lambda s: leaf_fn(tuple(s.shape), None), shapes)
        return jax.tree_util.tree_map(lambda s, t: leaf_fn(tuple(s.shape), t), shapes, tp_specs)

    def secondary_param_spec(self, shape: Tuple[int, ...],
                             base_spec: Optional[PartitionSpec] = None) -> PartitionSpec:
        """hpZ secondary-copy spec: sharded over the inner axes only."""
        return shard_leaf_spec(
            shape, self.secondary_axes, base_spec,
            threshold=self.config.stage3_param_persistence_threshold,
            axes_size=self.secondary_size,
            axis_sizes=self._axis_sizes(self.secondary_axes),
        )

    def param_shardings(self, param_shapes: Any, tp_specs: Optional[Any] = None) -> Any:
        mesh = self.topo.mesh
        specs = self._tree_specs(param_shapes, tp_specs, self.param_spec)
        return jax.tree_util.tree_map(lambda sp: NamedSharding(mesh, sp), specs,
                                      is_leaf=lambda x: isinstance(x, PartitionSpec))

    def secondary_param_shardings(self, param_shapes: Any,
                                  tp_specs: Optional[Any] = None) -> Any:
        """hpZ secondary (compute-copy) shardings — replicated over the outer
        'data' factor, sharded over 'zshard' (+ seq)."""
        mesh = self.topo.mesh
        specs = self._tree_specs(param_shapes, tp_specs, self.secondary_param_spec)
        return jax.tree_util.tree_map(lambda sp: NamedSharding(mesh, sp), specs,
                                      is_leaf=lambda x: isinstance(x, PartitionSpec))

    def grad_shardings(self, param_shapes: Any, tp_specs: Optional[Any] = None) -> Any:
        """Gradient placement: sharded like optimizer state from stage 2 up
        (reduce-scatter), like params otherwise (psum)."""
        mesh = self.topo.mesh
        if self.config.stage >= 2:
            specs = self._tree_specs(param_shapes, tp_specs, self.state_spec)
        else:
            specs = self._tree_specs(param_shapes, tp_specs, self.param_spec)
        return jax.tree_util.tree_map(lambda sp: NamedSharding(mesh, sp), specs,
                                      is_leaf=lambda x: isinstance(x, PartitionSpec))

    def opt_state_shardings(self, opt_state_shapes: Any) -> Any:
        """Sharding pytree for an optax-style optimizer state.

        Any leaf whose shape can host the ZeRO axes gets sharded (master
        weights, Adam moments — the big consumers the reference partitions in
        stage_1_and_2.py:97); scalars (step counts, loss scale) replicate.
        """
        mesh = self.topo.mesh

        def leaf(s):
            shape = tuple(getattr(s, "shape", ()))
            return NamedSharding(mesh, self.state_spec(shape, None))

        return jax.tree_util.tree_map(leaf, opt_state_shapes)


def compute_param_bytes(param_shapes: Any) -> int:
    total = 0
    for leaf in jax.tree_util.tree_leaves(param_shapes):
        total += int(np.prod(leaf.shape)) * jax.numpy.dtype(leaf.dtype).itemsize
    return total


# ======================================================================
# T3-style staged ZeRO-3 overlap schedule (docs/communication.md)
#
# GSPMD inserts ZeRO-3's all-gathers and reduce-scatters wherever the
# sharding constraints demand them, but the whole backward is one opaque
# jax.grad: the compiler sees one giant gather-everything /
# reduce-everything dataflow and its latency-hiding scheduler has nothing
# block-shaped to pipeline. The staged schedule splits the model into
# sequential blocks and issues each block's collectives EAGERLY — block
# i+1's weight all-gather before block i's forward compute, block i+1's
# gradient reduce-scatter deferred behind block i's backward — which is
# exactly the software-pipelined schedule T3 (arxiv 2401.16677) fuses in
# hardware and the reference builds with fetch/release hooks + prefetch
# (partitioned_param_coordinator.py:256). Same dataflow, per-block
# granularity, overlap-friendly issue order; serial mode issues every
# collective immediately at its consumer for the A/B.

@dataclass
class MatmulBlockSpec:
    """Optional per-block fusion hint for the kernel-backend seam
    (comm/backends.py): declares that block i's forward is

        h' = epilogue(h @ p[weight], rest_of_p_gathered, h)

    with ``weight`` the key of the 2-D matmul weight inside the block's
    (dict) param tree. A fused backend can then run the weight's
    all-gather inside the consuming matmul (per-tile dequant+multiply)
    and the weight gradient's reduce-scatter inside the grad matmul's
    epilogue. The epilogue must be a pure function of its three
    arguments — the engine differentiates through it with jax.vjp."""

    weight: str
    epilogue: Callable[[Any, Any, Any], Any]


@dataclass
class FusedBlockOps:
    """Backend-fused forward/backward for one block of the staged
    schedule (built by the engine from a :class:`MatmulBlockSpec` and a
    CollectiveBackend). ``forward(block_shard, h) -> h'`` consumes the
    SHARDED block params (the gather happens inside, fused);
    ``backward(block_shard, h_in, g_out) -> (reduced_grad_tree, g_h)``
    re-gathers what it needs and returns grads ALREADY reduced across
    the ZeRO group (the reduce-scatter is fused into the grad matmul),
    so the schedule skips its own gather/reduce for this block."""

    forward: Callable[[Any, Any], Any]
    backward: Callable[[Any, Any, Any], Tuple[Any, Any]]


@dataclass
class BlockProgram:
    """A model decomposed into sequential blocks for the staged ZeRO-3
    schedule. ``block_fns[i](p_i, h) -> h'`` consumes the FULL (gathered)
    params of block i; ``h0`` is the first block's input (derived from
    the batch); ``loss_tail(h) -> scalar loss`` closes over batch/rng;
    ``merge(block_trees) -> params_tree`` reassembles per-block pytrees
    (e.g. gradients) into the model's parameter-tree structure. A model
    opts into the staged engine path by exposing
    ``zero3_blocks(params, batch, rng) -> BlockProgram``; the params
    argument must be handled structurally (the engine also calls it on a
    PartitionSpec tree to learn per-block shardings).

    ``matmul_blocks`` (optional, parallel to ``block_fns``) carries
    :class:`MatmulBlockSpec` fusion hints; entries may be None and the
    whole field may be None — blocks without a hint always run the
    generic gather + ``block_fn`` path."""

    block_fns: List[Callable[[Any, Any], Any]]
    blocks: List[Any]
    h0: Any
    loss_tail: Callable[[Any], Any]
    merge: Callable[[List[Any]], Any]
    matmul_blocks: Optional[List[Optional[MatmulBlockSpec]]] = None


def _probed(probe, phase: str, i: int, fn):
    """Measurement seam for the schedule's per-block phases. ``probe``
    is a plain callable ``(phase, block_index, thunk) -> thunk()``
    installed ONLY by the host-side overlap profiler
    (profiling/overlap.py), which times each phase around the thunk; in
    every jitted use probe is None and this is a plain call — identical
    dataflow, no trace-time side effects."""
    if probe is None:
        return fn()
    return probe(phase, i, fn)


class Zero3BlockSchedule:
    """Explicit per-block forward/backward with pluggable (compressed)
    collectives. ``gather(i, block_shard) -> block_full`` and
    ``reduce(i, block_grads_full) -> block_grads_reduced`` come from the
    comm facade; ``overlapped`` picks the issue order (True = T3-style
    prefetch/defer, False = serial). Both orders have identical dataflow
    — results are bit-exact to each other by construction, and the tests
    pin that so neither path can drift semantically.

    Memory contract (the stage-3 point): the forward keeps only the
    per-block ACTIVATIONS; full block params are live just for their own
    stage. The backward RE-GATHERS each block and recomputes its forward
    to build the vjp (activation checkpointing at block boundaries —
    the reference's fetch/release + prefetch schedule,
    partitioned_param_coordinator.py:256). That is the 2-gathers + 1-
    reduce per step ``comm.compressed.modeled_exposure`` books; holding
    every vjp residual instead would keep the whole unsharded model
    resident and forfeit ZeRO-3 partitioning at exactly the scale this
    schedule targets."""

    def __init__(self, gather: Callable[[int, Any], Any],
                 reduce: Callable[[int, Any], Any],
                 overlapped: bool = True,
                 fused: Optional[dict] = None,
                 probe: Optional[Callable] = None):
        self.gather = gather
        self.reduce = reduce
        self.overlapped = overlapped
        # kernel-backend seam (comm/backends.py): {block index ->
        # FusedBlockOps}. Fused blocks run their gather INSIDE the
        # consuming matmul (per-tile ring) and return already-reduced
        # grads (reduce-scatter in the grad matmul's epilogue), so the
        # schedule issues no separate collectives for them; unfused
        # blocks keep the per-block prefetch/defer issue order.
        self.fused = fused or {}
        # per-block phase-timing seam (see :func:`_probed`): None on
        # every jitted path; the overlap profiler installs one to time
        # gather/fwd/regather/bwd/reduce per block on the host
        self.probe = probe

    def loss_and_grads(self, prog: BlockProgram, scale) -> Tuple[Any, List[Any]]:
        """(loss, per-block grad trees). Grads are wrt the FULL block
        params (each rank's local-batch contribution, reduced across the
        ZeRO group by ``reduce`` — or inside a fused block's backward);
        the loss comes back unreduced — the caller averages it over the
        data axes."""
        L = len(prog.block_fns)
        assert L == len(prog.blocks) and L > 0
        fused = self.fused
        probe = self.probe

        def _gather(i, phase="gather"):
            # fused blocks gather inside their own kernels
            if i in fused:
                return None
            return _probed(probe, phase, i,
                           lambda: self.gather(i, prog.blocks[i]))

        def _reduce(i, g):
            return _probed(probe, "reduce", i, lambda: self.reduce(i, g))

        # -- forward: prefetch next gather, save activations only
        hs: List[Any] = [prog.h0]
        h = prog.h0
        full = _gather(0)
        for i in range(L):
            nxt = None
            if self.overlapped and i + 1 < L:
                # prefetch: next block's gather issued BEFORE this
                # block's compute consumes anything
                nxt = _gather(i + 1)
            if i in fused:
                h = _probed(probe, "fwd", i,
                            lambda: fused[i].forward(prog.blocks[i], h))
            else:
                h = _probed(probe, "fwd", i,
                            lambda: prog.block_fns[i](full, h))
            hs.append(h)
            if i + 1 < L:
                full = nxt if self.overlapped else _gather(i + 1)
        loss, tail_vjp = jax.vjp(prog.loss_tail, h)
        (g_h,) = tail_vjp(jnp.ones_like(loss) * scale)
        # -- backward: re-gather + recompute each block's vjp; defer the
        # previous block's reduce behind this block's compute
        grads: List[Any] = [None] * L
        pending = None
        pending_i = -1
        full = _gather(L - 1, phase="regather")
        for i in reversed(range(L)):
            nxt = None
            if self.overlapped and i > 0:
                nxt = _gather(i - 1, phase="regather")
            if i in fused:
                grads[i], g_h = _probed(
                    probe, "bwd", i,
                    lambda: fused[i].backward(prog.blocks[i], hs[i], g_h))
            else:
                def _bwd(i=i, full=full, g=g_h):
                    _, vjp = jax.vjp(prog.block_fns[i], full, hs[i])
                    return vjp(g)

                g_full, g_h = _probed(probe, "bwd", i, _bwd)
                if self.overlapped:
                    if pending is not None:
                        grads[pending_i] = _reduce(pending_i, pending)
                    pending, pending_i = g_full, i
                else:
                    grads[i] = _reduce(i, g_full)
            if i > 0:
                full = nxt if self.overlapped else _gather(i - 1,
                                                           phase="regather")
        if pending is not None:
            grads[pending_i] = _reduce(pending_i, pending)
        return loss, grads


class SequentialBlockModel:
    """Reference implementation of the ``zero3_blocks`` protocol: a stack
    of dense layers with a mean-squared-error tail. This is the model
    the staged-schedule tests, the quant-comm smoke and the MULTICHIP
    comm lane drive — small enough to verify bit-level on CPU, block-
    structured enough that every per-block collective is visible.

    ``loss(params, batch, rng)`` is the composed (non-staged) path, used
    for eval parity and as the bit-level reference for the schedule."""

    def __init__(self, dims: Sequence[int], seed: int = 0):
        if len(dims) < 3:
            raise ValueError("SequentialBlockModel needs >= 2 layers")
        self.dims = tuple(int(d) for d in dims)
        self.seed = seed

    @property
    def n_blocks(self) -> int:
        return len(self.dims) - 1

    def init(self, rng) -> Any:
        params = {}
        for i in range(self.n_blocks):
            rng, k = jax.random.split(rng)
            params[f"block_{i}"] = {
                "w": jax.random.normal(
                    k, (self.dims[i], self.dims[i + 1]), jnp.float32) * 0.05,
                "b": jnp.zeros((self.dims[i + 1],), jnp.float32),
            }
        return params

    @staticmethod
    def _apply_block(p: Any, h: Any, last: bool) -> Any:
        y = h @ p["w"] + p["b"]
        return y if last else jnp.tanh(y)

    def loss(self, params, batch, rng=None):
        h = batch["x"]
        for i in range(self.n_blocks):
            h = self._apply_block(params[f"block_{i}"], h,
                                  last=(i == self.n_blocks - 1))
        return jnp.mean((h - batch["y"]) ** 2)

    def zero3_blocks(self, params, batch, rng=None) -> BlockProgram:
        L = self.n_blocks
        blocks = [params[f"block_{i}"] for i in range(L)]

        def block_fn(i):
            last = i == L - 1
            return lambda p, h: self._apply_block(p, h, last)

        def loss_tail(h):
            return jnp.mean((h - batch["y"]) ** 2)

        def merge(trees: List[Any]) -> Any:
            return {f"block_{i}": t for i, t in enumerate(trees)}

        def epilogue(i):
            # must mirror _apply_block exactly with y = h @ p["w"]
            # precomputed — the fused path's bit-exactness against the
            # generic path rides on this
            last = i == L - 1
            return lambda y, rest, h: (y + rest["b"] if last
                                       else jnp.tanh(y + rest["b"]))

        h0 = batch["x"] if isinstance(batch, dict) else batch
        return BlockProgram(block_fns=[block_fn(i) for i in range(L)],
                            blocks=blocks, h0=h0, loss_tail=loss_tail,
                            merge=merge,
                            matmul_blocks=[MatmulBlockSpec("w", epilogue(i))
                                           for i in range(L)])
