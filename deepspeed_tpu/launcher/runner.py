"""Multi-host launcher CLI (the ``deepspeed`` command).

Parity with reference ``deepspeed/launcher/runner.py:389`` (main: hostfile
parsing :201, --include/--exclude resource filters :256, world-info
encoding :354) + ``launcher/launch.py:132`` (node-local process fork), and
the per-backend MultiNodeRunner zoo (multinode_runner.py: PDSH/MPI/SLURM).

TPU-native redesign: a TPU pod slice is provisioned as a set of hosts that
each see their local chips; there is no ssh-fan-out from rank 0 — every host
runs the same command (GKE/TPU-VM startup, or ``gcloud compute tpus tpu-vm
ssh --worker=all``). So the launcher's job collapses to:

1. resolve the host topology (hostfile / TPU metadata env / flags),
2. export the JAX distributed rendezvous env
   (COORDINATOR_ADDRESS/NUM_PROCESSES/PROCESS_ID — the MASTER_ADDR/RANK
   analog),
3. exec the training script (optionally one process per local chip-group
   for CPU simulation, mirroring launch.py's per-rank fork).

``--module`` / ``--no_python`` / env passthrough match the reference flags.
"""

from __future__ import annotations

import argparse
import json
import os
import shlex
import subprocess
import sys
from typing import Dict, List, Optional, Tuple

from ..utils.logging import logger

DLTS_HOSTFILE = "/job/hostfile"


def parse_args(args=None):
    parser = argparse.ArgumentParser(
        prog="deepspeed-tpu",
        description="deepspeed-style launcher for TPU-native training")
    parser.add_argument("-H", "--hostfile", type=str, default=DLTS_HOSTFILE,
                        help="hostfile: lines of '<host> slots=<n>'")
    parser.add_argument("-i", "--include", type=str, default="",
                        help="inclusion filter, e.g. 'host1,host2' or 'host1:0,1'")
    parser.add_argument("-e", "--exclude", type=str, default="",
                        help="exclusion filter, same syntax as --include")
    parser.add_argument("--num_nodes", type=int, default=-1)
    parser.add_argument("--num_gpus", "--num_accelerators", type=int, default=-1,
                        dest="num_gpus")
    parser.add_argument("--master_addr", type=str, default=None,
                        help="coordinator address (JAX distributed rendezvous)")
    parser.add_argument("--master_port", type=int, default=29500)
    parser.add_argument("--node_rank", type=int, default=None,
                        help="this host's process index (auto on TPU metadata)")
    parser.add_argument("--module", action="store_true",
                        help="run script as a python module (python -m)")
    parser.add_argument("--no_python", action="store_true",
                        help="exec script directly without python")
    parser.add_argument("--force_multi", action="store_true")
    parser.add_argument("user_script", type=str)
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    return parser.parse_args(args)


def fetch_hostfile(path: str) -> Dict[str, int]:
    """'<host> slots=<n>' lines -> {host: slots} (reference
    runner.py:201)."""
    if not os.path.isfile(path):
        return {}
    resources: Dict[str, int] = {}
    with open(path) as f:
        for line in f:
            line = line.split("#")[0].strip()
            if not line:
                continue
            parts = line.split()
            host = parts[0]
            slots = 1
            for p in parts[1:]:
                if p.startswith("slots="):
                    slots = int(p.split("=", 1)[1])
            if host in resources:
                raise ValueError(f"duplicate host {host} in hostfile")
            resources[host] = slots
    return resources


def _parse_filter(spec: str) -> Dict[str, Optional[List[int]]]:
    """'host1:0,1@host2' style inclusion/exclusion specs (reference
    parse_resource_filter runner.py:256)."""
    out: Dict[str, Optional[List[int]]] = {}
    if not spec:
        return out
    for part in spec.replace("@", ",").split(","):
        part = part.strip()
        if not part:
            continue
        if ":" in part:
            host, slots = part.split(":", 1)
            out.setdefault(host, [])
            out[host].extend(int(s) for s in slots.split(";") if s)
        else:
            out.setdefault(part, None)
    return out


def filter_resources(resources: Dict[str, int], include: str,
                     exclude: str) -> Dict[str, List[int]]:
    """Apply --include/--exclude (reference parse_inclusion_exclusion)."""
    pool = {h: list(range(n)) for h, n in resources.items()}
    inc, exc = _parse_filter(include), _parse_filter(exclude)
    if inc and exc:
        raise ValueError("--include and --exclude are mutually exclusive")
    if inc:
        picked = {}
        for host, slots in inc.items():
            if host not in pool:
                raise ValueError(f"include: unknown host {host}")
            picked[host] = slots if slots else pool[host]
        return picked
    for host, slots in exc.items():
        if host not in pool:
            raise ValueError(f"exclude: unknown host {host}")
        if slots is None:
            pool.pop(host)
        else:
            pool[host] = [s for s in pool[host] if s not in slots]
    return {h: s for h, s in pool.items() if s}


def encode_world_info(resources: Dict[str, List[int]]) -> str:
    """base64 world-info blob (reference runner.py:354)."""
    import base64

    return base64.urlsafe_b64encode(
        json.dumps(resources, sort_keys=True).encode()).decode()


def decode_world_info(blob: str) -> Dict[str, List[int]]:
    import base64

    return json.loads(base64.urlsafe_b64decode(blob.encode()).decode())


def build_env(args, resources: Dict[str, List[int]]) -> Dict[str, str]:
    """JAX-distributed rendezvous env for THIS host (the RANK/MASTER_* of
    the reference's launch.py)."""
    env = dict(os.environ)
    hosts = sorted(resources)
    n_proc = len(hosts) if hosts else max(args.num_nodes, 1)
    master = args.master_addr or (hosts[0] if hosts else "127.0.0.1")
    node_rank = args.node_rank
    if node_rank is None:
        node_rank = int(os.environ.get("TPU_WORKER_ID",
                                       os.environ.get("NODE_RANK", 0)))
    env.update({
        "COORDINATOR_ADDRESS": f"{master}:{args.master_port}",
        "NUM_PROCESSES": str(n_proc),
        "PROCESS_ID": str(node_rank),
        "DS_TPU_WORLD_INFO": encode_world_info(resources),
    })
    return env


def build_cmd(args) -> List[str]:
    if args.no_python:
        cmd = [args.user_script]
    elif args.module:
        cmd = [sys.executable, "-m", args.user_script]
    else:
        cmd = [sys.executable, args.user_script]
    return cmd + list(args.user_args)


def main(argv=None) -> int:
    args = parse_args(argv)
    resources = fetch_hostfile(args.hostfile)
    if resources:
        resources = filter_resources(resources, args.include, args.exclude)
        if args.num_nodes > 0:
            resources = dict(list(resources.items())[: args.num_nodes])
    env = build_env(args, resources)
    cmd = build_cmd(args)
    logger.info(f"launcher: exec {' '.join(shlex.quote(c) for c in cmd)} "
                f"(process {env['PROCESS_ID']}/{env['NUM_PROCESSES']}, "
                f"coordinator {env['COORDINATOR_ADDRESS']})")
    proc = subprocess.run(cmd, env=env)
    return proc.returncode


if __name__ == "__main__":
    raise SystemExit(main())
