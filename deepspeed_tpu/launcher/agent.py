"""Elastic agent: supervise a training process, restart on failure.

Reference surface: ``deepspeed/elasticity/elastic_agent.py:28``
(``DSElasticAgent._invoke_run`` :118 — monitor the worker group, restart
within ``max_restarts`` on failure/membership change, torchrun rendezvous
handling node join/leave).

TPU-native redesign: there is no torch-elastic rendezvous to subclass —
a TPU slice under one controller restarts as a unit. The agent is a
process supervisor: it launches the training command, watches for
failure, and restarts it up to ``max_restarts`` times with
``DST_ELASTIC_RESTART=<n>`` exported so the trainee knows to resume from
its latest checkpoint (resume-from-latest is the recovery mechanism —
SURVEY §5.3; cross-mesh resume is already checkpoint-native). Restarts
back off exponentially with jitter (bounded by ``max_backoff_s``); a
worker that ran "healthily" (longer than ``healthy_after_s``) resets the
backoff, so a restart storm after a long stable run starts gentle again.

Every restart is classified (``exit:<rc>`` / ``signal:<name>``) and
surfaced two ways (docs/fault_tolerance.md):
 * the telemetry registry (``resilience/restarts`` plus
   ``resilience/restart_reasons/<reason>``), and
 * the worker's heartbeat file, overwritten with
   ``{"state": "restarting", "restarts": n, "reason": ...}`` while the
   worker is down — an external watchdog watching the heartbeat can tell
   "restarting" from "hung" instead of paging on every relaunch window.
"""

from __future__ import annotations

import os
import random
import signal
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence

from ..utils.fileio import write_json_atomic
from ..utils.logging import logger


#: exit code a worker uses to request a PLANNED restart: a zero-downtime
#: rollout (serving/rollout.py) that cannot hot-swap in place re-execs
#: the worker to load the new weights. Restarts so classified consume no
#: restart budget and skip the backoff ladder — a flip is an intentional
#: reload, not an incident, and must not look like a crash loop to the
#: agent or like a hang to the stall watchdog reading the heartbeat.
PLANNED_ROLLOUT_EXIT = 86


def classify_exit(returncode: int,
                  planned_codes: Sequence[int] = (PLANNED_ROLLOUT_EXIT,)
                  ) -> str:
    """Human-readable restart reason from a worker's return code.
    Three families: ``signal:<name>`` (killed), ``planned:rollout``
    (worker-requested reload — see :data:`PLANNED_ROLLOUT_EXIT`), and
    ``exit:<rc>`` (everything else)."""
    if returncode < 0:
        try:
            name = signal.Signals(-returncode).name
        except ValueError:
            name = str(-returncode)
        return f"signal:{name}"
    if returncode in planned_codes:
        return "planned:rollout"
    return f"exit:{returncode}"


@dataclass
class AgentReport:
    restarts: int
    returncode: int
    history: List[int] = field(default_factory=list)
    reasons: List[str] = field(default_factory=list)
    #: rollout-triggered reloads (``planned:*`` reasons) — relaunches
    #: that consumed NO restart budget and slept no backoff
    planned_restarts: int = 0

    @property
    def succeeded(self) -> bool:
        return self.returncode == 0


class ElasticAgent:
    """Supervise ``cmd`` with restart-on-failure semantics
    (DSElasticAgent parity)."""

    def __init__(self, cmd: Sequence[str], max_restarts: int = 3,
                 backoff_s: float = 1.0,
                 backoff_multiplier: float = 2.0,
                 max_backoff_s: float = 60.0,
                 jitter: float = 0.25,
                 healthy_after_s: Optional[float] = None,
                 heartbeat_path: Optional[str] = None,
                 env: Optional[dict] = None,
                 on_restart: Optional[Callable[[int], None]] = None,
                 sleep: Callable[[float], None] = time.sleep,
                 rng: Optional[random.Random] = None,
                 planned_exit_codes: Sequence[int] = (
                     PLANNED_ROLLOUT_EXIT,),
                 max_planned_restarts: int = 64):
        self.cmd = list(cmd)
        self.max_restarts = max_restarts
        # planned-reload taxonomy (serving/rollout.py flips): these exit
        # codes relaunch immediately — no budget, no backoff. The
        # separate generous cap is the runaway valve: a worker stuck
        # exiting "planned" forever is a bug, not a rollout.
        self.planned_exit_codes = tuple(planned_exit_codes)
        self.max_planned_restarts = max_planned_restarts
        self.backoff_s = backoff_s
        self.backoff_multiplier = backoff_multiplier
        self.max_backoff_s = max_backoff_s
        self.jitter = jitter
        self.healthy_after_s = healthy_after_s
        self.heartbeat_path = heartbeat_path
        self.env = dict(env if env is not None else os.environ)
        self.on_restart = on_restart
        self._sleep = sleep
        self._rng = rng or random.Random()

    # ------------------------------------------------------------------
    def _write_status(self, state: str, restarts: int,
                      reason: Optional[str] = None,
                      next_delay_s: Optional[float] = None) -> None:
        """Overwrite the worker's heartbeat file with the agent's view —
        same atomic-rename discipline as telemetry.heartbeat.Heartbeat."""
        if not self.heartbeat_path:
            return
        d = os.path.dirname(self.heartbeat_path)
        if d:
            os.makedirs(d, exist_ok=True)
        rec = {"state": state, "restarts": int(restarts),
               "time": time.time()}
        if reason is not None:
            rec["reason"] = reason
            # rollout-triggered reload: an external stall/crash-loop
            # watchdog must read this window as ROUTINE (the flip IS the
            # restart), not page on it
            if reason.startswith("planned:"):
                rec["planned"] = True
        if next_delay_s is not None:
            rec["next_delay_s"] = round(float(next_delay_s), 3)
        try:
            write_json_atomic(self.heartbeat_path, rec)
        except OSError as e:  # status is best-effort, never fatal
            logger.warning(f"elastic agent: heartbeat write failed: {e}")

    def run(self) -> AgentReport:
        history: List[int] = []
        reasons: List[str] = []
        delay = self.backoff_s
        attempt = 0          # FAILURE restarts consumed (budgeted)
        planned = 0          # rollout reloads (free, capped separately)
        launches = 0
        while attempt <= self.max_restarts:
            env = dict(self.env, DST_ELASTIC_RESTART=str(launches))
            launches += 1
            self._write_status("running", attempt)
            t0 = time.monotonic()
            proc = subprocess.run(self.cmd, env=env)
            elapsed = time.monotonic() - t0
            history.append(proc.returncode)
            if proc.returncode == 0:
                self._write_status("done", attempt)
                return AgentReport(restarts=attempt, returncode=0,
                                   history=history, reasons=reasons,
                                   planned_restarts=planned)
            reason = classify_exit(proc.returncode,
                                   self.planned_exit_codes)
            reasons.append(reason)
            from ..telemetry.registry import get_registry
            if (reason.startswith("planned:")
                    and planned < self.max_planned_restarts):
                # rollout-triggered reload: relaunch NOW — no restart
                # budget consumed, no backoff slept, and the failure
                # backoff ladder is untouched (a flip mid-incident must
                # not reset a crash loop's climbing delay). Beyond the
                # planned cap the exit falls through to the failure path
                # — a worker stuck "planning" forever is a crash loop
                # wearing a flag.
                planned += 1
                get_registry().counter(
                    f"resilience/restart_reasons/{reason}").inc()
                logger.info(
                    f"elastic agent: planned worker reload ({reason}, "
                    f"#{planned}) — restarting without backoff")
                self._write_status("restarting", attempt, reason=reason,
                                   next_delay_s=0.0)
                continue
            logger.warning(
                f"elastic agent: worker failed ({reason}) "
                f"(attempt {attempt + 1}/{self.max_restarts + 1})")
            if attempt < self.max_restarts:
                from ..resilience import record_restart

                record_restart()
                get_registry().counter(
                    f"resilience/restart_reasons/{reason}").inc()
                if self.on_restart is not None:
                    self.on_restart(attempt)
                if (self.healthy_after_s is not None
                        and elapsed >= self.healthy_after_s):
                    # a long stable run before this failure: fresh incident,
                    # restart the backoff schedule from the bottom
                    delay = self.backoff_s
                d = delay * (1.0 + self._rng.uniform(0.0, self.jitter))
                self._write_status("restarting", attempt + 1, reason=reason,
                                   next_delay_s=d)
                self._sleep(d)
                delay = min(delay * self.backoff_multiplier,
                            self.max_backoff_s)
            attempt += 1
        self._write_status("failed", self.max_restarts,
                           reason=reasons[-1] if reasons else None)
        return AgentReport(restarts=self.max_restarts,
                           returncode=history[-1], history=history,
                           reasons=reasons, planned_restarts=planned)


def main(argv: Optional[Sequence[str]] = None) -> int:
    """CLI: ``python -m deepspeed_tpu.launcher.agent [--max-restarts N]
    [--backoff S] [--max-backoff S] [--jitter F] [--heartbeat PATH]
    -- cmd args...``"""
    import argparse

    p = argparse.ArgumentParser(prog="deepspeed_tpu.launcher.agent")
    p.add_argument("--max-restarts", type=int, default=3)
    p.add_argument("--backoff", type=float, default=1.0)
    p.add_argument("--backoff-multiplier", type=float, default=2.0)
    p.add_argument("--max-backoff", type=float, default=60.0)
    p.add_argument("--jitter", type=float, default=0.25)
    p.add_argument("--healthy-after", type=float, default=None,
                   help="runs longer than this reset the backoff (seconds)")
    p.add_argument("--heartbeat", type=str, default=None,
                   help="status file overwritten while the worker is down")
    p.add_argument("cmd", nargs=argparse.REMAINDER,
                   help="training command (prefix with --)")
    args = p.parse_args(argv)
    cmd = args.cmd[1:] if args.cmd and args.cmd[0] == "--" else args.cmd
    if not cmd:
        p.error("no command given")
    report = ElasticAgent(cmd, max_restarts=args.max_restarts,
                          backoff_s=args.backoff,
                          backoff_multiplier=args.backoff_multiplier,
                          max_backoff_s=args.max_backoff,
                          jitter=args.jitter,
                          healthy_after_s=args.healthy_after,
                          heartbeat_path=args.heartbeat).run()
    logger.info(f"elastic agent: done restarts={report.restarts} "
                f"rc={report.returncode} reasons={report.reasons}")
    return report.returncode


if __name__ == "__main__":
    sys.exit(main())
