"""Elastic agent: supervise a training process, restart on failure.

Reference surface: ``deepspeed/elasticity/elastic_agent.py:28``
(``DSElasticAgent._invoke_run`` :118 — monitor the worker group, restart
within ``max_restarts`` on failure/membership change, torchrun rendezvous
handling node join/leave).

TPU-native redesign: there is no torch-elastic rendezvous to subclass —
a TPU slice under one controller restarts as a unit. The agent is a
process supervisor: it launches the training command, watches for
failure, and restarts it up to ``max_restarts`` times with
``DST_ELASTIC_RESTART=<n>`` exported so the trainee knows to resume from
its latest checkpoint (resume-from-latest is the recovery mechanism —
SURVEY §5.3; cross-mesh resume is already checkpoint-native). A restart
honors an optional backoff and re-reads the world size from the
environment, so a shrunk slice resumes with a recomputed elastic batch
config (elasticity/elasticity.py compute_elastic_config).
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence

from ..utils.logging import logger


@dataclass
class AgentReport:
    restarts: int
    returncode: int
    history: List[int] = field(default_factory=list)

    @property
    def succeeded(self) -> bool:
        return self.returncode == 0


class ElasticAgent:
    """Supervise ``cmd`` with restart-on-failure semantics
    (DSElasticAgent parity)."""

    def __init__(self, cmd: Sequence[str], max_restarts: int = 3,
                 backoff_s: float = 1.0,
                 env: Optional[dict] = None,
                 on_restart: Optional[Callable[[int], None]] = None):
        self.cmd = list(cmd)
        self.max_restarts = max_restarts
        self.backoff_s = backoff_s
        self.env = dict(env if env is not None else os.environ)
        self.on_restart = on_restart

    def run(self) -> AgentReport:
        history: List[int] = []
        for attempt in range(self.max_restarts + 1):
            env = dict(self.env, DST_ELASTIC_RESTART=str(attempt))
            proc = subprocess.run(self.cmd, env=env)
            history.append(proc.returncode)
            if proc.returncode == 0:
                return AgentReport(restarts=attempt, returncode=0,
                                   history=history)
            logger.warning(
                f"elastic agent: worker failed rc={proc.returncode} "
                f"(attempt {attempt + 1}/{self.max_restarts + 1})")
            if attempt < self.max_restarts:
                from ..resilience import record_restart

                record_restart()
                if self.on_restart is not None:
                    self.on_restart(attempt)
                time.sleep(self.backoff_s)
        return AgentReport(restarts=self.max_restarts,
                           returncode=history[-1], history=history)


def main(argv: Optional[Sequence[str]] = None) -> int:
    """CLI: ``python -m deepspeed_tpu.launcher.agent [--max-restarts N]
    -- cmd args...``"""
    import argparse

    p = argparse.ArgumentParser(prog="deepspeed_tpu.launcher.agent")
    p.add_argument("--max-restarts", type=int, default=3)
    p.add_argument("--backoff", type=float, default=1.0)
    p.add_argument("cmd", nargs=argparse.REMAINDER,
                   help="training command (prefix with --)")
    args = p.parse_args(argv)
    cmd = args.cmd[1:] if args.cmd and args.cmd[0] == "--" else args.cmd
    if not cmd:
        p.error("no command given")
    report = ElasticAgent(cmd, max_restarts=args.max_restarts,
                          backoff_s=args.backoff).run()
    logger.info(f"elastic agent: done restarts={report.restarts} "
                f"rc={report.returncode}")
    return report.returncode


if __name__ == "__main__":
    sys.exit(main())
