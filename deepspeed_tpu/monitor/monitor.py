"""Experiment monitoring.

Capability parity with the reference's ``deepspeed/monitor/`` —
``MonitorMaster`` (monitor.py:29) fanning out to TensorBoard
(tensorboard.py:13), CSV (csv_monitor.py:12) and W&B (wandb.py:12) writers;
the engine posts loss/lr/grad-norm events at step boundaries
(engine.py:2146-:2167 ``_write_monitor``).
"""

from __future__ import annotations

import csv
import os
from typing import Any, List, Optional, Tuple

from ..config import MonitorConfig
from ..utils.logging import logger

Event = Tuple[str, float, int]  # (name, value, step)


class CsvMonitor:
    def __init__(self, output_path: str, job_name: str):
        self.dir = os.path.join(output_path or "csv_monitor", job_name)
        os.makedirs(self.dir, exist_ok=True)
        # per-metric open handles: one os.open per metric per run, not one
        # open/close per event
        self._files = {}

    def _writer(self, name: str):
        entry = self._files.get(name)
        if entry is None:
            fname = os.path.join(self.dir, name.replace("/", "_") + ".csv")
            new = not os.path.exists(fname) or os.path.getsize(fname) == 0
            f = open(fname, "a", newline="")
            w = csv.writer(f)
            if new:
                w.writerow(["step", name])
            entry = (f, w)
            self._files[name] = entry
        return entry

    def write_events(self, events: List[Event]) -> None:
        touched = set()
        for name, value, step in events:
            _, w = self._writer(name)
            w.writerow([step, value])
            touched.add(name)
        for name in touched:
            self._files[name][0].flush()

    def close(self) -> None:
        for f, _ in self._files.values():
            if not f.closed:
                f.flush()
                f.close()
        self._files.clear()


class TensorBoardMonitor:
    def __init__(self, output_path: str, job_name: str):
        self.writer = None
        try:
            from torch.utils.tensorboard import SummaryWriter  # cpu torch is available

            self.writer = SummaryWriter(log_dir=os.path.join(output_path or "tensorboard", job_name))
        except Exception as e:
            logger.warning(f"tensorboard writer unavailable ({e}); events dropped")

    def write_events(self, events: List[Event]) -> None:
        if self.writer is None:
            return
        for name, value, step in events:
            self.writer.add_scalar(name, value, step)
        self.writer.flush()

    def close(self) -> None:
        if self.writer is not None:
            self.writer.close()
            self.writer = None


class WandbMonitor:
    def __init__(self, project: Optional[str], team: Optional[str], group: Optional[str]):
        self.run = None
        try:
            import wandb  # not in the image; gated

            self.run = wandb.init(project=project, entity=team, group=group)
        except Exception as e:
            logger.warning(f"wandb unavailable ({e}); events dropped")

    def write_events(self, events: List[Event]) -> None:
        if self.run is None:
            return
        for name, value, step in events:
            self.run.log({name: value}, step=step)

    def close(self) -> None:
        if self.run is not None:
            self.run.finish()
            self.run = None


class MonitorMaster:
    """Fan-out monitor (reference monitor/monitor.py:29)."""

    def __init__(self, config: MonitorConfig):
        self.writers: List[Any] = []
        if config.csv_enabled:
            self.writers.append(CsvMonitor(config.csv_output_path, config.csv_job_name))
        if config.tensorboard_enabled:
            self.writers.append(TensorBoardMonitor(config.tensorboard_output_path, config.tensorboard_job_name))
        if config.wandb_enabled:
            self.writers.append(WandbMonitor(config.wandb_project, config.wandb_team, config.wandb_group))

    def write_events(self, events: List[Event]) -> None:
        for w in self.writers:
            w.write_events(events)

    def close(self) -> None:
        """Flush and close every writer (idempotent). Called from engine
        shutdown — the TensorBoard writer in particular buffers events and
        loses the tail of a run if never closed."""
        for w in self.writers:
            close = getattr(w, "close", None)
            if close is not None:
                try:
                    close()
                except Exception as e:
                    logger.warning(f"monitor writer close failed: {e}")
        self.writers = []
