"""Experiment monitoring.

Capability parity with the reference's ``deepspeed/monitor/`` —
``MonitorMaster`` (monitor.py:29) fanning out to TensorBoard
(tensorboard.py:13), CSV (csv_monitor.py:12) and W&B (wandb.py:12) writers;
the engine posts loss/lr/grad-norm events at step boundaries
(engine.py:2146-:2167 ``_write_monitor``).
"""

from __future__ import annotations

import csv
import os
from typing import Any, List, Optional, Tuple

from ..config import MonitorConfig
from ..utils.logging import logger

Event = Tuple[str, float, int]  # (name, value, step)


class CsvMonitor:
    def __init__(self, output_path: str, job_name: str):
        self.dir = os.path.join(output_path or "csv_monitor", job_name)
        os.makedirs(self.dir, exist_ok=True)
        self._files = {}

    def write_events(self, events: List[Event]) -> None:
        for name, value, step in events:
            fname = os.path.join(self.dir, name.replace("/", "_") + ".csv")
            new = not os.path.exists(fname)
            with open(fname, "a", newline="") as f:
                w = csv.writer(f)
                if new:
                    w.writerow(["step", name])
                w.writerow([step, value])


class TensorBoardMonitor:
    def __init__(self, output_path: str, job_name: str):
        self.writer = None
        try:
            from torch.utils.tensorboard import SummaryWriter  # cpu torch is available

            self.writer = SummaryWriter(log_dir=os.path.join(output_path or "tensorboard", job_name))
        except Exception as e:
            logger.warning(f"tensorboard writer unavailable ({e}); events dropped")

    def write_events(self, events: List[Event]) -> None:
        if self.writer is None:
            return
        for name, value, step in events:
            self.writer.add_scalar(name, value, step)
        self.writer.flush()


class WandbMonitor:
    def __init__(self, project: Optional[str], team: Optional[str], group: Optional[str]):
        self.run = None
        try:
            import wandb  # not in the image; gated

            self.run = wandb.init(project=project, entity=team, group=group)
        except Exception as e:
            logger.warning(f"wandb unavailable ({e}); events dropped")

    def write_events(self, events: List[Event]) -> None:
        if self.run is None:
            return
        for name, value, step in events:
            self.run.log({name: value}, step=step)


class MonitorMaster:
    """Fan-out monitor (reference monitor/monitor.py:29)."""

    def __init__(self, config: MonitorConfig):
        self.writers: List[Any] = []
        if config.csv_enabled:
            self.writers.append(CsvMonitor(config.csv_output_path, config.csv_job_name))
        if config.tensorboard_enabled:
            self.writers.append(TensorBoardMonitor(config.tensorboard_output_path, config.tensorboard_job_name))
        if config.wandb_enabled:
            self.writers.append(WandbMonitor(config.wandb_project, config.wandb_team, config.wandb_group))

    def write_events(self, events: List[Event]) -> None:
        for w in self.writers:
            w.write_events(events)
