"""DeepSpeed-TPU: a TPU-native large-model training & inference framework.

Capability parity with DeepSpeed (reference snapshot v0.12.4), redesigned
for TPU: JAX/XLA/pjit for the compute path, one named device mesh
(data/seq/pipe/expert/model) for every parallelism flavor, Pallas kernels
for the hot ops, GSPMD placement instead of hook machinery for ZeRO.

Public API parity with ``deepspeed/__init__.py``: :func:`initialize`
(:64 in the reference) returning ``(engine, optimizer, dataloader,
lr_scheduler)``, :func:`init_inference` (:269), and
:func:`add_config_arguments` (:246).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

from .version import __version__  # noqa: F401
from .config import Config, ConfigError, add_config_arguments  # noqa: F401
from .parallel.mesh import Topology, get_topology, initialize_topology, set_topology  # noqa: F401
from .runtime.engine import TrainEngine
from .runtime.dataloader import DataLoader, RepeatingLoader  # noqa: F401
from . import comm  # noqa: F401
from . import serving  # noqa: F401
from . import telemetry  # noqa: F401
from .comm.comm import init_distributed  # noqa: F401
from .telemetry import MetricsRegistry, StepStats, get_telemetry  # noqa: F401
from .utils.logging import logger, log_dist  # noqa: F401


def initialize(*,
               loss_fn: Optional[Callable] = None,
               params: Any = None,
               model: Any = None,
               config: Any = None,
               config_params: Any = None,
               optimizer: Any = None,
               lr_scheduler: Any = None,
               training_data: Any = None,
               topology: Optional[Topology] = None,
               tp_specs: Any = None,
               collate_fn: Optional[Callable] = None,
               rng: Any = None,
               model_args: Tuple = (),
               ) -> Tuple[TrainEngine, Any, Any, Any]:
    """Bring up a training engine. Parity with ``deepspeed.initialize``
    (reference deepspeed/__init__.py:64) — returns
    ``(engine, optimizer, dataloader, lr_scheduler)``.

    TPU-native model protocol: pass either
      * ``loss_fn(params, batch, rng) -> loss | (loss, aux)`` plus ``params``
        (any pytree), or
      * ``model`` — an object with ``.init(rng, *model_args)`` and a
        ``.loss(params, batch, rng)`` method (every model in
        ``deepspeed_tpu.models`` implements this; flax modules wrap in one
        line via :func:`deepspeed_tpu.models.api.from_flax`).

    ``config`` is a dict or a path to a DeepSpeed-style JSON file.
    """
    cfg = Config.from_any(config if config is not None else config_params)
    if topology is None:
        # hpZ / MiCS factor the data axis into data × zshard (mesh.py)
        topology = Topology.build(cfg.mesh, zero_inner=cfg.zero.zero_inner_size())
    set_topology(topology)
    init_distributed()
    if model is not None and hasattr(model, "bind_topology"):
        model.bind_topology(topology)

    if loss_fn is None:
        if model is None or not hasattr(model, "loss"):
            raise ValueError("initialize() needs loss_fn+params, or a model exposing .loss()")
        loss_fn = model.loss
    if params is None:
        if model is None or not hasattr(model, "init"):
            raise ValueError("initialize() needs params, or a model exposing .init()")
        import jax

        from .parallel.zero import ZeroShardingRules

        # sharded init (zero.Init parity, reference
        # runtime/zero/partition_parameters.py:734): the param tree is
        # constructed BY a jitted init with ZeRO/TP out_shardings, so each
        # device only ever materializes its own shard — models larger than
        # one host/chip can construct. eval_shape costs nothing.
        init_rng = rng if rng is not None else jax.random.PRNGKey(cfg.train_seed)
        try:
            param_shapes = jax.eval_shape(model.init, init_rng, *model_args)
        except TypeError:
            # non-array model_args (e.g. a dtype) can't trace — fall back to
            # eager init; the engine re-places the tree afterwards
            param_shapes = None
        if param_shapes is not None:
            if tp_specs is None and hasattr(model, "partition_specs"):
                tp_specs = model.partition_specs(param_shapes, topology)
            rules = ZeroShardingRules(topology, cfg.zero)
            init_shardings = rules.param_shardings(param_shapes, tp_specs)
            params = jax.jit(model.init,  # dslint: disable=recompile-hazard -- one-shot sharded init at engine construction; initialize() runs once per process
                             out_shardings=init_shardings)(init_rng, *model_args)
        else:
            params = model.init(init_rng, *model_args)
    if tp_specs is None and model is not None and hasattr(model, "partition_specs"):
        tp_specs = model.partition_specs(params, topology)

    engine = TrainEngine(
        loss_fn=loss_fn, params=params, config=cfg, topology=topology,
        optimizer=optimizer, lr_scheduler=lr_scheduler, tp_specs=tp_specs, model=model)

    dataloader = None
    if training_data is not None:
        # prefetch_depth > 0: a producer thread runs collate + sharded
        # device_put ahead of the training loop (docs/performance.md)
        dataloader = DataLoader(training_data, cfg.train_batch_size, topology,
                                seed=cfg.train_seed, collate_fn=collate_fn,
                                prefetch_depth=cfg.dataloader.prefetch_depth)
        # checkpoints carry the loader position (epoch + batch index) so a
        # resumed run replays the exact remaining batch order
        engine.bind_dataloader(dataloader)
    if cfg.checkpoint.auto_resume and cfg.checkpoint.save_dir:
        # preemption-safe auto-resume (docs/fault_tolerance.md): pick up
        # from the newest VALID checkpoint — torn/corrupt tags are skipped
        # by the manifest verification; a missing dir is first boot
        engine.load_checkpoint(cfg.checkpoint.save_dir, auto=True)
    if cfg.compile.aot_warmup and dataloader is not None:
        # AOT-compile the fused step in the background (after auto-resume:
        # the loader's restored position decides the warmup batch shape),
        # overlapped with the prefetch pipeline's warm fill; the first
        # train_batch joins it (docs/performance.md)
        try:
            struct = dataloader.batch_struct()
            if struct is not None:
                engine.warmup_async(struct)
        except Exception as e:  # warmup is best-effort, never fatal
            logger.warning(f"AOT warmup skipped: {e}")
    return engine, engine.optimizer, dataloader, engine.lr_schedule


def init_inference(model: Any = None, config: Any = None, **kwargs):
    """Parity with ``deepspeed.init_inference`` (reference __init__.py:269).

    ``model`` may be a model object (random init), a ``(model, params)``
    pair (e.g. from :func:`deepspeed_tpu.checkpoint.from_pretrained`), or
    None with a ``checkpoint`` entry in the config pointing at an HF
    checkpoint directory (reference InferenceConfig.checkpoint +
    load_model_with_checkpoint, inference/engine.py:324).
    """
    from .inference.engine import InferenceEngine, InferenceConfig

    icfg = InferenceConfig.from_any(config, **kwargs)
    params = None
    if isinstance(model, tuple):
        model, params = model
    if icfg.extras.get("checkpoint") and params is None:
        from .checkpoint import from_pretrained

        loaded_model, params = from_pretrained(icfg.extras["checkpoint"],
                                               dtype=icfg.jnp_dtype)
        # a user-supplied model keeps serving (its config must match the
        # checkpoint — shape mismatches fail loudly at first forward);
        # otherwise the checkpoint's own config builds the model
        if model is None:
            model = loaded_model
    return InferenceEngine(model=model, config=icfg, params=params)
