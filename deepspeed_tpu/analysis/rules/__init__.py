"""Rule modules self-register on import (see ..registry)."""

from . import host_sync        # noqa: F401
from . import trace_hygiene    # noqa: F401
from . import recompile        # noqa: F401
from . import locks            # noqa: F401
from . import exceptions       # noqa: F401
from . import wall_clock       # noqa: F401
from . import comm_facade      # noqa: F401
from . import races            # noqa: F401
