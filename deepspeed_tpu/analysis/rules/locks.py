"""lock-discipline: what happens while a lock is held.

The serving layer's documented discipline (docs/serving.md):

* lock ORDER is region -> cell -> fleet -> replica (``Region._lock``
  before ``ServingCell._lock`` before ``ServingFleet._lock`` before
  ``ServingEngine._lock``); any path acquiring a pair in reverse can
  deadlock against the monitor/driver threads — which is why every
  upward callback (fleet->region retire hooks, route/hand-off
  escalation) is invoked OUTSIDE the caller's own lock;
* spans, KV export/import and handoff callbacks run OUTSIDE the serving
  lock — sink I/O or a multi-MB page copy under it stalls every
  ``submit()``/``cancel()``/tick on the replica;
* user callbacks (``on_token``/``on_handoff``/``on_retire``) never run
  under a lock the caller's code can re-enter.

The rule builds the package lock-acquisition graph: every
``with <lock>:`` region, the blocking operations lexically inside it,
and — transitively through the resolved call graph — the locks acquired
and blocking calls made by functions invoked while the lock is held.
Findings report the full call path so a human can audit the chain.

Checks:
* ``order-violation`` — an edge that contradicts the documented order;
* ``lock-cycle`` — a cycle in the acquisition graph (undocumented
  orders included: cycles deadlock regardless of documentation);
* ``self-deadlock`` — re-acquiring a non-reentrant ``Lock`` you hold;
* ``blocking-under-lock`` — sleep/join/wait, file or sink I/O,
  ``device_put``/transfers, unbounded ``queue`` ops under a held lock;
* ``callback-under-lock`` — invoking a user-supplied callback
  (``on_*`` / ``*_callback`` attributes that resolve to no package
  method) while holding a lock.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from ..findings import Finding
from ..model import (PackageModel, FunctionInfo, ModuleInfo, LockRegion,
                     final_attr_name, dotted_name, iter_shallow)
from ..registry import Rule, register

# Documented lock order, outermost first, matched by "Class.attr"
# suffix so the rule also drives the fixture corpus. Source of truth:
# docs/serving.md ("region -> cell -> fleet -> replica").
DOCUMENTED_LOCK_ORDER: Sequence[str] = (
    "Region._lock",
    "ServingCell._lock",
    "ServingFleet._lock",
    "ServingEngine._lock",
)

_CALLBACK_NAME = re.compile(r"^_?on_[a-z0-9_]+$|_callback$|^callback$")

_BLOCKING_SIMPLE = {
    "sleep": "time.sleep",
    "fsync": "os.fsync",
    "system": "os.system",
}
_DEVICE_CALLS = {"device_put", "device_get", "block_until_ready"}
_IO_RECEIVER_HINT = re.compile(
    r"(^|_)(sink|file|fh|fp|stream|writer|sock|socket)s?$")
_MAX_DEPTH = 4


def _lock_display(key: str) -> str:
    return key.split("::")[-1]


class _Summary:
    """Per-function facts the transitive walk composes."""

    def __init__(self) -> None:
        # (node, code, description) lexically in the function body but
        # OUTSIDE any nested with-lock (those are charged to the inner
        # region's holder)
        self.blocking: List[Tuple[ast.AST, str, str]] = []
        self.callbacks: List[Tuple[ast.AST, str]] = []
        self.locks: List[LockRegion] = []


@register
class LockDisciplineRule(Rule):
    id = "lock-discipline"
    summary = ("lock-order cycles vs the documented region->cell->"
               "fleet->replica order; blocking calls and user callbacks "
               "under a held lock")

    def run(self, pkg: PackageModel) -> Iterator[Finding]:
        self.pkg = pkg
        summaries: Dict[str, _Summary] = {}
        for f in pkg.functions.values():
            summaries[f.key] = self._summarize(f)
        # per-region findings + edge collection; ``self.edges`` is kept
        # for collect_lock_graph (the runtime sanitizer cross-validates
        # observed acquisition edges against exactly this graph)
        edges: Dict[Tuple[str, str], Tuple[FunctionInfo, ast.AST, str]] = {}
        self.edges = edges
        for f in pkg.functions.values():
            for region in f.lock_regions:
                yield from self._check_region(f, region, summaries,
                                              edges)
        yield from self._check_graph(edges)

    # -- summaries ------------------------------------------------------
    def _summarize(self, f: FunctionInfo) -> _Summary:
        s = _Summary()
        s.locks = list(f.lock_regions)
        mod = self.pkg.modules[f.module]
        lock_nodes = {id(r.with_node) for r in f.lock_regions}

        def walk(node: ast.AST, under_lock: bool) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda,
                                      ast.ClassDef)):
                    continue
                inner = under_lock or id(child) in lock_nodes
                if isinstance(child, ast.Call) and not inner:
                    hit = self._classify_blocking(child, f, mod)
                    if hit:
                        s.blocking.append((child,) + hit)
                    cb = self._classify_callback(child, f)
                    if cb:
                        s.callbacks.append((child, cb))
                walk(child, inner)

        walk(f.node, False)
        return s

    def _classify_blocking(self, call: ast.Call, f: FunctionInfo,
                           mod: ModuleInfo
                           ) -> Optional[Tuple[str, str]]:
        func = call.func
        name = final_attr_name(func)
        if name is None:
            return None
        if isinstance(func, ast.Name):
            if name == "open":
                return ("file-io", "open()")
            imp = mod.name_imports.get(name)
            if imp and imp[0].lstrip(".") == "time" and imp[1] == "sleep":
                return ("sleep", "time.sleep()")
            return None
        # attribute calls ------------------------------------------------
        recv = func.value
        recv_name = final_attr_name(recv) or ""
        dn = dotted_name(func) or ""
        head = dn.split(".")[0] if dn else ""
        real = mod.alias_to_module.get(head, "")
        if name in _BLOCKING_SIMPLE and real in {"time", "os"}:
            return ("sleep" if name == "sleep" else "file-io",
                    f"{_BLOCKING_SIMPLE[name]}()")
        if real == "subprocess" or real.startswith("subprocess."):
            return ("subprocess", f"subprocess.{name}()")
        if name in _DEVICE_CALLS:
            return ("device-transfer", f".{name}() (device round-trip)")
        if name == "join" and not isinstance(recv, ast.Constant) \
                and not (isinstance(recv, ast.Name)
                         and recv.id in {"sep", "delim"}):
            # "x".join(...) has a Constant receiver; thread/process
            # joins have names. String vars named like containers still
            # slip through — suppress those with a reason.
            if isinstance(recv, (ast.Name, ast.Attribute)) \
                    and not recv_name.startswith(("str", "text")):
                return ("join", f"{recv_name or '<expr>'}.join()")
            return None
        if name == "wait":
            return ("wait", f"{recv_name or '<expr>'}.wait()")
        if name in {"write", "flush", "dump"} \
                and _IO_RECEIVER_HINT.search(recv_name):
            return ("file-io", f"{recv_name}.{name}()")
        if name in {"dump", "save"} and real in {"json", "pickle",
                                                 "numpy"}:
            return ("file-io", f"{head}.{name}()")
        if name in {"put", "get"} and self._is_queue_recv(recv, f):
            if not any(kw.arg in {"timeout", "block"}
                       for kw in call.keywords):
                return ("queue-op",
                        f"unbounded {recv_name or 'queue'}.{name}()")
        return None

    def _is_queue_recv(self, recv: ast.AST, f: FunctionInfo) -> bool:
        """Receiver known to be a queue.Queue: an attr annotated/assigned
        Queue, or a name containing 'queue'/'_q'. dicts also have .get —
        never treat plain names without the hint as queues."""
        rn = (final_attr_name(recv) or "").lower()
        if rn in {"q", "queue"} or rn.endswith(("_q", "_queue")) \
                or rn.startswith("queue_"):
            # exclude the serving request *list* named _queue: list.append
            # etc. never reach here (only put/get do), and a list named
            # _queue has no put/get — safe.
            return True
        if isinstance(recv, ast.Attribute) and f.class_key:
            cls = self.pkg.classes[f.class_key]
            return cls.attr_types.get(recv.attr) == "Queue"
        return False

    def _classify_callback(self, call: ast.Call,
                           f: FunctionInfo) -> Optional[str]:
        name = final_attr_name(call.func)
        if name is None or not _CALLBACK_NAME.search(name):
            return None
        # a name that is a method of ANY package class (router.on_join,
        # the fleet's _on_handoff) is framework code, not a
        # caller-supplied callback — user callbacks (on_token) have no
        # definition inside the package
        if self.pkg.method_index.get(name):
            return None
        for site in f.calls:
            if site.node is call and site.targets:
                return None
        return name

    # -- region checks --------------------------------------------------
    def _check_region(self, f: FunctionInfo, region: LockRegion,
                      summaries: Dict[str, _Summary],
                      edges) -> Iterator[Finding]:
        mod = self.pkg.modules[f.module]
        held = region.lock_key

        # direct hits inside this with-block
        for node in iter_shallow(region.with_node):
            if isinstance(node, ast.Call):
                hit = self._classify_blocking(node, f, mod)
                if hit:
                    code, desc = hit
                    yield Finding(
                        rule=self.id, code="blocking-under-lock",
                        path=mod.key, line=node.lineno,
                        col=node.col_offset, symbol=f.qualname,
                        message=f"{desc} while holding "
                                f"{_lock_display(held)} ({code}) — "
                                f"move it outside the lock")
                cb = self._classify_callback(node, f)
                if cb:
                    yield Finding(
                        rule=self.id, code="callback-under-lock",
                        path=mod.key, line=node.lineno,
                        col=node.col_offset, symbol=f.qualname,
                        message=f"user callback {cb}() invoked while "
                                f"holding {_lock_display(held)} — "
                                f"caller code under our lock can "
                                f"re-enter or block the "
                                f"driver; defer it past the release")
            elif isinstance(node, ast.With) and node is not region.with_node:
                for item in node.items:
                    inner_key = self._region_key_of(f, node)
                    if inner_key and inner_key != held:
                        edges.setdefault(
                            (held, inner_key),
                            (f, node, f"{f.qualname} (direct)"))
                    break

        # transitive: calls made while the lock is held
        for site_node, path, target in self._calls_under(
                f, region, summaries):
            tsum = summaries.get(target)
            tf = self.pkg.functions.get(target)
            if tsum is None or tf is None:
                continue
            for r2 in tsum.locks:
                if r2.lock_key != held:
                    edges.setdefault(
                        (held, r2.lock_key),
                        (f, site_node, " -> ".join(path)))
                elif self._lock_ctor(held) == "Lock":
                    yield Finding(
                        rule=self.id, code="self-deadlock",
                        path=mod.key, line=site_node.lineno,
                        col=site_node.col_offset, symbol=f.qualname,
                        message=f"re-acquires non-reentrant "
                                f"{_lock_display(held)} already held "
                                f"(via {' -> '.join(path)}) — "
                                f"deadlock; use RLock or split the "
                                f"locked helper")
            for bnode, code, desc in tsum.blocking:
                yield Finding(
                    rule=self.id, code="blocking-under-lock",
                    path=mod.key, line=site_node.lineno,
                    col=site_node.col_offset, symbol=f.qualname,
                    message=f"{desc} at {self.pkg.functions[target].module}"
                            f":{bnode.lineno} runs while "
                            f"{_lock_display(held)} is held "
                            f"(via {' -> '.join(path)}) — {code}")
            for cnode, cb in tsum.callbacks:
                yield Finding(
                    rule=self.id, code="callback-under-lock",
                    path=mod.key, line=site_node.lineno,
                    col=site_node.col_offset, symbol=f.qualname,
                    message=f"user callback {cb}() (in "
                            f"{self.pkg.functions[target].qualname}) "
                            f"runs while {_lock_display(held)} is held "
                            f"(via {' -> '.join(path)})")

    def _region_key_of(self, f: FunctionInfo,
                       with_node: ast.With) -> Optional[str]:
        for r in f.lock_regions:
            if r.with_node is with_node:
                return r.lock_key
        return None

    def _calls_under(self, f: FunctionInfo, region: LockRegion,
                     summaries: Dict[str, _Summary]
                     ) -> Iterator[Tuple[ast.AST, List[str], str]]:
        """(site node, human path, target key) for every package
        function reachable from inside the with-block, depth-limited.
        Matched by site node (not ``isinstance(Call)``) so @property
        getter sites — attribute loads that acquire locks, like a fleet
        gauge pass reading ``r.serving.queue_depth`` — are followed
        too."""
        region_nodes = {id(n) for n in iter_shallow(region.with_node)}
        start: List[Tuple[ast.AST, str]] = []
        for site in f.calls:
            if id(site.node) in region_nodes:
                for t in site.targets:
                    start.append((site.node, t))
        seen: Set[str] = {f.key}
        frontier = [(node, [self.pkg.functions[t].qualname], t)
                    for node, t in start if t in self.pkg.functions]
        depth = 0
        while frontier and depth < _MAX_DEPTH:
            nxt = []
            for node, path, t in frontier:
                if t in seen:
                    continue
                seen.add(t)
                yield node, path, t
                tf = self.pkg.functions[t]
                for site in tf.calls:
                    for t2 in site.targets:
                        if t2 not in seen and t2 in self.pkg.functions:
                            nxt.append(
                                (node,
                                 path + [self.pkg.functions[t2].qualname],
                                 t2))
            frontier = nxt
            depth += 1

    # -- graph checks ---------------------------------------------------
    def _lock_ctor(self, lock_key: str) -> Optional[str]:
        if "::" not in lock_key:
            return None
        left, attr = lock_key.rsplit(".", 1)
        cls = self.pkg.classes.get(left)
        if cls is not None:
            return cls.lock_attrs.get(attr)
        mod_key, name = lock_key.split("::", 1)
        mod = self.pkg.modules.get(mod_key)
        if mod is not None:
            return mod.module_locks.get(name)
        return None

    def _order_pos(self, lock_key: str) -> Optional[int]:
        disp = _lock_display(lock_key)
        for i, suffix in enumerate(DOCUMENTED_LOCK_ORDER):
            if disp == suffix or disp.endswith("." + suffix):
                return i
        return None

    def _check_graph(self, edges) -> Iterator[Finding]:
        graph: Dict[str, Set[str]] = {}
        for (a, b) in edges:
            graph.setdefault(a, set()).add(b)
        # documented order
        for (a, b), (f, node, path) in sorted(
                edges.items(), key=lambda kv: (kv[1][0].module,
                                               kv[1][1].lineno)):
            pa, pb = self._order_pos(a), self._order_pos(b)
            if pa is not None and pb is not None and pb < pa:
                yield Finding(
                    rule=self.id, code="order-violation",
                    path=self.pkg.functions[f.key].module,
                    line=node.lineno, col=node.col_offset,
                    symbol=f.qualname,
                    message=f"acquires {_lock_display(b)} while holding "
                            f"{_lock_display(a)} (via {path}) — "
                            f"documented order is "
                            f"{' -> '.join(DOCUMENTED_LOCK_ORDER)}")
        # cycles (DFS)
        reported: Set[frozenset] = set()
        for start in sorted(graph):
            stack = [(start, [start])]
            while stack:
                cur, path = stack.pop()
                for nxt in sorted(graph.get(cur, ())):
                    if nxt == start and len(path) > 1:
                        key = frozenset(path)
                        if key in reported:
                            continue
                        reported.add(key)
                        f, node, epath = edges[(path[0], path[1])]
                        cyc = " -> ".join(_lock_display(p)
                                          for p in path + [start])
                        yield Finding(
                            rule=self.id, code="lock-cycle",
                            path=self.pkg.functions[f.key].module,
                            line=node.lineno, col=node.col_offset,
                            symbol=f.qualname,
                            message=f"lock acquisition cycle {cyc} — "
                                    f"two threads taking these in "
                                    f"different orders deadlock")
                    elif nxt not in path:
                        stack.append((nxt, path + [nxt]))


def collect_lock_graph(pkg: PackageModel) -> Dict[Tuple[str, str], str]:
    """The static lock-acquisition graph at display granularity:
    ``{("ServingFleet._lock", "ServingEngine._lock"): "<call path>"}``.
    This is the graph the runtime lock-order sanitizer
    (resilience/locksan.py) cross-validates against: every acquisition
    edge a real run observes must exist here, or the static model has a
    false negative (docs/static_analysis.md "races")."""
    rule = LockDisciplineRule()
    for _ in rule.run(pkg):
        pass
    out: Dict[Tuple[str, str], str] = {}
    for (a, b), (f, _node, path) in rule.edges.items():
        out[(_lock_display(a), _lock_display(b))] = f"{f.qualname}: {path}"
    return out
