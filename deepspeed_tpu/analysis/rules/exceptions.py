"""exception-discipline: broad handlers that swallow typed fault
semantics.

The resilience/serving layers communicate through exception TYPES:
``TickFault`` (recoverable tick error -> retry budget), ``PoolExhausted``
(typed KV exhaustion -> preempt-and-retry, explicitly distinct from a
generic device RuntimeError), ``InjectedFault`` (chaos, a BaseException
precisely so ``except Exception`` can never absorb an injected crash),
``RetryError`` (budget spent). A broad ``except Exception`` dropped into
a tick/retry path silently converts those contracts into "log and carry
on" — the soak passes, the recovery path rots.

Checks:
* ``bare-except`` — ``except:`` catches BaseException, including
  InjectedFault and KeyboardInterrupt; always flagged (package-wide)
  unless the handler re-raises;
* ``broad-baseexception`` — ``except BaseException`` without re-raise,
  same blast radius, package-wide;
* ``broad-except`` — ``except Exception`` in a tick/retry/serving/
  resilience path that neither re-raises, nor follows a narrower
  domain-fault handler, nor visibly hands the exception to a recovery
  function (passing ``e`` to a non-logging call);
* ``caught-injected-fault`` — explicitly catching InjectedFault outside
  the chaos harness defeats the whole point of injecting it.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, List, Optional

from ..findings import Finding
from ..model import (PackageModel, FunctionInfo, ModuleInfo,
                     final_attr_name, iter_shallow)
from ..registry import Rule, register

DOMAIN_FAULTS = {"TickFault", "PoolExhausted", "InjectedFault",
                 "CollectiveFault", "RetryError"}
_DOMAIN_PATH = re.compile(r"(^|/)(serving|resilience)(/|\.py$)")
_DOMAIN_FUNC = re.compile(r"tick|retry|drive|recover|resume")
_LOGGING_HEADS = {"logger", "logging", "warnings", "log", "print",
                  "log_dist"}


def _handler_names(h: ast.ExceptHandler) -> List[str]:
    if h.type is None:
        return []
    types = h.type.elts if isinstance(h.type, ast.Tuple) else [h.type]
    out = []
    for t in types:
        n = final_attr_name(t)
        if n:
            out.append(n)
    return out


def _reraises(h: ast.ExceptHandler) -> bool:
    for node in iter_shallow(h):
        if isinstance(node, ast.Raise):
            return True
    return False


def _hands_off(h: ast.ExceptHandler) -> bool:
    """The bound exception is passed as an argument to a non-logging
    call — the handler is routing the fault to recovery machinery
    (``self._on_tick_fault(uids, e)``), not swallowing it."""
    if h.name is None:
        return False
    for node in iter_shallow(h):
        if not isinstance(node, ast.Call):
            continue
        head = node.func
        while isinstance(head, ast.Attribute):
            head = head.value
        head_name = head.id if isinstance(head, ast.Name) else ""
        fname = final_attr_name(node.func) or ""
        if head_name in _LOGGING_HEADS or fname in _LOGGING_HEADS:
            continue
        for arg in node.args:
            if isinstance(arg, ast.Name) and arg.id == h.name:
                return True
    return False


@register
class ExceptionDisciplineRule(Rule):
    id = "exception-discipline"
    summary = ("bare/BaseException handlers anywhere; except Exception "
               "in tick/retry paths that swallows typed fault semantics")

    def run(self, pkg: PackageModel) -> Iterator[Finding]:
        for mod in pkg.modules.values():
            in_chaos = mod.key.endswith("resilience/chaos.py")
            for f in pkg.functions_in(mod.key):
                yield from self._check_function(f, mod, in_chaos)

    def _check_function(self, f: FunctionInfo, mod: ModuleInfo,
                        in_chaos: bool) -> Iterator[Finding]:
        domain = bool(_DOMAIN_PATH.search(mod.key)
                      or _DOMAIN_FUNC.search(f.name))
        for node in iter_shallow(f.node):
            if not isinstance(node, ast.Try):
                continue
            narrower_domain = False
            for h in node.handlers:
                names = _handler_names(h)
                if set(names) & DOMAIN_FAULTS:
                    if "InjectedFault" in names and not in_chaos:
                        yield Finding(
                            rule=self.id, code="caught-injected-fault",
                            path=mod.key, line=h.lineno,
                            col=h.col_offset, symbol=f.qualname,
                            message="catching InjectedFault defeats "
                                    "chaos testing — it is a "
                                    "BaseException precisely so fault "
                                    "injection can't be absorbed")
                    narrower_domain = True
                    continue
                if h.type is None:
                    if not _reraises(h):
                        yield Finding(
                            rule=self.id, code="bare-except",
                            path=mod.key, line=h.lineno,
                            col=h.col_offset, symbol=f.qualname,
                            message="bare `except:` swallows "
                                    "BaseException — including "
                                    "InjectedFault and "
                                    "KeyboardInterrupt; catch the "
                                    "specific types or re-raise")
                    continue
                if "BaseException" in names and not _reraises(h):
                    yield Finding(
                        rule=self.id, code="broad-baseexception",
                        path=mod.key, line=h.lineno, col=h.col_offset,
                        symbol=f.qualname,
                        message="`except BaseException` without "
                                "re-raise swallows InjectedFault / "
                                "KeyboardInterrupt")
                    continue
                if "Exception" in names and domain:
                    if _reraises(h) or narrower_domain or _hands_off(h):
                        continue
                    yield Finding(
                        rule=self.id, code="broad-except", path=mod.key,
                        line=h.lineno, col=h.col_offset,
                        symbol=f.qualname,
                        message="broad `except Exception` in a "
                                "tick/retry path can absorb "
                                "TickFault/PoolExhausted recovery "
                                "semantics — catch the typed faults "
                                "first, re-raise, or hand the "
                                "exception to the recovery path")
