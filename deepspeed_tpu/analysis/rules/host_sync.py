"""host-sync: device->host synchronization inside traced code.

The contract this enforces is PR 2's "zero extra host syncs when
telemetry is off" and ROADMAP item 1's compute-collective overlap: one
``.item()`` / ``float()`` / ``np.asarray`` on an array value inside a
``@jax.jit`` / ``lax.scan`` / ``shard_map`` body (or anything those
bodies call) either fails at trace time or — worse, via host callbacks
and debugging shims — silently serializes the device stream against the
host. ``print()`` in traced code doesn't sync but prints *tracers* once
at trace time, which is always a leftover debug statement; use
``jax.debug.print`` when output is really wanted.

Scope: ONLY functions in the traced set (see model.py). Host-side
orchestration code converts arrays freely — that is its job.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from ..findings import Finding
from ..model import (PackageModel, FunctionInfo, ModuleInfo,
                     final_attr_name, dotted_name, iter_shallow)
from ..registry import Rule, register

_SYNC_METHODS = {
    "item": "forces a device->host transfer of the value",
    "tolist": "copies the whole array to host",
    "block_until_ready": "blocks the host on the device stream",
}
_CASTS = {"float", "int", "bool", "complex"}


def _is_static_expr(node: ast.AST) -> bool:
    """Expressions that are trace-time constants (shape arithmetic):
    casting those is fine inside traced code."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.Attribute):
        return node.attr in {"shape", "ndim", "size", "dtype", "itemsize"}
    if isinstance(node, ast.Subscript):
        return _is_static_expr(node.value)
    if isinstance(node, ast.Call):
        name = final_attr_name(node.func)
        if name in {"len", "prod", "range", "getenv"}:
            return True
        # os.environ.get(...) is a host constant read at trace time
        return (name == "get" and isinstance(node.func, ast.Attribute)
                and final_attr_name(node.func.value) == "environ")
    if isinstance(node, ast.BinOp):
        return _is_static_expr(node.left) and _is_static_expr(node.right)
    if isinstance(node, ast.UnaryOp):
        return _is_static_expr(node.operand)
    return False


def _numpy_attr(mod: ModuleInfo, func: ast.AST) -> Optional[str]:
    """'asarray' when ``func`` is numpy's asarray/array via any alias."""
    if isinstance(func, ast.Attribute):
        dn = dotted_name(func)
        if dn is None:
            return None
        head = dn.split(".")[0]
        real = mod.alias_to_module.get(head)
        if real == "numpy" or (real or "").startswith("numpy."):
            return func.attr
    elif isinstance(func, ast.Name):
        imp = mod.name_imports.get(func.id)
        if imp and imp[0].lstrip(".") == "numpy":
            return imp[1]
    return None


def _jax_attr(mod: ModuleInfo, func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Attribute):
        dn = dotted_name(func)
        if dn is None:
            return None
        real = mod.alias_to_module.get(dn.split(".")[0])
        if real == "jax" or (real or "").startswith("jax."):
            return func.attr
    return None


@register
class HostSyncRule(Rule):
    id = "host-sync"
    summary = ("device->host syncs (.item()/float()/np.asarray/"
               "block_until_ready/print) inside traced code")

    def run(self, pkg: PackageModel) -> Iterator[Finding]:
        for f in pkg.functions.values():
            if f.traced_reason is None:
                continue
            mod = pkg.modules[f.module]
            yield from self._check(f, mod)

    def _check(self, f: FunctionInfo,
               mod: ModuleInfo) -> Iterator[Finding]:
        why = f" [traced: {f.traced_reason}]"
        for node in iter_shallow(f.node):
            if not isinstance(node, ast.Call):
                continue
            name = final_attr_name(node.func)
            if isinstance(node.func, ast.Attribute) \
                    and name in _SYNC_METHODS:
                yield Finding(
                    rule=self.id, code=f"{name}-call", path=mod.key,
                    line=node.lineno, col=node.col_offset,
                    symbol=f.qualname,
                    message=f".{name}() in traced code "
                            f"{_SYNC_METHODS[name]}{why}")
            elif isinstance(node.func, ast.Name) and name in _CASTS:
                if node.args and not _is_static_expr(node.args[0]):
                    yield Finding(
                        rule=self.id, code="scalar-cast", path=mod.key,
                        line=node.lineno, col=node.col_offset,
                        symbol=f.qualname,
                        message=f"{name}() on a (possibly traced) array "
                                f"value syncs the host; use jnp ops or "
                                f"hoist to the caller{why}")
            elif name == "print" and isinstance(node.func, ast.Name):
                yield Finding(
                    rule=self.id, code="print", path=mod.key,
                    line=node.lineno, col=node.col_offset,
                    symbol=f.qualname,
                    message="print() in traced code prints tracers at "
                            "trace time — use jax.debug.print or "
                            f"delete{why}")
            else:
                np_attr = _numpy_attr(mod, node.func)
                if np_attr in {"asarray", "array", "copy"}:
                    yield Finding(
                        rule=self.id, code="np-convert", path=mod.key,
                        line=node.lineno, col=node.col_offset,
                        symbol=f.qualname,
                        message=f"np.{np_attr}() in traced code pulls "
                                f"the value to host (use jnp.{np_attr} "
                                f"for trace-safe math){why}")
                    continue
                jax_attr = _jax_attr(mod, node.func)
                if jax_attr in {"device_get", "device_put"}:
                    yield Finding(
                        rule=self.id, code="device-transfer",
                        path=mod.key, line=node.lineno,
                        col=node.col_offset, symbol=f.qualname,
                        message=f"jax.{jax_attr}() inside traced code "
                                f"is a host round-trip{why}")
