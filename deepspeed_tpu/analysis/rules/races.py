"""races: Eraser-style static lockset analysis over the thread model.

dslint's lock-discipline rule checks what happens WHILE locks are held;
this rule checks what happens WITHOUT them: shared instance state
reachable from two thread roles where no common lock covers a write and
a conflicting access. The thread model (``model.ThreadEntry`` /
``FunctionInfo.thread_roles``) discovers entry points —
``threading.Thread(target=...)`` driver/monitor/watchdog loops,
``weakref.finalize`` callbacks, timers — and propagates roles over the
resolved call graph; the synthetic ``"main"`` role stands for any
caller thread.

For every class the rule collects each method's ``self.<attr>`` reads
and writes together with the lockset guaranteed at the access:

* the lexically enclosing ``with <lock>:`` regions, plus
* the function's *entry lockset* — the intersection, over every
  resolved internal call site, of the locks held at the call (so
  ``_dispatch``, always invoked under the serving lock, is modeled as
  lock-protected even though it takes no lock itself).

A finding fires when an attribute has a write and a conflicting access
(write-write or read-write) whose locksets share no lock and whose
roles span >= 2 threads. Findings are deduplicated to at most one per
(class, attribute, code), anchored at the first racy WRITE — suppress
there to accept a deliberate pattern.

Recognized safe idioms (no finding):

* **init publish** — accesses inside ``__init__``/``__post_init__``
  happen before any thread can hold the object;
* **queue / deque hand-off** — attributes constructed as
  ``queue.Queue`` (and friends) or ``collections.deque`` synchronize
  internally;
* **one-shot latch** — an attribute whose every non-init write assigns
  the same constant (``self._accepting = False``) is monotonic; racing
  readers see either the old or the final value;
* **lock/event attributes** — the synchronization objects themselves.

The runtime half of dsrace is resilience/locksan.py: instrumented lock
wrappers that record real acquisition orders under tests/DST and
cross-validate against the static lock graph (docs/static_analysis.md).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, Iterator, List, Optional, Set, Tuple

from ..findings import Finding
from ..model import (_SAFE_CONTAINER_CTORS, ClassInfo, FunctionInfo,
                     PackageModel, iter_shallow)
from ..registry import Rule, register

#: method calls on an attribute that mutate the container it names
#: (``self._queue.remove(req)`` writes ``_queue``). Deliberately
#: excludes the generic verbs (``put``/``get``/``set``/``pop``/``add``/
#: ``update``/``discard``) — those also name queue, engine and
#: domain-object methods (``self._engine.discard(uid)``), and a
#: misattributed "write" to the holder attribute floods the rule;
#: container attrs mutated ONLY through those verbs are in practice
#: also written via subscript/assign, which the rule does see.
_MUTATOR_METHODS = {
    "append", "appendleft", "extend", "insert", "remove",
    "popleft", "popitem", "clear", "sort", "reverse", "setdefault",
}

#: methods excluded wholesale: construction happens before the object
#: is published to any other thread
_INIT_METHODS = {"__init__", "__post_init__", "__new__", "__del__"}

#: sentinel lockset for "unknown entry context" (never called from
#: resolved package code): treated as fully locked — an unreachable
#: helper cannot witness a race
_TOP = None


@dataclass
class _Access:
    attr: str
    kind: str                 # "read" | "write"
    func: FunctionInfo
    line: int
    col: int
    locks: Optional[FrozenSet[str]]   # None = TOP (unknown, assume safe)
    roles: FrozenSet[str] = field(default_factory=frozenset)
    #: for the one-shot-latch idiom: the repr of a constant assigned by
    #: a plain ``self.x = <const>`` write, else None
    const: Optional[str] = None
    is_const_assign: bool = False


def _fmt_locks(locks: Optional[FrozenSet[str]]) -> str:
    if locks is _TOP:
        return "{?}"
    if not locks:
        return "{}"
    return "{" + ", ".join(sorted(k.split("::")[-1] for k in locks)) + "}"


def _fmt_roles(roles: FrozenSet[str]) -> str:
    return "+".join(sorted(roles)) if roles else "-"


@register
class RacesRule(Rule):
    id = "races"
    summary = ("Eraser-style lockset analysis: shared attributes "
               "reachable from >= 2 thread roles with no common lock "
               "between a write and a conflicting access")

    def run(self, pkg: PackageModel) -> Iterator[Finding]:
        self.pkg = pkg
        entry = self._entry_locksets()
        # class key -> attr -> accesses
        by_class: Dict[str, Dict[str, List[_Access]]] = {}
        for f in pkg.functions.values():
            if f.class_key is None or f.name in _INIT_METHODS:
                continue
            cls = pkg.classes.get(f.class_key)
            if cls is None:
                continue
            base = entry.get(f.key, _TOP)
            for acc in self._accesses(f, cls, base):
                acc.roles = frozenset(f.thread_roles)
                by_class.setdefault(cls.key, {}).setdefault(
                    acc.attr, []).append(acc)
        for cls_key in sorted(by_class):
            cls = pkg.classes[cls_key]
            for attr in sorted(by_class[cls_key]):
                yield from self._check_attr(cls, attr,
                                            by_class[cls_key][attr])

    # -- entry locksets --------------------------------------------------
    def _entry_locksets(self) -> Dict[str, Optional[FrozenSet[str]]]:
        """Guaranteed-held locks at function ENTRY: the intersection
        over every resolved internal call site of (caller's entry set
        union the locks lexically held at the site). Functions with no
        resolved internal caller are roots (empty set); functions only
        reachable through unresolved paths stay TOP (assumed safe)."""
        pkg = self.pkg
        # target -> list of (caller key, lexical locks at the site)
        callers: Dict[str, List[Tuple[str, FrozenSet[str]]]] = {}
        self._site_locks: Dict[str, Dict[int, FrozenSet[str]]] = {}
        for f in pkg.functions.values():
            site_locks = self._lexical_locks(f)
            self._site_locks[f.key] = site_locks
            for site in f.calls:
                held = site_locks.get(id(site.node), frozenset())
                for t in site.targets:
                    callers.setdefault(t, []).append((f.key, held))
        # a nested closure with no resolved caller (handed to a walker/
        # callback) runs, in this codebase, inside its defining function
        # — model it as called from its definition site, so a closure
        # defined under ``with self._lock:`` (the ring-walk predicate in
        # Region._pick_cell) inherits that lock context
        by_qual: Dict[Tuple[str, str], str] = {
            (f.module, f.qualname): k for k, f in pkg.functions.items()}
        for k, f in pkg.functions.items():
            if k in callers or ".<locals>." not in f.qualname:
                continue
            outer_qual = f.qualname.rsplit(".<locals>.", 1)[0]
            outer_key = by_qual.get((f.module, outer_qual))
            if outer_key is None:
                continue
            held = self._site_locks.get(outer_key, {}).get(
                id(f.node), frozenset())
            callers[k] = [(outer_key, held)]
        out: Dict[str, Optional[FrozenSet[str]]] = {}
        for k in pkg.functions:
            out[k] = frozenset() if k not in callers else _TOP
        # descending fixpoint (finite lattice, monotone meet)
        changed = True
        rounds = 0
        while changed and rounds < 20:
            changed = False
            rounds += 1
            for t, sites in callers.items():
                vals = []
                for caller, held in sites:
                    base = out.get(caller, _TOP)
                    if base is _TOP:
                        continue        # unknown path: no constraint yet
                    vals.append(base | held)
                if not vals:
                    continue
                new: Optional[FrozenSet[str]] = vals[0]
                for v in vals[1:]:
                    new = new & v
                if out.get(t, _TOP) is _TOP or new != out[t]:
                    if out.get(t, _TOP) is _TOP or new < out[t]:
                        out[t] = new
                        changed = True
        return out

    def _lexical_locks(self, f: FunctionInfo) -> Dict[int, FrozenSet[str]]:
        """id(node) -> lock keys lexically held at that node, for every
        node in the function body."""
        region_by_with = {id(r.with_node): r.lock_key
                         for r in f.lock_regions}
        out: Dict[int, FrozenSet[str]] = {}

        def walk(node: ast.AST, held: FrozenSet[str]) -> None:
            for child in ast.iter_child_nodes(node):
                inner = held
                if id(child) in region_by_with:
                    inner = held | {region_by_with[id(child)]}
                # nested defs are recorded (their DEFINITION site's lock
                # context seeds closure entry locksets) but not entered
                # — their bodies belong to their own FunctionInfo
                out[id(child)] = inner
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda,
                                      ast.ClassDef)):
                    continue
                walk(child, inner)

        out[id(f.node)] = frozenset()
        walk(f.node, frozenset())
        return out

    # -- access collection ----------------------------------------------
    def _skip_attr(self, cls: ClassInfo, attr: str) -> bool:
        if attr in cls.lock_attrs or attr in cls.event_attrs:
            return True
        if cls.attr_types.get(attr) in _SAFE_CONTAINER_CTORS:
            return True
        # inherited lock/queue attrs (single-inheritance walk)
        seen = 0
        cur = cls
        while cur.base_names and seen < 4:
            b = self.pkg.resolve_class(cur.base_names[0])
            if b is None or b.key == cur.key:
                break
            if attr in b.lock_attrs or attr in b.event_attrs:
                return True
            if b.attr_types.get(attr) in _SAFE_CONTAINER_CTORS:
                return True
            cur = b
            seen += 1
        return False

    def _accesses(self, f: FunctionInfo, cls: ClassInfo,
                  base: Optional[FrozenSet[str]]
                  ) -> Iterator[_Access]:
        site_locks = self._site_locks[f.key]

        def locks_at(node: ast.AST) -> Optional[FrozenSet[str]]:
            if base is _TOP:
                return _TOP
            return base | site_locks.get(id(node), frozenset())

        def self_attr(node: ast.AST) -> Optional[str]:
            if (isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "self"):
                return node.attr
            return None

        # iter_shallow, not ast.walk: nested function/lambda bodies
        # belong to their OWN FunctionInfo — walking into them here
        # would attribute a closure's accesses to the enclosing method
        # minus the closure's lock context (their lock coverage flows
        # through the closure entry-lockset seam instead)
        for node in iter_shallow(f.node):
            if isinstance(node, ast.Attribute):
                attr = self_attr(node)
                if attr is None or self._skip_attr(cls, attr):
                    continue
                if isinstance(node.ctx, (ast.Store, ast.Del)):
                    yield _Access(attr=attr, kind="write", func=f,
                                  line=node.lineno, col=node.col_offset,
                                  locks=locks_at(node))
                else:
                    yield _Access(attr=attr, kind="read", func=f,
                                  line=node.lineno, col=node.col_offset,
                                  locks=locks_at(node))
            elif isinstance(node, ast.Call):
                # self.X.append(...) mutates the container X names
                func = node.func
                if isinstance(func, ast.Attribute) \
                        and func.attr in _MUTATOR_METHODS:
                    attr = self_attr(func.value)
                    if attr is not None and not self._skip_attr(cls, attr):
                        yield _Access(attr=attr, kind="write", func=f,
                                      line=node.lineno,
                                      col=node.col_offset,
                                      locks=locks_at(node))
            elif isinstance(node, ast.Subscript) \
                    and isinstance(node.ctx, (ast.Store, ast.Del)):
                attr = self_attr(node.value)
                if attr is not None and not self._skip_attr(cls, attr):
                    yield _Access(attr=attr, kind="write", func=f,
                                  line=node.lineno, col=node.col_offset,
                                  locks=locks_at(node))
            elif isinstance(node, ast.Assign):
                # mark plain constant assigns for the one-shot idiom
                if isinstance(node.value, ast.Constant):
                    for t in node.targets:
                        attr = self_attr(t)
                        if attr is not None \
                                and not self._skip_attr(cls, attr):
                            yield _Access(
                                attr=attr, kind="write", func=f,
                                line=t.lineno, col=t.col_offset,
                                locks=locks_at(t),
                                const=repr(node.value.value),
                                is_const_assign=True)

    # -- the race check --------------------------------------------------
    def _check_attr(self, cls: ClassInfo, attr: str,
                    accesses: List[_Access]) -> Iterator[Finding]:
        # constant-assign accesses were emitted TWICE (once from the
        # Store-ctx Attribute walk, once annotated): keep the annotated
        # one per (line, col)
        const_keys = {(a.line, a.col) for a in accesses
                      if a.is_const_assign}
        accesses = [a for a in accesses
                    if a.is_const_assign
                    or a.kind != "write"
                    or (a.line, a.col) not in const_keys]
        writes = [a for a in accesses if a.kind == "write"]
        reads = [a for a in accesses if a.kind == "read"]
        if not writes:
            return
        # one-shot latch: every write assigns the same constant
        consts = {a.const for a in writes}
        if all(a.is_const_assign for a in writes) and len(consts) == 1:
            return

        def conflict(a: _Access, b: _Access) -> bool:
            if a.locks is _TOP or b.locks is _TOP:
                return False
            if a.locks & b.locks:
                return False
            union = a.roles | b.roles
            if len(union) < 2:
                return False
            return True

        order = sorted(writes, key=lambda a: (a.func.module, a.line,
                                              a.col))
        for code, others_all in (("write-write", writes),
                                 ("read-write", reads)):
            others = sorted(others_all, key=lambda a: (a.func.module,
                                                       a.line, a.col))
            hit = None
            for w in order:
                for o in others:
                    if o is w:
                        continue
                    if conflict(w, o):
                        hit = (w, o)
                        break
                # a single write site reachable from two roles races
                # against itself (two threads in the same function)
                if hit is None and code == "write-write" \
                        and len(w.roles) >= 2 and w.locks is not _TOP \
                        and not w.locks:
                    hit = (w, w)
                if hit:
                    break
            if hit is None:
                continue
            w, o = hit
            # anchor the finding at the UNLOCKED side of the pair — a
            # suppression accepting a deliberate pattern belongs where
            # the lock is missing (the unlocked peek, the lock-free
            # watchdog sample), not at the properly locked write
            anchored, other = (o, w) if (w.locks and not o.locks) \
                else (w, o)
            other_desc = ("concurrent entry to the same site"
                          if other is anchored else
                          f"{other.kind} in {other.func.qualname} "
                          f"({other.func.module}:{other.line}, locks "
                          f"{_fmt_locks(other.locks)}, roles "
                          f"{_fmt_roles(other.roles)})")
            yield Finding(
                rule=self.id, code=code,
                path=anchored.func.module, line=anchored.line,
                col=anchored.col, symbol=anchored.func.qualname,
                message=(
                    f"{cls.name}.{attr}: unsynchronized {anchored.kind} "
                    f"under locks {_fmt_locks(anchored.locks)} (roles "
                    f"{_fmt_roles(anchored.roles)}) vs {other_desc} — "
                    f"no common lock; guard both with one lock, or "
                    f"confine the attribute to one thread"))
