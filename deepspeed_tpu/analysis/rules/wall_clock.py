"""wall-clock: direct time access that bypasses the injectable clock
seam.

The serving / resilience / telemetry layers time everything — deadlines,
backoffs, poll intervals, span timestamps — through
:mod:`deepspeed_tpu.resilience.clock` (``get_clock()`` / an injected
``Clock``), which is what makes the deterministic simulation harness
(docs/dst.md) possible: a ``SimClock`` swaps in and the whole stack runs
on virtual time. One stray ``time.perf_counter()`` or raw
``Event.wait(timeout)`` re-couples the code to the host clock and
silently breaks simulation determinism — exactly the class of regression
that only shows up as an unreproducible soak flake months later.

Checks (scope: modules under ``serving/``, ``resilience/`` and
``telemetry/``; the clock module itself is exempt — it IS the seam):

* ``direct-time`` — calls into ``time.*`` wall-clock/sleep functions or
  ``datetime.now/utcnow/today``;
* ``raw-event-wait`` — ``.wait(...)`` on a ``threading.Event`` (a
  ``self._evt = threading.Event()`` attribute, or an inline
  ``threading.Event().wait``): use ``clock.wait_event(evt, timeout)``.

Deliberate wall-time sites (none ship today) take the usual
suppression-with-reason.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, Optional

from ..findings import Finding
from ..model import (FunctionInfo, ModuleInfo, PackageModel,
                     final_attr_name, iter_shallow)
from ..registry import Rule, register

#: modules whose timing must flow through the clock seam
_SCOPE = re.compile(r"(^|/)(serving|resilience|telemetry)/")
#: the seam itself: the only place wall time is allowed to live
_EXEMPT_SUFFIX = "resilience/clock.py"

_TIME_FUNCS = {"time", "time_ns", "monotonic", "monotonic_ns",
               "perf_counter", "perf_counter_ns", "process_time",
               "process_time_ns", "sleep"}
_DATETIME_FUNCS = {"now", "utcnow", "today"}


def _time_module_of(mod: ModuleInfo, func: ast.AST) -> Optional[str]:
    """Resolve the real module behind ``alias.attr(...)`` or a
    from-imported name (same alias-table walk as trace-hygiene)."""
    if isinstance(func, ast.Attribute) and isinstance(func.value, ast.Name):
        head = func.value.id
        real = mod.alias_to_module.get(head)
        if real is None:
            # ``from datetime import datetime`` then ``datetime.now()``:
            # the head is a from-imported NAME, not a module alias
            imp = mod.name_imports.get(head)
            if imp:
                real = imp[0].lstrip(".") + "." + imp[1]
        return real
    if isinstance(func, ast.Name):
        imp = mod.name_imports.get(func.id)
        if imp:
            return imp[0].lstrip(".")
    return None


@register
class WallClockRule(Rule):
    id = "wall-clock"
    summary = ("direct time.*/datetime-now calls or raw Event.wait in "
               "serving/resilience/telemetry outside the clock seam")

    def run(self, pkg: PackageModel) -> Iterator[Finding]:
        for mod in pkg.modules.values():
            if not _SCOPE.search(mod.key):
                continue
            if mod.key.endswith(_EXEMPT_SUFFIX):
                continue
            for f in pkg.functions_in(mod.key):
                yield from self._check(pkg, f, mod)

    def _check(self, pkg: PackageModel, f: FunctionInfo,
               mod: ModuleInfo) -> Iterator[Finding]:
        for node in iter_shallow(f.node):
            if not isinstance(node, ast.Call):
                continue
            name = final_attr_name(node.func)
            src_mod = _time_module_of(mod, node.func)
            if src_mod == "time" and name in _TIME_FUNCS:
                yield Finding(
                    rule=self.id, code="direct-time", path=mod.key,
                    line=node.lineno, col=node.col_offset,
                    symbol=f.qualname,
                    message=f"time.{name}() bypasses the injectable "
                            f"clock seam — use get_clock()/self._clock "
                            f"(resilience/clock.py) so simulation runs "
                            f"stay on virtual time")
            elif (src_mod in {"datetime", "datetime.datetime"}
                    and name in _DATETIME_FUNCS):
                yield Finding(
                    rule=self.id, code="direct-time", path=mod.key,
                    line=node.lineno, col=node.col_offset,
                    symbol=f.qualname,
                    message=f"datetime {name}() bypasses the injectable "
                            f"clock seam — use get_clock().time()")
            elif name == "wait" and isinstance(node.func, ast.Attribute):
                if self._is_event_receiver(pkg, f, mod, node.func.value):
                    yield Finding(
                        rule=self.id, code="raw-event-wait", path=mod.key,
                        line=node.lineno, col=node.col_offset,
                        symbol=f.qualname,
                        message="raw Event.wait() blocks on the host "
                                "clock — use clock.wait_event(event, "
                                "timeout) so a SimClock can pump "
                                "virtual time instead")

    def _is_event_receiver(self, pkg: PackageModel, f: FunctionInfo,
                           mod: ModuleInfo, recv: ast.AST) -> bool:
        # inline: threading.Event().wait(...)
        if isinstance(recv, ast.Call):
            ctor = final_attr_name(recv.func)
            if ctor == "Event":
                src = _time_module_of(mod, recv.func)
                return src == "threading" or (
                    isinstance(recv.func, ast.Name)
                    and mod.name_imports.get(recv.func.id,
                                             ("", ""))[0] == "threading")
        # self._evt.wait(...): the attribute was assigned
        # threading.Event() in this class (or a single-inheritance base)
        if (isinstance(recv, ast.Attribute)
                and isinstance(recv.value, ast.Name)
                and recv.value.id == "self" and f.class_key):
            cls = pkg.classes.get(f.class_key)
            seen = 0
            while cls is not None and seen < 8:
                if recv.attr in cls.event_attrs:
                    return True
                if recv.attr in cls.lock_attrs or recv.attr in cls.attr_types:
                    return False
                cls = (pkg.resolve_class(cls.base_names[0])
                       if cls.base_names else None)
                seen += 1
        return False
