"""recompile-hazard: jit wrappers constructed or invoked in ways that
defeat the trace cache.

PR 4's contract is ONE trace per program (`_trace_counts`, the
recompile guard): a second compile of a step program silently doubles
step latency and poisons the one-compile telemetry. The classic ways to
lose the cache without noticing:

* ``jax.jit(...)`` constructed inside a loop — every iteration builds a
  fresh wrapper with an empty cache;
* ``jax.jit(f)(x)`` built per call inside a method — same wrapper
  churn, one compile per invocation (fine at module import or in
  ``__init__``, where it runs once);
* unhashable (``list``/``dict``/``set``) literals passed for
  ``static_argnums``/``static_argnames`` parameters — TypeError at best,
  retrace-per-call via tuple conversion shims at worst;
* DIFFERENT constant values at a static position across call sites —
  each distinct value is its own trace-cache entry, and a per-call
  varying one compiles forever.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from ..findings import Finding
from ..model import (PackageModel, FunctionInfo, ModuleInfo,
                     final_attr_name, iter_shallow)
from ..registry import Rule, register

_JIT_NAMES = {"jit", "pjit"}


def _jit_call(node: ast.AST) -> Optional[ast.Call]:
    """The ``jax.jit(...)`` / ``partial(jax.jit, ...)`` Call, if any."""
    if not isinstance(node, ast.Call):
        return None
    name = final_attr_name(node.func)
    if name in _JIT_NAMES:
        return node
    if name == "partial" and node.args \
            and final_attr_name(node.args[0]) in _JIT_NAMES:
        return node
    return None


def _walk_with_loops(node: ast.AST, depth: int = 0):
    """Shallow walk yielding (node, loop_depth)."""
    for child in ast.iter_child_nodes(node):
        yield child, depth
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda, ast.ClassDef)):
            continue
        d = depth + 1 if isinstance(child, (ast.For, ast.While)) else depth
        yield from _walk_with_loops(child, d)


def _static_params(call: ast.Call) -> Tuple[Set[int], Set[str]]:
    nums: Set[int] = set()
    names: Set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            for v in _const_seq(kw.value):
                if isinstance(v, int):
                    nums.add(v)
        elif kw.arg == "static_argnames":
            for v in _const_seq(kw.value):
                if isinstance(v, str):
                    names.add(v)
    return nums, names


def _const_seq(node: ast.AST) -> List:
    if isinstance(node, ast.Constant):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant):
                out.append(e.value)
        return out
    return []


@register
class RecompileHazardRule(Rule):
    id = "recompile-hazard"
    summary = ("jit built in loops/per-call closures, unhashable or "
               "per-call-varying static args")

    def run(self, pkg: PackageModel) -> Iterator[Finding]:
        for mod in pkg.modules.values():
            yield from self._check_module(pkg, mod)

    # -- per-function construction hazards ------------------------------
    def _check_module(self, pkg: PackageModel,
                      mod: ModuleInfo) -> Iterator[Finding]:
        for fk in mod.functions:
            f = pkg.functions[fk]
            yield from self._check_function(f, mod)
        yield from self._check_static_args(pkg, mod)

    def _check_function(self, f: FunctionInfo,
                        mod: ModuleInfo) -> Iterator[Finding]:
        is_init = f.name == "__init__"
        cached_ok = bool({"lru_cache", "cache", "cached_property"}
                         & f.decorator_names)
        # locals assigned a jit wrapper, to catch construct-then-call
        jit_locals: Dict[str, ast.Call] = {}
        called_names: Set[str] = set()
        stored_names: Set[str] = set()   # cached on self/module/container
        flagged: Set[int] = set()
        for node, loop_depth in _walk_with_loops(f.node):
            jc = _jit_call(node)
            if jc is not None and loop_depth > 0 and id(jc) not in flagged:
                flagged.add(id(jc))
                yield Finding(
                    rule=self.id, code="jit-in-loop", path=mod.key,
                    line=node.lineno, col=node.col_offset,
                    symbol=f.qualname,
                    message="jax.jit constructed inside a loop: every "
                            "iteration gets a fresh wrapper with an "
                            "empty trace cache — hoist the wrapper out "
                            "of the loop")
                continue
            if isinstance(node, ast.Call):
                inner = _jit_call(node.func)
                if inner is not None and id(inner) in flagged:
                    inner = None
                elif inner is not None:
                    flagged.add(id(inner))
                if inner is not None and not is_init and not cached_ok:
                    yield Finding(
                        rule=self.id, code="jit-per-call", path=mod.key,
                        line=node.lineno, col=node.col_offset,
                        symbol=f.qualname,
                        message="jax.jit(f)(...) builds and discards "
                                "the wrapper per call — compile once "
                                "(module level, __init__, or a cached "
                                "builder) and reuse it")
                name = final_attr_name(node.func)
                if isinstance(node.func, ast.Name) and name:
                    called_names.add(name)
            if isinstance(node, ast.Assign):
                if len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name):
                    jc2 = _jit_call(node.value)
                    if jc2 is not None:
                        jit_locals[node.targets[0].id] = jc2
                # ``self._fns[shape] = fn`` / ``self._fn = fn``: the
                # wrapper is cached across calls — not a per-call build
                if isinstance(node.value, ast.Name) and any(
                        isinstance(t, (ast.Subscript, ast.Attribute))
                        for t in node.targets):
                    stored_names.add(node.value.id)
        if not is_init and not cached_ok:
            for name, jc in jit_locals.items():
                if id(jc) in flagged or name in stored_names:
                    continue
                if name in called_names:
                    yield Finding(
                        rule=self.id, code="jit-per-call", path=mod.key,
                        line=jc.lineno, col=jc.col_offset,
                        symbol=f.qualname,
                        message=f"`{name} = jax.jit(...)` is rebuilt on "
                                f"every call to {f.name}() and then "
                                f"invoked — each call recompiles; cache "
                                f"the wrapper on self or at module "
                                f"level")

    # -- static-arg hazards at call sites -------------------------------
    def _check_static_args(self, pkg: PackageModel,
                           mod: ModuleInfo) -> Iterator[Finding]:
        """Module-scope view: ``g = jax.jit(f, static_argnums=...)``
        then calls ``g(...)`` in the same module."""
        jitted: Dict[str, Tuple[Set[int], Set[str],
                                Optional[ast.FunctionDef]]] = {}
        # decorated defs
        for fk in mod.functions:
            f = pkg.functions[fk]
            node = f.node
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            for dec in node.decorator_list:
                jc = _jit_call(dec)
                if jc is not None:
                    jitted[f.name] = _static_params(jc) + (node,)
        # module-level assignments
        for stmt in mod.tree.body:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                jc = _jit_call(stmt.value)
                if jc is not None:
                    wrapped = None
                    if jc.args:
                        first = (jc.args[1] if final_attr_name(jc.func)
                                 == "partial" and len(jc.args) > 1
                                 else jc.args[0])
                        wname = final_attr_name(first)
                        for fk in mod.functions:
                            g = pkg.functions[fk]
                            if g.name == wname and isinstance(
                                    g.node, ast.FunctionDef):
                                wrapped = g.node
                                break
                    jitted[stmt.targets[0].id] = \
                        _static_params(jc) + (wrapped,)
        if not jitted:
            return
        # observed constants per (callee, static position)
        seen_consts: Dict[Tuple[str, str], Set] = {}
        sites: Dict[Tuple[str, str], List[ast.Call]] = {}
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call) \
                    or not isinstance(node.func, ast.Name) \
                    or node.func.id not in jitted:
                continue
            nums, names, wrapped = jitted[node.func.id]
            if wrapped is not None:
                params = [a.arg for a in wrapped.args.args]
                names = names | {params[i] for i in nums
                                 if i < len(params)}
                nums = nums | {params.index(n) for n in names
                               if n in params}
            for i, arg in enumerate(node.args):
                if i in nums:
                    yield from self._static_site(
                        mod, node, arg, node.func.id, f"arg {i}",
                        seen_consts, sites)
            for kw in node.keywords:
                if kw.arg in names:
                    yield from self._static_site(
                        mod, node, kw.value, node.func.id,
                        f"{kw.arg}=", seen_consts, sites)
        for key, consts in seen_consts.items():
            if len(consts) > 1:
                first = sites[key][0]
                callee, pos = key
                yield Finding(
                    rule=self.id, code="varying-static", path=mod.key,
                    line=first.lineno, col=first.col_offset,
                    symbol="<module>",
                    message=f"static argument {pos} of jitted "
                            f"`{callee}` receives {len(consts)} "
                            f"different literal values across call "
                            f"sites — each value is a separate "
                            f"compile; make it a traced argument or a "
                            f"single configuration constant")

    def _static_site(self, mod, call, arg, callee, pos,
                     seen_consts, sites) -> Iterator[Finding]:
        if isinstance(arg, (ast.List, ast.Dict, ast.Set)):
            kind = type(arg).__name__.lower()
            yield Finding(
                rule=self.id, code="unhashable-static", path=mod.key,
                line=arg.lineno, col=arg.col_offset, symbol="<module>",
                message=f"unhashable {kind} literal passed for static "
                        f"argument {pos} of jitted `{callee}` — static "
                        f"args must be hashable (use a tuple / "
                        f"frozen config)")
        elif isinstance(arg, ast.Constant):
            seen_consts.setdefault((callee, pos), set()).add(arg.value)
            sites.setdefault((callee, pos), []).append(call)
