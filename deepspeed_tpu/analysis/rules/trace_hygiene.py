"""trace-hygiene: host state touched from inside traced code.

A traced function body runs ONCE, at trace time — not once per step.
``time.time()`` reads the clock during tracing and bakes a constant
into the program; ``np.random`` draws a single sample forever;
mutating ``self``/globals from a traced body aliases trace-time state
into runtime expectations; and a telemetry call inside a jitted body
breaks PR 2's zero-sync-when-off contract (telemetry must observe the
*host* side of the step, never live inside the program).

Scope: the traced set only (same as host-sync).
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from ..findings import Finding
from ..model import (PackageModel, FunctionInfo, ModuleInfo,
                     final_attr_name, dotted_name, iter_shallow)
from ..registry import Rule, register

_TIME_FUNCS = {"time", "perf_counter", "monotonic", "process_time",
               "sleep", "perf_counter_ns", "time_ns"}
_TELEMETRY_CALLS = {"record_step", "record_request",
                    "record_request_span", "log_dist", "get_telemetry"}
_REGISTRY_FACTORIES = {"counter", "histogram", "gauge"}
_REGISTRY_OPS = {"inc", "observe"}
# request-tracer entry points (telemetry/tracing.py): spans/events and
# flight-recorder appends observe the HOST side of a step — inside a
# jitted body they would fire once at trace time and (worse) read the
# clock seam into a compiled constant. Distinctive names match any call
# shape; the generic ones (span/event/note) only as METHOD calls
# (tracer.span(...), flight.note(...)) so an unrelated local helper
# named `note` inside traced code is not hijacked.
_TRACER_CALLS = {"new_trace", "begin_span", "finish_span",
                 "span_complete", "get_tracer", "note_span"}
_TRACER_METHOD_CALLS = {"span", "event", "note"}


def _module_of(mod: ModuleInfo, func: ast.AST) -> Optional[str]:
    """Real dotted module a call like ``alias.attr(...)`` targets, or the
    source module of a from-imported name."""
    if isinstance(func, ast.Attribute):
        dn = dotted_name(func)
        if dn is None:
            return None
        head = dn.split(".")[0]
        real = mod.alias_to_module.get(head)
        if real is None:
            return None
        rest = dn[len(head):].rsplit(".", 1)[0]
        return real + rest if rest else real
    if isinstance(func, ast.Name):
        imp = mod.name_imports.get(func.id)
        if imp:
            return imp[0].lstrip(".")
    return None


@register
class TraceHygieneRule(Rule):
    id = "trace-hygiene"
    summary = ("wall clocks, host RNG, global/attribute mutation and "
               "telemetry calls inside traced code")

    def run(self, pkg: PackageModel) -> Iterator[Finding]:
        for f in pkg.functions.values():
            if f.traced_reason is None:
                continue
            yield from self._check(f, pkg.modules[f.module])

    def _check(self, f: FunctionInfo,
               mod: ModuleInfo) -> Iterator[Finding]:
        why = f" [traced: {f.traced_reason}]"
        for node in iter_shallow(f.node):
            if isinstance(node, ast.Global):
                yield Finding(
                    rule=self.id, code="global-stmt", path=mod.key,
                    line=node.lineno, col=node.col_offset,
                    symbol=f.qualname,
                    message="`global` inside traced code mutates host "
                            f"state at trace time, not per step{why}")
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    base = t
                    while isinstance(base, ast.Subscript):
                        base = base.value
                    if isinstance(base, ast.Attribute):
                        yield Finding(
                            rule=self.id, code="attr-mutation",
                            path=mod.key, line=node.lineno,
                            col=node.col_offset, symbol=f.qualname,
                            message=f"assignment to "
                                    f"`{dotted_name(base) or '<attr>'}` "
                                    f"inside traced code runs at trace "
                                    f"time only — return the value "
                                    f"through the carry instead{why}")
                        break
            elif isinstance(node, ast.Call):
                yield from self._check_call(node, f, mod, why)

    def _check_call(self, node: ast.Call, f: FunctionInfo,
                    mod: ModuleInfo, why: str) -> Iterator[Finding]:
        name = final_attr_name(node.func)
        src_mod = _module_of(mod, node.func)
        if src_mod == "time" and name in _TIME_FUNCS:
            yield Finding(
                rule=self.id, code="wall-clock", path=mod.key,
                line=node.lineno, col=node.col_offset, symbol=f.qualname,
                message=f"time.{name}() in traced code is evaluated "
                        f"once at trace time — time on the host, around "
                        f"the step call{why}")
        elif src_mod is not None and (
                src_mod == "numpy.random"
                or src_mod.startswith("numpy.random")):
            yield Finding(
                rule=self.id, code="np-random", path=mod.key,
                line=node.lineno, col=node.col_offset, symbol=f.qualname,
                message=f"np.random.{name}() in traced code draws ONE "
                        f"sample at trace time — thread a jax.random "
                        f"key through the carry{why}")
        elif src_mod == "random":
            yield Finding(
                rule=self.id, code="py-random", path=mod.key,
                line=node.lineno, col=node.col_offset, symbol=f.qualname,
                message=f"random.{name}() in traced code is a "
                        f"trace-time constant — use jax.random{why}")
        elif src_mod in {"datetime", "datetime.datetime"} \
                and name in {"now", "utcnow", "today"}:
            yield Finding(
                rule=self.id, code="wall-clock", path=mod.key,
                line=node.lineno, col=node.col_offset, symbol=f.qualname,
                message=f"datetime {name}() in traced code is a "
                        f"trace-time constant{why}")
        elif name in _TELEMETRY_CALLS:
            yield Finding(
                rule=self.id, code="telemetry-call", path=mod.key,
                line=node.lineno, col=node.col_offset, symbol=f.qualname,
                message=f"{name}() inside traced code breaks the "
                        f"zero-sync-when-off contract — record on the "
                        f"host after the step returns{why}")
        elif name in _TRACER_CALLS or (
                isinstance(node.func, ast.Attribute)
                and name in _TRACER_METHOD_CALLS):
            yield Finding(
                rule=self.id, code="tracer-call", path=mod.key,
                line=node.lineno, col=node.col_offset, symbol=f.qualname,
                message=f"{name}() (request tracer / flight recorder) "
                        f"inside traced code would fire once at trace "
                        f"time with a trace-time clock stamp — span on "
                        f"the host, around the step call{why}")
        elif isinstance(node.func, ast.Attribute) \
                and name in _REGISTRY_OPS:
            # x.inc(...) / x.observe(...): registry series mutation
            yield Finding(
                rule=self.id, code="telemetry-call", path=mod.key,
                line=node.lineno, col=node.col_offset, symbol=f.qualname,
                message=f".{name}() (metrics registry) inside traced "
                        f"code — metrics must be host-side{why}")
        elif (isinstance(node.func, ast.Attribute)
                and name in _REGISTRY_FACTORIES
                and isinstance(node.func.value, (ast.Name, ast.Attribute))
                and (final_attr_name(node.func.value) or "").lower()
                    .endswith(("registry", "telemetry"))):
            yield Finding(
                rule=self.id, code="telemetry-call", path=mod.key,
                line=node.lineno, col=node.col_offset, symbol=f.qualname,
                message=f"registry.{name}() inside traced code — "
                        f"metrics must be host-side{why}")
