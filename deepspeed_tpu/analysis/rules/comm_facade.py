"""comm-facade: raw ``jax.lax`` collectives in ZeRO-3 hot paths.

The compressed-collectives facade (``comm/compressed.py``,
docs/communication.md) is the shipped large-mesh ZeRO-3 communication
path: every collective it issues is metered in the bytes-on-wire ledger,
carries the compression policy (quantize the slow hop, stay dense on
fast ICI), and degrades cleanly when a tensor can't block-divide. A raw
``jax.lax.psum`` / ``all_gather`` / ``psum_scatter`` / ``all_to_all`` /
``ppermute`` dropped straight into ``parallel/zero.py`` or
``runtime/engine.py`` bypasses all three — the wire volume disappears
from the evidence ledger, the compression threshold silently stops
applying, and the T3 overlap schedule can't stage what it can't see.

Scope (path-based, like the wall-clock rule): files named
``parallel/zero*.py`` or ``runtime/engine*.py`` — the ZeRO placement /
schedule layer and the training engine — plus the kernel-backend
modules ``comm/backends*.py`` and ``ops/pallas/fused_collectives*.py``:
backends compose Pallas kernels with facade-routed wire hops
(``ring_permute``, ``quantized_chunk_exchange``, ``chunked_all_reduce``)
and must not smuggle raw collectives past the ledger either. The facade
module itself and the low-level collective layers (``comm/comm.py``,
``comm/compressed.py``, ``parallel/compressed.py``, ``parallel/ring.py``,
...) are out of scope: they ARE the implementation the facade wraps.

One check:

* ``raw-collective`` — a call that resolves to a ``jax.lax`` collective
  (``jax.lax.X(...)``, ``lax.X(...)`` via an import alias, or a
  from-imported ``X(...)``). Route it through ``deepspeed_tpu.comm``
  (the thin wrappers) or ``deepspeed_tpu.comm.compressed`` (the
  quantized/hierarchical paths).

Deliberate raw sites take the usual suppression-with-reason.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, List, Optional

from ..findings import Finding
from ..model import FunctionInfo, ModuleInfo, PackageModel, iter_shallow
from ..registry import Rule, register

#: ZeRO-3 hot-path modules whose collectives must flow through the facade
#: (incl. the kernel-backend seam: backends fuse compute with facade-
#: routed wire hops, never with raw jax.lax collectives)
_SCOPE = re.compile(r"(^|/)(parallel/zero[^/]*\.py|runtime/engine[^/]*\.py"
                    r"|comm/backends[^/]*\.py"
                    r"|ops/pallas/fused_collectives[^/]*\.py)$")

#: jax.lax collective primitives (the wire-moving set)
_COLLECTIVES = {"psum", "pmean", "pmax", "pmin", "all_gather",
                "psum_scatter", "reduce_scatter", "all_to_all", "ppermute"}


def _attr_chain(node: ast.AST) -> Optional[List[str]]:
    """['jax', 'lax', 'psum'] for jax.lax.psum — None for anything that
    isn't a plain dotted name."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return list(reversed(parts))
    return None


def _resolves_to_lax(mod: ModuleInfo, func: ast.AST) -> Optional[str]:
    """The collective name when ``func`` resolves to jax.lax.<collective>,
    else None. Handles ``jax.lax.X``, ``import jax.lax as lax`` /
    ``from jax import lax`` + ``lax.X``, and ``from jax.lax import X``."""
    if isinstance(func, ast.Name):
        imp = mod.name_imports.get(func.id)
        if imp and imp[0].lstrip(".") == "jax.lax" and imp[1] in _COLLECTIVES:
            return imp[1]
        return None
    chain = _attr_chain(func)
    if not chain or len(chain) < 2:
        return None
    name = chain[-1]
    if name not in _COLLECTIVES:
        return None
    head = chain[0]
    base = mod.alias_to_module.get(head)
    if base is None:
        imp = mod.name_imports.get(head)
        if imp:
            base = imp[0].lstrip(".") + "." + imp[1]
    if base is None:
        return None
    full = ".".join([base] + chain[1:-1])
    return name if full == "jax.lax" else None


@register
class CommFacadeRule(Rule):
    id = "comm-facade"
    summary = ("raw jax.lax collectives in ZeRO-3 hot paths "
               "(parallel/zero*.py, runtime/engine*.py) or kernel "
               "backends (comm/backends*.py, ops/pallas/"
               "fused_collectives*.py) that bypass the "
               "compressed-collectives facade and its wire ledger")

    def run(self, pkg: PackageModel) -> Iterator[Finding]:
        for mod in pkg.modules.values():
            if not _SCOPE.search(mod.key):
                continue
            for f in pkg.functions_in(mod.key):
                yield from self._check(f, mod)

    def _check(self, f: FunctionInfo, mod: ModuleInfo) -> Iterator[Finding]:
        for node in iter_shallow(f.node):
            if not isinstance(node, ast.Call):
                continue
            name = _resolves_to_lax(mod, node.func)
            if name is None:
                continue
            yield Finding(
                rule=self.id, code="raw-collective", path=mod.key,
                line=node.lineno, col=node.col_offset,
                symbol=f.qualname,
                message=f"raw jax.lax.{name} in a ZeRO-3 hot path bypasses "
                        f"the compressed-collectives facade — route it "
                        f"through deepspeed_tpu.comm (thin wrappers) or "
                        f"comm.compressed (quantized/hierarchical paths) so "
                        f"the bytes-on-wire ledger and compression policy "
                        f"see it (docs/communication.md)")
