"""The package model dslint rules run against.

One parse pass over every ``.py`` file builds a :class:`PackageModel`:
modules with their import alias tables, every function/method (including
nested defs and lambdas) with the calls it makes, a best-effort resolved
call graph, the *traced set* (functions whose bodies execute under a JAX
trace — ``@jax.jit`` decorations, callables handed to
``lax.scan``/``shard_map``/``pallas_call``/... and everything they
transitively call inside the package), and the lock model (lock
attributes per class, ``with <lock>:`` regions per function).

Everything here is pure stdlib ``ast`` — importing the analyzed code
would drag in jax and break the "lint anywhere" contract, so nothing is
ever executed or imported.

Call resolution is deliberately conservative and graded:

* **strong** — same-module names, ``self.method``, package-module
  qualified attributes (``mod.func`` through the import table), receiver
  attributes whose class annotates their type (``replica.serving`` where
  some ``__init__`` declares ``serving: ServingEngine``), constructor
  calls;
* **weak** — a bare method name defined by exactly one class in the
  package.

Rules choose the confidence they need: traced-set propagation follows
both (a wrongly-traced host helper surfaces as an obvious
false-positive and gets tuned; a missed traced callee silently hides a
host sync), while messages always carry the propagation path so a human
can audit the chain.
"""

from __future__ import annotations

import ast
import os
import tokenize
from dataclasses import dataclass, field
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set, Tuple

# Final attribute names that take a callable and trace its body. Maps
# name -> indices of positional args that are traced callables (None =
# all positional args from that transform are callables, used by
# cond/switch branches).
_TRANSFORM_CALLABLE_ARGS: Dict[str, Tuple[Optional[int], ...]] = {
    "jit": (0,),
    "pjit": (0,),
    "vmap": (0,),
    "pmap": (0,),
    "grad": (0,),
    "value_and_grad": (0,),
    "remat": (0,),
    "checkpoint": (0,),
    "shard_map": (0,),
    "shard_map_compat": (0,),   # parallel.mesh version-skew wrapper
    "pallas_call": (0,),
    "custom_vjp": (0,),
    "custom_jvp": (0,),
    "scan": (0,),
    "associative_scan": (0,),
    "while_loop": (0, 1),
    "fori_loop": (2,),
    "cond": (1, 2, 3),
    "switch": (None,),   # every positional arg after the index is a branch
}

# Decorator names that mark the decorated function itself as traced.
_TRACING_DECORATORS = {"jit", "pjit", "remat", "checkpoint",
                       "custom_vjp", "custom_jvp", "kernel"}

_LOCK_CONSTRUCTORS = {"Lock", "RLock", "Condition", "Semaphore",
                      "BoundedSemaphore"}

# Method names that collide with builtin container/file/thread APIs
# (``dict.get``, ``arr.at[i].set``, ``q.put``, ``f.write``, ...): a bare
# name match against a package class method would hijack nearly every
# call site, so these never resolve weakly. The second group are names
# the serving/region layers made common since PR 7 (``clock.pump`` vs
# ``fleet.step``, router/ring ``route``, cell-digest ``publish``, ...):
# several are no longer unique, but blocklisting keeps a future
# refactor from silently re-uniquifying one and hijacking its call
# sites (the PR-15 model spot-check pins this).
_WEAK_RESOLVE_BLOCKLIST = {
    "get", "set", "put", "pop", "update", "items", "keys", "values",
    "append", "extend", "remove", "discard", "clear", "copy", "close",
    "open", "read", "write", "flush", "join", "wait", "send", "recv",
    "next", "count", "index", "sort", "reverse", "split", "strip",
    "add", "insert", "setdefault", "start", "stop", "run", "result",
    "acquire", "release", "reshape", "astype", "item", "mean", "sum",
    "step", "route", "adopt", "evacuate", "publish",
}

# Attribute constructor types whose internal state is thread-safe by
# contract (queue.Queue hand-off, GIL-atomic deque append/popleft):
# the races rule treats accesses to these attributes as synchronized.
_SAFE_CONTAINER_CTORS = {"Queue", "LifoQueue", "PriorityQueue",
                         "SimpleQueue", "deque"}

#: annotation heads whose subscript carries the element/value type
#: (``Dict[str, Replica]`` -> ``Replica``; the VALUE side for mappings)
_CONTAINER_ANNOTATIONS = {"Dict", "dict", "List", "list", "Set", "set",
                          "Sequence", "Deque", "Mapping", "OrderedDict",
                          "DefaultDict", "defaultdict", "FrozenSet",
                          "Iterable", "Tuple", "tuple"}


def final_attr_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` -> ``c``; ``name`` -> ``name``; else None."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` -> ``"a.b.c"`` when the chain is pure Name/Attribute."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def annotation_types(node: ast.AST) -> Tuple[Optional[str], Optional[str]]:
    """(direct type name, container element/value type name) read off an
    annotation expression. ``Optional[T]`` unwraps to ``T``;
    ``Dict[K, V]`` yields the VALUE side; anything else best-effort."""
    if isinstance(node, ast.Subscript):
        head = final_attr_name(node.value)
        sl = node.slice
        if isinstance(sl, ast.Index):          # pragma: no cover (py<3.9)
            sl = sl.value
        if head == "Optional":
            return annotation_types(sl)
        if head in _CONTAINER_ANNOTATIONS:
            if isinstance(sl, ast.Tuple) and sl.elts:
                return None, final_attr_name(sl.elts[-1])
            return None, final_attr_name(sl)
        return None, None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        # string annotation: take the bare head ("ServingCell")
        name = node.value.strip().split("[")[0].split(".")[-1]
        return (name or None), None
    return final_attr_name(node), None


@dataclass
class CallSite:
    #: the ``ast.Call`` node — or, for ``is_property`` sites, the
    #: ``ast.Attribute`` load that invokes a ``@property`` getter
    node: ast.AST
    #: dotted text of the callee expression (``self._engine.put``) or None
    text: Optional[str]
    #: resolved FunctionInfo keys
    targets: List[str] = field(default_factory=list)
    weak: bool = False
    #: an attribute read resolved to a @property getter: it IS a call
    #: (the lock-discipline/races transitive walks follow it — a fleet
    #: gauge pass reading ``r.serving.queue_depth`` under the fleet lock
    #: acquires the replica lock through exactly this edge), but it is
    #: excluded from traced-set propagation (that set was tuned on
    #: explicit calls; property edges would need their own triage)
    is_property: bool = False


@dataclass
class LockRegion:
    """One ``with <lock>:`` block."""
    lock_key: str           # "module::Class.attr" or "module::NAME"
    with_node: ast.With
    lineno: int


@dataclass
class FunctionInfo:
    key: str                # "module::Qual.Path"
    module: str             # module key (display-relative path based)
    name: str               # bare name
    qualname: str           # "Class.method", "outer.<locals>.inner", ...
    class_key: Optional[str]
    node: ast.AST           # FunctionDef / AsyncFunctionDef / Lambda
    lineno: int
    calls: List[CallSite] = field(default_factory=list)
    lock_regions: List[LockRegion] = field(default_factory=list)
    #: why this function is traced, None if host-side ("@jax.jit", or a
    #: "via <caller key>" chain element added during propagation)
    traced_reason: Optional[str] = None
    decorator_names: Set[str] = field(default_factory=set)
    #: thread roles that may execute this function ("main" = any caller
    #: thread; other roles are named after discovered thread entry
    #: points — see ThreadEntry / _propagate_roles). Empty = unreached.
    thread_roles: Set[str] = field(default_factory=set)

    @property
    def is_property_getter(self) -> bool:
        return bool(self.decorator_names
                    & {"property", "cached_property"})


@dataclass
class ThreadEntry:
    """One discovered thread entry point: the target of a
    ``threading.Thread(target=...)``, a ``weakref.finalize`` callback,
    or a ``threading.Timer`` body. ``role`` is the thread's declared
    ``name=`` when it is a string constant (``"serving-driver"``),
    else a name derived from the target."""

    role: str
    func_key: str
    kind: str            # "thread" | "finalizer" | "timer"
    module: str
    lineno: int


@dataclass
class ClassInfo:
    key: str                # "module::Name"
    name: str
    module: str
    node: ast.ClassDef
    methods: Dict[str, str] = field(default_factory=dict)   # name -> func key
    #: attr name -> class name (unresolved text) from annotations or
    #: ``self.x = ClassName(...)`` / ``self.x = param`` with an annotation
    attr_types: Dict[str, str] = field(default_factory=dict)
    #: attr name -> ELEMENT/VALUE class name for annotated containers
    #: (``self._replicas: Dict[str, Replica]`` -> ``Replica``), so
    #: ``self._replicas.get(k)`` / ``for r in self._replicas.values()``
    #: type their results
    attr_elem_types: Dict[str, str] = field(default_factory=dict)
    #: attr name -> constructor name for threading primitives
    lock_attrs: Dict[str, str] = field(default_factory=dict)
    #: attr name -> "Event" for threading.Event attributes (wall-clock
    #: rule: raw ``event.wait`` bypasses the injectable clock seam)
    event_attrs: Dict[str, str] = field(default_factory=dict)
    base_names: List[str] = field(default_factory=list)


@dataclass
class ModuleInfo:
    key: str                # display-relative posix path, e.g. "deepspeed_tpu/serving/server.py"
    path: str               # absolute path
    tree: ast.Module
    source_lines: List[str]
    #: comment text by line number (from tokenize), for suppressions
    comments: Dict[int, str] = field(default_factory=dict)
    #: import alias -> real dotted module ("np" -> "numpy")
    alias_to_module: Dict[str, str] = field(default_factory=dict)
    #: from-import: local name -> (dotted module, original name)
    name_imports: Dict[str, Tuple[str, str]] = field(default_factory=dict)
    functions: List[str] = field(default_factory=list)      # func keys
    classes: List[str] = field(default_factory=list)        # class keys
    module_locks: Dict[str, str] = field(default_factory=dict)  # NAME -> ctor

    def line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.source_lines):
            return self.source_lines[lineno - 1]
        return ""


class PackageModel:
    def __init__(self) -> None:
        self.modules: Dict[str, ModuleInfo] = {}
        self.functions: Dict[str, FunctionInfo] = {}
        self.classes: Dict[str, ClassInfo] = {}
        # bare method name -> set of func keys (for weak resolution)
        self.method_index: Dict[str, Set[str]] = {}
        # attr name -> set of annotated type names (for receiver typing)
        self.attr_type_index: Dict[str, Set[str]] = {}
        # class bare name -> set of class keys
        self.class_index: Dict[str, Set[str]] = {}
        # module-level function bare name -> keys (diagnostics only)
        self.function_index: Dict[str, Set[str]] = {}
        # discovered thread entry points (the thread model's roots)
        self.thread_entries: List[ThreadEntry] = []

    # -- queries --------------------------------------------------------
    def functions_in(self, module_key: str) -> Iterator[FunctionInfo]:
        mod = self.modules.get(module_key)
        if mod is None:
            return
        for k in mod.functions:
            yield self.functions[k]

    def resolve_class(self, name: str) -> Optional[ClassInfo]:
        keys = self.class_index.get(name, set())
        if len(keys) == 1:
            return self.classes[next(iter(keys))]
        return None

    def is_traced(self, func_key: str) -> bool:
        f = self.functions.get(func_key)
        return f is not None and f.traced_reason is not None


# ----------------------------------------------------------------------
# collection
# ----------------------------------------------------------------------

def _iter_py_files(paths: Sequence[str]) -> Iterator[str]:
    for p in paths:
        if os.path.isfile(p):
            yield p
        else:
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in {"__pycache__", ".git"})
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(root, f)


def _display_key(path: str, base: str) -> str:
    rel = os.path.relpath(path, base)
    return rel.replace(os.sep, "/")


def _read_comments(path: str) -> Dict[int, str]:
    comments: Dict[int, str] = {}
    try:
        with tokenize.open(path) as fh:
            for tok in tokenize.generate_tokens(fh.readline):
                if tok.type == tokenize.COMMENT:
                    comments[tok.start[0]] = tok.string
    except (tokenize.TokenizeError, SyntaxError, OSError):
        pass
    return comments


class _Collector(ast.NodeVisitor):
    """First pass over one module: functions, classes, imports, locks."""

    def __init__(self, pkg: PackageModel, mod: ModuleInfo) -> None:
        self.pkg = pkg
        self.mod = mod
        self.class_stack: List[ClassInfo] = []
        self.func_stack: List[FunctionInfo] = []

    # -- imports --------------------------------------------------------
    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            local = alias.asname or alias.name.split(".")[0]
            self.mod.alias_to_module[local] = (alias.name if alias.asname
                                               else alias.name.split(".")[0])
            if alias.asname:
                self.mod.alias_to_module[alias.asname] = alias.name

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        src = ("." * node.level) + (node.module or "")
        for alias in node.names:
            local = alias.asname or alias.name
            self.mod.name_imports[local] = (src, alias.name)

    # -- defs -----------------------------------------------------------
    def _qual_prefix(self) -> str:
        if self.func_stack:
            return self.func_stack[-1].qualname + ".<locals>."
        if self.class_stack:
            return self.class_stack[-1].name + "."
        return ""

    def _add_function(self, node: ast.AST, name: str) -> FunctionInfo:
        qual = self._qual_prefix() + name
        key = f"{self.mod.key}::{qual}"
        # a redefinition (same name at same scope) gets a line suffix
        if key in self.pkg.functions:
            key = f"{key}@{getattr(node, 'lineno', 0)}"
        class_key = (self.class_stack[-1].key
                     if self.class_stack and not self.func_stack else None)
        info = FunctionInfo(key=key, module=self.mod.key, name=name,
                            qualname=qual, class_key=class_key, node=node,
                            lineno=getattr(node, "lineno", 0))
        self.pkg.functions[key] = info
        self.mod.functions.append(key)
        if class_key is not None:
            cls = self.classes_top()
            cls.methods.setdefault(name, key)
            self.pkg.method_index.setdefault(name, set()).add(key)
        else:
            self.pkg.function_index.setdefault(name, set()).add(key)
        return info

    def classes_top(self) -> ClassInfo:
        return self.class_stack[-1]

    def _visit_funcdef(self, node) -> None:
        info = self._add_function(node, node.name)
        for dec in node.decorator_list:
            dn = final_attr_name(dec if not isinstance(dec, ast.Call)
                                 else dec.func)
            if dn:
                info.decorator_names.add(dn)
            if isinstance(dec, ast.Call):
                # @partial(jax.jit, ...) / @functools.partial(jit, ...)
                if (final_attr_name(dec.func) == "partial" and dec.args
                        and final_attr_name(dec.args[0]) in
                        _TRACING_DECORATORS):
                    info.decorator_names.add(final_attr_name(dec.args[0]))
        if info.decorator_names & _TRACING_DECORATORS:
            deco = sorted(info.decorator_names & _TRACING_DECORATORS)[0]
            info.traced_reason = f"decorated @{deco}"
        self.func_stack.append(info)
        for child in ast.iter_child_nodes(node):
            if child in node.decorator_list:
                continue
            self.visit(child)
        self.func_stack.pop()

    visit_FunctionDef = _visit_funcdef
    visit_AsyncFunctionDef = _visit_funcdef

    def visit_Lambda(self, node: ast.Lambda) -> None:
        info = self._add_function(node, f"<lambda>@{node.lineno}")
        self.func_stack.append(info)
        self.generic_visit(node)
        self.func_stack.pop()

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        if self.func_stack or self.class_stack:
            # nested classes: record but don't model methods specially
            key = f"{self.mod.key}::{self._qual_prefix()}{node.name}"
        else:
            key = f"{self.mod.key}::{node.name}"
        cls = ClassInfo(key=key, name=node.name, module=self.mod.key,
                        node=node,
                        base_names=[b for b in
                                    (final_attr_name(x) for x in node.bases)
                                    if b])
        self.pkg.classes[key] = cls
        self.mod.classes.append(key)
        self.pkg.class_index.setdefault(node.name, set()).add(key)
        # class-body annotations: ``serving: ServingEngine``
        for stmt in node.body:
            if isinstance(stmt, ast.AnnAssign) and isinstance(
                    stmt.target, ast.Name):
                t, elem = annotation_types(stmt.annotation)
                if t:
                    cls.attr_types[stmt.target.id] = t
                if elem:
                    cls.attr_elem_types[stmt.target.id] = elem
        self.class_stack.append(cls)
        saved, self.func_stack = self.func_stack, []
        self.generic_visit(node)
        self.func_stack = saved
        self.class_stack.pop()
        for attr, tname in cls.attr_types.items():
            self.pkg.attr_type_index.setdefault(attr, set()).add(tname)

    # -- assignments: lock attrs + attr types ---------------------------
    def _record_self_assign(self, target: ast.AST, value: ast.AST) -> None:
        if not (isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self" and self.class_stack):
            return
        cls = self.class_stack[-1]
        attr = target.attr
        if isinstance(value, ast.Call):
            ctor = final_attr_name(value.func)
            if ctor in _LOCK_CONSTRUCTORS and self._is_threading(value.func):
                cls.lock_attrs[attr] = ctor
            elif ctor in ("named_lock", "named_rlock") \
                    and self._is_locksan(value.func):
                # the runtime lock-order sanitizer's construction seam
                # (resilience/locksan.py): statically these ARE the
                # serving locks — the lock model must keep seeing them
                cls.lock_attrs[attr] = ("RLock" if ctor == "named_rlock"
                                        else "Lock")
            elif ctor == "Event" and self._is_threading(value.func):
                cls.event_attrs[attr] = ctor
            elif ctor in _SAFE_CONTAINER_CTORS:
                cls.attr_types.setdefault(attr, ctor)
            elif ctor and ctor[:1].isupper():
                cls.attr_types.setdefault(attr, ctor)
        elif isinstance(value, ast.Name) and self.func_stack:
            # ``self.x = x`` with an annotated parameter ``x: T``
            fn = self.func_stack[-1].node
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for arg in (list(fn.args.posonlyargs) + list(fn.args.args)
                            + list(fn.args.kwonlyargs)):
                    if arg.arg == value.id and arg.annotation is not None:
                        t = final_attr_name(arg.annotation)
                        if t:
                            cls.attr_types.setdefault(attr, t)

    def _is_threading(self, func_expr: ast.AST) -> bool:
        """``threading.Lock`` / aliased module / from-imported name."""
        if isinstance(func_expr, ast.Attribute) and isinstance(
                func_expr.value, ast.Name):
            real = self.mod.alias_to_module.get(func_expr.value.id,
                                                func_expr.value.id)
            return real == "threading" or real.startswith("threading.")
        if isinstance(func_expr, ast.Name):
            imp = self.mod.name_imports.get(func_expr.id)
            return bool(imp and imp[0].lstrip(".") == "threading")
        return False

    def _is_locksan(self, func_expr: ast.AST) -> bool:
        """Constructed via resilience/locksan.py's named_lock/named_rlock
        (any import flavor)."""
        if isinstance(func_expr, ast.Attribute) and isinstance(
                func_expr.value, ast.Name):
            real = self.mod.alias_to_module.get(func_expr.value.id,
                                                func_expr.value.id)
            return real.split(".")[-1] == "locksan"
        if isinstance(func_expr, ast.Name):
            imp = self.mod.name_imports.get(func_expr.id)
            return bool(imp and imp[0].lstrip(".").split(".")[-1]
                        == "locksan")
        return False

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._record_self_assign(t, node.value)
            if (isinstance(t, ast.Name) and not self.func_stack
                    and not self.class_stack
                    and isinstance(node.value, ast.Call)):
                ctor = final_attr_name(node.value.func)
                if ctor in _LOCK_CONSTRUCTORS and self._is_threading(
                        node.value.func):
                    self.mod.module_locks[t.id] = ctor
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._record_self_assign(node.target, node.value)
        # ``self._replicas: Dict[str, Replica] = {}`` — the annotation
        # types the attribute (and its container elements) even when the
        # assigned value is an empty literal
        if (isinstance(node.target, ast.Attribute)
                and isinstance(node.target.value, ast.Name)
                and node.target.value.id == "self" and self.class_stack):
            cls = self.class_stack[-1]
            t, elem = annotation_types(node.annotation)
            if t:
                cls.attr_types.setdefault(node.target.attr, t)
            if elem:
                cls.attr_elem_types.setdefault(node.target.attr, elem)
        self.generic_visit(node)


# ----------------------------------------------------------------------
# second pass: call sites, lock regions, traced roots
# ----------------------------------------------------------------------

def iter_shallow(node: ast.AST) -> Iterator[ast.AST]:
    """Walk ``node`` without descending into nested function/class
    bodies (their statements belong to their own FunctionInfo)."""
    for child in ast.iter_child_nodes(node):
        yield child
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda, ast.ClassDef)):
            continue
        yield from iter_shallow(child)


class _Resolver:
    def __init__(self, pkg: PackageModel, mod: ModuleInfo) -> None:
        self.pkg = pkg
        self.mod = mod
        # package-internal module resolution: map "…serving.server"-ish
        # suffixes of imported module names to module keys
        self._mod_by_tail: Dict[str, str] = {}
        for key in pkg.modules:
            tail = key[:-3] if key.endswith(".py") else key
            tail = tail.replace("/", ".")
            if tail.endswith(".__init__"):
                tail = tail[: -len(".__init__")]
            self._mod_by_tail[tail] = key

    def module_key_for(self, dotted: str) -> Optional[str]:
        """Best-effort: match an imported dotted module (possibly
        relative, possibly absolute) to an analyzed module key."""
        dotted = dotted.lstrip(".")
        if not dotted:
            return None
        for tail, key in self._mod_by_tail.items():
            if tail == dotted or tail.endswith("." + dotted):
                return key
        return None

    def _module_level_func(self, module_key: str,
                           name: str) -> Optional[str]:
        mod = self.pkg.modules.get(module_key)
        if mod is None:
            return None
        for fk in mod.functions:
            f = self.pkg.functions[fk]
            if f.name == name and f.class_key is None \
                    and "<locals>" not in f.qualname:
                return fk
        return None

    def _class_in_module(self, module_key: str,
                         name: str) -> Optional[ClassInfo]:
        mod = self.pkg.modules.get(module_key)
        if mod is None:
            return None
        for ck in mod.classes:
            if self.pkg.classes[ck].name == name:
                return self.pkg.classes[ck]
        return None

    def _lookup_class_method(self, cls: ClassInfo, name: str,
                             _depth: int = 0) -> Optional[str]:
        if name in cls.methods:
            return cls.methods[name]
        if _depth >= 4:
            return None
        for base in cls.base_names:
            base_cls = (self._class_in_module(cls.module, base)
                        or self.pkg.resolve_class(base))
            if base_cls is not None and base_cls.key != cls.key:
                got = self._lookup_class_method(base_cls, name, _depth + 1)
                if got:
                    return got
        return None

    def resolve(self, call: ast.Call,
                owner: FunctionInfo,
                local_defs: Dict[str, str],
                local_types: Optional[Dict[str, str]] = None) -> CallSite:
        local_types = local_types or {}
        func = call.func
        site = CallSite(node=call, text=dotted_name(func))
        # plain name --------------------------------------------------
        if isinstance(func, ast.Name):
            name = func.id
            if name in local_defs:
                site.targets = [local_defs[name]]
                return site
            fk = self._module_level_func(self.mod.key, name)
            if fk:
                site.targets = [fk]
                return site
            cls = next((self.pkg.classes[ck] for ck in self.mod.classes
                        if self.pkg.classes[ck].name == name), None)
            if cls is None and name in self.mod.name_imports:
                src, orig = self.mod.name_imports[name]
                mk = self.module_key_for(src)
                if mk:
                    fk = self._module_level_func(mk, orig)
                    if fk:
                        site.targets = [fk]
                        return site
                    cls = self._class_in_module(mk, orig)
            if cls is not None:
                init = self._lookup_class_method(cls, "__init__")
                if init:
                    site.targets = [init]
                return site
            return site
        if not isinstance(func, ast.Attribute):
            return site
        # self.method -------------------------------------------------
        recv = func.value
        if isinstance(recv, ast.Name) and recv.id in ("self", "cls") \
                and owner.class_key:
            cls = self.pkg.classes[owner.class_key]
            got = self._lookup_class_method(cls, func.attr)
            if got:
                site.targets = [got]
            return site
        # module-qualified: mod.func / pkg.mod.func -------------------
        dn = dotted_name(recv)
        if dn is not None:
            head = dn.split(".")[0]
            real = self.mod.alias_to_module.get(head)
            if real is not None:
                full = real + dn[len(head):]
                mk = self.module_key_for(full)
                if mk:
                    fk = self._module_level_func(mk, func.attr)
                    if fk:
                        site.targets = [fk]
                        return site
            if head in self.mod.name_imports:
                src, orig = self.mod.name_imports[head]
                mk = self.module_key_for(src.rstrip(".") + "." + orig
                                         if not src.endswith(".")
                                         else src + orig)
                if mk is None:
                    mk = self.module_key_for(orig)
                if mk:
                    fk = self._module_level_func(mk, func.attr)
                    if fk:
                        site.targets = [fk]
                        return site
        # typed receiver attr: x.serving.submit_request ---------------
        if isinstance(recv, ast.Attribute):
            types = self.pkg.attr_type_index.get(recv.attr, set())
            if len(types) == 1:
                cls = self.pkg.resolve_class(next(iter(types)))
                if cls is not None:
                    got = self._lookup_class_method(cls, func.attr)
                    if got:
                        site.targets = [got]
                        return site
        # typed LOCAL receiver: cell.fleet... where the local's type was
        # inferred (annotation, constructor, container element)
        if isinstance(recv, ast.Name) and recv.id in local_types:
            cls = self.pkg.resolve_class(local_types[recv.id])
            if cls is not None:
                got = self._lookup_class_method(cls, func.attr)
                if got:
                    site.targets = [got]
                    return site
        # weak: unique method name ------------------------------------
        # ... but never on the result of a call the model cannot
        # resolve: ``hashlib.sha256(data).digest()`` is a method on an
        # EXTERNAL object, and weak-resolving it to the one package
        # method named ``digest`` (ServingCell.digest) planted a
        # phantom Fleet->Cell edge in the lock graph that no runtime
        # path can ever exercise (race-lane hot-edge gate).
        if isinstance(recv, ast.Call):
            inner = self.resolve(recv, owner, local_defs, local_types)
            if not inner.targets:
                return site
        if func.attr not in _WEAK_RESOLVE_BLOCKLIST:
            keys = self.pkg.method_index.get(func.attr, set())
            if len(keys) == 1:
                site.targets = [next(iter(keys))]
                site.weak = True
        return site

    def resolve_property(self, node: ast.Attribute, owner: FunctionInfo,
                         local_types: Dict[str, str]
                         ) -> Optional[CallSite]:
        """An attribute LOAD that invokes a ``@property`` getter of a
        package class (``cell.digest``, ``r.serving.queue_depth``) is a
        call in disguise — and the serving tier's property getters take
        locks, so the lock-discipline graph and the races rule must see
        the edge. Only strong receiver typings resolve (self, typed
        local, typed attribute); a miss returns None."""
        target_cls: Optional[ClassInfo] = None
        recv = node.value
        if isinstance(recv, ast.Name):
            if recv.id in ("self", "cls") and owner.class_key:
                target_cls = self.pkg.classes[owner.class_key]
            elif recv.id in local_types:
                target_cls = self.pkg.resolve_class(local_types[recv.id])
        elif isinstance(recv, ast.Attribute):
            if isinstance(recv.value, ast.Name) \
                    and recv.value.id == "self" and owner.class_key:
                t = self.pkg.classes[owner.class_key].attr_types.get(
                    recv.attr)
                if t:
                    target_cls = self.pkg.resolve_class(t)
            if target_cls is None:
                types = self.pkg.attr_type_index.get(recv.attr, set())
                if len(types) == 1:
                    target_cls = self.pkg.resolve_class(next(iter(types)))
        if target_cls is None:
            return None
        got = self._lookup_class_method(target_cls, node.attr)
        if got is None:
            return None
        tf = self.pkg.functions.get(got)
        if tf is None or not tf.is_property_getter:
            return None
        return CallSite(node=node, text=dotted_name(node), targets=[got],
                        is_property=True)


class _SecondPass:
    def __init__(self, pkg: PackageModel, mod: ModuleInfo) -> None:
        self.pkg = pkg
        self.mod = mod
        self.resolver = _Resolver(pkg, mod)

    def run(self) -> None:
        # map (function node) -> FunctionInfo for this module
        by_node = {self.pkg.functions[k].node: self.pkg.functions[k]
                   for k in self.mod.functions}
        for fk in self.mod.functions:
            f = self.pkg.functions[fk]
            local_defs = self._local_defs(f, by_node)
            self._scan_function(f, local_defs, by_node)
        # module-level transform calls (jitted module constants etc.)
        mod_defs = {self.pkg.functions[k].name: k
                    for k in self.mod.functions
                    if self.pkg.functions[k].class_key is None
                    and "<locals>" not in self.pkg.functions[k].qualname}
        for node in iter_shallow(self.mod.tree):
            if isinstance(node, ast.Call):
                self._mark_transform_args(node, mod_defs, by_node)
                self._mark_thread_entry(node, None, mod_defs, by_node)

    def _local_defs(self, f: FunctionInfo,
                    by_node) -> Dict[str, str]:
        """Names of functions defined lexically inside ``f`` (one level
        is enough: transforms take the directly-nested step fn), plus
        module-level defs."""
        defs: Dict[str, str] = {}
        for k in self.mod.functions:
            g = self.pkg.functions[k]
            if g.class_key is None and "<locals>" not in g.qualname:
                defs.setdefault(g.name, k)
        prefix = f.qualname + ".<locals>."
        for k in self.mod.functions:
            g = self.pkg.functions[k]
            if g.qualname.startswith(prefix) \
                    and "." not in g.qualname[len(prefix):]:
                defs[g.name] = k
        return defs

    def _scan_function(self, f: FunctionInfo,
                       local_defs: Dict[str, str], by_node) -> None:
        if isinstance(f.node, ast.Lambda):
            # a lambda body IS an expression — usually a single Call
            # (``jit(lambda x: helper(x))``); iter_shallow only yields
            # children, so the body node itself must be scanned too or
            # the traced set never reaches ``helper``
            nodes: List[ast.AST] = [f.node.body]
            nodes = nodes + list(iter_shallow(f.node.body))
        else:
            nodes = list(iter_shallow(f.node))
        local_types = self._infer_local_types(f, nodes)
        for node in nodes:
            if isinstance(node, ast.Call):
                site = self.resolver.resolve(node, f, local_defs,
                                             local_types)
                f.calls.append(site)
                self._mark_transform_args(node, local_defs, by_node)
                self._mark_thread_entry(node, f, local_defs, by_node)
            elif isinstance(node, ast.With):
                for item in node.items:
                    lk = self._lock_key(item.context_expr, f)
                    if lk:
                        f.lock_regions.append(LockRegion(
                            lock_key=lk, with_node=node,
                            lineno=node.lineno))
        # property getters invoked by attribute loads: calls in disguise
        # (see Resolver.resolve_property). An attribute that is itself
        # the callee of a Call was already handled above.
        callee_ids = {id(n.func) for n in nodes
                      if isinstance(n, ast.Call)}
        for node in nodes:
            if (isinstance(node, ast.Attribute)
                    and isinstance(node.ctx, ast.Load)
                    and id(node) not in callee_ids):
                site = self.resolver.resolve_property(node, f, local_types)
                if site is not None:
                    f.calls.append(site)

    def _infer_local_types(self, f: FunctionInfo,
                           nodes: Sequence[ast.AST]) -> Dict[str, str]:
        """Best-effort local-variable typing: parameter annotations,
        annotated assigns, constructor assigns, ``self.attr`` loads of
        typed attributes, and container-element extraction
        (``self._cells.get(k)`` / ``self._cells[k]`` /
        ``for r in self._replicas.values()`` / comprehensions) using
        the class's annotated container value types. Flow-insensitive;
        two lexical passes so a loop over a list built later still
        types."""
        types: Dict[str, str] = {}
        elems: Dict[str, str] = {}   # local list/dict var -> element type
        cls = (self.pkg.classes.get(f.class_key)
               if f.class_key else None)

        def self_attr_type(v: ast.AST) -> Optional[str]:
            if (isinstance(v, ast.Attribute)
                    and isinstance(v.value, ast.Name)
                    and v.value.id == "self" and cls is not None):
                return cls.attr_types.get(v.attr)
            return None

        def self_attr_elem(v: ast.AST) -> Optional[str]:
            if (isinstance(v, ast.Attribute)
                    and isinstance(v.value, ast.Name)
                    and v.value.id == "self" and cls is not None):
                return cls.attr_elem_types.get(v.attr)
            return None

        def elem_type_of(it: ast.AST) -> Optional[str]:
            """Element type of an iterable expression."""
            got = self_attr_elem(it)
            if got:
                return got
            if isinstance(it, ast.Name):
                return elems.get(it.id)
            if isinstance(it, ast.Call):
                fn = it.func
                if isinstance(fn, ast.Attribute) \
                        and fn.attr == "values":
                    return self_attr_elem(fn.value)
                if final_attr_name(fn) in ("list", "sorted", "reversed",
                                           "iter") and it.args:
                    return elem_type_of(it.args[0])
            if isinstance(it, (ast.ListComp, ast.SetComp,
                               ast.GeneratorExp)):
                gen = it.generators[0] if it.generators else None
                if gen is not None and isinstance(it.elt, ast.Name) \
                        and isinstance(gen.target, ast.Name) \
                        and it.elt.id == gen.target.id:
                    return elem_type_of(gen.iter)
            return None

        def value_type(v: ast.AST) -> Optional[str]:
            got = self_attr_type(v)
            if got:
                return got
            if isinstance(v, ast.Name):
                return types.get(v.id)
            if isinstance(v, ast.Subscript):
                return elem_type_of(v.value)
            if isinstance(v, ast.Call):
                fn = v.func
                ctor = final_attr_name(fn)
                if ctor and ctor[:1].isupper() \
                        and self.pkg.class_index.get(ctor):
                    return ctor
                if isinstance(fn, ast.Attribute) and fn.attr == "get":
                    return elem_type_of(fn.value)
                if ctor == "next" and v.args:
                    return elem_type_of(v.args[0])
            return None

        for _pass in range(2):
            for node in nodes:
                if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name):
                    name = node.targets[0].id
                    t = value_type(node.value)
                    if t:
                        types.setdefault(name, t)
                    e = elem_type_of(node.value)
                    if e:
                        elems.setdefault(name, e)
                elif isinstance(node, ast.AnnAssign) \
                        and isinstance(node.target, ast.Name):
                    t, e = annotation_types(node.annotation)
                    if t:
                        types.setdefault(node.target.id, t)
                    if e:
                        elems.setdefault(node.target.id, e)
                elif isinstance(node, (ast.For, ast.AsyncFor)) \
                        and isinstance(node.target, ast.Name):
                    t = elem_type_of(node.iter)
                    if t:
                        types.setdefault(node.target.id, t)
                elif isinstance(node, (ast.ListComp, ast.SetComp,
                                       ast.GeneratorExp, ast.DictComp)):
                    for gen in node.generators:
                        if isinstance(gen.target, ast.Name):
                            t = elem_type_of(gen.iter)
                            if t:
                                types.setdefault(gen.target.id, t)
        if isinstance(f.node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for arg in (list(f.node.args.posonlyargs)
                        + list(f.node.args.args)
                        + list(f.node.args.kwonlyargs)):
                if arg.annotation is not None:
                    t, _ = annotation_types(arg.annotation)
                    if t:
                        types[arg.arg] = t
        return types

    def _expr_module(self, func_expr: ast.AST) -> Optional[str]:
        """Real module behind ``alias.attr`` or a from-imported name."""
        if isinstance(func_expr, ast.Attribute) and isinstance(
                func_expr.value, ast.Name):
            return self.mod.alias_to_module.get(func_expr.value.id,
                                                func_expr.value.id)
        if isinstance(func_expr, ast.Name):
            imp = self.mod.name_imports.get(func_expr.id)
            if imp:
                return imp[0].lstrip(".")
        return None

    def _callable_key(self, arg: Optional[ast.AST],
                      owner: Optional[FunctionInfo],
                      local_defs: Dict[str, str],
                      by_node) -> Optional[str]:
        """Resolve a callable-valued expression to a function key (the
        thread-entry version of _mark_callable — prefers the owner
        class over the global unique-name index for ``self.x``)."""
        if arg is None:
            return None
        if isinstance(arg, ast.Lambda):
            got = by_node.get(arg)
            return got.key if got is not None else None
        if isinstance(arg, ast.Name) and arg.id in local_defs:
            return local_defs[arg.id]
        if isinstance(arg, ast.Attribute) and isinstance(
                arg.value, ast.Name):
            if arg.value.id == "self" and owner is not None \
                    and owner.class_key:
                cls = self.pkg.classes[owner.class_key]
                got = self.resolver._lookup_class_method(cls, arg.attr)
                if got:
                    return got
            keys = self.pkg.method_index.get(arg.attr, set())
            if len(keys) == 1:
                return next(iter(keys))
        return None

    def _mark_thread_entry(self, call: ast.Call,
                           owner: Optional[FunctionInfo],
                           local_defs: Dict[str, str], by_node) -> None:
        """Record thread entry points: ``threading.Thread(target=...)``
        (role = the thread's ``name=`` string when constant),
        ``threading.Timer(t, fn)`` and ``weakref.finalize(obj, fn)``."""
        name = final_attr_name(call.func)
        if name in ("Thread", "Timer"):
            if self._expr_module(call.func) != "threading":
                return
            target = None
            role_name = None
            for kw in call.keywords:
                if kw.arg == "target":
                    target = kw.value
                elif kw.arg == "name" \
                        and isinstance(kw.value, ast.Constant) \
                        and isinstance(kw.value.value, str):
                    role_name = kw.value.value
            if name == "Timer" and target is None and len(call.args) >= 2:
                target = call.args[1]
            key = self._callable_key(target, owner, local_defs, by_node)
            if key is None:
                return
            role = role_name or f"thread:{self.pkg.functions[key].qualname}"
            self.pkg.thread_entries.append(ThreadEntry(
                role=role, func_key=key,
                kind="thread" if name == "Thread" else "timer",
                module=self.mod.key, lineno=call.lineno))
        elif name == "finalize" and self._expr_module(call.func) \
                == "weakref" and len(call.args) >= 2:
            key = self._callable_key(call.args[1], owner, local_defs,
                                     by_node)
            if key is not None:
                self.pkg.thread_entries.append(ThreadEntry(
                    role="finalizer", func_key=key, kind="finalizer",
                    module=self.mod.key, lineno=call.lineno))

    def _lock_key(self, expr: ast.AST,
                  f: FunctionInfo) -> Optional[str]:
        if isinstance(expr, ast.Attribute) and isinstance(
                expr.value, ast.Name) and expr.value.id == "self" \
                and f.class_key:
            cls = self.pkg.classes[f.class_key]
            if expr.attr in cls.lock_attrs:
                return f"{cls.key}.{expr.attr}"
            # inherited lock attr
            for base in cls.base_names:
                b = self.pkg.resolve_class(base)
                if b and expr.attr in b.lock_attrs:
                    return f"{b.key}.{expr.attr}"
            return None
        if isinstance(expr, ast.Name) \
                and expr.id in self.mod.module_locks:
            return f"{self.mod.key}::{expr.id}"
        if isinstance(expr, ast.Attribute):
            # x.lockattr where type(x) is uniquely annotated
            if isinstance(expr.value, (ast.Name, ast.Attribute)):
                recv_attr = final_attr_name(expr.value)
                types = self.pkg.attr_type_index.get(recv_attr or "", set())
                if len(types) == 1:
                    cls = self.pkg.resolve_class(next(iter(types)))
                    if cls and expr.attr in cls.lock_attrs:
                        return f"{cls.key}.{expr.attr}"
            # unique lock attr name across package classes
            owners = [c for c in self.pkg.classes.values()
                      if expr.attr in c.lock_attrs]
            if len(owners) == 1:
                return f"{owners[0].key}.{expr.attr}"
        return None

    def _mark_transform_args(self, call: ast.Call,
                             local_defs: Dict[str, str],
                             by_node) -> None:
        name = final_attr_name(call.func)
        if name == "partial" and call.args:
            inner = final_attr_name(call.args[0])
            if inner in _TRANSFORM_CALLABLE_ARGS and len(call.args) > 1:
                self._mark_callable(call.args[1], f"partial({inner}, ...)",
                                    local_defs, by_node)
            return
        spec = _TRANSFORM_CALLABLE_ARGS.get(name or "")
        if spec is None:
            return
        if spec == (None,):
            args = call.args[1:]
        else:
            args = [call.args[i] for i in spec if i < len(call.args)]
        for arg in args:
            self._mark_callable(arg, f"passed to {name}()", local_defs,
                                by_node)

    def _mark_callable(self, arg: ast.AST, why: str,
                       local_defs: Dict[str, str], by_node) -> None:
        target: Optional[FunctionInfo] = None
        if isinstance(arg, ast.Lambda):
            target = by_node.get(arg)
        elif isinstance(arg, ast.Name) and arg.id in local_defs:
            target = self.pkg.functions.get(local_defs[arg.id])
        elif isinstance(arg, ast.Attribute) and isinstance(
                arg.value, ast.Name) and arg.value.id == "self":
            keys = self.pkg.method_index.get(arg.attr, set())
            if len(keys) == 1:
                target = self.pkg.functions.get(next(iter(keys)))
        elif isinstance(arg, ast.Call):
            # e.g. jit(partial(step, cfg)) / scan(partial(body, x), ...)
            if final_attr_name(arg.func) == "partial" and arg.args:
                self._mark_callable(arg.args[0], why, local_defs, by_node)
            return
        if target is not None and target.traced_reason is None:
            target.traced_reason = why


def _propagate_traced(pkg: PackageModel) -> None:
    """BFS the call graph from traced roots: anything a traced function
    calls (resolvably, inside the package) also runs under the trace."""
    frontier = [k for k, f in pkg.functions.items()
                if f.traced_reason is not None]
    seen = set(frontier)
    while frontier:
        nxt: List[str] = []
        for k in frontier:
            f = pkg.functions[k]
            for site in f.calls:
                if site.is_property:
                    # property-getter edges feed the lock/races graphs
                    # only — the traced set stays explicit-call based
                    continue
                for t in site.targets:
                    if t in seen:
                        continue
                    g = pkg.functions.get(t)
                    if g is None:
                        continue
                    # constructors aren't traced by being called with
                    # tracer args at build time in practice; skip dunder
                    # targets to cut false chains
                    if g.name.startswith("__") and g.name.endswith("__"):
                        continue
                    g.traced_reason = f"called from traced {f.qualname}"
                    seen.add(t)
                    nxt.append(t)
        frontier = nxt


def _propagate_roles(pkg: PackageModel) -> None:
    """Thread-role propagation over the call graph.

    Seeds: each discovered thread entry gets its role; the externally
    callable surface — public names, dunders, and anything with no
    resolved internal caller (minus the thread entries themselves) —
    gets the synthetic ``"main"`` role (any caller thread). Roles then
    flow caller -> callee to a fixpoint, so a helper reachable from both
    ``step()`` (caller-driven) and the driver loop carries both roles —
    exactly the "accessed from >= 2 threads" precondition the races
    rule tests."""
    entry_keys = set()
    for e in pkg.thread_entries:
        f = pkg.functions.get(e.func_key)
        if f is not None:
            f.thread_roles.add(e.role)
            entry_keys.add(e.func_key)
    incoming: Set[str] = set()
    for f in pkg.functions.values():
        for site in f.calls:
            incoming.update(site.targets)
    for k, f in pkg.functions.items():
        if k in entry_keys:
            continue
        public = (not f.name.startswith("_")
                  or (f.name.startswith("__") and f.name.endswith("__")))
        if public or k not in incoming:
            f.thread_roles.add("main")
    work = [k for k, f in pkg.functions.items() if f.thread_roles]
    while work:
        k = work.pop()
        f = pkg.functions[k]
        for site in f.calls:
            for t in site.targets:
                g = pkg.functions.get(t)
                if g is None:
                    continue
                if not f.thread_roles <= g.thread_roles:
                    g.thread_roles |= f.thread_roles
                    work.append(t)


def build_package_model(paths: Sequence[str],
                        base: Optional[str] = None) -> PackageModel:
    """Parse every ``.py`` under ``paths`` into a PackageModel. ``base``
    anchors display-relative module keys (defaults to the common parent
    of ``paths``)."""
    paths = [os.path.abspath(p) for p in paths]
    if base is None:
        base = os.path.commonpath([p if os.path.isdir(p)
                                   else os.path.dirname(p)
                                   for p in paths]) if paths else os.getcwd()
        base = os.path.dirname(base) if os.path.isdir(base) else base
    pkg = PackageModel()
    for path in _iter_py_files(paths):
        try:
            with open(path, "r", encoding="utf-8") as fh:
                source = fh.read()
            tree = ast.parse(source, filename=path)
        except (SyntaxError, UnicodeDecodeError, OSError):
            continue
        mod = ModuleInfo(key=_display_key(path, base), path=path,
                         tree=tree, source_lines=source.splitlines(),
                         comments=_read_comments(path))
        pkg.modules[mod.key] = mod
        _Collector(pkg, mod).visit(tree)
    for mod in pkg.modules.values():
        _SecondPass(pkg, mod).run()
    _propagate_traced(pkg)
    _propagate_roles(pkg)
    return pkg
