"""dslint CLI: ``python -m deepspeed_tpu.analysis [paths...]``.

Modes:

* default — analyze and print human-readable findings;
* ``--check`` — exit 1 on any finding that is neither suppressed
  in-source nor grandfathered in the baseline (the CI gate);
* ``--update-baseline`` — rewrite the baseline to exactly today's
  unsuppressed findings (run after fixing or deliberately accepting);
* ``--format json`` — machine-readable output;
* ``--list-rules`` — the rule catalog.

With no paths, the ``deepspeed_tpu`` package containing this module is
analyzed — so the committed gate line works from the repo root with no
arguments beyond the baseline.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from typing import List, Optional, Sequence, Tuple

from .findings import (Baseline, Finding, apply_suppressions,
                       parse_suppressions)
from .model import build_package_model
from .registry import all_rules, known_rule_ids


def analyze(paths: Sequence[str], base: Optional[str] = None,
            select: Optional[Sequence[str]] = None,
            ignore: Optional[Sequence[str]] = None,
            report_unused: bool = True) -> List[Finding]:
    """Run every (selected) rule over ``paths``; returns findings with
    suppression flags applied (suppressed ones are kept, marked).
    ``report_unused=False`` drops the suppression meta-rule's UNUSED
    findings only — whether a suppression matches is a whole-package
    property, so ``--changed``'s scoped model cannot judge it."""
    pkg = build_package_model(paths, base=base)
    known = set(known_rule_ids())
    rules = all_rules()
    active = [rid for rid in sorted(rules)
              if (not select or rid in select)
              and (not ignore or rid not in ignore)]
    findings: List[Finding] = []
    for rid in active:
        findings.extend(rules[rid]().run(pkg))
    sups = []
    meta_on = (not select or "suppression" in select) and \
        (not ignore or "suppression" not in ignore)
    for mod in pkg.modules.values():
        s, problems = parse_suppressions(mod.key, mod.comments, known)
        sups.extend(s)
        if meta_on:
            findings.extend(problems)
    unused = apply_suppressions(findings, sups)
    if meta_on and report_unused:
        findings.extend(unused)
    for f in findings:
        mod = pkg.modules.get(f.path)
        if mod is not None:
            f.source_line = mod.line(f.line)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule, f.code))
    return findings


def _default_paths() -> List[str]:
    pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return [pkg_dir]


def changed_py_files(cwd: Optional[str] = None
                     ) -> Optional[Tuple[str, List[str]]]:
    """``(repo_toplevel, abs_paths)`` of ``.py`` files changed vs HEAD
    (staged, unstaged and untracked), for ``--changed``. git reports
    paths relative to the REPO ROOT, so they are resolved against
    ``git rev-parse --show-toplevel`` — never the cwd, which may be a
    subdirectory (joining there silently dropped every changed file
    outside it and green-lit the gate). None when git is unavailable /
    not a repo."""
    cwd = cwd or os.getcwd()

    def git(args: List[str]) -> Optional[List[str]]:
        try:
            out = subprocess.run(["git"] + args, cwd=cwd,
                                 capture_output=True, text=True,
                                 timeout=30)
        except (OSError, subprocess.TimeoutExpired):
            return None
        if out.returncode != 0:
            return None
        return [line.strip() for line in out.stdout.splitlines()
                if line.strip()]

    top = git(["rev-parse", "--show-toplevel"])
    if not top:
        return None
    root = top[0]
    files: List[str] = []
    for args in (["diff", "--name-only", "HEAD", "--"],
                 ["ls-files", "--others", "--exclude-standard",
                  "--full-name"]):
        got = git(args)
        if got is None:
            return None
        files.extend(got)
    seen: List[str] = []
    for f in files:
        path = os.path.join(root, f)
        if not f.endswith(".py") or not os.path.exists(path) \
                or path in seen:
            continue
        if "/fixtures/" in f.replace(os.sep, "/"):
            # rule fixtures contain PLANTED violations by design — the
            # pre-commit fast mode must not fail on editing one (the
            # golden tests are their gate)
            continue
        seen.append(path)
    return root, sorted(seen)


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m deepspeed_tpu.analysis",
        description="dslint: AST invariant checker for host-sync, "
                    "trace-hygiene, recompile-hazard, lock-discipline "
                    "and exception-discipline (docs/static_analysis.md)")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to analyze (default: the "
                         "deepspeed_tpu package)")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 on unsuppressed, un-baselined findings")
    ap.add_argument("--changed", action="store_true",
                    help="fast pre-commit mode: analyze only .py files "
                         "changed vs HEAD (staged/unstaged/untracked). "
                         "Cross-module context (weak resolution, the "
                         "package lock graph, thread roles) is limited "
                         "to the changed set — the full gate remains "
                         "authoritative")
    ap.add_argument("--baseline", default=None,
                    help="baseline JSON of grandfathered findings")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite --baseline from current findings")
    ap.add_argument("--format", choices=("human", "json"),
                    default="human")
    ap.add_argument("--select", default=None,
                    help="comma-separated rule ids to run")
    ap.add_argument("--ignore", default=None,
                    help="comma-separated rule ids to skip")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--show-suppressed", action="store_true",
                    help="also print suppressed/baselined findings")
    args = ap.parse_args(argv)

    if args.list_rules:
        rules = all_rules()
        for rid in sorted(rules):
            print(f"{rid:24s} {rules[rid].summary}")
        print(f"{'suppression':24s} malformed / reasonless / unused "
              f"dslint suppression comments (meta-rule)")
        return 0

    repo_root = None
    if args.changed:
        got = changed_py_files()
        if got is None:
            print("dslint: --changed needs a git checkout",
                  file=sys.stderr)
            return 2
        repo_root, changed = got
        if args.paths:
            # an explicit path list scopes the changed set further
            roots = [os.path.abspath(p) for p in args.paths]
            changed = [f for f in changed
                       if any(f == r or f.startswith(r + os.sep)
                              for r in roots)]
        if not changed:
            print("dslint: no changed python files; gate: PASS")
            return 0
        paths: List[str] = changed
    else:
        paths = list(args.paths) or _default_paths()
    for p in paths:
        if not os.path.exists(p):
            print(f"dslint: no such path: {p}", file=sys.stderr)
            return 2
    if repo_root is not None:
        # repo-root-relative display keys keep path-scoped rules and
        # baseline/suppression fingerprints identical to the full gate
        # no matter which subdirectory --changed runs from
        base = repo_root
    else:
        cwd = os.getcwd()
        base = cwd if all(os.path.abspath(p).startswith(cwd + os.sep)
                          or os.path.abspath(p) == cwd for p in paths) \
            else None
    select = args.select.split(",") if args.select else None
    ignore = args.ignore.split(",") if args.ignore else None
    findings = analyze(paths, base=base, select=select, ignore=ignore,
                       report_unused=not args.changed)

    stale = 0
    if args.baseline and not args.update_baseline:
        stale = Baseline.load(args.baseline).absorb(findings)
    if args.update_baseline:
        if not args.baseline:
            print("dslint: --update-baseline needs --baseline",
                  file=sys.stderr)
            return 2
        Baseline.from_findings(findings).save(args.baseline)

    live = [f for f in findings if not f.suppressed and not f.baselined]
    suppressed = sum(1 for f in findings if f.suppressed)
    baselined = sum(1 for f in findings if f.baselined)
    n_files = len({f.path for f in live})

    if args.format == "json":
        shown = findings if args.show_suppressed else live
        print(json.dumps({
            "findings": [f.to_dict() for f in shown],
            "summary": {"total": len(findings), "live": len(live),
                        "suppressed": suppressed,
                        "baselined": baselined,
                        "stale_baseline_entries": stale}},
            indent=1, sort_keys=True))
    else:
        shown = findings if args.show_suppressed else live
        for f in shown:
            tag = ""
            if f.suppressed:
                tag = " [suppressed]"
            elif f.baselined:
                tag = " [baselined]"
            print(f"{f.location()}: {f.rule}[{f.code}] {f.message} "
                  f"(in {f.symbol}){tag}")
        verdict = "PASS" if not live else "FAIL"
        gate = f"; gate: {verdict}" if args.check else ""
        print(f"dslint: {len(live)} finding(s) in {n_files} file(s) "
              f"({suppressed} suppressed, {baselined} baselined"
              + (f", {stale} stale baseline entrie(s)" if stale else "")
              + f"){gate}")

    if args.update_baseline:
        return 0
    if args.check and live:
        return 1
    return 0
