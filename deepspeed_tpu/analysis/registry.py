"""Rule registry: a rule is a named family of related checks.

A rule subclasses :class:`Rule`, registers via :func:`register`, and
yields :class:`~.findings.Finding` objects from :meth:`run`. Suppression
and baseline granularity is the rule *family* id (``host-sync``), while
each finding also carries a ``code`` naming the specific check
(``item-call``) for humans and golden tests.

Adding a rule (see docs/static_analysis.md for the worked example):

1. create ``rules/my_rule.py`` with a ``Rule`` subclass and
   ``@register`` it;
2. import the module from ``rules/__init__.py``;
3. add a planted true-positive and a near-miss true-negative fixture
   under ``tests/fixtures/dslint/`` and a golden entry in
   ``tests/test_static_analysis.py``;
4. document it in docs/static_analysis.md.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Type

from .findings import Finding
from .model import PackageModel


class Rule:
    #: family id used in suppressions / --select / baseline entries
    id: str = ""
    #: one-line description for --list-rules
    summary: str = ""

    def run(self, pkg: PackageModel) -> Iterator[Finding]:
        raise NotImplementedError


_RULES: Dict[str, Type[Rule]] = {}


def register(cls: Type[Rule]) -> Type[Rule]:
    if not cls.id:
        raise ValueError(f"rule {cls.__name__} has no id")
    if cls.id in _RULES:
        raise ValueError(f"duplicate rule id {cls.id!r}")
    _RULES[cls.id] = cls
    return cls


def all_rules() -> Dict[str, Type[Rule]]:
    # import for side effect: rule modules self-register
    from . import rules  # noqa: F401

    return dict(_RULES)


def known_rule_ids() -> List[str]:
    ids = sorted(all_rules())
    return ids + ["suppression"]   # the meta-rule has no Rule class
