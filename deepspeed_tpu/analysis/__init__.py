"""dslint — AST-based invariant checker for deepspeed_tpu.

Machine-checks the invariants the perf and serving layers are built on
(docs/static_analysis.md): no host syncs or host state inside traced
code, no per-call jit construction, lock order region -> cell ->
fleet -> replica with no blocking work or user callbacks under a held
lock, no broad ``except`` swallowing the typed fault semantics, and —
dsrace — no shared attribute reachable from two thread roles without a
common lock (Eraser-style lockset analysis over the discovered thread
model, cross-validated at runtime by resilience/locksan.py). Pure
stdlib ``ast`` — nothing in this package imports jax or executes
analyzed code.

CLI: ``python -m deepspeed_tpu.analysis --check --baseline
dslint_baseline.json`` (the run_tests.sh gate; ``--changed`` is the
git-diff-scoped pre-commit fast mode).
"""

from .cli import analyze, main  # noqa: F401
from .findings import Baseline, Finding  # noqa: F401
from .model import PackageModel, build_package_model  # noqa: F401
from .registry import Rule, all_rules, known_rule_ids, register  # noqa: F401
