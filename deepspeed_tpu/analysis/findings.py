"""Findings, per-line suppressions, and the committed baseline.

Suppression syntax (the reason is REQUIRED — a suppression without one
is itself a finding):

    something_risky()   # dslint: disable=host-sync -- trace-time constant
    # dslint: disable-next-line=lock-discipline -- dedicated sink mutex
    sink.write(record)

Multiple rules: ``disable=host-sync,trace-hygiene -- reason``. The
comment must sit on the flagged line (or the line above, with
``disable-next-line``). Suppressions that match no finding are reported
too (``suppression`` rule): a stale suppression hides nothing today but
will silently hide a regression tomorrow.

The baseline file grandfathers pre-existing findings so the CI gate can
demand *zero new* findings without requiring a big-bang cleanup.
Entries are fingerprinted by (rule, path, symbol, normalized source
line) — never by line number, which drifts on every unrelated edit.
"""

from __future__ import annotations

import hashlib
import json
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Tuple

_SUPPRESS_RE = re.compile(
    r"#\s*dslint:\s*(disable(?:-next-line)?)\s*=\s*"
    r"(?P<rules>[A-Za-z0-9_,\- ]+?)"
    r"\s*(?:--\s*(?P<reason>.*))?$")


@dataclass
class Finding:
    rule: str          # rule family id, e.g. "host-sync"
    code: str          # sub-check, e.g. "item-call"
    path: str          # display-relative path
    line: int
    col: int
    message: str
    symbol: str = "<module>"
    #: set post-collection
    source_line: str = ""
    suppressed: bool = False
    baselined: bool = False

    def fingerprint(self) -> str:
        norm = " ".join(self.source_line.split())
        h = hashlib.sha1(
            f"{self.rule}|{self.path}|{self.symbol}|{norm}".encode())
        return h.hexdigest()[:16]

    def location(self) -> str:
        return f"{self.path}:{self.line}:{self.col}"

    def to_dict(self) -> Dict:
        return {"rule": self.rule, "code": self.code, "path": self.path,
                "line": self.line, "col": self.col,
                "message": self.message, "symbol": self.symbol,
                "suppressed": self.suppressed,
                "baselined": self.baselined,
                "fingerprint": self.fingerprint()}


@dataclass
class Suppression:
    path: str
    comment_line: int       # line the comment sits on
    applies_to: int         # line findings must be on to match
    rules: Tuple[str, ...]
    reason: str
    used_rules: Set[str] = field(default_factory=set)


def parse_suppressions(path: str, comments: Dict[int, str],
                       known_rules: Set[str]
                       ) -> Tuple[List[Suppression], List[Finding]]:
    """Extract suppressions from one module's comments; malformed ones
    come back as findings of the ``suppression`` meta-rule."""
    sups: List[Suppression] = []
    problems: List[Finding] = []
    for lineno, text in sorted(comments.items()):
        # pragma syntax is the tool name followed by a colon — prose
        # comments may mention the tool by bare name without being
        # parsed as suppressions
        if "dslint" + ":" not in text:
            continue
        m = _SUPPRESS_RE.search(text)
        if not m:
            problems.append(Finding(
                rule="suppression", code="malformed", path=path,
                line=lineno, col=0,
                message="malformed dslint comment (expected "
                        "'# dslint: disable=<rule> -- <reason>')"))
            continue
        rules = tuple(r.strip() for r in m.group("rules").split(",")
                      if r.strip())
        reason = (m.group("reason") or "").strip()
        unknown = [r for r in rules if r not in known_rules]
        if unknown:
            problems.append(Finding(
                rule="suppression", code="unknown-rule", path=path,
                line=lineno, col=0,
                message=f"suppression names unknown rule(s): "
                        f"{', '.join(unknown)}"))
            rules = tuple(r for r in rules if r in known_rules)
            if not rules:   # nothing valid left: no (unused) suppression
                continue
        if not reason:
            problems.append(Finding(
                rule="suppression", code="missing-reason", path=path,
                line=lineno, col=0,
                message="suppression without a reason — add "
                        "'-- <why this is safe here>'"))
            continue   # a reasonless suppression does not suppress
        applies = lineno + 1 if m.group(1) == "disable-next-line" \
            else lineno
        sups.append(Suppression(path=path, comment_line=lineno,
                                applies_to=applies, rules=rules,
                                reason=reason))
    return sups, problems


def apply_suppressions(findings: List[Finding],
                       sups: List[Suppression]) -> List[Finding]:
    """Mark suppressed findings; return findings for UNUSED suppressions
    (a suppression that matches nothing is dead weight that will hide a
    future regression — keep them honest)."""
    index: Dict[Tuple[str, int], List[Suppression]] = {}
    for s in sups:
        index.setdefault((s.path, s.applies_to), []).append(s)
    for f in findings:
        for s in index.get((f.path, f.line), []):
            if f.rule in s.rules:
                f.suppressed = True
                s.used_rules.add(f.rule)
    unused: List[Finding] = []
    for s in sups:
        # per-RULE accounting: `disable=a,b` where only `a` ever fires
        # leaves a dead `b` that would silently swallow a future
        # b-finding on this line — report the unmatched subset
        dead = [r for r in s.rules if r not in s.used_rules]
        if dead:
            unused.append(Finding(
                rule="suppression", code="unused", path=s.path,
                line=s.comment_line, col=0,
                message=f"unused suppression for "
                        f"{', '.join(dead)} — nothing on this line "
                        f"triggers it; remove "
                        f"{'the comment' if len(dead) == len(s.rules) else 'that rule from the comment'}"))
    return unused


# ----------------------------------------------------------------------
# baseline
# ----------------------------------------------------------------------

class Baseline:
    """Fingerprint multiset of grandfathered findings."""

    def __init__(self, counts: Optional[Dict[str, int]] = None,
                 meta: Optional[Dict[str, Dict]] = None) -> None:
        self.counts: Dict[str, int] = dict(counts or {})
        self.meta: Dict[str, Dict] = dict(meta or {})

    @classmethod
    def load(cls, path: str) -> "Baseline":
        try:
            with open(path, "r", encoding="utf-8") as fh:
                data = json.load(fh)
        except FileNotFoundError:
            return cls()
        counts: Dict[str, int] = {}
        meta: Dict[str, Dict] = {}
        for e in data.get("entries", []):
            fp = e["fingerprint"]
            counts[fp] = counts.get(fp, 0) + int(e.get("count", 1))
            meta[fp] = e
        return cls(counts, meta)

    @classmethod
    def from_findings(cls, findings: Iterable[Finding]) -> "Baseline":
        b = cls()
        for f in findings:
            if f.suppressed:
                continue
            fp = f.fingerprint()
            b.counts[fp] = b.counts.get(fp, 0) + 1
            b.meta.setdefault(fp, {
                "fingerprint": fp, "rule": f.rule, "path": f.path,
                "symbol": f.symbol, "message": f.message})
        return b

    def save(self, path: str) -> None:
        entries = []
        for fp in sorted(self.counts):
            e = dict(self.meta.get(fp, {"fingerprint": fp}))
            e["fingerprint"] = fp
            e["count"] = self.counts[fp]
            entries.append(e)
        with open(path, "w", encoding="utf-8") as fh:
            json.dump({"version": 1, "entries": entries}, fh, indent=1,
                      sort_keys=True)
            fh.write("\n")

    def absorb(self, findings: List[Finding]) -> int:
        """Mark up to ``count`` findings per fingerprint as baselined.
        Returns the number of STALE baseline entries (fingerprints with
        no surviving finding — the code was fixed; the entry should be
        dropped via --update-baseline)."""
        remaining = dict(self.counts)
        for f in findings:
            if f.suppressed:
                continue
            fp = f.fingerprint()
            if remaining.get(fp, 0) > 0:
                remaining[fp] -= 1
                f.baselined = True
        return sum(1 for fp, n in remaining.items() if n > 0)
