"""Pipeline module specification: layer lists, partitioning, tied weights.

Parity with the reference's ``runtime/pipe/module.py`` (``LayerSpec`` :86's
deferred construction, ``TiedLayerSpec`` :77, ``PipelineModule`` partition
methods uniform/parameters/type:regex) — re-designed for functional JAX:

* a layer is anything with ``init(rng) -> params`` and
  ``apply(params, x) -> x`` (or a parameterless callable ``x -> x``);
* tied layers *share one params entry* — in JAX tying is aliasing in the
  pytree, and the gradient summation the reference implements as
  ``ReduceTiedGrads`` (pipe/engine.py:253) falls out of autodiff when both
  uses reference the same leaf;
* partitioning returns stage boundaries; execution is either the compiled
  rotating-microbatch pipeline (``parallel/pipeline.py``) when every stage
  is structurally identical (the transformer fast path), or — for
  heterogeneous graphs — :meth:`pipeline_loss` pipelines the longest run
  of structurally identical layers (the repeated trunk) and runs the
  asymmetric prefix/suffix (embedding, head, reshapes) under plain GSPMD,
  exactly how the reference's ``partition_method`` ends up treating the
  embed/head stages (runtime/pipe/module.py:86).
"""

from __future__ import annotations

import re
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class LayerSpec:
    """Deferred layer construction (reference module.py:86). Holds the
    class/factory and args; ``build()`` instantiates."""

    def __init__(self, typename: Callable, *args, **kwargs):
        self.typename = typename
        self.args = args
        self.kwargs = kwargs

    def build(self):
        return self.typename(*self.args, **self.kwargs)

    @property
    def name(self) -> str:
        return getattr(self.typename, "__name__", str(self.typename))

    def __repr__(self):
        return f"LayerSpec({self.name})"


class TiedLayerSpec(LayerSpec):
    """LayerSpec whose parameters are shared across every layer built with
    the same ``key`` (reference module.py:77 — embedding/LM-head tying)."""

    def __init__(self, key: str, typename: Callable, *args,
                 forward_fn: Optional[Callable] = None, **kwargs):
        super().__init__(typename, *args, **kwargs)
        self.key = key
        self.forward_fn = forward_fn

    def __repr__(self):
        return f"TiedLayerSpec({self.key}, {self.name})"


def partition_balanced(weights: Sequence[float], num_parts: int) -> List[int]:
    """Contiguous partition of ``weights`` into ``num_parts`` minimizing the
    max part weight (reference deepspeed/runtime/utils.py partition_balanced,
    used by PipelineModule._partition_layers). Returns ``num_parts + 1``
    boundary indices."""
    n = len(weights)
    assert num_parts <= n, f"cannot split {n} layers into {num_parts} stages"
    prefix = np.concatenate([[0.0], np.cumsum(np.asarray(weights, np.float64))])

    def parts_needed(cap: float) -> Optional[List[int]]:
        bounds = [0]
        start = 0
        for _ in range(num_parts):
            # furthest end with sum(weights[start:end]) <= cap
            end = int(np.searchsorted(prefix, prefix[start] + cap, side="right") - 1)
            end = max(end, start + 1)  # always advance
            end = min(end, n)
            bounds.append(end)
            start = end
            if end == n:
                break
        if bounds[-1] < n:
            return None
        while len(bounds) < num_parts + 1:
            bounds.append(n)
        return bounds

    lo, hi = float(np.max(weights)) if n else 0.0, float(prefix[-1])
    best = parts_needed(hi)
    for _ in range(50):
        mid = (lo + hi) / 2
        got = parts_needed(mid)
        if got is not None:
            best, hi = got, mid
        else:
            lo = mid
    assert best is not None
    return best


def _is_layer_obj(layer: Any) -> bool:
    return hasattr(layer, "init") and hasattr(layer, "apply")


class PipelineModule:
    """Partition a layer list across pipeline stages
    (reference module.py:86 PipelineModule).

    ``partition_method``: ``"uniform"`` (equal layer counts),
    ``"parameters"`` (balance by parameter count), or ``"type:<regex>"``
    (stage boundaries at layers whose name matches).
    """

    def __init__(self, layers: Sequence[Any], num_stages: int,
                 partition_method: str = "parameters",
                 loss_fn: Optional[Callable] = None):
        self.specs = list(layers)
        self.num_stages = num_stages
        self.partition_method = partition_method
        self.loss_fn = loss_fn
        self._built = [s.build() if isinstance(s, LayerSpec) else s for s in self.specs]
        self.parts = self._partition_layers()
        self._mesh = None
        self._pipe_size = 1

    # -- partitioning ---------------------------------------------------
    def _layer_param_counts(self) -> List[float]:
        counts = []
        for layer in self._built:
            if _is_layer_obj(layer):
                shapes = jax.eval_shape(lambda l=layer: l.init(jax.random.PRNGKey(0)))
                counts.append(float(sum(int(np.prod(s.shape))
                                        for s in jax.tree_util.tree_leaves(shapes))))
            else:
                counts.append(0.0)
        return counts

    def _partition_layers(self) -> List[int]:
        method = self.partition_method.lower()
        n = len(self.specs)
        if method == "uniform":
            return partition_balanced([1.0] * n, self.num_stages)
        if method == "parameters":
            counts = self._layer_param_counts()
            if sum(counts) == 0:
                counts = [1.0] * n
            return partition_balanced(counts, self.num_stages)
        if method.startswith("type:"):
            pattern = method.split(":", 1)[1]
            weights = []
            for spec in self.specs:
                name = spec.name if isinstance(spec, LayerSpec) else type(spec).__name__
                weights.append(1.0 if re.search(pattern, name, re.IGNORECASE) else 0.0)
            if sum(weights) == 0:
                raise ValueError(f"no layer matches partition regex {pattern!r}")
            return partition_balanced(weights, self.num_stages)
        raise ValueError(f"unknown partition_method {self.partition_method!r}")

    def stage_layers(self, stage_id: int) -> List[Any]:
        return self._built[self.parts[stage_id]:self.parts[stage_id + 1]]

    def stage_of_layer(self, layer_idx: int) -> int:
        for s in range(self.num_stages):
            if self.parts[s] <= layer_idx < self.parts[s + 1]:
                return s
        raise IndexError(layer_idx)

    # -- params ---------------------------------------------------------
    def init(self, rng) -> Dict[str, Any]:
        """Build the parameter pytree: one entry per layer, tied layers
        collapsing onto a shared ``tied/<key>`` entry."""
        params: Dict[str, Any] = {"layers": {}, "tied": {}}
        keys = jax.random.split(rng, max(len(self._built), 1))
        for i, (spec, layer) in enumerate(zip(self.specs, self._built)):
            if not _is_layer_obj(layer):
                continue
            if isinstance(spec, TiedLayerSpec):
                if spec.key not in params["tied"]:
                    params["tied"][spec.key] = layer.init(keys[i])
            else:
                params["layers"][str(i)] = layer.init(keys[i])
        return params

    # -- execution ------------------------------------------------------
    def apply(self, params: Dict[str, Any], x: Any, **kwargs) -> Any:
        """Sequential forward through all layers. Under GSPMD with the
        ``pipe``-axis placement from :meth:`partition_specs` this is the
        correctness path; :meth:`pipeline_loss` pipelines the homogeneous
        trunk through ``parallel/pipeline.py``."""
        return self._apply_range(params, x, 0, len(self._built))

    def loss(self, params: Dict[str, Any], batch: Any, rng=None) -> jnp.ndarray:
        assert self.loss_fn is not None, "PipelineModule needs loss_fn for training"
        out = self.apply(params, batch["input"] if isinstance(batch, dict) else batch)
        target = batch["target"] if isinstance(batch, dict) else None
        return self.loss_fn(out, target)

    # -- heterogeneous-graph pipelining ---------------------------------
    def bind_topology(self, topo) -> None:
        self._mesh = topo.mesh
        self._pipe_size = topo.pipe_parallel_size

    def _param_signature(self, i: int):
        layer = self._built[i]
        spec = self.specs[i]
        if not _is_layer_obj(layer) or isinstance(spec, TiedLayerSpec):
            return None
        shapes = jax.eval_shape(lambda l=layer: l.init(jax.random.PRNGKey(0)))
        leaves, treedef = jax.tree_util.tree_flatten(shapes)
        # construction args are part of the identity: two same-class layers
        # with identical param shapes but different config (activation,
        # flags, ...) must NOT merge into one trunk — the scan body applies
        # ONE layer's behavior to every trunk slice. Layers whose apply is
        # arg-independent (e.g. only an init seed differs) opt out by
        # exposing pipeline_signature().
        if hasattr(layer, "pipeline_signature"):
            behavior = (repr(layer.pipeline_signature()),)
        elif isinstance(spec, LayerSpec):
            behavior = (repr(spec.args), repr(sorted(spec.kwargs.items())))
        else:
            behavior = (repr(sorted((k, repr(v)) for k, v in
                                    vars(layer).items()))
                        if hasattr(layer, "__dict__") else "",)
        return (type(layer).__name__, str(treedef),
                tuple((tuple(s.shape), str(s.dtype)) for s in leaves),
                behavior)

    def _signatures(self):
        if not hasattr(self, "_sig_cache") or self._sig_cache is None:
            self._sig_cache = [self._param_signature(i)
                               for i in range(len(self._built))]
        return self._sig_cache

    def pipeline_trunk(self, stages: Optional[int] = None) -> Tuple[int, int]:
        """[start, end) of the longest run of structurally identical layers
        whose length divides by the executing stage count (the bound
        topology's pipe size when available — NOT necessarily
        ``num_stages``) — the pipelinable middle of an
        embed/trunk/head-asymmetric graph.

        Signature identity means: same layer class, same construction
        args, same param treedef/shapes/dtypes. Layers whose apply does
        not depend on constructor args (e.g. only an init seed differs)
        expose ``pipeline_signature()`` to merge anyway."""
        if stages is None:
            stages = (self._pipe_size if getattr(self, "_pipe_size", 1) > 1
                      else self.num_stages)
        sigs = self._signatures()
        best = (0, 0)
        i = 0
        while i < len(sigs):
            if sigs[i] is None:
                i += 1
                continue
            j = i
            while j < len(sigs) and sigs[j] == sigs[i]:
                j += 1
            if j - i > best[1] - best[0]:
                best = (i, j)
            i = j
        start, end = best
        n = end - start
        usable = n - (n % stages)
        return start, start + usable

    def pipeline_loss(self, params: Dict[str, Any], batch: Any, rng,
                      num_microbatches: int) -> jnp.ndarray:
        """Pipelined loss for heterogeneous graphs: prefix and suffix
        layers run sequentially under GSPMD on the full batch; the
        homogeneous trunk runs through the rotating-microbatch executor
        over the ``pipe`` axis (stage body = scan over its trunk slice)."""
        from ..parallel.pipeline import (microbatch, pipeline_apply,
                                         stack_stage_params)

        assert self.loss_fn is not None, "PipelineModule needs loss_fn"
        assert getattr(self, "_pipe_size", 1) > 1 and self._mesh is not None, \
            "pipeline_loss requires bind_topology with pipe axis > 1"
        # stage count for EXECUTION is the bound topology's pipe size —
        # num_stages (the partitioning hint) may differ
        start, end = self.pipeline_trunk(self._pipe_size)
        if end - start < self._pipe_size:
            # nothing pipelinable — fall back to the sequential GSPMD path
            return self.loss(params, batch, rng)
        if rng is None:
            rng = jax.random.PRNGKey(0)

        x = batch["input"] if isinstance(batch, dict) else batch
        target = batch["target"] if isinstance(batch, dict) else None
        x = self._apply_range(params, x, 0, start)

        trunk_idx = list(range(start, end))
        trunk_apply = self._built[start].apply
        stacked = jax.tree_util.tree_map(
            lambda *leaves: jnp.stack(leaves),
            *[params["layers"][str(i)] for i in trunk_idx])
        stage_params = stack_stage_params(stacked, self._pipe_size)

        xs = microbatch(x, num_microbatches).astype(jnp.float32)

        def stage_fn(lp_stage, xmb, consts, sub_rng, valid):
            dtype = x.dtype

            def body(y, lp):
                return trunk_apply(lp, y.astype(dtype)).astype(jnp.float32), None

            y, _ = jax.lax.scan(body, xmb.astype(jnp.float32), lp_stage)
            return y, jnp.zeros([], jnp.float32)

        ys, _ = pipeline_apply(stage_fn, stage_params, xs, rng, self._mesh,
                               consts=jnp.zeros([], jnp.float32))
        y = ys.reshape((-1,) + ys.shape[2:]).astype(x.dtype)
        y = self._apply_range(params, y, end, len(self._built))
        return self.loss_fn(y, target)

    def _apply_range(self, params, x, lo: int, hi: int):
        for i in range(lo, hi):
            spec, layer = self.specs[i], self._built[i]
            if _is_layer_obj(layer):
                if isinstance(spec, TiedLayerSpec):
                    p = params["tied"][spec.key]
                    fwd = spec.forward_fn or (lambda l, pp, xx: l.apply(pp, xx))
                    x = fwd(layer, p, x)
                else:
                    x = layer.apply(params["layers"][str(i)], x)
            else:
                x = layer(x)
        return x

    def __len__(self):
        return len(self.specs)

    def __repr__(self):
        return (f"PipelineModule({len(self.specs)} layers, "
                f"{self.num_stages} stages, parts={self.parts})")
