"""Pipeline module specification: layer lists, partitioning, tied weights.

Parity with the reference's ``runtime/pipe/module.py`` (``LayerSpec`` :86's
deferred construction, ``TiedLayerSpec`` :77, ``PipelineModule`` partition
methods uniform/parameters/type:regex) — re-designed for functional JAX:

* a layer is anything with ``init(rng) -> params`` and
  ``apply(params, x) -> x`` (or a parameterless callable ``x -> x``);
* tied layers *share one params entry* — in JAX tying is aliasing in the
  pytree, and the gradient summation the reference implements as
  ``ReduceTiedGrads`` (pipe/engine.py:253) falls out of autodiff when both
  uses reference the same leaf;
* partitioning returns stage boundaries; execution is either the compiled
  rotating-microbatch pipeline (``parallel/pipeline.py``) when every stage
  is structurally identical (the transformer fast path), or a sequential
  composition under GSPMD with per-stage sharding hints otherwise.
"""

from __future__ import annotations

import re
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class LayerSpec:
    """Deferred layer construction (reference module.py:86). Holds the
    class/factory and args; ``build()`` instantiates."""

    def __init__(self, typename: Callable, *args, **kwargs):
        self.typename = typename
        self.args = args
        self.kwargs = kwargs

    def build(self):
        return self.typename(*self.args, **self.kwargs)

    @property
    def name(self) -> str:
        return getattr(self.typename, "__name__", str(self.typename))

    def __repr__(self):
        return f"LayerSpec({self.name})"


class TiedLayerSpec(LayerSpec):
    """LayerSpec whose parameters are shared across every layer built with
    the same ``key`` (reference module.py:77 — embedding/LM-head tying)."""

    def __init__(self, key: str, typename: Callable, *args,
                 forward_fn: Optional[Callable] = None, **kwargs):
        super().__init__(typename, *args, **kwargs)
        self.key = key
        self.forward_fn = forward_fn

    def __repr__(self):
        return f"TiedLayerSpec({self.key}, {self.name})"


def partition_balanced(weights: Sequence[float], num_parts: int) -> List[int]:
    """Contiguous partition of ``weights`` into ``num_parts`` minimizing the
    max part weight (reference deepspeed/runtime/utils.py partition_balanced,
    used by PipelineModule._partition_layers). Returns ``num_parts + 1``
    boundary indices."""
    n = len(weights)
    assert num_parts <= n, f"cannot split {n} layers into {num_parts} stages"
    prefix = np.concatenate([[0.0], np.cumsum(np.asarray(weights, np.float64))])

    def parts_needed(cap: float) -> Optional[List[int]]:
        bounds = [0]
        start = 0
        for _ in range(num_parts):
            # furthest end with sum(weights[start:end]) <= cap
            end = int(np.searchsorted(prefix, prefix[start] + cap, side="right") - 1)
            end = max(end, start + 1)  # always advance
            end = min(end, n)
            bounds.append(end)
            start = end
            if end == n:
                break
        if bounds[-1] < n:
            return None
        while len(bounds) < num_parts + 1:
            bounds.append(n)
        return bounds

    lo, hi = float(np.max(weights)) if n else 0.0, float(prefix[-1])
    best = parts_needed(hi)
    for _ in range(50):
        mid = (lo + hi) / 2
        got = parts_needed(mid)
        if got is not None:
            best, hi = got, mid
        else:
            lo = mid
    assert best is not None
    return best


def _is_layer_obj(layer: Any) -> bool:
    return hasattr(layer, "init") and hasattr(layer, "apply")


class PipelineModule:
    """Partition a layer list across pipeline stages
    (reference module.py:86 PipelineModule).

    ``partition_method``: ``"uniform"`` (equal layer counts),
    ``"parameters"`` (balance by parameter count), or ``"type:<regex>"``
    (stage boundaries at layers whose name matches).
    """

    def __init__(self, layers: Sequence[Any], num_stages: int,
                 partition_method: str = "parameters",
                 loss_fn: Optional[Callable] = None):
        self.specs = list(layers)
        self.num_stages = num_stages
        self.partition_method = partition_method
        self.loss_fn = loss_fn
        self._built = [s.build() if isinstance(s, LayerSpec) else s for s in self.specs]
        self.parts = self._partition_layers()

    # -- partitioning ---------------------------------------------------
    def _layer_param_counts(self) -> List[float]:
        counts = []
        for layer in self._built:
            if _is_layer_obj(layer):
                shapes = jax.eval_shape(lambda l=layer: l.init(jax.random.PRNGKey(0)))
                counts.append(float(sum(int(np.prod(s.shape))
                                        for s in jax.tree_util.tree_leaves(shapes))))
            else:
                counts.append(0.0)
        return counts

    def _partition_layers(self) -> List[int]:
        method = self.partition_method.lower()
        n = len(self.specs)
        if method == "uniform":
            return partition_balanced([1.0] * n, self.num_stages)
        if method == "parameters":
            counts = self._layer_param_counts()
            if sum(counts) == 0:
                counts = [1.0] * n
            return partition_balanced(counts, self.num_stages)
        if method.startswith("type:"):
            pattern = method.split(":", 1)[1]
            weights = []
            for spec in self.specs:
                name = spec.name if isinstance(spec, LayerSpec) else type(spec).__name__
                weights.append(1.0 if re.search(pattern, name, re.IGNORECASE) else 0.0)
            if sum(weights) == 0:
                raise ValueError(f"no layer matches partition regex {pattern!r}")
            return partition_balanced(weights, self.num_stages)
        raise ValueError(f"unknown partition_method {self.partition_method!r}")

    def stage_layers(self, stage_id: int) -> List[Any]:
        return self._built[self.parts[stage_id]:self.parts[stage_id + 1]]

    def stage_of_layer(self, layer_idx: int) -> int:
        for s in range(self.num_stages):
            if self.parts[s] <= layer_idx < self.parts[s + 1]:
                return s
        raise IndexError(layer_idx)

    # -- params ---------------------------------------------------------
    def init(self, rng) -> Dict[str, Any]:
        """Build the parameter pytree: one entry per layer, tied layers
        collapsing onto a shared ``tied/<key>`` entry."""
        params: Dict[str, Any] = {"layers": {}, "tied": {}}
        keys = jax.random.split(rng, max(len(self._built), 1))
        for i, (spec, layer) in enumerate(zip(self.specs, self._built)):
            if not _is_layer_obj(layer):
                continue
            if isinstance(spec, TiedLayerSpec):
                if spec.key not in params["tied"]:
                    params["tied"][spec.key] = layer.init(keys[i])
            else:
                params["layers"][str(i)] = layer.init(keys[i])
        return params

    # -- execution ------------------------------------------------------
    def apply(self, params: Dict[str, Any], x: Any, **kwargs) -> Any:
        """Sequential forward through all layers. Under GSPMD with the
        ``pipe``-axis placement from :meth:`partition_specs` this is the
        correctness path; the homogeneous-stage fast path goes through
        ``parallel/pipeline.py`` (see models/transformer.py)."""
        for i, (spec, layer) in enumerate(zip(self.specs, self._built)):
            if _is_layer_obj(layer):
                if isinstance(spec, TiedLayerSpec):
                    p = params["tied"][spec.key]
                    fwd = spec.forward_fn or (lambda l, pp, xx: l.apply(pp, xx))
                    x = fwd(layer, p, x)
                else:
                    x = layer.apply(params["layers"][str(i)], x)
            else:
                x = layer(x)
        return x

    def loss(self, params: Dict[str, Any], batch: Any, rng=None) -> jnp.ndarray:
        assert self.loss_fn is not None, "PipelineModule needs loss_fn for training"
        out = self.apply(params, batch["input"] if isinstance(batch, dict) else batch)
        target = batch["target"] if isinstance(batch, dict) else None
        return self.loss_fn(out, target)

    def __len__(self):
        return len(self.specs)

    def __repr__(self):
        return (f"PipelineModule({len(self.specs)} layers, "
                f"{self.num_stages} stages, parts={self.parts})")
