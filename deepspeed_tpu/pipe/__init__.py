"""Pipeline parallelism: layer specs, partitioning, instruction schedules,
and the compiled SPMD executor (parallel/pipeline.py).

Parity surface with the reference's ``deepspeed/pipe`` + ``runtime/pipe``
(PipelineModule, LayerSpec, TiedLayerSpec re-exported at deepspeed/pipe/
__init__.py; schedules in runtime/pipe/schedule.py).
"""

from .module import LayerSpec, PipelineModule, TiedLayerSpec, partition_balanced
from .schedule import (
    BackwardPass,
    ForwardPass,
    InferenceSchedule,
    LoadMicroBatch,
    OptimizerStep,
    PipeInstruction,
    PipeSchedule,
    RecvActivation,
    RecvGrad,
    ReduceGrads,
    ReduceTiedGrads,
    SendActivation,
    SendGrad,
    TrainSchedule,
    bubble_fraction,
)

__all__ = [
    "LayerSpec", "TiedLayerSpec", "PipelineModule", "partition_balanced",
    "PipeSchedule", "TrainSchedule", "InferenceSchedule", "PipeInstruction",
    "ForwardPass", "BackwardPass", "SendActivation", "RecvActivation",
    "SendGrad", "RecvGrad", "LoadMicroBatch", "ReduceGrads",
    "ReduceTiedGrads", "OptimizerStep", "bubble_fraction",
]
