"""Pipeline instruction schedules.

Parity with the reference's ``runtime/pipe/schedule.py`` (TrainSchedule 1F1B
:189, InferenceSchedule :135, instruction set :327-:475). On TPU the
schedule is not interpreted at runtime — the compiled rotating-microbatch
program in ``parallel/pipeline.py`` realizes the same dependency structure —
but the explicit instruction list remains the specification of that
structure: tests assert the compiled executor's tick/stage mapping agrees
with these schedules, and tooling (trace viewers, the autotuner's bubble
model) consumes them.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterator, List


@dataclass(frozen=True)
class PipeInstruction:
    """Base instruction (reference schedule.py:327)."""
    micro_batch: int = -1

    def __repr__(self):
        mb = f"(mb={self.micro_batch})" if self.micro_batch >= 0 else ""
        return f"{type(self).__name__}{mb}"


class OptimizerStep(PipeInstruction):
    pass


class ReduceGrads(PipeInstruction):
    pass


class ReduceTiedGrads(PipeInstruction):
    pass


class LoadMicroBatch(PipeInstruction):
    pass


class ForwardPass(PipeInstruction):
    pass


class BackwardPass(PipeInstruction):
    pass


class SendActivation(PipeInstruction):
    pass


class RecvActivation(PipeInstruction):
    pass


class SendGrad(PipeInstruction):
    pass


class RecvGrad(PipeInstruction):
    pass


class PipeSchedule:
    """Yields lists of instructions per clock step for one stage
    (reference schedule.py:11 PipeSchedule ABC)."""

    def __init__(self, micro_batches: int, stages: int, stage_id: int):
        assert 0 <= stage_id < stages
        self.micro_batches = micro_batches
        self.stages = stages
        self.stage_id = stage_id

    @property
    def is_first_stage(self) -> bool:
        return self.stage_id == 0

    @property
    def is_last_stage(self) -> bool:
        return self.stage_id == self.stages - 1

    def num_pipe_buffers(self) -> int:
        raise NotImplementedError

    def steps(self) -> Iterator[List[PipeInstruction]]:
        raise NotImplementedError

    def __iter__(self):
        return self.steps()


class InferenceSchedule(PipeSchedule):
    """Forward-only fill/drain (reference schedule.py:135)."""

    def num_pipe_buffers(self) -> int:
        return 2

    def steps(self):
        total = self.micro_batches + self.stages - 1
        for t in range(total):
            cmds: List[PipeInstruction] = []
            mb = t - self.stage_id
            if 0 <= mb < self.micro_batches:
                if self.is_first_stage:
                    cmds.append(LoadMicroBatch(mb))
                else:
                    cmds.append(RecvActivation(mb))
                cmds.append(ForwardPass(mb))
                if not self.is_last_stage:
                    cmds.append(SendActivation(mb))
            yield cmds


class TrainSchedule(PipeSchedule):
    """1F1B interleave (reference schedule.py:189).

    Each stage runs forwards ahead of backwards by at most
    ``stages - stage_id`` micro-batches, bounding live activations to
    ``num_pipe_buffers`` instead of M (the whole point of 1F1B). Total
    wall-clock steps: ``2 * (micro_batches + stages - 1)``.
    """

    def num_pipe_buffers(self) -> int:
        # reference schedule.py:248: min(stages - stage_id + 1, micro_batches)
        buffers = min(self.stages - self.stage_id + 1, self.micro_batches)
        return max(2, buffers)

    def _step_to_micro_batch(self, step_id: int):
        """Map a clock step to (micro_batch, is_forward) for this stage.
        Even steps forward, odd steps backward, offset so stage s starts its
        first forward at step s and its first backward after the pipeline
        fills (mirrors reference schedule.py:257-:280)."""
        if _is_even(step_id) and _is_even(self.stage_id):
            mb = step_id // 2 - self.stage_id // 2
            return mb, True
        if _is_odd(step_id) and _is_odd(self.stage_id):
            mb = step_id // 2 - self.stage_id // 2
            return mb, True
        if _is_odd(step_id) and _is_even(self.stage_id):
            mb = (step_id - 1) // 2 - (self.stages - 1) + self.stage_id // 2
            return mb, False
        mb = (step_id - 1) // 2 - (self.stages - 1) + (self.stage_id + 1) // 2
        return mb, False

    def steps(self):
        total_steps = 2 * (self.micro_batches + self.stages - 1)
        prev_mb = -1
        for step_id in range(total_steps):
            mb, is_forward = self._step_to_micro_batch(step_id)
            cmds: List[PipeInstruction] = []
            if 0 <= mb < self.micro_batches:
                if is_forward:
                    if self.is_first_stage:
                        cmds.append(LoadMicroBatch(mb))
                    else:
                        cmds.append(RecvActivation(mb))
                    cmds.append(ForwardPass(mb))
                    if not self.is_last_stage:
                        cmds.append(SendActivation(mb))
                else:
                    if not self.is_last_stage:
                        cmds.append(RecvGrad(mb))
                    cmds.append(BackwardPass(mb))
                    if not self.is_first_stage:
                        cmds.append(SendGrad(mb))
                prev_mb = mb
            if step_id == total_steps - 1:
                cmds.append(ReduceTiedGrads())
                cmds.append(ReduceGrads())
                cmds.append(OptimizerStep())
            yield cmds


def _is_even(x: int) -> bool:
    return x % 2 == 0


def _is_odd(x: int) -> bool:
    return x % 2 != 0


def bubble_fraction(micro_batches: int, stages: int) -> float:
    """Idle fraction of the 1F1B schedule: (P-1)/(M+P-1)."""
    return (stages - 1) / (micro_batches + stages - 1)
